"""Op-level cost attribution plane (fluid.opprof): stable instance
scope naming, capture attribution that sums honestly (remainder under
unattributed/, fused-kernel time split across constituents, malformed
rows counted not eaten), eager-replay parity with the step report's
dispatch wall, deterministic worklist ranking with pallas coverage
cross-references, the JSON-able /statusz op_costs section, and zero
fingerprint drift when the flag flips mid-run."""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (health, monitor, opprof, profiler,
                              trace)

OPPROF_FLAGS = ('FLAGS_opprof', 'FLAGS_opprof_snapshot_steps')


@pytest.fixture(autouse=True)
def _clean():
    from paddle_tpu.fluid import compile_cache
    prev = fluid.get_flags(list(OPPROF_FLAGS))
    compile_cache.reset_plane()
    monitor.reset()
    opprof.reset()
    trace.disable()
    trace.reset()
    yield
    fluid.set_flags(prev)
    compile_cache.reset_plane()
    monitor.reset()
    opprof.reset()
    trace.disable()
    trace.reset()


def _build_mlp(width=16):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data('x', shape=[width], dtype='float32')
        h = fluid.layers.fc(x, width, act='relu')
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main_p, startup, loss


# ------------------------------------------------- instance provenance
def test_instance_scopes_unique_and_stable():
    main_p, _startup, _loss = _build_mlp()
    ops = list(main_p.global_block().ops)
    names = [opprof.op_scope(op) for op in ops]
    assert len(set(names)) == len(names), 'instance names must be ' \
        'unique within a block'
    for op, name in zip(ops, names):
        typ, idx = opprof.split_instance(name)
        assert typ == op.type and idx is not None
    # a retrace walks the SAME block again — and a cleared memo (fresh
    # process, new trace) must rebuild the identical names, because
    # the suffix is the op's position in its block, not visit order
    again = [opprof.op_scope(op) for op in ops]
    assert again == names
    opprof.reset()
    assert [opprof.op_scope(op) for op in ops] == names
    # the fused-optimizer override keeps the anchor op's index
    assert opprof.op_scope(ops[0], 'fused_x') == \
        'fused_x#%d' % opprof.split_instance(names[0])[1]


def test_want_snapshot_gate():
    fluid.set_flags({'FLAGS_opprof': False})
    assert not any(opprof.want_snapshot(s) for s in range(50))
    fluid.set_flags({'FLAGS_opprof': True,
                     'FLAGS_opprof_snapshot_steps': 8})
    hits = [s for s in range(33) if opprof.want_snapshot(s)]
    assert hits == [0, 8, 16, 24, 32]
    # a zero cadence clamps to every step instead of dividing by zero
    fluid.set_flags({'FLAGS_opprof_snapshot_steps': 0})
    assert all(opprof.want_snapshot(s) for s in range(3))


# ------------------------------------------------ capture attribution
def test_capture_sums_with_honest_unattributed_remainder():
    events = [
        {'ph': 'X', 'name': 'fusion.1', 'dur': 100,
         'args': {'tf_op': 'jit_seg/relu#2'}},
        {'ph': 'X', 'name': 'copy.3', 'dur': 50,
         'args': {'tf_op': 'jit_seg/grad_glue'}},
        {'ph': 'C', 'name': 'counter', 'args': {}},       # filtered
        {'ph': 'X', 'name': 'nometa.0', 'dur': 7,
         'args': {'tf_op': None}},                        # dropped
        'not even a dict',                                # dropped
    ]
    res = opprof.record_capture(events, program='cap', steps=2)
    assert res['dropped'] == 2
    rep = opprof.report()
    # attributed + unattributed reconstruct the capture total (the
    # X-event dur sum / steps) — nothing silently vanishes
    attributed = sum(c['ms_per_step'] for c in rep['top'])
    assert attributed == pytest.approx(100e-3 / 2)
    assert rep['unattributed_ms'] == pytest.approx(50e-3 / 2)
    assert attributed + rep['unattributed_ms'] <= \
        (100 + 50) * 1e-3 / 2 + 1e-9
    assert rep['top'][0]['instance'] == 'relu#2'
    assert monitor.counter_value('opprof/capture_events') == 4.0
    assert monitor.counter_value('opprof/dropped_events') == 2.0
    assert monitor.gauge_value('opprof/attributed_ms_total') == \
        pytest.approx(attributed)


def test_fused_kernel_time_splits_across_constituents():
    # one fusion event carrying three source paths: two resolve to
    # instances, the third's share lands in unattributed — equal split
    events = [{'ph': 'X', 'name': 'fusion.9', 'dur': 90,
               'args': {'tf_op': 'jit_s/relu#1;jit_s/tanh#4;'
                                 'jit_s/opaque_glue'}}]
    recs, stats = profiler.attribute_trace_events(
        events, per_instance=True, with_stats=True)
    assert stats == {'events': 1, 'attributed': 1, 'dropped': 0}
    assert recs['relu#1'][1] == pytest.approx(30e-6)
    assert recs['tanh#4'][1] == pytest.approx(30e-6)
    assert recs['unattributed/fusion'][1] == pytest.approx(30e-6)
    # transform wrappers strip; without per_instance the '#' names
    # stay unresolved (type-only mode is the legacy profiler table)
    recs2 = profiler.attribute_trace_events(
        [{'ph': 'X', 'name': 'k', 'dur': 5,
          'args': {'tf_op': 'jit_s/transpose(jvp(relu))/max'}}])
    assert recs2['relu'][1] == pytest.approx(5e-6)


def test_negative_lookup_cache_and_dropped_accounting():
    # a capture repeats each unattributable scope every step: the
    # negative cache folds the repeats without re-splitting, and the
    # stats count malformed rows instead of eating them
    events = [{'ph': 'X', 'name': 'copy.1', 'dur': 2,
               'args': {'tf_op': 'jit_s/not_an_op/really_not'}}] * 500
    events += [{'ph': 'X', 'name': 'bad', 'dur': 1, 'args': {}},
               {'ph': 'X', 'name': 'bad2', 'dur': 1,
                'args': {'tf_op': 123}}]
    recs, stats = profiler.attribute_trace_events(
        events, per_instance=True, with_stats=True)
    assert recs['unattributed/copy'][0] == 500
    assert stats['events'] == 502 and stats['dropped'] == 2
    assert stats['attributed'] == 0


# ------------------------------------------------------- eager replay
@pytest.mark.filterwarnings('ignore::UserWarning')
def test_replay_parity_with_step_report_on_lenet():
    from paddle_tpu import models
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        _feeds, _pred, loss, _acc = models.lenet.build()
        fluid.optimizer.Adam(1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.rand(16, 1, 28, 28).astype('float32'),
            'label': rng.randint(0, 10, (16, 1)).astype('int64')}
    fluid.set_flags({'FLAGS_opprof': True,
                     'FLAGS_opprof_snapshot_steps': 1})
    trace.enable()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.warmup(main_p,
                   feed_shapes={'img': ((16, 1, 28, 28), 'float32'),
                                'label': ((16, 1), 'int64')},
                   fetch_list=[loss], wait=True)
        for _ in range(2):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        assert monitor.counter_value('opprof/snapshots') >= 1
        done = opprof.replay_all()
    assert done and all(isinstance(v, int) for v in done.values()), \
        'replay must walk every stashed segment: %r' % done
    rep = opprof.report()
    replay_segs = [s for s in rep['segments']
                   if s['source'] == 'replay']
    assert replay_segs
    for seg in replay_segs:
        # normalization contract: instance costs sum to the measured
        # synchronous wall of the snapshot step, exactly
        assert seg['measured_ms'] is not None
        assert seg['attributed_ms'] == pytest.approx(
            seg['measured_ms'], rel=1e-3)
    # ...and that measured wall is the SAME number the step report's
    # dispatch phase carries for the snapshot step (the sync is parked
    # inside the dispatch span) — 10% band for clock-read skew
    sr = trace.step_report()
    last = sr['steps'][-1]
    disp_ms = last['phases_ms'].get('dispatch', 0.0)
    total_measured = sum(s['measured_ms'] for s in replay_segs)
    assert disp_ms > 0
    assert total_measured == pytest.approx(disp_ms, rel=0.10)
    # the replay measured real work: bytes and layers resolve
    top = rep['top']
    assert any(c['bytes_per_step'] > 0 for c in top)
    assert any(c.get('layer') for c in top)
    assert monitor.counter_value('opprof/replays') >= 1


# ---------------------------------------------------------- worklist
def _adam_run_capture():
    events = [
        {'ph': 'X', 'name': 'f.0', 'dur': 40,
         'args': {'tf_op': 'jit_s/adam#5'}},
        {'ph': 'X', 'name': 'f.1', 'dur': 35,
         'args': {'tf_op': 'jit_s/adam#6'}},
        {'ph': 'X', 'name': 'f.2', 'dur': 30,
         'args': {'tf_op': 'jit_s/adam#7'}},
        {'ph': 'X', 'name': 'f.3', 'dur': 20,
         'args': {'tf_op': 'jit_s/relu#0'}},
        # same type but NOT block-contiguous: its own run
        {'ph': 'X', 'name': 'f.4', 'dur': 10,
         'args': {'tf_op': 'jit_s/adam#9'}},
    ]
    opprof.record_capture(events, program='cap', steps=1)


def test_worklist_ranks_contiguous_runs_deterministically(tmp_path):
    _adam_run_capture()
    wl1 = opprof.kernel_worklist()
    wl2 = opprof.kernel_worklist()
    assert wl1 == wl2, 'ranking must be deterministic'
    assert [r['rank'] for r in wl1] == list(range(1, len(wl1) + 1))
    top = wl1[0]
    # the three contiguous adam instances coalesce into ONE run ranked
    # by summed cost; adam#9 stays a separate (non-contiguous) run
    assert top['op_type'] == 'adam'
    assert top['ops'] == ['adam#5', 'adam#6', 'adam#7']
    assert top['span'] == [5, 7]
    assert top['ms_per_step'] == pytest.approx((40 + 35 + 30) * 1e-3)
    assert ['adam#9'] in [r['ops'] for r in wl1]
    # coverage cross-reference: the pallas registry already declares a
    # fused kernel for adam runs
    assert top['covered_by'] == 'fused_optimizer'
    assert monitor.gauge_value('opprof/worklist_candidates') == \
        float(len(wl1))
    # the artifact round-trips as schema-stable JSON
    path = str(tmp_path / 'op_worklist.json')
    assert opprof.write_worklist(path) == path
    with open(path) as f:
        doc = json.load(f)
    assert doc['version'] == 1 and doc['generated_by'] == 'fluid.opprof'
    assert doc['candidates'][0]['ops'] == ['adam#5', 'adam#6', 'adam#7']
    assert set(doc) >= {'candidates', 'by_type', 'by_layer',
                        'segments'}


# ------------------------------------------------------ statusz / json
def test_report_and_statusz_json_able():
    _adam_run_capture()
    fluid.set_flags({'FLAGS_opprof': True})
    rep = opprof.report()
    json.dumps(rep)   # must never raise
    assert rep['enabled'] and rep['top']
    assert rep['by_type']['adam']['ms_per_step'] > 0
    sz = health.statusz()
    assert sz.get('op_costs'), '/statusz must carry the op_costs ' \
        'section once the registry has rows'
    json.dumps(sz['op_costs'])
    assert sz['op_costs']['top'][0]['instance'] == 'adam#5'


# ---------------------------------------------- fingerprint neutrality
def test_zero_fingerprint_drift_under_flag_flips():
    main_p, startup, loss = _build_mlp()
    feed = {'x': np.ones((8, 16), 'float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(2):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        misses = monitor.counter_value('segment_cache_miss')
        # flipping the flag mid-run keys NO cache: zero new compiles
        fluid.set_flags({'FLAGS_opprof': True,
                         'FLAGS_opprof_snapshot_steps': 1})
        for _ in range(2):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        assert monitor.counter_value('opprof/snapshots') >= 1
        fluid.set_flags({'FLAGS_opprof': False})
        for _ in range(2):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        assert monitor.counter_value('segment_cache_miss') == misses
