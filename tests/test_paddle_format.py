"""Reference binary checkpoint/model format interop (round-4 VERDICT
item 5).

Golden-bytes cross-checks: the expected bytes are built (a) fully by
hand from the documented stream layout (lod_tensor.cc:219,
tensor_util.cc TensorToStream) and (b) with REAL protobuf — protoc
compiles the reference's framework.proto and google.protobuf encodes
the ProgramDesc — so the hand-rolled wire codec is validated against
an independent implementation, not against itself."""

import os
import struct
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid import paddle_format as pf

REFERENCE_PROTO = '/root/reference/paddle/fluid/framework/framework.proto'


@pytest.fixture(scope='module')
def framework_pb2(tmp_path_factory):
    if not os.path.exists(REFERENCE_PROTO):
        pytest.skip('reference framework.proto unavailable')
    d = tmp_path_factory.mktemp('pb')
    import shutil
    shutil.copy(REFERENCE_PROTO, d / 'framework.proto')
    subprocess.run(['protoc', '--python_out=.', 'framework.proto'],
                   cwd=d, check=True)
    sys.path.insert(0, str(d))
    try:
        import framework_pb2 as mod
    finally:
        sys.path.pop(0)
    return mod


def test_lod_tensor_golden_bytes(tmp_path):
    """[2,3] f32 vs the byte layout SerializeToStream documents."""
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    path = str(tmp_path / 't')
    pf.save_tensors(path, [('t', arr)])
    got = open(path, 'rb').read()
    desc = (b'\x08\x05'        # field 1 varint: data_type FP32 (5)
            b'\x10\x02'        # field 2 varint: dim 2
            b'\x10\x03')       # field 2 varint: dim 3
    want = (struct.pack('<I', 0) +      # LoDTensor version
            struct.pack('<Q', 0) +      # lod levels
            struct.pack('<I', 0) +      # Tensor version
            struct.pack('<i', len(desc)) + desc +
            arr.tobytes())
    assert got == want
    (back, lod), = pf.load_tensors(path, count=1)
    np.testing.assert_array_equal(back, arr)
    assert lod == []


def test_tensor_desc_matches_real_protobuf(framework_pb2):
    """Our TensorDesc encoder must byte-match google.protobuf's."""
    d = framework_pb2.VarType.TensorDesc()
    d.data_type = framework_pb2.VarType.INT64
    d.dims.extend([128, 30522])
    assert pf._encode_tensor_desc('int64', [128, 30522]) == \
        d.SerializeToString()
    dtype, dims = pf._decode_tensor_desc(d.SerializeToString())
    assert dtype == 'int64' and dims == [128, 30522]


def test_roundtrip_dtypes_lod_and_combined(tmp_path):
    rng = np.random.RandomState(0)
    arrays = [
        ('f32', rng.randn(4, 5).astype('float32')),
        ('f64', rng.randn(3).astype('float64')),
        ('f16', rng.randn(2, 2).astype('float16')),
        ('i64', rng.randint(0, 100, (7,)).astype('int64')),
        ('i32', rng.randint(0, 100, (2, 3)).astype('int32')),
        ('u8', rng.randint(0, 255, (4,)).astype('uint8')),
        ('b', (rng.randn(3) > 0)),
    ]
    combined = str(tmp_path / 'all')
    pf.save_tensors(combined, arrays)
    back = pf.load_tensors(combined)
    assert len(back) == len(arrays)
    for (name, arr), (got, _lod) in zip(arrays, back):
        np.testing.assert_array_equal(got, arr)
        assert got.dtype == arr.dtype
    # LoD info round-trips
    with open(str(tmp_path / 'lod'), 'wb') as f:
        pf.write_lod_tensor(f, np.zeros((5, 2), 'float32'),
                            lod=[[0, 2, 5]])
    with open(str(tmp_path / 'lod'), 'rb') as f:
        arr, lod = pf.read_lod_tensor(f)
    assert arr.shape == (5, 2)
    np.testing.assert_array_equal(lod[0], [0, 2, 5])


def test_load_persistables_from_reference_format_dir(tmp_path):
    """A dir of per-var binary LoDTensor files (what reference
    save_persistables writes) populates the scope through the normal
    fluid.io.load_persistables call; the save side round-trips through
    save_format='paddle'."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        h = layers.fc(x, size=8, act='relu')
        out = layers.fc(h, size=2)
    rng = np.random.RandomState(1)
    xd = rng.randn(6, 4).astype('float32')

    ref_dir = str(tmp_path / 'refmodel')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        base, = exe.run(main, feed={'x': xd}, fetch_list=[out])
        # writer leg: reference layout, one file per var
        fluid.io.save_persistables(exe, ref_dir, main,
                                   save_format='paddle')
    for p in fluid.io._persistable_vars(main):
        assert os.path.exists(os.path.join(ref_dir, p.name))
        assert pf.looks_like_lod_tensor_file(
            os.path.join(ref_dir, p.name))

    # reader leg: a FRESH scope loads the reference-format dir
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fluid.io.load_persistables(exe, ref_dir, main)
        got, = exe.run(main, feed={'x': xd}, fetch_list=[out])
    np.testing.assert_allclose(got, base, rtol=1e-6)

    # combined (save_combine) layout round-trips too
    comb_dir = str(tmp_path / 'refcomb')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fluid.io.load_persistables(exe, ref_dir, main)
        fluid.io.save_persistables(exe, comb_dir, main,
                                   filename='__params__',
                                   save_format='paddle')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fluid.io.load_persistables(exe, comb_dir, main,
                                   filename='__params__')
        got2, = exe.run(main, feed={'x': xd}, fetch_list=[out])
    np.testing.assert_allclose(got2, base, rtol=1e-6)


def _build_reference_model_pb(framework_pb2, w, b):
    """Encode with REAL protobuf the inference ProgramDesc reference
    fluid would save for out = relu(x @ w + b): feed -> mul ->
    elementwise_add -> relu -> fetch."""
    fp = framework_pb2
    prog = fp.ProgramDesc()
    blk = prog.blocks.add()
    blk.idx, blk.parent_idx = 0, 0

    def add_var(name, dims, dtype, kind=None, persistable=False):
        v = blk.vars.add()
        v.name = name
        v.persistable = persistable
        if kind is not None:
            v.type.type = kind
            return v
        v.type.type = fp.VarType.LOD_TENSOR
        v.type.lod_tensor.tensor.data_type = dtype
        v.type.lod_tensor.tensor.dims.extend(dims)
        return v

    add_var('feed', [], 0, kind=fp.VarType.FEED_MINIBATCH)
    add_var('fetch', [], 0, kind=fp.VarType.FETCH_LIST)
    add_var('x', [-1, 4], fp.VarType.FP32)
    add_var('w', list(w.shape), fp.VarType.FP32, persistable=True)
    add_var('b', list(b.shape), fp.VarType.FP32, persistable=True)
    add_var('mul_out', [-1, 2], fp.VarType.FP32)
    add_var('add_out', [-1, 2], fp.VarType.FP32)
    add_var('relu_out', [-1, 2], fp.VarType.FP32)

    def add_op(type_, ins, outs, attrs=()):
        op = blk.ops.add()
        op.type = type_
        for slot, args in ins:
            var = op.inputs.add()
            var.parameter = slot
            var.arguments.extend(args)
        for slot, args in outs:
            var = op.outputs.add()
            var.parameter = slot
            var.arguments.extend(args)
        for name, atype, val in attrs:
            a = op.attrs.add()
            a.name = name
            a.type = atype
            if atype == fp.INT:
                a.i = val
            elif atype == fp.FLOAT:
                a.f = val
            elif atype == fp.STRING:
                a.s = val
            elif atype == fp.INTS:
                a.ints.extend(val)
            elif atype == fp.BOOLEAN:
                a.b = val
            elif atype == fp.LONG:
                a.l = val

    add_op('feed', [('X', ['feed'])], [('Out', ['x'])],
           [('col', fp.INT, 0)])
    add_op('mul', [('X', ['x']), ('Y', ['w'])],
           [('Out', ['mul_out'])],
           [('x_num_col_dims', fp.INT, 1), ('y_num_col_dims', fp.INT, 1)])
    add_op('elementwise_add', [('X', ['mul_out']), ('Y', ['b'])],
           [('Out', ['add_out'])], [('axis', fp.INT, 1)])
    add_op('relu', [('X', ['add_out'])], [('Out', ['relu_out'])])
    add_op('fetch', [('X', ['relu_out'])], [('Out', ['fetch'])],
           [('col', fp.INT, 0)])
    return prog.SerializeToString()


def test_load_inference_model_from_reference_binary(framework_pb2,
                                                    tmp_path):
    """End to end: binary __model__ (real protobuf bytes) + per-var
    param files -> load_inference_model -> executor serves it; numpy
    oracle checks the math."""
    rng = np.random.RandomState(5)
    w = rng.randn(4, 2).astype('float32')
    b = rng.randn(2).astype('float32')
    d = str(tmp_path / 'refinf')
    os.makedirs(d)
    with open(os.path.join(d, '__model__'), 'wb') as f:
        f.write(_build_reference_model_pb(framework_pb2, w, b))
    pf.save_tensors(os.path.join(d, 'w'), [('w', w)])
    pf.save_tensors(os.path.join(d, 'b'), [('b', b)])

    xd = rng.randn(8, 4).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        program, feed_names, fetch_vars = fluid.io.load_inference_model(
            d, exe)
        assert feed_names == ['x']
        assert [v.name for v in fetch_vars] == ['relu_out']
        got, = exe.run(program, feed={'x': xd}, fetch_list=fetch_vars)
    oracle = np.maximum(xd @ w + b, 0.0)
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-6)


def test_parse_program_desc_attr_types(framework_pb2):
    """Every AttrType the decoder claims must round-trip through real
    protobuf encoding."""
    fp = framework_pb2
    prog = fp.ProgramDesc()
    blk = prog.blocks.add()
    blk.idx, blk.parent_idx = 0, 0
    op = blk.ops.add()
    op.type = 'dropout'
    a = op.attrs.add(); a.name = 'i'; a.type = fp.INT; a.i = -3
    a = op.attrs.add(); a.name = 'f'; a.type = fp.FLOAT; a.f = 0.5
    a = op.attrs.add(); a.name = 's'; a.type = fp.STRING
    a.s = 'downgrade_in_infer'
    a = op.attrs.add(); a.name = 'ints'; a.type = fp.INTS
    a.ints.extend([1, -2, 3])
    a = op.attrs.add(); a.name = 'floats'; a.type = fp.FLOATS
    a.floats.extend([0.25, -1.5])
    a = op.attrs.add(); a.name = 'strings'; a.type = fp.STRINGS
    a.strings.extend(['a', 'bc'])
    a = op.attrs.add(); a.name = 'b'; a.type = fp.BOOLEAN; a.b = True
    a = op.attrs.add(); a.name = 'bools'; a.type = fp.BOOLEANS
    a.bools.extend([True, False])
    a = op.attrs.add(); a.name = 'blk'; a.type = fp.BLOCK
    a.block_idx = 1
    a = op.attrs.add(); a.name = 'l'; a.type = fp.LONG
    a.l = 1 << 40
    a = op.attrs.add(); a.name = 'longs'; a.type = fp.LONGS
    a.longs.extend([-(1 << 40), 7])

    program = pf.parse_program_desc(prog.SerializeToString())
    got = program.global_block().ops[0].attrs
    assert got['i'] == -3
    assert abs(got['f'] - 0.5) < 1e-7
    assert got['s'] == 'downgrade_in_infer'
    assert got['ints'] == [1, -2, 3]
    assert got['floats'] == [0.25, -1.5]
    assert got['strings'] == ['a', 'bc']
    assert got['b'] is True
    assert got['bools'] == [True, False]
    assert got['blk'] == 1
    assert got['l'] == 1 << 40
    assert got['longs'] == [-(1 << 40), 7]
