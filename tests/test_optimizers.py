"""Optimizer op math vs numpy references.

Mirrors reference tests test_sgd_op.py, test_momentum_op.py,
test_adam_op.py (python/paddle/fluid/tests/unittests/), plus whole-loop
convergence checks through the Python optimizer classes.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import registry

rng = np.random.RandomState(11)


def run_lowering(op, ins, attrs=None):
    return registry.get(op).fn(registry.LowerCtx(0),
                               {k: [v] for k, v in ins.items()},
                               attrs or {})


def test_sgd_op():
    p = rng.randn(4, 3).astype('float32')
    g = rng.randn(4, 3).astype('float32')
    lr = np.array([0.1], 'float32')
    out = run_lowering('sgd', {'Param': p, 'Grad': g,
                               'LearningRate': lr})
    np.testing.assert_allclose(out['ParamOut'][0], p - 0.1 * g,
                               rtol=1e-6)


def test_momentum_op():
    p = rng.randn(4).astype('float32')
    g = rng.randn(4).astype('float32')
    v = rng.randn(4).astype('float32')
    lr = np.array([0.01], 'float32')
    out = run_lowering('momentum',
                       {'Param': p, 'Grad': g, 'Velocity': v,
                        'LearningRate': lr}, {'mu': 0.9})
    v2 = 0.9 * v + g
    np.testing.assert_allclose(out['VelocityOut'][0], v2, rtol=1e-6)
    np.testing.assert_allclose(out['ParamOut'][0], p - 0.01 * v2,
                               rtol=1e-6)


def test_adam_op():
    p = rng.randn(6).astype('float32')
    g = rng.randn(6).astype('float32')
    m1 = rng.randn(6).astype('float32') * 0.1
    m2 = np.abs(rng.randn(6)).astype('float32') * 0.1
    b1p = np.array([0.9], 'float32')
    b2p = np.array([0.999], 'float32')
    lr = np.array([0.001], 'float32')
    out = run_lowering('adam',
                       {'Param': p, 'Grad': g, 'Moment1': m1,
                        'Moment2': m2, 'Beta1Pow': b1p, 'Beta2Pow': b2p,
                        'LearningRate': lr},
                       {'beta1': 0.9, 'beta2': 0.999, 'epsilon': 1e-8})
    m1n = 0.9 * m1 + 0.1 * g
    m2n = 0.999 * m2 + 0.001 * g * g
    lr_t = 0.001 * np.sqrt(1 - b2p * 0.999) / (1 - b1p * 0.9)
    pn = p - lr_t * m1n / (np.sqrt(m2n) + 1e-8)
    np.testing.assert_allclose(out['ParamOut'][0], pn, rtol=1e-5)
    np.testing.assert_allclose(out['Beta1PowOut'][0], b1p * 0.9,
                               rtol=1e-6)


def _train_quadratic(optimizer, steps=100):
    """Minimize ||Wx - y||^2; returns final loss."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[2], dtype='float32')
        pred = fluid.layers.fc(x, 2, bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        optimizer.minimize(loss)
    scope = fluid.Scope()
    r = np.random.RandomState(0)
    W = r.randn(4, 2).astype('float32')
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        final = None
        for _ in range(steps):
            xs = r.randn(16, 4).astype('float32')
            ys = xs @ W
            final, = exe.run(main, feed={'x': xs, 'y': ys},
                             fetch_list=[loss])
    return float(final)


@pytest.mark.parametrize('opt_fn,steps,tol', [
    (lambda: fluid.optimizer.SGD(0.1), 100, 0.05),
    (lambda: fluid.optimizer.Momentum(0.05, momentum=0.9), 100, 0.05),
    (lambda: fluid.optimizer.Momentum(0.05, momentum=0.9,
                                      use_nesterov=True), 100, 0.05),
    (lambda: fluid.optimizer.Adam(0.05), 100, 0.05),
    (lambda: fluid.optimizer.AdamW(0.05, weight_decay=0.001), 100, 0.05),
    (lambda: fluid.optimizer.Adagrad(0.3), 100, 0.05),
    (lambda: fluid.optimizer.RMSProp(0.05), 100, 0.05),
    (lambda: fluid.optimizer.Lamb(0.05), 100, 0.05),
    # adamax / adadelta ramp up slowly by construction
    (lambda: fluid.optimizer.Adamax(0.1), 400, 0.1),
    (lambda: fluid.optimizer.Adadelta(1.0), 900, 0.5),
    (lambda: fluid.optimizer.Ftrl(0.5), 100, 0.05),
])
def test_optimizer_converges(opt_fn, steps, tol):
    final = _train_quadratic(opt_fn(), steps=steps)
    assert final < tol, final


def test_weight_decay_regularizer():
    opt = fluid.optimizer.SGD(
        0.1, regularization=fluid.regularizer.L2Decay(0.01))
    final = _train_quadratic(opt)
    assert final < 0.1


def test_global_norm_clip():
    opt = fluid.optimizer.SGD(
        0.1, grad_clip=fluid.clip.GradientClipByGlobalNorm(0.5))
    final = _train_quadratic(opt, steps=200)
    assert final < 0.1, final


def test_adam_matches_hand_rollout_multi_param():
    """Hand-rollout parity for the shared-beta-pow Adam (round 4): two
    params, three steps, exact bias-corrected trajectory; the shared
    pow advances once per STEP (not once per param)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    b1, b2, lr = 0.8, 0.95, 0.1
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, size=3, bias_attr=False,
                      param_attr=fluid.ParamAttr(name='w_a'))
        p = layers.fc(h, size=1, bias_attr=False,
                      param_attr=fluid.ParamAttr(name='w_b'))
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        fluid.optimizer.Adam(lr, beta1=b1, beta2=b2).minimize(loss)
    xd = np.asarray([[1., 2., -1., 0.5], [0.5, -1., 2., 1.]],
                    dtype='float32')
    yd = np.zeros((2, 1), 'float32')
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        wa = np.asarray(fluid.core.as_array(sc.find_var('w_a'))).copy()
        wb = np.asarray(fluid.core.as_array(sc.find_var('w_b'))).copy()
        ma = np.zeros_like(wa); va = np.zeros_like(wa)
        mb = np.zeros_like(wb); vb = np.zeros_like(wb)
        for t in range(1, 4):
            exe.run(main, feed={'x': xd, 'y': yd}, fetch_list=[loss])
            hidden = xd @ wa
            pred = hidden @ wb
            dpred = (2.0 / xd.shape[0]) * (pred - yd)
            gb = hidden.T @ dpred
            ga = xd.T @ (dpred @ wb.T)
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            for (w, m, v, g) in ((wa, ma, va, ga), (wb, mb, vb, gb)):
                m *= b1; m += (1 - b1) * g
                v *= b2; v += (1 - b2) * g * g
                w -= lr_t * m / (np.sqrt(v) + 1e-8)
        got_a = np.asarray(fluid.core.as_array(sc.find_var('w_a')))
        got_b = np.asarray(fluid.core.as_array(sc.find_var('w_b')))
        # the SHARED pow advanced exactly beta^3 (once per step)
        pows = [float(np.asarray(fluid.core.as_array(v)).ravel()[0])
                for n, v in sc._vars.items() if 'beta1_pow_acc' in n]
    assert len(pows) == 1, pows  # ONE shared accumulator
    assert abs(pows[0] - b1 ** 3) < 1e-6, pows
    np.testing.assert_allclose(got_a, wa, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_b, wb, rtol=1e-4, atol=1e-5)


def test_beta_pow_advances_once_per_step_adam_and_lamb():
    """Regression for the shared-pow refactor: after ONE step with N
    params, beta1_pow must equal beta1 exactly — for Adam (one shared
    pow) AND Lamb (per-param pows advanced by its own op)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    for opt_cls, kw in ((fluid.optimizer.Adam, {}),
                        (fluid.optimizer.Lamb,
                         {'lamb_weight_decay': 0.0})):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4], dtype='float32')
            h = layers.fc(x, size=3)           # weight + bias
            p = layers.fc(h, size=1)           # weight + bias
            loss = layers.reduce_mean(p)
            opt_cls(0.01, beta1=0.9, **kw).minimize(loss)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.run(main, feed={'x': np.ones((2, 4), 'float32')},
                    fetch_list=[loss])
            pows = [float(np.asarray(fluid.core.as_array(v)).ravel()[0])
                    for n, v in sc._vars.items()
                    if 'beta1_pow_acc' in n]
        assert pows, opt_cls
        for pw in pows:
            assert abs(pw - 0.9) < 1e-6, (opt_cls.__name__, pows)
