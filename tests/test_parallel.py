"""Ring attention / Ulysses / pipeline correctness on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.parallel.ring_attention import (ring_attention,
                                                reference_attention)
from paddle_tpu.parallel.ulysses import ulysses_attention
from paddle_tpu.parallel.pipeline import pipeline_apply


def _qkv(rng, b=2, t=32, h=8, d=16):
    q = rng.randn(b, t, h, d).astype('float32')
    k = rng.randn(b, t, h, d).astype('float32')
    v = rng.randn(b, t, h, d).astype('float32')
    return q, k, v


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    mesh = pmesh.create_mesh(dp=1, sp=8)
    out = ring_attention(q, k, v, mesh, axis='sp', causal=causal)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_match():
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng, t=16, h=4, d=8)
    mesh = pmesh.create_mesh(dp=1, sp=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, axis='sp',
                                      causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize('causal', [False, True])
def test_ulysses_matches_dense(causal):
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng)
    mesh = pmesh.create_mesh(dp=1, sp=8)
    out = ulysses_attention(q, k, v, mesh, axis='sp', causal=causal)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pipeline_matches_sequential():
    rng = np.random.RandomState(3)
    n_stages = 8
    dim = 16
    ws = rng.randn(n_stages, dim, dim).astype('float32') * 0.3
    bs = rng.randn(n_stages, dim).astype('float32') * 0.1
    x = rng.randn(8, dim).astype('float32')
    mesh = pmesh.create_mesh(dp=1, pp=8)

    def stage_fn(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    out = pipeline_apply(stage_fn, (ws, bs), x, mesh, axis='pp',
                         n_microbatches=4)
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5,
                               rtol=1e-5)


def test_pipeline_differentiable():
    rng = np.random.RandomState(4)
    n_stages, dim = 8, 8
    ws = rng.randn(n_stages, dim, dim).astype('float32') * 0.3
    bs = np.zeros((n_stages, dim), 'float32')
    x = rng.randn(4, dim).astype('float32')
    mesh = pmesh.create_mesh(dp=1, pp=8)

    def stage_fn(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    def loss(ws, bs):
        return jnp.sum(pipeline_apply(stage_fn, (ws, bs), x, mesh,
                                      axis='pp', n_microbatches=2) ** 2)

    def ref_loss(ws, bs):
        h = jnp.asarray(x)
        for i in range(n_stages):
            h = jnp.tanh(h @ ws[i] + bs[i])
        return jnp.sum(h ** 2)

    g = jax.grad(loss)(jnp.asarray(ws), jnp.asarray(bs))
    g_ref = jax.grad(ref_loss)(jnp.asarray(ws), jnp.asarray(bs))
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------- MoE / ep

def test_moe_matches_dense():
    from paddle_tpu.parallel.moe import moe_ffn, reference_moe_ffn
    rng = np.random.RandomState(3)
    ep, e_loc, b, t, d, h = 4, 2, 8, 4, 16, 32
    e = ep * e_loc
    x = rng.randn(b, t, d).astype('float32')
    wg = rng.randn(d, e).astype('float32') * 0.1
    w1 = rng.randn(e, d, h).astype('float32') * 0.1
    w2 = rng.randn(e, h, d).astype('float32') * 0.1
    mesh = pmesh.create_mesh(dp=2, ep=ep)
    out, aux = moe_ffn(x, wg, w1, w2, mesh, axis='ep')
    # per-token-shard reference with identical per-shard capacity
    b_loc = b // ep
    refs = [reference_moe_ffn(x[i * b_loc:(i + 1) * b_loc], wg, w1, w2)[0]
            for i in range(ep)]
    ref = np.concatenate([np.asarray(r) for r in refs], axis=0)
    assert np.abs(ref).sum() > 0  # guard against trivially-zero match
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux))


def test_moe_differentiable():
    from paddle_tpu.parallel.moe import moe_ffn
    rng = np.random.RandomState(4)
    ep, e_loc, b, t, d, h = 4, 1, 4, 4, 8, 16
    e = ep * e_loc
    x = jnp.asarray(rng.randn(b, t, d).astype('float32'))
    wg = jnp.asarray(rng.randn(d, e).astype('float32') * 0.1)
    w1 = jnp.asarray(rng.randn(e, d, h).astype('float32') * 0.1)
    w2 = jnp.asarray(rng.randn(e, h, d).astype('float32') * 0.1)
    mesh = pmesh.create_mesh(dp=2, ep=ep)

    def loss(w1, w2, wg):
        out, aux = moe_ffn(x, wg, w1, w2, mesh, axis='ep')
        return jnp.mean(out ** 2) + 0.01 * aux

    g1, g2, gg = jax.grad(loss, argnums=(0, 1, 2))(w1, w2, wg)
    for g in (g1, g2, gg):
        assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g1).sum()) > 0
    assert float(jnp.abs(gg).sum()) > 0


def test_3d_pipeline_tp_dp_composition():
    """The classic 3D composition — GPipe over 'pp', Megatron TP inside
    each stage over 'mp', batch over 'dp' — trains and matches the
    single-host numpy oracle (asserted inside _dryrun_3d)."""
    import sys
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as graft
    graft._dryrun_3d(8)
