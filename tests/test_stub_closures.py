"""Round-2 stub closures: lrn wrapper, adaptive_pool2d arbitrary grids,
nce custom_dist, multi-target calc_gradient."""

import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _run(main, startup, feed, fetch):
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_lrn_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4, 6, 6], dtype='float32')
        y = layers.lrn(x, n=3, k=1.0, alpha=0.1, beta=0.5)
    xv = np.random.RandomState(0).randn(2, 4, 6, 6).astype('float32')
    out, = _run(main, startup, {'x': xv}, [y])
    # reference formula on channel 1: k + alpha * sum over [0,1,2]
    sq = xv ** 2
    acc = sq[:, 0] + sq[:, 1] + sq[:, 2]
    want = xv[:, 1] / np.sqrt(1.0 + 0.1 * acc)
    np.testing.assert_allclose(np.asarray(out)[:, 1], want, rtol=1e-5)


def test_adaptive_pool2d_arbitrary_grid():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[2, 7, 10], dtype='float32')
        ya = layers.adaptive_pool2d(x, [3, 4], pool_type='avg')
        ym = layers.adaptive_pool2d(x, [3, 4], pool_type='max')
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 2, 7, 10).astype('float32')
    out_a, out_m = _run(main, startup, {'x': xv}, [ya, ym])
    assert np.asarray(out_a).shape == (2, 2, 3, 4)

    def windows(h, oh):
        return [((i * h) // oh, -(-((i + 1) * h) // oh))
                for i in range(oh)]

    for i, (hs, he) in enumerate(windows(7, 3)):
        for j, (ws, we) in enumerate(windows(10, 4)):
            win = xv[:, :, hs:he, ws:we]
            np.testing.assert_allclose(np.asarray(out_a)[:, :, i, j],
                                       win.mean((2, 3)), rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(out_m)[:, :, i, j],
                                       win.max((2, 3)), rtol=1e-5,
                                       atol=1e-6)


def test_nce_custom_dist_trains():
    vocab = 50
    dist = np.arange(1, vocab + 1, dtype='float64')
    dist = (dist / dist.sum()).tolist()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[16], dtype='float32')
        y = layers.data('y', shape=[1], dtype='int64')
        h = layers.fc(x, 16)
        cost = layers.nce(h, y, vocab, num_neg_samples=5,
                          sampler='custom_dist', custom_dist=dist)
        loss = layers.mean(cost)
        fluid.optimizer.SGD(0.05).minimize(loss)
    rng = np.random.RandomState(2)
    xv = rng.randn(32, 16).astype('float32')
    yv = rng.randint(0, vocab, (32, 1)).astype('int64')
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(20):
            l, = exe.run(main, feed={'x': xv, 'y': yv},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert losses[-1] < losses[0], losses


def test_calc_gradient_multi_target():
    # z1 = 2x, z2 = x^2; d(sum(z1) + sum(w2*z2))/dx = 2 + 2*w2*x
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        x.stop_gradient = False
        z1 = layers.scale(x, scale=2.0)
        z2 = layers.square(x)
        w2 = layers.fill_constant([1, 4], 'float32', 3.0)
        g, = fluid.backward.calc_gradient([z1, z2], [x],
                                          target_gradients=[None, w2])
    assert g is not None
    xv = np.array([[1.0, 2.0, -1.0, 0.5]], np.float32)
    gv, = _run(main, startup, {'x': xv}, [g.name])
    np.testing.assert_allclose(np.asarray(gv), 2.0 + 6.0 * xv,
                               rtol=1e-5)


def test_executor_public_compile_api():
    """Executor.compile: program -> one pure jittable CompiledStep."""
    import jax
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        step = exe.compile(main, feed_names=('x',),
                           fetch_names=(h.name,))
        scope = fluid.core.global_scope()
        state = {n: fluid.core.as_array(scope.find_var(n))
                 for n in step.state_names}
        data = {n: fluid.core.as_array(scope.find_var(n))
                for n in step.input_names if n != 'x'}
        xv = np.random.RandomState(0).randn(2, 8).astype('float32')
        data['x'] = xv
        out = jax.jit(step.fn)(0, state, data)
        assert np.asarray(out[h.name]).shape == (2, 4)
        # parity with exe.run
        ref, = exe.run(main, feed={'x': xv}, fetch_list=[h])
        np.testing.assert_allclose(np.asarray(out[h.name]), ref,
                                   rtol=1e-6)

    # host ops split the program -> the pure-step contract refuses
    # with guidance, and allow_host=True compiles the PIPELINE instead
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x2 = layers.data('x', shape=[4], dtype='float32')
        y2 = layers.scale(x2, scale=2.0)
        layers.Print(y2)
        z2 = layers.scale(y2, scale=3.0)
    exe2 = fluid.Executor(fluid.XLAPlace(0))
    with pytest.raises(ValueError, match='single-segment'):
        exe2.compile(main2, feed_names=('x',), fetch_names=(z2.name,))
    pipe = exe2.compile(main2, feed_names=('x',),
                        fetch_names=(z2.name,), allow_host=True)
    assert pipe.host_op_types == ['print']
    xv2 = np.random.RandomState(1).randn(2, 4).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        got, = pipe({'x': xv2})
    np.testing.assert_allclose(np.asarray(got), xv2 * 6.0, rtol=1e-6)


def test_executor_compile_validates_names():
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 4)
    exe = fluid.Executor(fluid.XLAPlace(0))
    # Variable objects accepted in both slots
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        exe2.run(startup)
        step = exe2.compile(main, feed_names=(x,), fetch_names=(h,))
        assert 'x' in step.input_names
    with pytest.raises(ValueError, match='not produced'):
        exe.compile(main, feed_names=('x',), fetch_names=('x',))
    with pytest.raises(ValueError, match='not read'):
        exe.compile(main, feed_names=('tpyo',),
                    fetch_names=(h.name,))


def test_diag_layer():
    """Round-3 stub closure: layers.diag (reference diag_op.cc)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        d = layers.data('d', shape=[4], dtype='float32',
                        append_batch_size=False)
        m = layers.diag(d)
    dv = np.array([1., 2., 3., 4.], 'float32')
    out, = _run(main, startup, {'d': dv}, [m])
    np.testing.assert_allclose(np.asarray(out), np.diag(dv))


def test_where_index_capacity_padded():
    """Round-3: where_index with a capacity attr returns [K, rank]
    indices padded with -1 (the TPU static-shape variant)."""
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[3, 4], dtype='float32',
                        append_batch_size=False)
        cond = layers.cast(x, 'bool')
        block = main.current_block()
        out = block.create_var(name='wi_out', shape=(6, 2),
                               dtype='int64')
        block.append_op('where_index', inputs={'Condition': cond},
                        outputs={'Out': out},
                        attrs={'capacity': 6})
    xv = np.zeros((3, 4), 'float32')
    xv[0, 1] = xv[2, 3] = 1.0
    got, = _run(main, startup, {'x': xv}, [out])
    got = np.asarray(got)
    assert got.shape == (6, 2)
    real = got[got[:, 0] >= 0]
    np.testing.assert_array_equal(real, [[0, 1], [2, 3]])
    assert (got[2:] == -1).all()

    # without capacity: loud guidance (at shape-inference time), not a
    # wrong shape
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x2 = layers.data('x', shape=[3], dtype='float32',
                         append_batch_size=False)
        c2 = layers.cast(x2, 'bool')
        b2 = main2.current_block()
        o2 = b2.create_var(name='wi2', shape=(3, 1), dtype='int64')
        with pytest.raises(Exception, match='capacity'):
            b2.append_op('where_index', inputs={'Condition': c2},
                         outputs={'Out': o2}, attrs={})


def test_dice_loss_formula():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = layers.data('p', shape=[4, 3], dtype='float32',
                        append_batch_size=False)
        lbl = layers.data('l', shape=[4, 1], dtype='int64',
                          append_batch_size=False)
        loss = layers.dice_loss(p, lbl)
    rng = np.random.RandomState(1)
    pv = rng.rand(4, 3).astype('float32')
    lv = rng.randint(0, 3, (4, 1)).astype('int64')
    got, = _run(main, startup, {'p': pv, 'l': lv}, [loss])
    onehot = np.eye(3, dtype='float32')[lv[:, 0]]
    inter = (pv * onehot).sum(1)
    union = pv.sum(1) + onehot.sum(1)
    want = (1 - 2 * inter / (union + 1e-5)).mean()
    np.testing.assert_allclose(float(np.asarray(got)), want, rtol=1e-5)
