"""Async double-buffered input pipeline (round-4 VERDICT item 4).

Reference: operators/reader/buffered_reader.cc (double-buffer batches
to the device) + python/paddle/fluid/reader.py:298 (GeneratorLoader
over LoDTensorBlockingQueue).  The rebuild's GeneratorLoader now runs
the user generator on a background thread into a bounded queue
(capacity) and stages batches onto the device as they are enqueued
(use_double_buffer) — these tests pin the semantics the parameters
promise."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.reader import _AsyncBatchIterator


def _feed_vars():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
    return main, startup, [x, y]


def test_loader_preserves_order_and_values():
    _, _, feeds = _feed_vars()
    loader = fluid.io.DataLoader.from_generator(
        feed_list=feeds, capacity=4, use_double_buffer=True)

    def gen():
        for i in range(10):
            yield {'x': np.full((2, 4), i, 'float32'),
                   'y': np.full((2, 1), i, 'float32')}
    loader.set_batch_generator(gen)
    seen = [float(np.asarray(b['x']).ravel()[0]) for b in loader]
    assert seen == [float(i) for i in range(10)]
    # a second iteration re-runs the generator from scratch
    seen2 = [float(np.asarray(b['x']).ravel()[0]) for b in loader]
    assert seen2 == seen


def test_double_buffer_stages_batches_on_device():
    import jax
    _, _, feeds = _feed_vars()
    loader = fluid.io.DataLoader.from_generator(
        feed_list=feeds, capacity=2, use_double_buffer=True)
    loader.set_batch_generator(
        lambda: iter([{'x': np.zeros((2, 4), 'float32'),
                       'y': np.zeros((2, 1), 'float32')}]))
    batch = next(iter(loader))
    assert isinstance(batch['x'], jax.Array)
    # no double buffer -> host arrays pass through untouched
    loader2 = fluid.io.DataLoader.from_generator(
        feed_list=feeds, capacity=2, use_double_buffer=False)
    loader2.set_batch_generator(
        lambda: iter([{'x': np.zeros((2, 4), 'float32'),
                       'y': np.zeros((2, 1), 'float32')}]))
    batch2 = next(iter(loader2))
    assert isinstance(batch2['x'], np.ndarray)


def test_capacity_bounds_producer_runahead():
    """With a slow consumer the producer must park at `capacity`
    batches ahead, not drain the generator eagerly."""
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield {'x': np.full((1,), i, 'float32')}

    it = _AsyncBatchIterator(gen, capacity=3, device=None)
    next(it)
    time.sleep(0.3)  # producer free-runs until the queue fills
    # bounded by capacity(3) + stage window(2) + in-hand(1) + consumed
    assert len(produced) <= 8, produced
    it.close()


def test_exhaustion_is_sticky():
    """next() after StopIteration must raise StopIteration again, not
    park forever on an empty queue."""
    it = _AsyncBatchIterator(
        lambda: iter([{'x': np.zeros(1, 'float32')}]), capacity=2,
        device=None)
    assert next(it) is not None
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(it)


def test_new_iteration_closes_abandoned_one():
    _, _, feeds = _feed_vars()
    loader = fluid.io.DataLoader.from_generator(
        feed_list=feeds, capacity=2, use_double_buffer=False)

    def gen():
        for i in range(100):
            yield {'x': np.full((1,), i, 'float32')}
    loader.set_batch_generator(gen)
    it1 = iter(loader)
    next(it1)
    it2 = iter(loader)  # must close it1's pipeline
    assert it1._stop.is_set()
    assert float(np.asarray(next(it2)['x'])[0]) == 0.0
    loader._live_iter.close()


def test_producer_exception_reraises_at_consumer():
    def gen():
        yield {'x': np.zeros(1, 'float32')}
        raise RuntimeError('boom in the reader thread')

    it = _AsyncBatchIterator(gen, capacity=2, device=None)
    next(it)
    with pytest.raises(RuntimeError, match='boom in the reader'):
        next(it)


def test_early_break_stops_producer_without_deadlock():
    stopped = threading.Event()

    def gen():
        try:
            for i in range(10 ** 6):
                yield {'x': np.full((1,), i, 'float32')}
        finally:
            stopped.set()

    it = _AsyncBatchIterator(gen, capacity=2, device=None)
    for k, _ in enumerate(it):
        if k == 3:
            break
    it.close()
    # producer notices the stop within its put timeout
    assert stopped.wait(2.0) or it._thread.join(2.0) is None
    assert not it._thread.is_alive()


def test_training_through_async_loader_learns():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        p = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype('float32')

    def gen():
        for _ in range(40):
            xb = rng.randn(16, 4).astype('float32')
            yield {'x': xb, 'y': xb @ w}

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[x, y], capacity=8, use_double_buffer=True)
    loader.set_batch_generator(gen)
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for batch in loader:
            l, = exe.run(main, feed=batch, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert len(losses) == 40
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_train_from_dataset_thread_prefetch(tmp_path):
    """thread=N now drives the N-deep device prefetch (was a silent
    no-op — round-3 VERDICT weak #5); result must match the serial
    path's step count and still learn."""
    from tests.test_dataset_trainer import _write_ctr_file
    rng = np.random.RandomState(1)
    path = str(tmp_path / 'train.txt')
    _write_ctr_file(path, 640, rng)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = layers.data('dense', shape=[4], dtype='float32')
        ids = layers.data('ids', shape=[3], dtype='int64')
        label = layers.data('label', shape=[1], dtype='int64')
        emb = layers.embedding(ids, size=[50, 8])
        emb = layers.reshape(emb, [0, 24])
        h = layers.fc(layers.concat([dense, emb], axis=1), 32,
                      act='relu')
        logit = layers.fc(h, 1)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(
                logit, layers.cast(label, 'float32')))
        fluid.optimizer.Adam(1e-3).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    dataset.set_batch_size(64)
    dataset.set_thread(2)
    dataset.set_filelist([path])
    dataset.set_use_var([dense, ids, label])
    dataset.load_into_memory()

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        steps = exe.train_from_dataset(main, dataset, thread=4,
                                       fetch_list=[loss],
                                       print_period=5)
    assert steps == 10, steps


def test_trainer_desc_and_factory_surface():
    """TrainerDesc/DeviceWorker config plane (reference
    trainer_desc.py:21, device_worker.py:19, trainer_factory.py:23):
    the knobs must be real state, the factory must map fleet opt_info
    to trainer+worker classes, and junk must raise."""
    from paddle_tpu.fluid.trainer_desc import (
        TrainerDesc, MultiTrainer, DistMultiTrainer, PipelineTrainer,
        TrainerFactory)
    from paddle_tpu.fluid.device_worker import (
        DeviceWorker, Hogwild, DownpourSGD, Section,
        DeviceWorkerFactory)

    t = TrainerFactory()._create_trainer(None)
    assert isinstance(t, MultiTrainer)
    assert isinstance(t._device_worker, Hogwild)
    t._gen_trainer_desc()
    assert t._desc()['device_worker_name'] == 'HogwildWorker'

    t2 = TrainerFactory()._create_trainer(
        {'trainer': 'DistMultiTrainer', 'device_worker': 'DownpourSGD',
         'fleet_desc': {'tables': 1}, 'thread_num': 7})
    assert isinstance(t2, DistMultiTrainer)
    assert isinstance(t2._device_worker, DownpourSGD)
    t2._gen_trainer_desc()
    d = t2._desc()
    assert d['thread_num'] == 7
    assert d['device_worker_name'] == 'DownpourWorker'
    assert d['fleet_desc'] == {'tables': 1}

    t3 = TrainerFactory()._create_trainer(
        {'trainer': 'PipelineTrainer', 'device_worker': 'Section'})
    assert isinstance(t3, PipelineTrainer)
    assert isinstance(t3._device_worker, Section)

    class V:
        name = 'v'
    td = TrainerDesc()
    td._set_fetch_var_and_info([V()], ['loss: '], 5)
    td._set_debug(True)
    fc = td._desc()['fetch_config']
    assert fc['fetch_var_names'] == ['v'] and fc['print_period'] == 5
    assert td._desc()['debug'] is True

    with pytest.raises(ValueError):
        TrainerFactory()._create_trainer({'trainer': 'NopeTrainer'})
    with pytest.raises(ValueError):
        DeviceWorkerFactory()._create_device_worker('nope')
    with pytest.raises(NotImplementedError):
        DeviceWorker()._gen_worker_desc({})


def test_stage_exclude_keeps_host_fields_on_host():
    import jax
    _, _, feeds = _feed_vars()
    loader = fluid.io.DataLoader.from_generator(
        feed_list=feeds, capacity=2, use_double_buffer=True,
        stage_exclude=['y'])
    loader.set_batch_generator(
        lambda: iter([{'x': np.zeros((2, 4), 'float32'),
                       'y': np.zeros((2, 1), 'float32')}]))
    batch = next(iter(loader))
    assert isinstance(batch['x'], jax.Array)
    assert isinstance(batch['y'], np.ndarray)
