"""Pallas kernel library: parity vs dense references (interpret mode
on CPU), dispatch observability, the comms_plan fused-quant pricing,
and the trace-level rewrites that route existing Programs through the
fused ops with no user change."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, progcheck
from paddle_tpu.fluid.flags import _DEFAULTS, set_flags
from paddle_tpu.ops import registry
from paddle_tpu.ops.pallas import common, embedding, fused_optimizer


_PALLAS_FLAGS = [k for k in _DEFAULTS if k.startswith('FLAGS_pallas_')]


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    set_flags({k: _DEFAULTS[k] for k in _PALLAS_FLAGS})
    set_flags({'FLAGS_comms_quantize': _DEFAULTS['FLAGS_comms_quantize'],
               'FLAGS_comms_hbm_budget_bytes':
               _DEFAULTS['FLAGS_comms_hbm_budget_bytes']})


def _force(on=True):
    set_flags({'FLAGS_pallas_force': on})


# ------------------------------------------- fused optimizer updates

def _opt_ins(n_tensors, seed=0, zero_grad_idx=None):
    rng = np.random.RandomState(seed)
    shapes = [(33, 47), (128,), (5, 8, 13), (257,)][:n_tensors]
    ins = {k: [] for k in ('Param', 'Grad', 'Moment1', 'Moment2',
                           'LearningRate', 'Beta1Pow', 'Beta2Pow')}
    for i, s in enumerate(shapes):
        g = rng.randn(*s).astype('float32')
        if zero_grad_idx == i:
            g[:] = 0.0
        ins['Param'].append(jnp.asarray(rng.randn(*s).astype('float32')))
        ins['Grad'].append(jnp.asarray(g))
        ins['Moment1'].append(jnp.asarray(
            (0.0 if zero_grad_idx == i else 1.0) *
            rng.randn(*s).astype('float32')))
        ins['Moment2'].append(jnp.asarray(
            np.abs(rng.randn(*s)).astype('float32') *
            (0.0 if zero_grad_idx == i else 1.0)))
        ins['LearningRate'].append(jnp.asarray(
            np.float32(0.001 * (i + 1))))
        ins['Beta1Pow'].append(jnp.asarray(np.float32(0.9 ** (i + 1))))
        ins['Beta2Pow'].append(jnp.asarray(np.float32(0.999 ** (i + 1))))
    return ins


@pytest.mark.parametrize('kind', ['adam', 'adamw', 'lamb'])
def test_fused_optimizer_parity(kind):
    """Forced-fused (interpret) vs the per-tensor dense lowerings over
    a 4-tensor run with distinct shapes / lrs / beta powers.  The
    compiled kernel body may contract mul+add into FMAs the dense
    op-by-op chain rounds individually — parity is 1-2 ulp."""
    ins = _opt_ins(4, seed=3)
    attrs = {'beta1': 0.9, 'beta2': 0.999}
    _force(True)
    fused = fused_optimizer.apply(kind, registry.LowerCtx(0), ins, attrs)
    _force(False)
    dense = fused_optimizer._dense(kind, registry.LowerCtx(0), ins, attrs)
    for slot in ('ParamOut', 'Moment1Out', 'Moment2Out',
                 'Beta1PowOut', 'Beta2PowOut'):
        assert len(fused[slot]) == len(dense[slot]) == 4
        for a, b in zip(fused[slot], dense[slot]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-6, atol=3e-7,
                err_msg='%s %s' % (kind, slot))


def test_fused_optimizer_dense_dispatch_bitwise():
    """Off-TPU without force the dispatcher picks the dense fallback,
    which IS the per-tensor lowerings — bitwise, not just close."""
    ins = _opt_ins(3, seed=5)
    out = fused_optimizer.apply('adam', registry.LowerCtx(0), ins, {})
    ref = fused_optimizer._dense('adam', registry.LowerCtx(0), ins, {})
    for slot in ref:
        for a, b in zip(out[slot], ref[slot]):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    assert common._LAST['fused_optimizer']['reason'] == 'off_tpu'


def test_lamb_trust_ratio_edge_cases():
    """The in-kernel per-tensor trust ratio: a tensor whose r-norm is
    zero (zero grad/moments/weight-decay) must take the trust=1 branch
    while its run-mates get ||p||/||r|| — per-tensor, not per-run."""
    ins = _opt_ins(3, seed=7, zero_grad_idx=1)
    attrs = {'weight_decay': 0.0}
    _force(True)
    fused = fused_optimizer.apply('lamb', registry.LowerCtx(0), ins,
                                  attrs)
    _force(False)
    dense = fused_optimizer._dense('lamb', registry.LowerCtx(0), ins,
                                   attrs)
    for a, b in zip(fused['ParamOut'], dense['ParamOut']):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=3e-7)
    # the zero-r tensor is untouched (trust branch, zero update)
    assert np.array_equal(np.asarray(fused['ParamOut'][1]),
                          np.asarray(ins['Param'][1]))


def test_fused_optimizer_below_floor_reason():
    set_flags({'FLAGS_pallas_opt_min_tensors': 8})
    _force(True)
    fused_optimizer.apply('adam', registry.LowerCtx(0), _opt_ins(2), {})
    assert common._LAST['fused_optimizer'] == {
        'path': 'dense', 'reason': 'below_floor', 'interpret': False}


def test_executor_groups_optimizer_run():
    """An Adam program with several params runs the fused op at the
    executor level and matches the ungrouped lowering bitwise (dense
    dispatch) / at tolerance (forced fused)."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[8], dtype='float32')
            h = layers.fc(x, 16, act='relu')
            h = layers.fc(h, 16, act='relu')
            pred = layers.fc(h, 4)
            loss = layers.reduce_mean(pred)
            fluid.optimizer.Adam(1e-2).minimize(loss)
        return main, startup, loss

    feed = {'x': np.random.RandomState(0).randn(4, 8).astype('float32')}

    def run(opt_fuse, force):
        set_flags({'FLAGS_pallas_opt_fuse': opt_fuse,
                   'FLAGS_pallas_force': force})
        main, startup, loss = build()
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            out = [exe.run(main, feed=feed, fetch_list=[loss])[0]
                   for _ in range(3)]
        return np.asarray(out[-1])

    base = run(False, False)
    grouped = run(True, False)
    forced = run(True, True)
    assert np.array_equal(base, grouped)
    np.testing.assert_allclose(forced, base, rtol=2e-5, atol=1e-6)
    assert monitor.counter_value(
        'pallas/fused_optimizer/dispatch_fused') > 0
    assert monitor.counter_value(
        'pallas/fused_optimizer/dispatch_dense') > 0


def test_pallas_flag_flip_rekeys_live_executor():
    """Flipping a FLAGS_pallas_* knob on an ALREADY-COMPILED executor
    must re-dispatch (the per-step executable cache keys on the pallas
    flag tuple); flipping back must be a cache hit, not a retrace."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        pred = layers.fc(x, 4)
        loss = layers.reduce_mean(pred)
        fluid.optimizer.Adam(1e-2).minimize(loss)
    feed = {'x': np.random.RandomState(3).randn(4, 8).astype('float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        assert common._LAST['fused_optimizer']['path'] == 'dense'
        set_flags({'FLAGS_pallas_force': True})
        exe.run(main, feed=feed, fetch_list=[loss])
        assert common._LAST['fused_optimizer'] == {
            'path': 'fused', 'reason': 'forced_interpret',
            'interpret': True}
        set_flags({'FLAGS_pallas_force': False})
        lowered = monitor.counter_value('executor/segments_lowered')
        exe.run(main, feed=feed, fetch_list=[loss])
        assert monitor.counter_value(
            'executor/segments_lowered') == lowered


# ------------------------------------------ fused embedding kernels

def test_embedding_lookup_parity_bitwise():
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(600, 16).astype('float32'))
    ids = jnp.asarray(rng.randint(0, 600, size=(7, 5)).astype('int64'))
    set_flags({'FLAGS_pallas_embedding': True})
    _force(True)
    fused = embedding.embedding_lookup(w, ids, padding_idx=3)
    _force(False)
    dense = embedding._dense_lookup(w, ids, 3)
    assert np.array_equal(np.asarray(fused), np.asarray(dense))


def test_embedding_lookup_grad_collisions_bitwise():
    """Cotangent scatter with heavily repeated ids: sorted runs
    accumulate in-VMEM; result is bitwise the dense .at[].add."""
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(520, 8).astype('float32'))
    ids = jnp.asarray(
        np.array([0, 5, 5, 5, 2, 519, 2, 5, 0, 0], np.int64))

    def loss(fn, w):
        return jnp.sum(fn(w, ids, -1) ** 2)

    _force(True)
    gf = jax.grad(lambda w: loss(embedding.embedding_lookup, w))(w)
    _force(False)
    gd = jax.grad(lambda w: loss(embedding._dense_lookup, w))(w)
    assert np.array_equal(np.asarray(gf), np.asarray(gd))


def test_embedding_update_collisions_and_padding():
    rng = np.random.RandomState(2)
    v, d = 530, 8
    w = jnp.asarray(rng.randn(v, d).astype('float32'))
    mom = jnp.asarray(np.abs(rng.randn(v, d)).astype('float32'))
    ids = jnp.asarray(
        np.array([7, 7, 7, 1, 0, 529, 1, 7], np.int64))
    g = jnp.asarray(rng.randn(8, d).astype('float32'))
    ins = {'Param': [w], 'Moment': [mom], 'Ids': [ids], 'Grad': [g],
           'LearningRate': [jnp.asarray(np.float32(0.1))]}
    attrs = {'epsilon': 1e-6, 'padding_idx': 1}
    set_flags({'FLAGS_pallas_embedding': True})
    _force(True)
    fused = embedding.apply_update(registry.LowerCtx(0), ins, attrs)
    _force(False)
    dense = embedding.apply_update(registry.LowerCtx(0), ins, attrs)
    for slot in ('ParamOut', 'MomentOut'):
        np.testing.assert_allclose(
            np.asarray(fused[slot][0]), np.asarray(dense[slot][0]),
            rtol=2e-6, atol=2e-6, err_msg=slot)
    # padding rows and untouched rows are bit-identical to the input
    for row in (1, 2, 100):
        assert np.array_equal(np.asarray(fused['ParamOut'][0][row]),
                              np.asarray(w[row]))


def test_adagrad_embedding_rewrite_end_to_end():
    """Embedding + Adagrad: the graph rewrite replaces the dense
    lookup_table_v2_grad scatter + full-table adagrad pair with one
    fused_emb_update op, and training matches the unrewritten program
    bitwise under dense dispatch."""
    def build(rewrite):
        set_flags({'FLAGS_pallas_embedding': rewrite})
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 13
        with fluid.program_guard(main, startup):
            ids = layers.data('ids', shape=[1], dtype='int64')
            emb = layers.embedding(ids, size=[600, 16])
            pred = layers.fc(emb, 4)
            loss = layers.reduce_mean(pred)
            fluid.optimizer.Adagrad(0.05).minimize(loss)
        return main, startup, loss

    main, _, _ = build(True)
    types = [op.type for op in main.global_block().ops]
    assert 'fused_emb_update' in types
    assert 'lookup_table_v2_grad' not in types
    main, _, _ = build(False)
    types = [op.type for op in main.global_block().ops]
    assert 'fused_emb_update' not in types

    feed = {'ids': np.random.RandomState(3).randint(
        0, 600, size=(6, 1)).astype('int64')}

    def run(rewrite, force):
        main, startup, loss = build(rewrite)
        set_flags({'FLAGS_pallas_force': force})
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            return np.asarray(
                [exe.run(main, feed=feed, fetch_list=[loss])[0]
                 for _ in range(4)])

    base = run(False, False)
    rewritten = run(True, False)
    forced = run(True, True)
    assert np.array_equal(base, rewritten)
    np.testing.assert_allclose(forced, base, rtol=2e-5, atol=1e-6)


# --------------------------------------- fused quantized collective

def test_quant_collective_parity_bitwise():
    """Fused quantize / dequant-reduce-requant vs the dense arm over a
    real 8-way mesh (padding exercised by the un-aligned size)."""
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 devices')
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.compat import shard_map
    from paddle_tpu.ops import collective_ops
    mesh = Mesh(np.array(jax.devices()[:8]), ('dp',))
    x = np.random.RandomState(0).randn(8, 1000).astype('float32')
    x[:, 100:150] = 0.0      # all-zero blocks hit the s>0 guard

    def run(force):
        set_flags({'FLAGS_pallas_force': force,
                   'FLAGS_pallas_quant_collective': True})
        return np.asarray(jax.jit(shard_map(
            lambda v: collective_ops._quant_allreduce(v, 'dp', 8, 256),
            mesh=mesh, in_specs=P('dp'), out_specs=P('dp')))(x))

    dense = run(False)
    fused = run(True)
    assert np.array_equal(dense, fused)


def test_quantize_blocks_bitwise():
    from paddle_tpu.ops.pallas import quant_collective as qc
    flat = np.random.RandomState(0).randn(32, 256).astype('float32')
    flat[3] = 0.0
    qv, s = qc.quantize_blocks(jnp.asarray(flat), True)

    def q(v):
        s = jnp.max(jnp.abs(v), axis=-1, keepdims=True) / 127.0
        s = jnp.where(s > 0, s, 1.0)
        return (jnp.clip(jnp.rint(v / s), -127, 127).astype(jnp.int8),
                s.astype(jnp.float32))

    qref, sref = jax.jit(q)(jnp.asarray(flat))
    assert np.array_equal(np.asarray(qv), np.asarray(qref))
    assert np.array_equal(np.asarray(s), np.asarray(sref))


def test_comms_plan_fused_quant_admissibility():
    """The acceptance budget: 1.5x payload of headroom.  The legacy
    2.25x temporary estimate rejects the quant arm; the fused-kernel
    0.75x term admits it — and the digest carries the bit so the flip
    retraces exactly once."""
    from paddle_tpu.fluid import comms_plan
    payload = 1 << 20
    set_flags({'FLAGS_comms_quantize': True,
               'FLAGS_comms_hbm_budget_bytes': int(1.5 * (1 << 20)),
               'FLAGS_pallas_quant_collective': True,
               'FLAGS_pallas_force': False})
    assert not comms_plan._fused_quant_available()
    assert comms_plan.quant_hbm_temp(payload) == 2.25 * payload
    rejected = comms_plan.decide(payload, 4, 8)
    assert rejected['arm'] == 'dense'
    d0 = comms_plan.digest()
    assert 'qfuse=0' in d0
    set_flags({'FLAGS_pallas_force': True})
    assert comms_plan._fused_quant_available()
    assert comms_plan.quant_hbm_temp(payload) == 0.75 * payload
    admitted = comms_plan.decide(payload, 4, 8)
    assert admitted['arm'] == 'quant'
    d1 = comms_plan.digest()
    assert 'qfuse=1' in d1 and d0 != d1
    # the flag also kills availability regardless of platform
    set_flags({'FLAGS_pallas_quant_collective': False})
    assert not comms_plan._fused_quant_available()


# -------------------------------- dispatch observability / registry

def test_kernel_registry_contract():
    ks = common.kernels()
    for name in ('flash_attention', 'fused_optimizer',
                 'embedding_lookup', 'embedding_update',
                 'quant_collective'):
        assert name in ks, name
        assert ks[name]['dense_fallback'], name


def test_dispatch_reasons_and_statusz():
    set_flags({'FLAGS_pallas_opt_fuse': False})
    fused_optimizer.apply('adam', registry.LowerCtx(0), _opt_ins(2), {})
    assert common._LAST['fused_optimizer']['reason'] == 'flag_off'
    assert monitor.counter_value(
        'pallas/fused_optimizer/fallback/flag_off') > 0
    from paddle_tpu.fluid import health
    rep = health.statusz()['pallas']
    assert rep and 'fused_optimizer' in rep['kernels']
    k = rep['kernels']['fused_optimizer']
    assert k['last']['reason'] == 'flag_off'
    assert k['dense_fallback']


# --------------------------------------------------- progcheck pass

def test_progcheck_programs_with_fused_ops():
    """The static verifier walks programs containing each fused op
    (shape inference runs the real lowerings via eval_shape)."""
    # fused_emb_update via the Adagrad rewrite
    set_flags({'FLAGS_pallas_embedding': True})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[1], dtype='int64')
        emb = layers.embedding(ids, size=[600, 16])
        loss = layers.reduce_mean(layers.fc(emb, 4))
        fluid.optimizer.Adagrad(0.05).minimize(loss)
    assert 'fused_emb_update' in [op.type for op in
                                  main.global_block().ops]
    rep = progcheck.verify_program(
        main, feed_names=('ids',), fetch_names=(loss.name,),
        startup_program=startup, level='full', raise_on_error=False)
    assert rep.ok(), rep.format()

    # fused_adam / fused_adamw / fused_lamb as explicit graph ops
    for fused_type in ('fused_adam', 'fused_adamw', 'fused_lamb'):
        main = fluid.Program()
        blk = main.global_block()
        names = {}
        for slot, shape in (('p0', (8, 8)), ('g0', (8, 8)),
                            ('m10', (8, 8)), ('m20', (8, 8)),
                            ('p1', (16,)), ('g1', (16,)),
                            ('m11', (16,)), ('m21', (16,))):
            names[slot] = blk.create_var(
                name=slot, shape=list(shape), dtype='float32',
                persistable=True)
        for slot in ('lr', 'b1p0', 'b2p0', 'b1p1', 'b2p1'):
            names[slot] = blk.create_var(
                name=slot, shape=[1], dtype='float32', persistable=True)
        blk.append_op(
            type=fused_type,
            inputs={'Param': [names['p0'], names['p1']],
                    'Grad': [names['g0'], names['g1']],
                    'Moment1': [names['m10'], names['m11']],
                    'Moment2': [names['m20'], names['m21']],
                    'LearningRate': [names['lr'], names['lr']],
                    'Beta1Pow': [names['b1p0'], names['b1p1']],
                    'Beta2Pow': [names['b2p0'], names['b2p1']]},
            outputs={'ParamOut': [names['p0'], names['p1']],
                     'Moment1Out': [names['m10'], names['m11']],
                     'Moment2Out': [names['m20'], names['m21']],
                     'Beta1PowOut': [names['b1p0'], names['b1p1']],
                     'Beta2PowOut': [names['b2p0'], names['b2p1']]},
            attrs={'beta1': 0.9, 'beta2': 0.999},
            infer_shape=False)
        rep = progcheck.verify_program(main, level='full',
                                       raise_on_error=False)
        assert rep.ok(), '%s: %s' % (fused_type, rep.format())
