"""Collective planner (fluid.comms_plan): cost-model-driven arm
selection (dense flat / reduce-scatter+allgather / block-scaled int8
quantized), grad-bucket fusion in the GradAllReduce transpiler, and
the observability contract (plan_arm counters, dense-equivalent wire
bytes, predicted-vs-measured, /statusz plan section).

Loss-parity posture mirrors test_dgc: the quantized arm must converge
within tolerance of the dense run on a small model, and fall back
BIT-EXACT when FLAGS_comms_quantize is off or every tensor sits below
the size floor."""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import comms, comms_plan, layers, monitor
from paddle_tpu.fluid.transpiler.collective import GradAllReduce

PLAN_FLAGS = ('FLAGS_comms_plan', 'FLAGS_comms_quantize',
              'FLAGS_comms_quantize_min_bytes',
              'FLAGS_comms_quant_block', 'FLAGS_comms_bucket_bytes',
              'FLAGS_comms_model_path', 'FLAGS_comms_rs_ag_min_bytes',
              'FLAGS_comms_hbm_budget_bytes')


@pytest.fixture(autouse=True)
def _clean():
    prev = fluid.get_flags(list(PLAN_FLAGS))
    monitor.reset()
    comms.reset()
    comms_plan.reset()
    yield
    fluid.set_flags(prev)
    monitor.reset()
    comms.reset()
    comms_plan.reset()


def _write_model(tmp_path, collectives):
    path = tmp_path / 'comms_model.json'
    path.write_text(json.dumps({'version': 1, 'devices': 8,
                                'collectives': collectives}))
    return str(path)


# ---------------------------------------------------------- unit: planner
def test_quant_wire_bytes_is_quarter_of_dense():
    payload = 4 << 20      # 4 MiB fp32
    dense = comms.wire_bytes('allreduce', payload, 8)
    quant = comms_plan.quant_wire_bytes(payload, 4, 8, block=256)
    # int8 payload + 4/256 scale overhead: ~dense/4 * 1.0156
    assert quant == pytest.approx(dense / 4 * (1 + 4 / 256), rel=1e-6)
    assert comms_plan.quant_wire_bytes(payload, 4, 1) == 0.0


def test_decide_dense_default_and_quant_gate():
    fluid.set_flags({'FLAGS_comms_quantize': False})
    d = comms_plan.decide(1 << 20, 4, 8)
    assert d['arm'] == 'dense' and d['strategy'] == 'flat'
    assert d['wire_bytes'] == d['dense_wire_bytes'] > 0
    # flag on: eligible above the floor, dense below it
    fluid.set_flags({'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 65536})
    assert comms_plan.decide(1 << 20, 4, 8)['arm'] == 'quant'
    assert comms_plan.decide(1 << 10, 4, 8)['arm'] == 'dense'
    # int8 payloads have nothing to quantize
    assert comms_plan.decide(1 << 20, 1, 8)['arm'] == 'dense'
    # single participant: nothing moves
    assert comms_plan.decide(1 << 20, 4, 1)['wire_bytes'] == 0.0
    # forced arm (calibrator) bypasses the gate
    fluid.set_flags({'FLAGS_comms_quantize': False})
    d = comms_plan.decide(1 << 20, 4, 8, forced_arm='quant')
    assert d['arm'] == 'quant'
    assert d['wire_bytes'] < d['dense_wire_bytes'] / 3


def test_decide_strategy_from_model(tmp_path):
    # model A: rs+ag much cheaper than flat -> rs_ag
    path = _write_model(tmp_path, {
        'allreduce': {'latency_s': 1e-3, 'inv_bw_s_per_byte': 1e-8},
        'reducescatter': {'latency_s': 1e-5,
                          'inv_bw_s_per_byte': 1e-10},
        'allgather': {'latency_s': 1e-5, 'inv_bw_s_per_byte': 1e-10}})
    fluid.set_flags({'FLAGS_comms_model_path': path})
    d = comms_plan.decide(1 << 20, 4, 8)
    assert d['strategy'] == 'rs_ag'
    # forced dense baseline skips strategy synthesis entirely
    forced = comms_plan.decide(1 << 20, 4, 8, forced_arm='dense')
    assert forced['arm'] == 'dense' and forced['strategy'] == 'flat'
    assert d['predicted_s'] == pytest.approx(
        2e-5 + 1e-10 * (comms.wire_bytes('reducescatter', 1 << 20, 8) +
                        comms.wire_bytes('allgather', (1 << 20) / 8,
                                         8)))
    # model B: flat cheaper -> flat
    path_b = tmp_path / 'b.json'
    path_b.write_text(json.dumps({'collectives': {
        'allreduce': {'latency_s': 1e-6, 'inv_bw_s_per_byte': 1e-12},
        'reducescatter': {'latency_s': 1e-3,
                          'inv_bw_s_per_byte': 1e-8},
        'allgather': {'latency_s': 1e-3, 'inv_bw_s_per_byte': 1e-8}}}))
    fluid.set_flags({'FLAGS_comms_model_path': str(path_b)})
    assert comms_plan.decide(1 << 20, 4, 8)['strategy'] == 'flat'


def test_decide_heuristic_without_model():
    fluid.set_flags({'FLAGS_comms_model_path': '/nonexistent.json',
                     'FLAGS_comms_rs_ag_min_bytes': 1 << 20})
    assert comms_plan.decide(1 << 19, 4, 8)['strategy'] == 'flat'
    assert comms_plan.decide(1 << 21, 4, 8)['strategy'] == 'rs_ag'
    assert comms_plan.decide(1 << 21, 4, 8)['predicted_s'] is None


def test_decide_partial_model_never_mislabels_prediction(tmp_path):
    # allreduce-only model + heuristic rs_ag pick: predicted_s must be
    # None (the rs_ag arm cannot be priced), NOT the flat prediction —
    # else the predicted-vs-measured honesty metrics are poisoned
    path = _write_model(tmp_path, {
        'allreduce': {'latency_s': 1e-5, 'inv_bw_s_per_byte': 1e-9}})
    fluid.set_flags({'FLAGS_comms_model_path': path,
                     'FLAGS_comms_rs_ag_min_bytes': 1 << 20})
    d = comms_plan.decide(1 << 21, 4, 8)
    assert d['strategy'] == 'rs_ag' and d['predicted_s'] is None
    # below the cut the flat pick keeps its (valid) flat prediction
    d = comms_plan.decide(1 << 19, 4, 8)
    assert d['strategy'] == 'flat' and d['predicted_s'] is not None


def test_quant_respects_hbm_headroom():
    fluid.set_flags({'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 1024,
                     'FLAGS_comms_hbm_budget_bytes': 1 << 20})
    monitor.set_gauge('executor/segment_peak_bytes', (1 << 20) - 4096)
    # headroom ~4KiB < 2.25 * 512KiB payload: quant degrades to dense
    assert comms_plan.decide(512 << 10, 4, 8)['arm'] == 'dense'
    monitor.set_gauge('executor/segment_peak_bytes', 0.0)
    assert comms_plan.decide(100 << 10, 4, 8)['arm'] == 'quant'


def test_bucket_grads_grouping_and_caps():
    grads = [('a', 1000, 'float32'), ('b', 1000, 'float32'),
             ('c', 500, 'float16'), ('d', 1000, 'float32'),
             ('e', 10 ** 9, 'float32'), ('f', 0, 'float32')]
    buckets = comms_plan.bucket_grads(grads, cap_bytes=2500)
    names = [b['names'] for b in buckets]
    # same-dtype grads group to the cap; dtype change opens a bucket;
    # oversized and unknown-size grads stand alone
    assert ['a', 'b'] in names            # 2000 <= cap, 'd' would pass
    assert ['c'] in names                 # dtype break
    assert ['e'] in names and ['f'] in names
    assert any('d' in n for n in names)
    # every grad appears exactly once
    flat = [n for b in buckets for n in b['names']]
    assert sorted(flat) == sorted(g[0] for g in grads)
    # cap 0 disables fusion entirely
    assert all(len(b['names']) == 1 for b in
               comms_plan.bucket_grads(grads, cap_bytes=0))


def test_fuse_cutoff_from_model_crossover(tmp_path):
    # bandwidth-bound grads skip fusion: without a model the flag is
    # the floor; with one, the model's own alpha/beta crossover
    fluid.set_flags({'FLAGS_comms_fuse_grad_max_bytes': 64 << 10})
    assert comms_plan.fuse_cutoff_bytes(cap=4 << 20) == 64 << 10
    path = _write_model(tmp_path, {
        'allreduce': {'latency_s': 1e-4, 'inv_bw_s_per_byte': 1e-9}})
    fluid.set_flags({'FLAGS_comms_model_path': path})
    # the alpha/beta crossover is in wire bytes; payload cutoff is
    # half (ring wire ~ 2x payload): 100KB wire -> 50KB payload
    assert comms_plan.fuse_cutoff_bytes(cap=4 << 20) == \
        pytest.approx(1e-4 / 1e-9 / 2)
    # large grads stand alone even when the cap would admit them
    buckets = comms_plan.bucket_grads(
        [('w', 200 << 10, 'float32'), ('b', 256, 'float32'),
         ('b2', 256, 'float32')], cap_bytes=4 << 20)
    assert [b['names'] for b in buckets] == [['w'], ['b', 'b2']]


def test_bucket_cap_respects_hbm_budget():
    fluid.set_flags({'FLAGS_comms_bucket_bytes': 4 << 20,
                     'FLAGS_comms_hbm_budget_bytes': 0})
    assert comms_plan.bucket_cap_bytes() == 4 << 20
    fluid.set_flags({'FLAGS_comms_hbm_budget_bytes': 2 << 20})
    monitor.set_gauge('executor/segment_peak_bytes', 1 << 20)
    # quarter of the 1MiB headroom, floored at 64KiB
    assert comms_plan.bucket_cap_bytes() == pytest.approx((1 << 20) / 4)
    monitor.set_gauge('executor/segment_peak_bytes', 2 << 20)
    assert comms_plan.bucket_cap_bytes() == 64 << 10


def test_order_axes_largest_first():
    assert comms_plan.order_axes([('sp', 2), ('dp', 8), ('mp', 4)]) \
        == ['dp', 'mp', 'sp']
    # stable tie-break by name
    assert comms_plan.order_axes([('b', 4), ('a', 4)]) == ['a', 'b']


def test_multi_axis_planned_allreduce_ring_ids():
    # a planned c_allreduce_sum with a ring_ids attr reduces over both
    # mesh axes (planner-ordered phases), matching a two-axis psum
    import jax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.compat import shard_map
    from paddle_tpu.ops import collective_ops, registry
    if len(jax.devices()) < 4:
        pytest.skip('needs a multi-axis mesh')
    from paddle_tpu.parallel import mesh as pmesh
    mesh = pmesh.create_mesh(dp=len(jax.devices()) // 2, mp=2)
    prev_rings = dict(collective_ops.RING_AXES)
    try:
        collective_ops.RING_AXES = {0: 'dp', 1: 'mp'}
        x = np.arange(len(jax.devices()) * 6,
                      dtype='float32').reshape(-1, 6)

        def f(v):
            out = registry.get('c_allreduce_sum').fn(
                registry.LowerCtx(0), {'X': [v]},
                {'ring_ids': [0, 1], 'plan': True})['Out'][0]
            return out, jax.lax.psum(v, ('dp', 'mp'))

        got, want = jax.jit(shard_map(
            f, mesh=mesh, in_specs=P('dp'),
            out_specs=(P('dp'), P('dp'))))(x)
        assert np.allclose(np.asarray(got), np.asarray(want),
                           rtol=1e-6)
    finally:
        collective_ops.RING_AXES = prev_rings


def test_digest_tracks_flags_and_model(tmp_path):
    d0 = comms_plan.digest()
    assert d0 == comms_plan.digest()      # deterministic
    fluid.set_flags({'FLAGS_comms_quantize': True})
    d1 = comms_plan.digest()
    assert d1 != d0
    path = _write_model(tmp_path, {
        'allreduce': {'latency_s': 0, 'inv_bw_s_per_byte': 1e-10}})
    fluid.set_flags({'FLAGS_comms_model_path': path})
    d2 = comms_plan.digest()
    assert d2 != d1
    # the HBM-headroom gate reads a runtime gauge: a materially (power
    # of two) changed headroom must change the digest, so cached
    # executables can never be silently stale against the gate
    fluid.set_flags({'FLAGS_comms_hbm_budget_bytes': 1 << 20})
    monitor.set_gauge('executor/segment_peak_bytes', 0.0)
    d3 = comms_plan.digest()
    assert d3 != d2
    monitor.set_gauge('executor/segment_peak_bytes', (1 << 20) - 1024)
    assert comms_plan.digest() != d3


# ----------------------------------------------- transpiler bucket rewrite
def _build_mlp(width=64, seed=3):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = seed
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[width], dtype='float32')
        h = layers.fc(x, width, act='relu')
        loss = layers.reduce_mean(layers.fc(h, 1))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main_p, startup, loss


def test_transpiler_fuses_buckets():
    main_p, startup, _ = _build_mlp()
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    ops = [op.type for op in main_p.global_block().ops]
    # 4 small grads coalesce into one fused planned collective + the
    # reference's per-grad 1/nranks scale
    assert ops.count('c_allreduce_fused') == 1
    assert ops.count('c_allreduce_sum') == 0
    assert ops.count('scale') >= 4
    fused = [op for op in main_p.global_block().ops
             if op.type == 'c_allreduce_fused'][0]
    assert len(fused.input('X')) == 4
    assert fused.attrs['plan'] is True
    snap = monitor.snapshot()['collective']
    assert snap['plan_buckets'] == 1.0
    assert snap['plan_fused_grads'] == 4.0
    # ops_inserted reports collectives actually in the block (1 fused
    # bucket), bytes_per_step still the payload of all 4 synced grads
    assert snap['allreduce_ops_inserted'] == 1.0
    assert snap['allreduce_bytes_per_step'] > 0
    # the plan is on the /statusz registry
    plans = comms_plan.program_plans()
    assert plans['programs']
    (label, summary), = plans['programs'].items()
    assert summary['grads'] == 4 and len(summary['buckets']) == 1
    assert summary['buckets'][0]['arm_preview'] == 'dense'


def test_transpiler_off_restores_v16_shape():
    fluid.set_flags({'FLAGS_comms_plan': False})
    main_p, startup, _ = _build_mlp()
    n_before = len(main_p.global_block().ops)
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    ops = [op.type for op in main_p.global_block().ops]
    assert ops.count('c_allreduce_sum') == 4
    assert ops.count('c_allreduce_fused') == 0
    assert len(ops) == n_before + 8


def test_transpiler_bucket_cap_splits():
    # a tiny bucket target forces one planned collective per grad
    fluid.set_flags({'FLAGS_comms_bucket_bytes': 8})
    main_p, startup, _ = _build_mlp()
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    ops = [op.type for op in main_p.global_block().ops]
    assert ops.count('c_allreduce_sum') == 4
    assert ops.count('c_allreduce_fused') == 0


# -------------------------------------------------------- execution parity
def _train(n_steps=40, width=64, seed=0):
    comms.reset()
    main_p, startup, loss = _build_mlp(width=width)
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    exe = fluid.Executor(fluid.XLAPlace(0))
    rng = np.random.RandomState(seed)
    W = rng.randn(width, 1).astype('float32')
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(n_steps):
            xs = rng.randn(16, width).astype('float32')
            lv, = exe.run(main_p, feed={'x': xs}, fetch_list=[loss])
            losses.append(np.asarray(lv))
    return np.concatenate([l.reshape(-1) for l in losses])


def test_planned_dense_bit_exact_vs_v16():
    fluid.set_flags({'FLAGS_comms_plan': False})
    base = _train()
    fluid.set_flags({'FLAGS_comms_plan': True})
    planned = _train()
    # fused dense buckets compute the same elementwise sum
    assert np.array_equal(base, planned)


def test_quant_loss_parity_and_bit_exact_fallback():
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_quantize': False})
    dense = _train()
    fluid.set_flags({'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 256})
    quant = _train()
    # quantized training converges alongside dense: same trajectory
    # within a few percent, same final loss neighborhood (DGC-style
    # parity posture)
    assert quant.shape == dense.shape
    assert not np.array_equal(dense, quant)   # the arm really ran
    assert float(abs(quant[-1] - dense[-1])) <= \
        max(0.05 * abs(float(dense[-1])), 5e-3)
    assert np.max(np.abs(quant - dense)) <= \
        0.1 * max(1.0, float(np.max(np.abs(dense))))
    # below the floor every tensor is ineligible: BIT-EXACT fallback
    fluid.set_flags({'FLAGS_comms_quantize_min_bytes': 1 << 30})
    below_floor = _train()
    assert np.array_equal(dense, below_floor)
    # flag off: bit-exact again
    fluid.set_flags({'FLAGS_comms_quantize': False,
                     'FLAGS_comms_quantize_min_bytes': 256})
    off = _train()
    assert np.array_equal(dense, off)


def test_rs_ag_strategy_matches_flat():
    # force rs_ag for everything via the no-model heuristic cut
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_rs_ag_min_bytes': 1})
    rs = _train(n_steps=10)
    fluid.set_flags({'FLAGS_comms_rs_ag_min_bytes': 1 << 30})
    flat = _train(n_steps=10)
    assert np.allclose(rs, flat, rtol=1e-6, atol=1e-6)
    arm = monitor.counter_value('comms/plan_arm/dense')
    assert arm > 0


def test_dispatch_reports_arm_and_savings_counters():
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 256})
    _train(n_steps=6)
    flat = monitor.flat()
    assert flat.get('comms/plan_arm/quant', 0) > 0
    wire = flat.get('comms/plan_wire_bytes', 0)
    dense_equiv = flat.get('comms/plan_dense_equiv_bytes', 0)
    # ~4x payload reduction for fp32 -> int8+scales
    assert 0 < wire < 0.3 * dense_equiv
    assert flat.get('comms/plan_fused_grads', 0) > 0
    assert flat.get('comms/bytes_on_wire', 0) > 0


def test_predicted_vs_measured_with_model(tmp_path):
    path = _write_model(tmp_path, {
        'allreduce': {'latency_s': 1e-5, 'inv_bw_s_per_byte': 1e-9},
        'reducescatter': {'latency_s': 1e-5,
                          'inv_bw_s_per_byte': 1e-9},
        'allgather': {'latency_s': 1e-5, 'inv_bw_s_per_byte': 1e-9}})
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_model_path': path})
    _train(n_steps=6)
    flat = monitor.flat()
    assert flat.get('comms/plan_predicted_seconds', 0) > 0
    assert flat.get('comms/plan_measured_seconds', 0) > 0


def test_statusz_carries_comms_plan_section():
    from paddle_tpu.fluid import health
    fluid.set_flags({'FLAGS_comms_plan': True})
    _train(n_steps=3)
    doc = health.statusz()
    sec = doc.get('comms_plan')
    assert sec and sec['programs']
    assert sec['digest'].startswith('comms_plan(')
    assert sec['arm_counters']['dense'] > 0


def test_zero_retrace_post_warmup():
    # planner decisions are part of the segment fingerprint: repeated
    # steps after the first must never re-trace (segment cache hits
    # only), with the planner + quant arm active
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 256})
    comms.reset()
    main_p, startup, loss = _build_mlp()
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    exe = fluid.Executor(fluid.XLAPlace(0))
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(16, 64).astype('float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[loss])
        misses0 = monitor.counter_value('parallel/segment_cache_miss')
        for _ in range(5):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        assert monitor.counter_value('parallel/segment_cache_miss') \
            == misses0
        assert monitor.counter_value('parallel/segment_cache_hit') >= 5


def test_stat_summary_plan_rollup(tmp_path, capsys):
    import importlib
    import os
    import sys
    fluid.set_flags({'FLAGS_comms_plan': True,
                     'FLAGS_comms_quantize': True,
                     'FLAGS_comms_quantize_min_bytes': 256})
    _train(n_steps=4)
    p = str(tmp_path / 'run.jsonl')
    monitor.dump_jsonl(p)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), 'tools'))
    import stat_summary
    importlib.reload(stat_summary)
    rc = stat_summary.main(['--plan', p])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'arm quant' in out and 'reduction' in out
    # a record with no planner activity reports so
    monitor.reset()
    monitor.dump_jsonl(p)
    assert stat_summary.main(['--plan', p]) == 1


def test_fused_op_identity_without_mesh():
    # outside shard_map (single-device executor) the fused op is the
    # nranks==1 identity, like c_allreduce_sum
    from paddle_tpu.ops import registry
    xs = [np.ones((2, 2), 'float32'), np.arange(3, dtype='float32')]
    out = registry.get('c_allreduce_fused').fn(
        registry.LowerCtx(0), {'X': xs}, {'ring_id': 0, 'plan': True})
    assert len(out['Out']) == 2
    assert np.array_equal(np.asarray(out['Out'][0]), xs[0])
    assert np.array_equal(np.asarray(out['Out'][1]), xs[1])
