"""fluid.serving: multi-tenant continuous batching over CompiledStep.

Covers the serving-plane contract: the pad/mask/slice helpers are
bitwise-transparent, coalesced batches return exactly what unbatched
execution returns, tenants are scope-isolated, the warmed bucket
ladder serves every admissible shape without retracing, serving steps
are tenant-tagged in the trace plane, and the health plane gates
readiness on serving warmup and lists resident programs."""

import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import health, layers, monitor, serving
from paddle_tpu.fluid import trace as pt_trace
from paddle_tpu.fluid.reader import (bucket_for, mask_name,
                                     pow2_bucket_ladder)


def _build_mlp(width=24, seed=3, in_w=8):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = seed
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[in_w], dtype='float32')
        h = layers.fc(x, width, act='relu')
        y = layers.fc(h, 6, act='softmax')
    return main_p, startup, y


@pytest.fixture
def exe():
    return fluid.Executor(fluid.XLAPlace(0))


def test_pow2_bucket_ladder():
    assert pow2_bucket_ladder(1) == [1]
    assert pow2_bucket_ladder(8) == [1, 2, 4, 8]
    assert pow2_bucket_ladder(6) == [1, 2, 4, 8]
    assert bucket_for(3, [1, 2, 4, 8]) == 4
    assert bucket_for(8, [1, 2, 4, 8]) == 8
    with pytest.raises(ValueError):
        bucket_for(9, [1, 2, 4, 8])
    assert mask_name('x') == 'x@MASK'
    assert mask_name('x', {'x': 'm'}) == 'm'


def test_pad_rows_to_bucket_and_slice():
    feed = {'x': np.arange(12, dtype='float32').reshape(3, 4),
            'side': np.float32(2.0)}   # not batch-aligned: untouched
    padded, waste = serving.pad_rows_to_bucket(
        feed, 3, 4, mask_specs=(('x@MASK', ()),))
    assert padded['x'].shape == (4, 4)
    assert np.array_equal(padded['x'][:3], feed['x'])
    assert not padded['x'][3].any()
    assert np.array_equal(padded['x@MASK'],
                          np.array([1, 1, 1, 0], 'float32'))
    assert padded['side'] == np.float32(2.0)
    assert waste == 4 * 4  # one f32 pad row
    # slice back: batch-aligned outputs slice, aggregates pass through
    out = np.arange(8, dtype='float32').reshape(4, 2)
    assert np.array_equal(serving.slice_rows(out, 1, 2, 4), out[1:3])
    assert serving.slice_rows(np.float32(7.0), 1, 2, 4) == 7.0
    # already-bucketed feed is returned as-is (no copies, no masks)
    same, waste = serving.pad_rows_to_bucket(feed, 3, 3)
    assert same is feed and waste == 0.0


def test_padded_equals_unbatched(exe):
    """The acceptance-criteria core: pad-to-bucket + slice is bitwise
    invisible."""
    main_p, startup, y = _build_mlp()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        xv = np.random.RandomState(0).randn(3, 8).astype('float32')
        direct, = exe.run(main_p, feed={'x': xv}, fetch_list=[y])
        padded, _ = serving.pad_rows_to_bucket({'x': xv}, 3, 4)
        batched, = exe.run(main_p, feed=padded, fetch_list=[y])
    assert np.array_equal(np.asarray(direct),
                          serving.slice_rows(np.asarray(batched),
                                             0, 3, 4))


def test_serving_executor_soak_bitwise_and_zero_retrace(exe):
    main_a, start_a, y_a = _build_mlp(width=16, seed=5)
    main_b, start_b, y_b = _build_mlp(width=24, seed=6)
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    scopes = {}
    for name, (mp, sp, y) in (('a', (main_a, start_a, y_a)),
                              ('b', (main_b, start_b, y_b))):
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        scopes[name] = (mp, sc, y)
        srv.add_program(name, mp, ['x'], [y], scope=sc)
    try:
        srv.warmup(wait=True)
        assert srv.ready
        lowered0 = monitor.counter_value('executor/segments_lowered')
        rng = np.random.RandomState(1)
        futs, expect = [], []
        for i in range(16):
            name = 'ab'[i % 2]
            rows = (1, 3, 2, 5)[i % 4]
            xv = rng.randn(rows, 8).astype('float32')
            futs.append(srv.submit(name, {'x': xv}))
            expect.append((name, xv))
        outs = [f.result(120) for f in futs]
        # zero retraces: every bucket came from the warmed ladder
        assert monitor.counter_value(
            'executor/segments_lowered') == lowered0
        assert srv.resident_report()['tenants'][0]['retraces'] == 0
        # bitwise vs unbatched execution at the bucket the request
        # actually ran in: coalescing picks the bucket from the TOTAL
        # batch rows, and XLA's gemm accumulation order may differ
        # across bucket shapes — within one bucket, bytes match
        for (name, xv), res in zip(expect, outs):
            mp, sc, y = scopes[name]
            rows = xv.shape[0]
            matched = False
            for b in (bb for bb in (1, 2, 4, 8) if bb >= rows):
                padded, _ = serving.pad_rows_to_bucket(
                    {'x': xv}, rows, b)
                with fluid.scope_guard(sc):
                    direct, = exe.run(mp, feed=padded, fetch_list=[y])
                if np.array_equal(np.asarray(direct)[:rows], res[0]):
                    matched = True
                    break
            assert matched
        # SLO metrics recorded
        assert monitor.histogram_value(
            'serving/admit_to_done_seconds')['count'] >= 16
        assert monitor.histogram_value(
            'serving/batch_occupancy')['count'] >= 1
        assert monitor.gauge_value('serving/queue_depth/a', -1) >= 0
    finally:
        srv.close()


def test_tenant_scope_isolation(exe):
    """Two tenants over CONTENT-IDENTICAL programs (unique_name.guard
    makes the op/var descs byte-equal) but different parameter values
    must serve from their own scopes."""
    with fluid.unique_name.guard():
        main_a, start_a, y_a = _build_mlp(width=16, seed=7)
    with fluid.unique_name.guard():
        main_b, start_b, y_b = _build_mlp(width=16, seed=7)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    sc_a, sc_b = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(sc_a):
        exe.run(start_a)
    with fluid.scope_guard(sc_b):
        exe.run(start_b)
    # same program content, same init — perturb tenant b's weights so
    # only scope isolation can explain differing outputs
    for pname in [p.name for p in main_b.all_parameters()]:
        v = np.asarray(fluid.core.as_array(sc_b.find_var(pname)))
        sc_b.set_var(pname, v * 2.0)
    srv.add_program('a', main_a, ['x'], [y_a], scope=sc_a)
    srv.add_program('b', main_b, ['x'], [y_b], scope=sc_b)
    try:
        srv.warmup(wait=True)
        # identical program content → one fingerprint, two tenants
        rep = srv.resident_report()['tenants']
        assert rep[0]['fingerprint'] == rep[1]['fingerprint']
        xv = np.random.RandomState(2).randn(2, 8).astype('float32')
        out_a, = srv.infer('a', {'x': xv}, timeout=120)
        out_b, = srv.infer('b', {'x': xv}, timeout=120)
        assert not np.array_equal(out_a, out_b)
        with fluid.scope_guard(sc_a):
            direct_a, = exe.run(main_a, feed={'x': xv},
                                fetch_list=[y_a])
        assert np.array_equal(np.asarray(direct_a), out_a)
    finally:
        srv.close()


def test_concurrent_feeders(exe):
    main_p, startup, y = _build_mlp(width=16, seed=9)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    srv.add_program('m', main_p, ['x'], [y], scope=sc)
    try:
        srv.warmup(wait=True)
        errors = []

        def feeder(fid):
            rng = np.random.RandomState(fid)
            for i in range(8):
                xv = rng.randn((i % 3) + 1, 8).astype('float32')
                try:
                    out, = srv.infer('m', {'x': xv}, timeout=120)
                    assert out.shape[0] == xv.shape[0]
                except Exception as e:  # noqa: BLE001
                    errors.append(str(e))

        threads = [threading.Thread(target=feeder, args=(fid,))
                   for fid in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors
        assert srv.resident_report()['tenants'][0][
            'requests_served'] == 32
    finally:
        srv.close()


def test_submit_validation(exe):
    main_p, startup, y = _build_mlp(width=16, seed=10)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    srv.add_program('m', main_p, ['x'], [y], scope=sc)
    try:
        with pytest.raises(KeyError):
            srv.submit('nope', {'x': np.zeros((1, 8), 'float32')})
        with pytest.raises(ValueError):
            srv.submit('m', {})            # missing feed
        with pytest.raises(ValueError):    # beyond the ladder
            srv.submit('m', {'x': np.zeros((5, 8), 'float32')})
        with pytest.raises(ValueError):    # duplicate tenant
            srv.add_program('m', main_p, ['x'], [y], scope=sc)
    finally:
        srv.close()


def test_mismatched_leading_dims_rejected_at_submit(exe):
    """One malformed request must fail at submit(), not poison the
    coalesced batch it would have joined."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 16
    with fluid.program_guard(main_p, startup):
        a = layers.data('a', shape=[4], dtype='float32')
        b = layers.data('b', shape=[4], dtype='float32')
        y = layers.fc(layers.elementwise_add(a, b), 4)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    srv.add_program('two', main_p, ['a', 'b'], [y], scope=sc)
    try:
        with pytest.raises(ValueError, match='mismatched leading'):
            srv.submit('two', {'a': np.zeros((2, 4), 'float32'),
                               'b': np.zeros((3, 4), 'float32')})
    finally:
        srv.close()


def test_aggregate_fetch_rejected_at_registration(exe):
    """A whole-batch aggregate fetch cannot be sliced back per request
    (pad rows would contaminate it): add_program must refuse it."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 17
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        agg = layers.reduce_mean(layers.fc(x, 4))
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    try:
        with pytest.raises(ValueError, match='aggregate'):
            srv.add_program('agg', main_p, ['x'], [agg], scope=sc)
    finally:
        srv.close()


def test_cancelled_future_does_not_kill_dispatcher(exe):
    main_p, startup, y = _build_mlp(width=16, seed=18)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    srv.add_program('m', main_p, ['x'], [y], scope=sc)
    try:
        srv.warmup(wait=True)
        xv = np.zeros((1, 8), 'float32')
        # a burst where the middle request is cancelled while queued
        f1 = srv.submit('m', {'x': xv})
        f2 = srv.submit('m', {'x': xv})
        f2.cancel()
        f3 = srv.submit('m', {'x': xv})
        assert f1.result(120)[0].shape == (1, 6)
        assert f3.result(120)[0].shape == (1, 6)
        # the dispatcher survived: a later request still serves
        out, = srv.infer('m', {'x': xv}, timeout=120)
        assert out.shape == (1, 6)
    finally:
        srv.close()


def test_step_tags_attribution(exe):
    main_p, startup, y = _build_mlp(width=16, seed=11)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        pt_trace.enable(buffer_steps=8)
        try:
            with pt_trace.step_tags(tenant='t1', bucket=4):
                exe.run(main_p, feed={'x': np.zeros((4, 8),
                                                    'float32')},
                        fetch_list=[y])
            exe.run(main_p, feed={'x': np.zeros((4, 8), 'float32')},
                    fetch_list=[y])
            rep = pt_trace.step_report()
            tagged = [s for s in rep['steps'] if s.get('tags')]
            assert len(tagged) == 1
            assert tagged[0]['tags'] == {'tenant': 't1', 'bucket': 4}
            # the rendered table carries the tags too
            assert 'tenant=t1' in pt_trace.format_step_report(rep)
            # and the flight-recorder dump round-trips them
            import json
            with open(pt_trace.dump()) as f:
                doc = json.load(f)
            assert any(r.get('tags') == {'tenant': 't1', 'bucket': 4}
                       for r in doc['ptSteps'])
        finally:
            pt_trace.disable()
            pt_trace.reset()


def test_health_readiness_and_statusz(exe):
    main_p, startup, y = _build_mlp(width=16, seed=12)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=2, executor=exe)
    srv.add_program('resident', main_p, ['x'], [y], scope=sc)
    try:
        st = health.status()
        assert st['ready'] is False
        assert st['serving_ready'] is False
        assert any('resident' in r for r in st['reasons'])
        srv.warmup(wait=True)
        st = health.status()
        assert st['ready'] is True and st['serving_ready'] is True
        sz = health.statusz()
        tenants = [t for rep in sz['serving'] for t in rep['tenants']]
        mine = [t for t in tenants if t['tenant'] == 'resident']
        assert mine and mine[0]['warmed']
        assert mine[0]['bucket_ladder'] == [1, 2]
        assert mine[0]['fingerprint']
    finally:
        srv.close()
    # closed executors drop out of the readiness view
    ready, _ = serving.readiness()
    assert ready in (None, True)


def test_predictor_bucket_parity(exe, tmp_path):
    """Single-shot predictor run() routes through the same
    pad/mask/slice helper: padded and unpadded results bitwise-equal
    (the ISSUE's satellite acceptance)."""
    main_p, startup, y = _build_mlp(width=16, seed=13)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path), ['x'], [y], exe,
                                      main_program=main_p)
    from paddle_tpu.inference import (AnalysisConfig,
                                      create_paddle_predictor)
    xv = np.random.RandomState(4).randn(3, 8).astype('float32')
    cfg = AnalysisConfig(str(tmp_path))
    assert cfg._serving_buckets   # bucket routing is the default
    bucketed, = create_paddle_predictor(cfg).run_dict({'x': xv})
    cfg_off = AnalysisConfig(str(tmp_path))
    cfg_off.switch_serving_buckets(False)
    plain, = create_paddle_predictor(cfg_off).run_dict({'x': xv})
    assert bucketed.shape == plain.shape == (3, 6)
    assert np.array_equal(bucketed, plain)


def test_predictor_serve_entry_point(exe, tmp_path):
    main_p, startup, y = _build_mlp(width=16, seed=14)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path), ['x'], [y], exe,
                                      main_program=main_p)
    from paddle_tpu.inference import (AnalysisConfig,
                                      create_paddle_predictor)
    pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
    srv = pred.serve(tenant='model', max_batch=4)
    try:
        assert srv.ready
        xv = np.random.RandomState(5).randn(2, 8).astype('float32')
        out, = srv.infer('model', {'x': xv}, timeout=120)
        plain, = pred.run_dict({'x': xv})
        assert np.array_equal(out, plain)
    finally:
        srv.close()


def test_mask_synthesis_for_declared_mask_vars(exe):
    """A program declaring '<feed>@MASK' gets a synthesized row mask:
    live rows 1.0, padding 0.0 — the bucketed-loader convention."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 15
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        m = layers.data('x@MASK', shape=[1], dtype='float32')
        y = layers.elementwise_mul(layers.fc(x, 4), m)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=4, executor=exe)
    t = srv.add_program('masked', main_p, ['x'], [y], scope=sc)
    try:
        assert t.mask_specs == (('x@MASK', (1,)),)
        srv.warmup(wait=True)
        xv = np.ones((3, 4), 'float32')
        out, = srv.infer('masked', {'x': xv}, timeout=120)
        assert out.shape[0] == 3
        # mask multiplied through: live rows intact
        with fluid.scope_guard(sc):
            direct, = exe.run(
                main_p, feed={'x': xv,
                              'x@MASK': np.ones((3, 1), 'float32')},
                fetch_list=[y])
        assert np.allclose(out, np.asarray(direct))
    finally:
        srv.close()
