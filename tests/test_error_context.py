"""Enforce-style runtime error context.

Reference: PADDLE_ENFORCE (platform/enforce.h) raises with the op's
Python creation callstack (framework/op_call_stack.h, op_callstack
attr).  Here: every append_op stamps the user frames; lowering failures
attach op type + input shapes + that callstack as exception notes.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_lowering_error_carries_op_context():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        out = fluid.layers.fc(x, 4)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # runtime shape violation: feed contradicts the declared [., 8]
        with pytest.raises(Exception) as ei:
            exe.run(main, feed={'x': np.zeros((4, 3), np.float32)},
                    fetch_list=[out])
    notes = '\n'.join(getattr(ei.value, '__notes__', []))
    assert 'lowering op [mul]' in notes
    assert 'shape=' in notes
    # callstack points at THIS test file, not framework internals
    assert 'test_error_context.py' in notes


def test_op_callstack_attr_recorded():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        fluid.layers.fc(x, 4)
    stamped = [op for op in main.global_block().ops
               if op.attrs.get('__op_callstack__')]
    assert stamped, 'ops should carry creation callstacks'
    joined = '\n'.join(stamped[0].attrs['__op_callstack__'])
    assert 'test_error_context.py' in joined


def test_undefined_var_error_names_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        out = fluid.layers.fc(x, 4)
    # sabotage: rename an input so lowering can't find it
    for op in main.global_block().ops:
        if op.type == 'mul':
            op.inputs['X'] = ['nonexistent_var']
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # lazily at bind time, or — with FLAGS_program_verify on (the
        # PADDLE_TPU_VERIFY sweep) — statically at plan build, where
        # fluid.progcheck names the op and the dangling input
        with pytest.raises(RuntimeError,
                           match='undefined var|not initialized'
                                 '|undefined_read') as ei:
            exe.run(main, feed={'x': np.zeros((4, 8), np.float32)},
                    fetch_list=[out])
        assert 'nonexistent_var' in str(ei.value)
