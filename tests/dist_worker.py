"""Multi-process data-parallel trainer worker.

The TPU-native analog of the reference's `dist_mnist.py`-style trainer
scripts (`python/paddle/fluid/tests/unittests/test_dist_base.py:510`
spawns these as subprocesses on 127.0.0.1): each process is one
"trainer" that rendezvouses through jax.distributed (the gen_nccl_id
replacement), feeds its OWN shard of the global batch, and trains with
the fleet collective GradAllReduce rewrite.  The parent test asserts
loss/parameter parity against a single-process full-batch run.

Launched via `python -m paddle_tpu.distributed.launch` (which sets the
PADDLE_TRAINER_* + JAX_* env contract).
"""

import json
import os
import sys


def build_model(seed):
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 32, act='relu')
        h2 = fluid.layers.fc(h, 16, act='relu')
        logits = fluid.layers.fc(h2, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
    return main, startup, loss


def make_batches(steps=6, n=16):
    import numpy as np
    rng = np.random.RandomState(5)
    out = []
    for _ in range(steps):
        x = rng.randn(n, 8).astype('float32')
        y = (np.abs(x).sum(1, keepdims=True) * 2).astype('int64') % 4
        out.append((x, y))
    return out


def _dygraph_main(rank, world):
    """Eager DataParallel: scale_loss + apply_collective_grads (sum)
    across 2 real processes — reference parallel_dygraph_mnist.py."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.dygraph import Linear, to_variable
    from paddle_tpu.fluid.dygraph.parallel import DataParallel, \
        prepare_context
    from paddle_tpu.fluid.framework import _dygraph_tracer

    class Net(fluid.dygraph.Layer):
        def __init__(self):
            super(Net, self).__init__()
            self.fc1 = Linear(8, 16, act='relu')
            self.fc2 = Linear(16, 1)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    losses = []
    with fluid.dygraph.guard():
        np.random.seed(17)
        strategy = prepare_context()
        model = DataParallel(Net(), strategy)
        opt = fluid.optimizer.SGD(0.1)
        for x, y in make_batches():
            n_local = x.shape[0] // world
            lo = rank * n_local
            xl = x[lo:lo + n_local]
            yl = x[lo:lo + n_local].sum(1, keepdims=True).astype(
                'float32')
            xv, yv = to_variable(xl), to_variable(yl)
            pred = model(xv)
            diff = pred - yv
            loss = _dygraph_tracer().trace_op(
                'mean', {'X': [diff * diff]})['Out'][0]
            loss = model.scale_loss(loss)
            loss.backward()
            model.apply_collective_grads()
            opt.minimize(loss, parameter_list=model.parameters())
            for p in model.parameters():
                p.clear_gradient()
            losses.append(float(np.asarray(loss.value).ravel()[0]))
        w = np.asarray(model._layers.fc1.weight.value)

    outdir = sys.argv[1]
    with open(os.path.join(outdir, 'rank%d.json' % rank), 'w') as f:
        json.dump({'rank': rank, 'world': world, 'losses': losses,
                   'param': w.tolist()}, f)
    print('dygraph worker %d/%d done' % (rank, world))


def build_sparse_model(seed, lr=0.1):
    """Wide&Deep-style sparse model over a host-sharded embedding (the
    multi-process PS: table sharded by id across processes, pull/push
    through the host collective fabric)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding
    emb = HostShardedEmbedding('dist_sparse_emb', 1000, 8,
                               optimizer='adagrad', learning_rate=lr,
                               seed=17)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data('ids', shape=[6], dtype='int64')
        label = fluid.layers.data('label', shape=[1], dtype='float32')
        rows = emb.lookup(ids)
        feat = fluid.layers.reshape(rows, [0, 6 * 8])
        pred = fluid.layers.fc(feat, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, label))
    return main, startup, loss, emb


def make_sparse_batches(steps=6, n=16):
    import numpy as np
    rng = np.random.RandomState(23)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, 400, (n, 6)).astype('int64')
        y = rng.rand(n, 1).astype('float32')
        out.append((ids, y))
    return out


def _sparse_ps_main(rank, world):
    """Sparse-path 2-process PS: embedding pull/push crosses processes
    (owner = id % world); dense grads ride the collective rewrite."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.incubate.fleet.collective import fleet, \
        DistributedStrategy
    from paddle_tpu.fluid.incubate.fleet.base import role_maker

    main_prog, startup, loss, emb = build_sparse_model(9)
    assert emb.world == world, (emb.world, world)
    fleet.init(role_maker.PaddleCloudRoleMaker())
    with fluid.program_guard(main_prog, startup):
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1),
                                          DistributedStrategy())
        opt.minimize(loss)
        emb.apply_gradients(main_prog)

    losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for ids, y in make_sparse_batches():
            n_local = ids.shape[0] // world
            lo = rank * n_local
            l, = exe.run(main_prog,
                         feed={'ids': ids[lo:lo + n_local],
                               'label': y[lo:lo + n_local]},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    outdir = sys.argv[1]
    # ship the locally-owned shard rows so the parent can check the
    # global table against the single-process run
    shard_sample = emb.table[:50].tolist()
    with open(os.path.join(outdir, 'rank%d.json' % rank), 'w') as f:
        json.dump({'rank': rank, 'world': world, 'losses': losses,
                   'param': shard_sample}, f)
    print('worker %d/%d done' % (rank, world))


def main():
    # one CPU device per process by default: strip any forced
    # host-device count inherited from the pytest parent before jax
    # initializes; gspmd_mp mode instead gives every process TWO
    # devices so a multi-process dp x mp mesh exists
    flags = os.environ.get('XLA_FLAGS', '').split()
    flags = [f for f in flags
             if 'xla_force_host_platform_device_count' not in f]
    if len(sys.argv) > 2 and sys.argv[2] == 'gspmd_mp':
        flags.append('--xla_force_host_platform_device_count=2')
    os.environ['XLA_FLAGS'] = ' '.join(flags)
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from paddle_tpu.distributed.launch import init_distributed
    init_distributed()

    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.incubate.fleet.collective import fleet, \
        DistributedStrategy
    from paddle_tpu.fluid.incubate.fleet.base import role_maker

    rank = jax.process_index()
    world = jax.process_count()
    assert world > 1, 'worker expects a multi-process jax runtime'
    mode = sys.argv[2] if len(sys.argv) > 2 else 'collective'
    if mode == 'dygraph':
        return _dygraph_main(rank, world)
    if mode == 'sparse_ps':
        return _sparse_ps_main(rank, world)

    main_prog, startup, loss = build_model(9)
    compiled = None
    if mode in ('collective', 'local_sgd'):
        fleet.init(role_maker.PaddleCloudRoleMaker())
        strategy = DistributedStrategy()
        if mode == 'local_sgd':
            strategy.use_local_sgd = True
            strategy.local_sgd_period = 2
        with fluid.program_guard(main_prog, startup):
            opt = fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.1), strategy)
            opt.minimize(loss)
    elif mode == 'gspmd_mp':
        # multi-process AND multi-axis: every process holds 2 devices;
        # the mesh is (dp=world, mp=2) spanning all processes — batch
        # sharded on dp, fc weight matrices column-sharded on mp
        import numpy as _np
        from jax.sharding import Mesh, PartitionSpec as P
        with fluid.program_guard(main_prog, startup):
            fluid.optimizer.SGD(0.1).minimize(loss)
        devs = sorted(jax.devices(), key=lambda d: (d.process_index,
                                                    d.id))
        mesh = Mesh(_np.array(devs).reshape(world, 2), ('dp', 'mp'))

        def shard_rule(name, shape):
            if len(shape) == 2 and min(shape) >= 4:
                return P(None, 'mp')
            return None

        compiled = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=loss.name).with_mesh(mesh).with_param_shardings(
            shard_rule)
    else:  # gspmd: CompiledProgram DP + ZeRO-sharded optimizer state
        with fluid.program_guard(main_prog, startup):
            fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
        compiled = fluid.CompiledProgram(main_prog).with_data_parallel(
            loss_name=loss.name).with_sharded_optimizer_states()

    batches = make_batches()
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for x, y in batches:
            n_local = x.shape[0] // world
            lo = rank * n_local
            xl, yl = x[lo:lo + n_local], y[lo:lo + n_local]
            l, = exe.run(compiled if compiled is not None else main_prog,
                         feed={'x': xl, 'y': yl}, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        pname = main_prog.all_parameters()[0].name
        from paddle_tpu.fluid.parallel_executor import _fetch_to_host
        final_param = _fetch_to_host(scope.find_var(pname))

    outdir = sys.argv[1]
    with open(os.path.join(outdir, 'rank%d.json' % rank), 'w') as f:
        json.dump({'rank': rank, 'world': world, 'losses': losses,
                   'param': final_param.tolist()}, f)
    print('worker %d/%d done' % (rank, world))


if __name__ == '__main__':
    main()
