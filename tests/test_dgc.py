"""DGC momentum: sparsified comm grads, convergence preserved."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import registry


def test_dgc_op_topk_and_error_feedback():
    g = np.array([[0.1, -2.0], [0.5, 0.05]], 'float32')
    u = np.zeros((2, 2), 'float32')
    v = np.zeros((2, 2), 'float32')
    out = registry.get('dgc').fn(
        registry.LowerCtx(0), {'Grad': [g], 'U': [u], 'V': [v]},
        {'m': 0.9, 'sparsity_ratio': 0.75})  # keep top-1
    enc = np.asarray(out['EncodeGrad'][0])
    assert (enc != 0).sum() == 1
    assert enc[0, 1] == -2.0
    vout = np.asarray(out['VOut'][0])
    assert vout[0, 1] == 0.0            # communicated -> cleared
    assert vout[1, 0] == 0.5            # retained locally


def test_dgc_momentum_converges():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[2], dtype='float32')
        pred = fluid.layers.fc(x, 2, bias_attr=False)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.DGCMomentumOptimizer(
            0.05, momentum=0.9, sparsity=(0.75,))
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    W = rng.randn(4, 2).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        final = None
        for _ in range(300):
            xs = rng.randn(16, 4).astype('float32')
            final, = exe.run(main, feed={'x': xs, 'y': xs @ W},
                             fetch_list=[loss])
    assert float(final) < 0.1, float(final)
