"""DistributeTranspiler PS mode -> embedded parameter-server runtime.

Reference workflow (distribute_transpiler.py:536,634,1110): minimize,
transpile, TRAINER runs get_trainer_program(), PSERVER runs
get_pserver_program(ep).  Here the server is embedded: sparse
lookup_table ops are rewritten onto host-sharded tables (pull/push
sparse), async mode strips dense optimizer ops onto the in-process
store, and the pserver program is an explicit no-op.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding

layers = fluid.layers


def _build(is_sparse, seed=21):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[4], dtype='int64')
        label = layers.data('label', shape=[1], dtype='float32')
        emb = layers.embedding(ids, size=[500, 8], is_sparse=is_sparse,
                               param_attr=fluid.ParamAttr(name='emb_w'))
        feat = layers.reshape(emb, [0, 4 * 8])
        pred = layers.fc(feat, 1, param_attr=fluid.ParamAttr(name='fc_w'))
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feeds(steps=8, batch=16):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(steps):
        out.append({
            'ids': rng.randint(0, 200, (batch, 4)).astype('int64'),
            'label': rng.rand(batch, 1).astype('float32')})
    return out


def _run_program(main, startup, loss, feeds):
    losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for feed in feeds:
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    return losses


def test_ps_sync_transpile_parity():
    """Sync PS trainer program == local training losses exactly: the
    sparse path moves to the host table (same per-row sgd), the dense
    path keeps its optimizer ops."""
    feeds = _feeds()
    main_l, startup_l, loss_l = _build(is_sparse=True)
    local = _run_program(main_l, startup_l, loss_l, feeds)

    HostShardedEmbedding._REGISTRY.pop('emb_w', None)
    main_d, startup_d, loss_d = _build(is_sparse=True)
    t = fluid.DistributeTranspiler(
        config=_ps_config())
    t.transpile(0, program=main_d, pservers='127.0.0.1:6174',
                trainers=1, sync_mode=True, startup_program=startup_d)
    trainer = t.get_trainer_program()
    ops = [op.type for op in trainer.global_block().ops]
    assert 'host_emb_lookup' in ops and 'host_emb_update' in ops
    assert 'lookup_table' not in ops and 'lookup_table_grad' not in ops
    dist = _run_program(trainer, startup_d, loss_d, feeds)
    np.testing.assert_allclose(local, dist, rtol=2e-4)
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)


def test_ps_async_transpile_trains():
    """Async PS: dense optimizer ops leave the trainer program, updates
    flow through the communicator (bounded staleness -> loss parity is
    approximate; assert it trains and the program shape is right)."""
    feeds = _feeds(steps=16)
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)
    main, startup, loss = _build(is_sparse=True, seed=23)
    t = fluid.DistributeTranspiler(config=_ps_config())
    t.transpile(0, program=main, pservers='127.0.0.1:6174',
                trainers=1, sync_mode=False, startup_program=startup)
    trainer = t.get_trainer_program()
    types = [op.type for op in trainer.global_block().ops]
    assert 'sgd' not in types          # dense updates moved to server
    assert 'host_emb_update' in types  # sparse push stays
    losses = _run_program(trainer, startup, loss, feeds)
    from paddle_tpu.fluid.incubate.fleet.parameter_server import fleet
    fleet.stop_worker()
    assert losses[-1] < losses[0], losses
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)


def test_ps_async_transpile_adam_rules():
    """Transpiling an Adam-minimized program moves the adam rule to
    the server (reference: per-param optimize sub-blocks with adam,
    distribute_transpiler.py:1110) — no SGD-only restriction."""
    feeds = _feeds(steps=20)
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 31
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[4], dtype='int64')
        label = layers.data('label', shape=[1], dtype='float32')
        emb = layers.embedding(ids, size=[500, 8], is_sparse=True,
                               param_attr=fluid.ParamAttr(name='emb_w'))
        feat = layers.reshape(emb, [0, 4 * 8])
        pred = layers.fc(feat, 1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.Adam(0.01, beta1=0.8).minimize(loss)
    t = fluid.DistributeTranspiler(config=_ps_config())
    t.transpile(0, program=main, pservers='127.0.0.1:6174',
                trainers=1, sync_mode=False, startup_program=startup)
    trainer = t.get_trainer_program()
    types = [op.type for op in trainer.global_block().ops]
    assert 'adam' not in types  # moved to the server
    rules = trainer._ps_async['rules']
    assert all(r['optimizer'] == 'adam' and r['beta1'] == 0.8
               for r in rules.values()), rules
    losses = _run_program(trainer, startup, loss, feeds)
    from paddle_tpu.fluid.incubate.fleet.parameter_server import fleet
    fleet.stop_worker()
    assert losses[-1] < losses[0], losses
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)


def test_ps_server_programs_are_noop():
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)
    main, startup, loss = _build(is_sparse=True, seed=29)
    t = fluid.DistributeTranspiler(config=_ps_config())
    t.transpile(0, program=main, pservers='127.0.0.1:6174', trainers=1)
    pserver = t.get_pserver_program('127.0.0.1:6174')
    pstart = t.get_startup_program('127.0.0.1:6174', pserver)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(pstart)
        exe.run(pserver)  # returns immediately (embedded server)
    HostShardedEmbedding._REGISTRY.pop('emb_w', None)


def _ps_config():
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = 'pserver'
    cfg.sync_mode = True
    return cfg
