"""Real multi-process distributed training: cluster-in-a-box.

The reference validates distribution by spawning trainer subprocesses on
127.0.0.1 and asserting loss parity against a local run
(`test_dist_base.py:510`, `test_collective_base.py:34`).  Same fixture
here: two OS processes, each a jax.distributed participant with one CPU
device, trained via the fleet collective rewrite and the
`paddle_tpu.distributed.launch` CLI; parity vs a single-process
full-batch run.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

from dist_worker import build_model, make_batches

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference(make_opt=lambda: fluid.optimizer.SGD(0.1)):
    main, startup, loss = build_model(9)
    with fluid.program_guard(main, startup):
        make_opt().minimize(loss)
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for x, y in make_batches():
            l, = exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        pname = main.all_parameters()[0].name
        param = np.asarray(scope.find_var(pname))
    return losses, param


def _launch_two_workers(tmp_path, mode, nproc=2):
    port = _free_port()
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    cmd = [sys.executable, '-m', 'paddle_tpu.distributed.launch',
           '--nproc_per_node', str(nproc), '--started_port', str(port),
           '--log_dir', str(tmp_path / 'logs'),
           os.path.join(REPO, 'tests', 'dist_worker.py'),
           str(tmp_path), mode]
    # own process group so a timeout kill reaps the workers, not just
    # the launcher
    popen = subprocess.Popen(cmd, env=env, cwd=REPO,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
    try:
        out, err = popen.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        import signal
        os.killpg(popen.pid, signal.SIGKILL)
        popen.wait()
        raise
    proc = subprocess.CompletedProcess(cmd, popen.returncode, out, err)
    if proc.returncode != 0:
        logs = ''
        logdir = tmp_path / 'logs'
        if logdir.exists():
            for f in sorted(logdir.iterdir()):
                logs += '\n==== %s ====\n' % f.name + f.read_text()[-4000:]
        raise AssertionError('launch failed rc=%d\nstdout=%s\nstderr=%s%s'
                             % (proc.returncode, proc.stdout[-2000:],
                                proc.stderr[-2000:], logs))

    results = []
    for r in range(nproc):
        with open(tmp_path / ('rank%d.json' % r)) as f:
            results.append(json.load(f))
    assert results[0]['world'] == nproc
    return results


def test_two_process_collective_parity(tmp_path):
    results = _launch_two_workers(tmp_path, 'collective')

    # SPMD invariant: both trainers hold identical updated parameters
    p0 = np.asarray(results[0]['param'])
    p1 = np.asarray(results[1]['param'])
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)

    # parity vs single-process full-batch training (reference
    # test_dist_base invariant: allreduced mean grads == full-batch grads)
    ref_losses, ref_param = _single_process_reference()
    np.testing.assert_allclose(ref_param, p0, rtol=1e-4, atol=1e-5)

    # each worker's local loss averaged across workers ~= global loss
    mean_losses = np.mean([results[0]['losses'], results[1]['losses']],
                          axis=0)
    np.testing.assert_allclose(ref_losses, mean_losses, rtol=1e-3,
                               atol=1e-4)


def test_two_process_local_sgd(tmp_path):
    """k-step LocalSGD: local replicas diverge between syncs, params
    identical across ranks at sync boundaries (reference
    transpiler/collective.py LocalSGD)."""
    results = _launch_two_workers(tmp_path, 'local_sgd')
    # 6 steps, period 2 -> final step is a sync point
    p0 = np.asarray(results[0]['param'])
    p1 = np.asarray(results[1]['param'])
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)
    # workers really trained (different local data -> finite losses)
    for r in results:
        assert np.isfinite(r['losses']).all()
    # local losses DIFFER between ranks (local training, unlike
    # grad-allreduce where every rank computes on its own shard too)
    assert results[0]['losses'] != results[1]['losses']


def _dygraph_reference():
    """Single-process full-batch eager training mirroring the dygraph
    worker."""
    from paddle_tpu.fluid.dygraph import Linear, to_variable
    from paddle_tpu.fluid.framework import _dygraph_tracer

    class Net(fluid.dygraph.Layer):
        def __init__(self):
            super(Net, self).__init__()
            self.fc1 = Linear(8, 16, act='relu')
            self.fc2 = Linear(16, 1)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    losses = []
    with fluid.dygraph.guard():
        np.random.seed(17)
        net = Net()
        opt = fluid.optimizer.SGD(0.1)
        for x, _ in make_batches():
            y = x.sum(1, keepdims=True).astype('float32')
            xv, yv = to_variable(x), to_variable(y)
            diff = net(xv) - yv
            loss = _dygraph_tracer().trace_op(
                'mean', {'X': [diff * diff]})['Out'][0]
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            for p in net.parameters():
                p.clear_gradient()
            losses.append(float(np.asarray(loss.value).ravel()[0]))
        w = np.asarray(net.fc1.weight.value)
    return losses, w


def test_two_process_dygraph_dataparallel_parity(tmp_path):
    """Eager DataParallel (scale_loss + apply_collective_grads) across
    two real processes — reference parallel_dygraph_mnist fixture."""
    results = _launch_two_workers(tmp_path, 'dygraph')

    p0 = np.asarray(results[0]['param'])
    p1 = np.asarray(results[1]['param'])
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)

    ref_losses, ref_param = _dygraph_reference()
    np.testing.assert_allclose(ref_param, p0, rtol=1e-4, atol=1e-5)
    # scaled local losses: sum across workers ~= full-batch loss
    sum_losses = np.sum([results[0]['losses'], results[1]['losses']],
                        axis=0)
    np.testing.assert_allclose(ref_losses, sum_losses, rtol=1e-3,
                               atol=1e-4)


def test_two_process_gspmd_zero_parity(tmp_path):
    """CompiledProgram GSPMD DP + ZeRO-sharded Momentum accumulators
    across two real processes."""
    results = _launch_two_workers(tmp_path, 'gspmd')

    p0 = np.asarray(results[0]['param'])
    p1 = np.asarray(results[1]['param'])
    np.testing.assert_allclose(p0, p1, rtol=1e-6, atol=1e-7)

    ref_losses, ref_param = _single_process_reference(
        lambda: fluid.optimizer.Momentum(0.1, momentum=0.9))
    np.testing.assert_allclose(ref_param, p0, rtol=1e-4, atol=1e-5)
    # GSPMD fetch is the global mean loss
    np.testing.assert_allclose(
        ref_losses, results[0]['losses'], rtol=1e-3, atol=1e-4)


def test_two_process_sparse_ps_parity(tmp_path):
    """The SPARSE path across 2 real processes: the embedding table is
    sharded by id (owner = id % world), pull gathers rows from owners,
    push routes merged row-grads back — loss parity with a
    single-process full-batch run of the same model (VERDICT round-1
    'done' criterion for the multi-process sparse PS)."""
    from dist_worker import build_sparse_model, make_sparse_batches
    from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding

    results = _launch_two_workers(tmp_path, 'sparse_ps')

    # single-process full-batch reference (same seeds, world=1)
    HostShardedEmbedding._REGISTRY.pop('dist_sparse_emb', None)
    main, startup, loss, emb = build_sparse_model(9)
    assert emb.world == 1
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(0.1).minimize(loss)
        emb.apply_gradients(main)
    ref_losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for ids, y in make_sparse_batches():
            l, = exe.run(main, feed={'ids': ids, 'label': y},
                         fetch_list=[loss])
            ref_losses.append(float(np.asarray(l).ravel()[0]))

    # mean of the two workers' per-shard losses == full-batch loss
    mean_losses = [(a + b) / 2.0 for a, b in
                   zip(results[0]['losses'], results[1]['losses'])]
    np.testing.assert_allclose(mean_losses, ref_losses, rtol=2e-4)

    # table parity: worker rank r owns global ids {r, r+2, ...}; its
    # local row j is global id 2j+r — compare against the reference
    full = emb.table
    for r in range(2):
        shard = np.asarray(results[r]['param'])
        want = full[r::2][:shard.shape[0]]
        np.testing.assert_allclose(shard, want, rtol=2e-4, atol=1e-6)
    HostShardedEmbedding._REGISTRY.pop('dist_sparse_emb', None)


def test_four_process_collective_parity(tmp_path):
    """nproc=4 (the VERDICT round-1 gap: multi-process coverage beyond
    2): four real trainer processes, fleet collective GradAllReduce,
    loss parity with single-process full-batch training."""
    results = _launch_two_workers(tmp_path, 'collective', nproc=4)
    params = [np.asarray(r['param']) for r in results]
    for p in params[1:]:
        np.testing.assert_allclose(params[0], p, rtol=1e-6, atol=1e-7)
    ref_losses, _ = _single_process_reference()
    mean_losses = [sum(r['losses'][i] for r in results) / 4.0
                   for i in range(len(ref_losses))]
    np.testing.assert_allclose(mean_losses, ref_losses, rtol=2e-4)


def test_multiprocess_multiaxis_mesh_parity(tmp_path):
    """Multi-process x multi-axis (the other VERDICT round-1 gap): 2
    processes x 2 local devices = a (dp=2, mp=2) mesh spanning
    processes; batch dp-sharded, fc weights mp-sharded; loss parity
    with single-process full-batch SGD."""
    results = _launch_two_workers(tmp_path, 'gspmd_mp', nproc=2)
    ref_losses, _ = _single_process_reference(
        make_opt=lambda: fluid.optimizer.SGD(0.1))
    for r in results:
        np.testing.assert_allclose(r['losses'], ref_losses, rtol=2e-4)
