"""Per-op tests: math / elementwise / reduction ops.

Mirrors reference tests test_matmul_op.py, test_elementwise_*_op.py,
test_reduce_op.py etc. (python/paddle/fluid/tests/unittests/).
"""

import numpy as np
import pytest

from op_test import OpTest


rng = np.random.RandomState(42)


class TestMatmul(OpTest):
    def test_basic(self):
        x = rng.randn(4, 5).astype('float32')
        y = rng.randn(5, 3).astype('float32')
        self.check_output('matmul', {'X': x, 'Y': y},
                          expect={'Out': x @ y})

    def test_transpose(self):
        x = rng.randn(5, 4).astype('float32')
        y = rng.randn(3, 5).astype('float32')
        self.check_output('matmul', {'X': x, 'Y': y},
                          attrs={'transpose_X': True, 'transpose_Y': True},
                          expect={'Out': x.T @ y.T})

    def test_batched(self):
        x = rng.randn(2, 4, 5).astype('float32')
        y = rng.randn(2, 5, 3).astype('float32')
        self.check_output('matmul', {'X': x, 'Y': y},
                          expect={'Out': np.matmul(x, y)})

    def test_grad(self):
        x = rng.randn(3, 4).astype('float32')
        y = rng.randn(4, 2).astype('float32')
        self.check_grad('matmul', {'X': x, 'Y': y})


class TestMul(OpTest):
    def test_flatten(self):
        x = rng.randn(2, 3, 4).astype('float32')
        y = rng.randn(12, 5).astype('float32')
        self.check_output('mul', {'X': x, 'Y': y},
                          attrs={'x_num_col_dims': 1, 'y_num_col_dims': 1},
                          expect={'Out': x.reshape(2, 12) @ y})

    def test_grad(self):
        x = rng.randn(3, 4).astype('float32')
        y = rng.randn(4, 2).astype('float32')
        self.check_grad('mul', {'X': x, 'Y': y})


class TestElementwise(OpTest):
    def test_add_broadcast_axis(self):
        x = rng.randn(2, 3, 4).astype('float32')
        y = rng.randn(3,).astype('float32')
        self.check_output('elementwise_add', {'X': x, 'Y': y},
                          attrs={'axis': 1},
                          expect={'Out': x + y.reshape(1, 3, 1)})

    def test_ops(self):
        x = rng.randn(3, 4).astype('float32')
        y = rng.rand(3, 4).astype('float32') + 0.5
        for op, fn in [('elementwise_add', np.add),
                       ('elementwise_sub', np.subtract),
                       ('elementwise_mul', np.multiply),
                       ('elementwise_div', np.divide),
                       ('elementwise_min', np.minimum),
                       ('elementwise_max', np.maximum)]:
            self.check_output(op, {'X': x, 'Y': y},
                              expect={'Out': fn(x, y)})

    def test_grad_broadcast(self):
        x = rng.randn(2, 3).astype('float32')
        y = rng.randn(3,).astype('float32')
        self.check_grad('elementwise_add', {'X': x, 'Y': y},
                        attrs={'axis': -1})
        self.check_grad('elementwise_mul', {'X': x, 'Y': y},
                        attrs={'axis': -1})


class TestReduce(OpTest):
    def test_all(self):
        x = rng.randn(3, 4, 5).astype('float32')
        self.check_output('reduce_sum', {'X': x},
                          attrs={'reduce_all': True},
                          expect={'Out': x.sum()})
        self.check_output('reduce_mean', {'X': x},
                          attrs={'dim': [1], 'keep_dim': True},
                          expect={'Out': x.mean(1, keepdims=True)})
        self.check_output('reduce_max', {'X': x}, attrs={'dim': [-1]},
                          expect={'Out': x.max(-1)})

    def test_grad(self):
        x = rng.randn(3, 4).astype('float32')
        self.check_grad('reduce_sum', {'X': x}, attrs={'dim': [1]})
        self.check_grad('reduce_mean', {'X': x},
                        attrs={'reduce_all': True})


class TestActivations(OpTest):
    def test_forward(self):
        x = rng.randn(3, 4).astype('float32')
        self.check_output('relu', {'X': x},
                          expect={'Out': np.maximum(x, 0)})
        self.check_output('sigmoid', {'X': x},
                          expect={'Out': 1 / (1 + np.exp(-x))})
        self.check_output('tanh', {'X': x}, expect={'Out': np.tanh(x)})
        self.check_output('square', {'X': x}, expect={'Out': x * x})
        self.check_output('leaky_relu', {'X': x}, attrs={'alpha': 0.1},
                          expect={'Out': np.where(x > 0, x, 0.1 * x)})

    def test_grad(self):
        x = (rng.randn(3, 4) + 2.0).astype('float32')  # keep off kinks
        for op in ('sigmoid', 'tanh', 'exp', 'square', 'softplus'):
            self.check_grad(op, {'X': x})


class TestSoftmax(OpTest):
    def test_forward(self):
        x = rng.randn(3, 5).astype('float32')
        e = np.exp(x - x.max(-1, keepdims=True))
        self.check_output('softmax', {'X': x},
                          expect={'Out': e / e.sum(-1, keepdims=True)})

    def test_grad(self):
        x = rng.randn(2, 4).astype('float32')
        self.check_grad('softmax', {'X': x})


class TestScaleClip(OpTest):
    def test_scale(self):
        x = rng.randn(3, 4).astype('float32')
        self.check_output('scale', {'X': x},
                          attrs={'scale': 2.5, 'bias': 1.0},
                          expect={'Out': x * 2.5 + 1.0})

    def test_clip(self):
        x = rng.randn(3, 4).astype('float32')
        self.check_output('clip', {'X': x},
                          attrs={'min': -0.5, 'max': 0.5},
                          expect={'Out': np.clip(x, -0.5, 0.5)})


class TestCompare(OpTest):
    def test_cmp(self):
        x = rng.randn(3, 4).astype('float32')
        y = rng.randn(3, 4).astype('float32')
        self.check_output('less_than', {'X': x, 'Y': y},
                          expect={'Out': x < y})
        self.check_output('equal', {'X': x, 'Y': x},
                          expect={'Out': np.ones_like(x, bool)})


class TestTopK(OpTest):
    def test_topk(self):
        x = rng.randn(4, 10).astype('float32')
        got = self.run_op('top_k', {'X': x}, attrs={'k': 3},
                          out_slots=('Out', 'Indices'))
        expect = np.sort(x, axis=-1)[:, ::-1][:, :3]
        np.testing.assert_allclose(got['Out'], expect, rtol=1e-5)


class TestArgMax(OpTest):
    def test_argmax(self):
        x = rng.randn(4, 7).astype('float32')
        self.check_output('arg_max', {'X': x}, attrs={'axis': 1},
                          expect={'Out': x.argmax(1)})


class TestSum(OpTest):
    def test_sum_n(self):
        xs = [('a', rng.randn(3, 4).astype('float32')),
              ('b', rng.randn(3, 4).astype('float32')),
              ('c', rng.randn(3, 4).astype('float32'))]
        self.check_output('sum', {'X': xs},
                          expect={'Out': sum(a for _, a in xs)})
