"""Detection op tests vs numpy references."""

import numpy as np
import pytest

from paddle_tpu.ops import registry

ctx = registry.LowerCtx(0)
rng = np.random.RandomState(0)


def test_iou_similarity():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], 'float32')
    y = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], 'float32')
    out = np.asarray(registry.get('iou_similarity').fn(
        ctx, {'X': [x], 'Y': [y]}, {})['Out'][0])
    np.testing.assert_allclose(out[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[1, 0], 1 / 7, rtol=1e-5)
    np.testing.assert_allclose(out[1, 1], 1 / 7, rtol=1e-5)


def test_box_coder_roundtrip():
    prior = np.array([[0, 0, 4, 4], [2, 2, 8, 10]], 'float32')
    target = np.array([[1, 1, 3, 3], [2, 3, 9, 9]], 'float32')
    enc = np.asarray(registry.get('box_coder').fn(
        ctx, {'PriorBox': [prior], 'TargetBox': [target]},
        {'code_type': 'encode_center_size'})['OutputBox'][0])
    dec = np.asarray(registry.get('box_coder').fn(
        ctx, {'PriorBox': [prior], 'TargetBox': [enc[None]]},
        {'code_type': 'decode_center_size'})['OutputBox'][0])
    np.testing.assert_allclose(dec[0], target, rtol=1e-4, atol=1e-4)


def test_prior_box_shapes():
    feat = np.zeros((1, 8, 4, 4), 'float32')
    img = np.zeros((1, 3, 64, 64), 'float32')
    out = registry.get('prior_box').fn(
        ctx, {'Input': [feat], 'Image': [img]},
        {'min_sizes': [16.0], 'max_sizes': [32.0],
         'aspect_ratios': [2.0], 'flip': True})
    boxes = np.asarray(out['Boxes'][0])
    assert boxes.shape == (4, 4, 4, 4)  # 1 + 2 flipped ars + 1 max size
    assert (boxes[..., 2] >= boxes[..., 0]).all()


def test_multiclass_nms_suppresses():
    # two overlapping boxes + one distinct, single class
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                       [50, 50, 60, 60]]], 'float32')
    scores = np.array([[[0.9, 0.8, 0.7]]], 'float32')
    out = np.asarray(registry.get('multiclass_nms').fn(
        ctx, {'BBoxes': [boxes], 'Scores': [scores]},
        {'score_threshold': 0.1, 'nms_threshold': 0.5,
         'keep_top_k': 3, 'nms_top_k': 3})['Out'][0])
    valid = out[0][out[0, :, 0] >= 0]
    assert valid.shape[0] == 2  # overlapping pair suppressed to one
    np.testing.assert_allclose(sorted(valid[:, 1].tolist()),
                               [0.7, 0.9], rtol=1e-5)


def test_yolo_box_shapes():
    x = rng.randn(2, 3 * 7, 4, 4).astype('float32')
    img = np.array([[416, 416], [320, 480]], 'int32')
    out = registry.get('yolo_box').fn(
        ctx, {'X': [x], 'ImgSize': [img]},
        {'anchors': [10, 13, 16, 30, 33, 23], 'class_num': 2,
         'conf_thresh': 0.0, 'downsample_ratio': 32})
    assert np.asarray(out['Boxes'][0]).shape == (2, 48, 4)
    assert np.asarray(out['Scores'][0]).shape == (2, 48, 2)


def test_roi_align_identity():
    # a constant image must pool to the constant
    x = np.full((1, 2, 8, 8), 3.5, 'float32')
    rois = np.array([[0, 0, 8, 8]], 'float32')
    out = np.asarray(registry.get('roi_align').fn(
        ctx, {'X': [x], 'ROIs': [rois]},
        {'pooled_height': 2, 'pooled_width': 2,
         'spatial_scale': 1.0})['Out'][0])
    np.testing.assert_allclose(out, np.full((1, 2, 2, 2), 3.5),
                               rtol=1e-5)
