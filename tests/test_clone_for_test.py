"""clone(for_test=True) must prune backward/optimize ops so eval never
mutates state (reference: Program.clone framework.py:3839 +
core.prune_backward)."""

import numpy as np

import paddle_tpu.fluid as fluid


def _net():
    x = fluid.layers.data('x', shape=[8], dtype='float32')
    y = fluid.layers.data('y', shape=[1], dtype='float32')
    h = fluid.layers.fc(x, 16, act='relu')
    pred = fluid.layers.fc(h, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return loss


def _batch(rng, n=16):
    xs = rng.randn(n, 8).astype('float32')
    ys = rng.randn(n, 1).astype('float32')
    return xs, ys


def _eval_twice(test_prog, startup, loss, feed):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        e1, = exe.run(test_prog, feed=feed, fetch_list=[loss])
        e2, = exe.run(test_prog, feed=feed, fetch_list=[loss])
    return float(np.asarray(e1).ravel()[0]), float(np.asarray(e2).ravel()[0])


def test_clone_prunes_optimizer():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        loss = _net()
        fluid.optimizer.Adam(0.1).minimize(loss)
    t = main.clone(for_test=True)
    assert all(op.attrs.get('__op_role__') == 'forward'
               for op in t.global_block().ops)
    rng = np.random.RandomState(0)
    xs, ys = _batch(rng)
    e1, e2 = _eval_twice(t, startup, loss, {'x': xs, 'y': ys})
    assert e1 == e2, (e1, e2)


def test_clone_prunes_amp_ops():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        loss = _net()
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(0.1), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    t = main.clone(for_test=True)
    kept = [op.type for op in t.global_block().ops]
    assert 'check_finite_and_unscale' not in kept
    assert 'update_loss_scaling' not in kept
    rng = np.random.RandomState(1)
    xs, ys = _batch(rng)
    e1, e2 = _eval_twice(t, startup, loss, {'x': xs, 'y': ys})
    assert e1 == e2, (e1, e2)


def test_clone_prunes_model_average():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        loss = _net()
        fluid.optimizer.SGD(0.1).minimize(loss)
        fluid.optimizer.ModelAverage(0.15)
    t = main.clone(for_test=True)
    kept = [op.type for op in t.global_block().ops]
    assert 'increment' not in kept
