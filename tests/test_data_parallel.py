"""Data-parallel loss parity: multi-device vs single-device.

Mirrors the reference fixture parallel_executor_test_base.py (compare
ParallelExecutor losses against single-device Executor on the same seed)
and test_dist_base.py:510 (distributed vs local loss parity) — here on the
8-device CPU mesh.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def build_model(seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 32, act='relu')
        h2 = fluid.layers.fc(h, 16, act='relu')
        logits = fluid.layers.fc(h2, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
    return main, startup, loss


def make_batches(steps=6, n=16):
    rng = np.random.RandomState(5)
    out = []
    for _ in range(steps):
        x = rng.randn(n, 8).astype('float32')
        y = (np.abs(x).sum(1, keepdims=True) * 2
             ).astype('int64') % 4
        out.append((x, y))
    return out


def train(program_runner, main, startup, loss, batches, opt):
    with fluid.program_guard(main, startup):
        opt.minimize(loss)
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for x, y in batches:
            l, = program_runner(exe, main,
                                {'x': x, 'y': y}, [loss])
            losses.append(float(l))
        pname = main.all_parameters()[0].name
        final_param = np.asarray(scope.find_var(pname))
    return losses, final_param


def _single(exe, main, feed, fetch):
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_gspmd_data_parallel_loss_parity():
    batches = make_batches()
    m1, s1, l1 = build_model(3)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.SGD(0.1))

    m2, s2, l2 = build_model(3)

    compiled_box = {}

    def _parallel(exe, main, feed, fetch):
        if 'cp' not in compiled_box:
            compiled_box['cp'] = fluid.CompiledProgram(
                main).with_data_parallel(loss_name=l2.name)
        return exe.run(compiled_box['cp'], feed=feed, fetch_list=fetch)

    par, par_p = train(_parallel, m2, s2, l2, batches,
                       fluid.optimizer.SGD(0.1))
    np.testing.assert_allclose(ref, par, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ref_p, par_p, rtol=1e-4, atol=1e-5)
    assert par[-1] < par[0]


def test_fleet_collective_loss_parity():
    from paddle_tpu.fluid.incubate.fleet.collective import fleet, \
        DistributedStrategy
    from paddle_tpu.fluid.incubate.fleet.base import role_maker

    batches = make_batches()
    m1, s1, l1 = build_model(9)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.SGD(0.1))

    m2, s2, l2 = build_model(9)
    fleet.init(role_maker.PaddleCloudRoleMaker())
    with fluid.program_guard(m2, s2):
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), DistributedStrategy())
        opt.minimize(l2)
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(s2)
        for x, y in batches:
            l, = exe.run(m2, feed={'x': x, 'y': y}, fetch_list=[l2])
            losses.append(float(l))
        pname = m2.all_parameters()[0].name
        col_p = np.asarray(scope.find_var(pname))
    # collective mode fetches a device-local loss (2-sample shard, not the
    # global mean) — matching the reference, which fetches trainer-0's
    # loss.  The real invariant is identical parameter updates:
    # allreduced mean grads == single-device full-batch grads.
    np.testing.assert_allclose(ref_p, col_p, rtol=1e-4, atol=1e-5)


def test_fleet_local_sgd_single_process_parity():
    """In-graph LocalSGD (single-process multi-device): local SGD step
    then param averaging == gradient allreduce for SGD (the update is
    linear in the grad), so it must match single-device full batch."""
    from paddle_tpu.fluid.incubate.fleet.collective import fleet, \
        DistributedStrategy
    from paddle_tpu.fluid.incubate.fleet.base import role_maker

    batches = make_batches()
    m1, s1, l1 = build_model(21)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.SGD(0.1))

    m2, s2, l2 = build_model(21)
    fleet.init(role_maker.PaddleCloudRoleMaker())
    strategy = DistributedStrategy()
    strategy.use_local_sgd = True
    with fluid.program_guard(m2, s2):
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1),
                                          strategy)
        opt.minimize(l2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(s2)
        for x, y in batches:
            exe.run(m2, feed={'x': x, 'y': y}, fetch_list=[l2])
        pname = m2.all_parameters()[0].name
        lsgd_p = np.asarray(scope.find_var(pname))
    np.testing.assert_allclose(ref_p, lsgd_p, rtol=1e-4, atol=1e-5)


def test_fleet_local_sgd_momentum_parity():
    """Stateful optimizer under in-graph LocalSGD: velocity accumulators
    are averaged alongside params (both are linear in the grad, so this
    equals synchronous momentum = single-device full batch)."""
    from paddle_tpu.fluid.incubate.fleet.collective import fleet, \
        DistributedStrategy
    from paddle_tpu.fluid.incubate.fleet.base import role_maker

    batches = make_batches()
    m1, s1, l1 = build_model(23)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.Momentum(0.1, momentum=0.9))

    m2, s2, l2 = build_model(23)
    fleet.init(role_maker.PaddleCloudRoleMaker())
    strategy = DistributedStrategy()
    strategy.use_local_sgd = True
    with fluid.program_guard(m2, s2):
        opt = fleet.distributed_optimizer(
            fluid.optimizer.Momentum(0.1, momentum=0.9), strategy)
        opt.minimize(l2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(s2)
        for x, y in batches:
            exe.run(m2, feed={'x': x, 'y': y}, fetch_list=[l2])
        pname = m2.all_parameters()[0].name
        lsgd_p = np.asarray(scope.find_var(pname))
    np.testing.assert_allclose(ref_p, lsgd_p, rtol=1e-4, atol=1e-5)


def test_collective_ops_semantics():
    """c_allreduce/c_allgather/c_broadcast inside shard_map match numpy."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.ops import registry

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ('dp',))
    n = len(devs)
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)

    def body(xs):
        ctx = registry.LowerCtx(0)
        ar = registry.get('c_allreduce_sum').fn(
            ctx, {'X': [xs]}, {'ring_id': 0})['Out'][0]
        mx = registry.get('c_allreduce_max').fn(
            ctx, {'X': [xs]}, {'ring_id': 0})['Out'][0]
        ag = registry.get('c_allgather').fn(
            ctx, {'X': [xs]}, {'ring_id': 0, 'nranks': n})['Out'][0]
        bc = registry.get('c_broadcast').fn(
            ctx, {'X': [xs]}, {'ring_id': 0, 'root': 2})['Out'][0]
        return ar, mx, ag, bc

    from paddle_tpu.compat import shard_map
    f = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P('dp'),),
        out_specs=(P(), P(), P(), P('dp'))))
    ar, mx, ag, bc = f(x)
    np.testing.assert_allclose(np.asarray(ar).reshape(3), x.sum(0))
    np.testing.assert_allclose(np.asarray(mx).reshape(3), x.max(0))
    np.testing.assert_allclose(np.asarray(ag), x)
    np.testing.assert_allclose(np.asarray(bc),
                               np.tile(x[2], (n, 1)))


def test_zero_sharded_optimizer_states_parity():
    """ZeRO-1 weight-update sharding: same losses/params as replicated."""
    batches = make_batches()
    m1, s1, l1 = build_model(21)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.Adam(0.01))

    m2, s2, l2 = build_model(21)
    box = {}

    def _zero(exe, main, feed, fetch):
        if 'cp' not in box:
            box['cp'] = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=l2.name).with_sharded_optimizer_states()
        return exe.run(box['cp'], feed=feed, fetch_list=fetch)

    par, par_p = train(_zero, m2, s2, l2, batches,
                       fluid.optimizer.Adam(0.01))
    np.testing.assert_allclose(ref, par, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ref_p, par_p, rtol=1e-4, atol=1e-5)


def test_reduce_strategy_maps_to_zero_sharding():
    """BuildStrategy ReduceStrategy.Reduce -> ZeRO-style sharded
    optimizer states (the kReduce param-ownership analog), with full
    loss parity."""
    batches = make_batches()
    m1, s1, l1 = build_model(31)
    ref, ref_p = train(_single, m1, s1, l1, batches,
                       fluid.optimizer.Momentum(0.1, momentum=0.9))

    m2, s2, l2 = build_model(31)
    bs = fluid.BuildStrategy()
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    box = {}

    def _parallel(exe, main, feed, fetch):
        if 'cp' not in box:
            box['cp'] = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=l2.name, build_strategy=bs)
            assert box['cp']._shard_opt_states_axis is not None
        return exe.run(box['cp'], feed=feed, fetch_list=fetch)

    par, par_p = train(_parallel, m2, s2, l2, batches,
                       fluid.optimizer.Momentum(0.1, momentum=0.9))
    np.testing.assert_allclose(ref, par, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ref_p, par_p, rtol=1e-4, atol=1e-5)
