"""CRF / CTC / NCE / hsigmoid / beam search / edit distance op tests.

Mirrors the reference's OpTest style (op_test.py): numpy reference
implementations (brute force where feasible) vs the op lowerings."""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import registry


def run_op(op_type, ins, attrs=None):
    d = registry.get(op_type)
    from paddle_tpu.ops.registry import LowerCtx
    ctx = LowerCtx(step=jnp.asarray(0, jnp.int32), op_seed=7)
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return d.fn(ctx, ins, dict(attrs or {}))


# ----------------------------------------------------------------- CRF

def crf_brute(x, trans, label, length):
    """Brute-force -log p(label) by enumerating all tag paths."""
    d = x.shape[-1]
    w_start, w_end, w = trans[0], trans[1], trans[2:]

    def score(path):
        s = w_start[path[0]] + x[0, path[0]] + w_end[path[-1]]
        for k in range(1, len(path)):
            s += x[k, path[k]] + w[path[k - 1], path[k]]
        return s

    logz = None
    for path in itertools.product(range(d), repeat=length):
        s = score(path)
        logz = s if logz is None else np.logaddexp(logz, s)
    return logz - score(tuple(label[:length]))


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(0)
    b, t, d = 3, 5, 4
    x = rng.randn(b, t, d).astype('float32')
    trans = rng.randn(d + 2, d).astype('float32')
    label = rng.randint(0, d, (b, t)).astype('int64')
    length = np.array([5, 3, 1], 'int64')
    out = run_op('linear_chain_crf',
                 {'Emission': [x], 'Transition': [trans],
                  'Label': [label], 'Length': [length]})
    nll = np.asarray(out['LogLikelihood'][0]).ravel()
    for i in range(b):
        want = crf_brute(x[i], trans, label[i], int(length[i]))
        np.testing.assert_allclose(nll[i], want, rtol=1e-4, atol=1e-4)


def test_crf_decoding_matches_brute_force():
    rng = np.random.RandomState(1)
    b, t, d = 2, 4, 3
    x = rng.randn(b, t, d).astype('float32')
    trans = rng.randn(d + 2, d).astype('float32')
    length = np.array([4, 2], 'int64')
    out = run_op('crf_decoding',
                 {'Emission': [x], 'Transition': [trans],
                  'Length': [length]})
    path = np.asarray(out['ViterbiPath'][0])
    w_start, w_end, w = trans[0], trans[1], trans[2:]
    for i in range(b):
        ln = int(length[i])
        best, best_path = None, None
        for p in itertools.product(range(d), repeat=ln):
            s = w_start[p[0]] + x[i, 0, p[0]] + w_end[p[-1]]
            for k in range(1, ln):
                s += x[i, k, p[k]] + w[p[k - 1], p[k]]
            if best is None or s > best:
                best, best_path = s, p
        assert tuple(path[i, :ln]) == best_path
        assert (path[i, ln:] == 0).all()


def test_crf_gradient_flows():
    rng = np.random.RandomState(2)
    b, t, d = 2, 4, 3
    x = jnp.asarray(rng.randn(b, t, d).astype('float32'))
    trans = jnp.asarray(rng.randn(d + 2, d).astype('float32'))
    label = jnp.asarray(rng.randint(0, d, (b, t)).astype('int32'))
    length = jnp.asarray(np.array([4, 3], 'int32'))

    def loss(x, trans):
        out = run_op('linear_chain_crf',
                     {'Emission': [x], 'Transition': [trans],
                      'Label': [label], 'Length': [length]})
        return jnp.mean(out['LogLikelihood'][0])

    gx, gt = jax.grad(loss, argnums=(0, 1))(x, trans)
    assert np.isfinite(np.asarray(gx)).all()
    assert np.isfinite(np.asarray(gt)).all()
    assert float(jnp.abs(gx).sum()) > 0
    # padded tail of seq 1 (len 3 of 4) must get zero emission grad
    assert float(jnp.abs(gx[1, 3]).sum()) == 0.0


# ----------------------------------------------------------------- chunk_eval

def test_chunk_eval_iob():
    # IOB, 2 chunk types: tags B-0=0 I-0=1 B-1=2 I-1=3 O=4
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inf = fluid.layers.data('inf', shape=[6], dtype='int64')
        lab = fluid.layers.data('lab', shape=[6], dtype='int64')
        ln = fluid.layers.data('ln', shape=[1], dtype='int64')
        p, r, f1, ni, nl, nc = fluid.layers.chunk_eval(
            inf, lab, chunk_scheme='IOB', num_chunk_types=2,
            seq_length=ln)
    label = np.array([[0, 1, 4, 2, 3, 4]], 'int64')   # chunks: (0,1,t0) (3,4,t1)
    infer = np.array([[0, 1, 4, 2, 4, 4]], 'int64')   # chunks: (0,1,t0) (3,3,t1)
    length = np.array([[6]], 'int64')
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        pv, rv, fv, niv, nlv, ncv = exe.run(
            main, feed={'inf': infer, 'lab': label, 'ln': length},
            fetch_list=[p, r, f1, ni, nl, nc])
    assert int(niv[0]) == 2 and int(nlv[0]) == 2 and int(ncv[0]) == 1
    assert abs(float(pv[0]) - 0.5) < 1e-6
    assert abs(float(rv[0]) - 0.5) < 1e-6
    assert abs(float(fv[0]) - 0.5) < 1e-6


# ----------------------------------------------------------------- CTC

def test_warpctc_matches_manual_simple():
    # Single frame, single label u: loss = -log softmax(logits)[u]
    rng = np.random.RandomState(3)
    logits = rng.randn(2, 1, 5).astype('float32')
    label = np.array([[2], [4]], 'int64')
    out = run_op('warpctc', {'Logits': [logits], 'Label': [label]},
                 {'blank': 0})
    loss = np.asarray(out['Loss'][0]).ravel()
    p = np.exp(logits[:, 0]) / np.exp(logits[:, 0]).sum(-1, keepdims=True)
    want = -np.log(p[np.arange(2), label.ravel()])
    np.testing.assert_allclose(loss, want, rtol=1e-4, atol=1e-4)


def test_warpctc_grad_and_lengths():
    rng = np.random.RandomState(4)
    logits = jnp.asarray(rng.randn(2, 6, 5).astype('float32'))
    label = jnp.asarray(np.array([[1, 2, 0], [3, 0, 0]], 'int32'))
    lo_len = jnp.asarray(np.array([6, 4], 'int32'))
    la_len = jnp.asarray(np.array([2, 1], 'int32'))

    def loss_fn(lg):
        out = run_op('warpctc',
                     {'Logits': [lg], 'Label': [label],
                      'LogitsLength': [lo_len], 'LabelLength': [la_len]},
                     {'blank': 0})
        return jnp.sum(out['Loss'][0])

    g = jax.grad(loss_fn)(logits)
    assert np.isfinite(np.asarray(g)).all()
    # frames beyond logit length get no gradient
    assert float(jnp.abs(g[1, 4:]).sum()) == 0.0


def test_ctc_align():
    x = np.array([[1, 1, 0, 2, 2, 0, 3],
                  [0, 4, 4, 4, 0, 0, 5]], 'int64')
    out = run_op('ctc_align', {'Input': [x]}, {'blank': 0})
    got = np.asarray(out['Output'][0])
    ln = np.asarray(out['OutputLength'][0]).ravel()
    assert list(got[0, :3]) == [1, 2, 3] and ln[0] == 3
    assert list(got[1, :2]) == [4, 5] and ln[1] == 2
    assert (got[0, 3:] == 0).all()


def test_edit_distance():
    import difflib  # noqa: F401  (manual expected values below)
    hyp = np.array([[1, 2, 3, 0], [1, 1, 1, 1]], 'int64')
    ref = np.array([[1, 3, 3], [2, 2, 2]], 'int64')
    h_len = np.array([3, 4], 'int64')
    r_len = np.array([3, 3], 'int64')
    out = run_op('edit_distance',
                 {'Hyps': [hyp], 'Refs': [ref],
                  'HypsLength': [h_len], 'RefsLength': [r_len]},
                 {'normalized': False})
    d = np.asarray(out['Out'][0]).ravel()
    assert d[0] == 1.0   # substitute 2->3
    assert d[1] == 4.0   # 3 substitutions + 1 deletion
    assert int(np.asarray(out['SequenceNum'][0])[0]) == 2


# ----------------------------------------------------------------- sampling

def test_nce_trains_word2vec_style():
    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        lab = fluid.layers.data('lab', shape=[1], dtype='int64')
        cost = fluid.layers.nce(x, lab, num_total_classes=20,
                                num_neg_samples=5)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(0.5).minimize(loss)
    emb = rng.randn(20, 8).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for i in range(40):
            ids = rng.randint(0, 20, (32,))
            feed = {'x': emb[ids], 'lab': ids[:, None].astype('int64')}
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_nce_log_uniform_sampler():
    """Zipfian negative sampler (reference math/sampler.cc
    LogUniformSampler): trains, and the drawn negatives follow the
    log-uniform marginal (low ids much more frequent than high)."""
    rng = np.random.RandomState(5)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        lab = fluid.layers.data('lab', shape=[1], dtype='int64')
        cost = fluid.layers.nce(x, lab, num_total_classes=50,
                                num_neg_samples=8,
                                sampler='log_uniform')
        loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(0.5).minimize(loss)
    emb = rng.randn(50, 8).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for i in range(40):
            ids = rng.randint(0, 50, (32,))
            feed = {'x': emb[ids], 'lab': ids[:, None].astype('int64')}
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # marginal check on the OP's own negatives: SampleLabels carries
    # [true, negatives]; under log-uniform with v=1000,
    # P(id < 10) = log(11)/log(1001) ~ 0.347
    v, b, k = 1000, 200, 100
    out = run_op('nce',
                 {'Input': [np.ones((b, 4), 'float32')],
                  'Weight': [np.zeros((v, 4), 'float32')],
                  'Label': [np.zeros((b, 1), 'int64')]},
                 {'num_total_classes': v, 'num_neg_samples': k,
                  'sampler': 'log_uniform', 'seed': 3})
    neg = np.asarray(out['SampleLabels'][0])[:, 1:]
    assert neg.shape == (b, k)
    assert (neg >= 0).all() and (neg < v).all()
    frac = (neg < 10).mean()
    assert 0.30 < frac < 0.40, frac


def test_hsigmoid_loss_decreases_and_path_math():
    # path math: num_classes=4 -> codes 4..7, length 2
    from paddle_tpu.ops.lang_ops import hierarchical_sigmoid  # noqa: F401
    rng = np.random.RandomState(6)
    x = rng.randn(3, 4).astype('float32')
    w = rng.randn(3, 4).astype('float32')
    bias = rng.randn(3).astype('float32')
    label = np.array([0, 2, 3], 'int64')
    out = run_op('hierarchical_sigmoid',
                 {'X': [x], 'W': [w], 'Bias': [bias], 'Label': [label]},
                 {'num_classes': 4})
    got = np.asarray(out['Out'][0]).ravel()
    # manual: code=label+4; bits b=0,1; node=(code>>(b+1))-1; bit=(code>>b)&1
    for i, lb in enumerate(label):
        code = lb + 4
        want = 0.0
        for b in range(2):
            node = (code >> (b + 1)) - 1
            bit = (code >> b) & 1
            z = x[i] @ w[node] + bias[node]
            want += np.log1p(np.exp(z)) - bit * z
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-4)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 10
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data('x', shape=[8], dtype='float32')
        lab = fluid.layers.data('lab', shape=[1], dtype='int64')
        cost = fluid.layers.hsigmoid(xv, lab, num_classes=16)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(0.5).minimize(loss)
    feats = rng.randn(16, 8).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for i in range(40):
            ids = rng.randint(0, 16, (32,))
            feed = {'x': feats[ids], 'lab': ids[:, None].astype('int64')}
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_cos_sim():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 6).astype('float32')
    y = rng.randn(4, 6).astype('float32')
    out = run_op('cos_sim', {'X': [x], 'Y': [y]})
    got = np.asarray(out['Out'][0]).ravel()
    want = (x * y).sum(1) / (np.linalg.norm(x, axis=1)
                             * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------------- beam search

def test_beam_search_step_and_gather_tree():
    # 1 batch, beam 2, vocab 4
    pre_ids = np.array([[2, 3]], 'int64')
    pre_scores = np.array([[-1.0, -2.0]], 'float32')
    scores = np.log(np.array([[[0.1, 0.2, 0.3, 0.4],
                               [0.4, 0.3, 0.2, 0.1]]], 'float32'))
    out = run_op('beam_search',
                 {'PreIds': [pre_ids], 'PreScores': [pre_scores],
                  'Scores': [scores]},
                 {'beam_size': 2, 'end_id': 0})
    ids = np.asarray(out['SelectedIds'][0])
    parent = np.asarray(out['ParentIdx'][0])
    total = pre_scores[0][:, None] + scores[0]
    flat = total.ravel()
    top2 = np.argsort(-flat)[:2]
    assert list(ids[0]) == [int(t % 4) for t in top2]
    assert list(parent[0]) == [int(t // 4) for t in top2]

    # finished beam (pre_id == end_id) only extends end_id at no cost
    pre_ids2 = np.array([[0, 3]], 'int64')
    out2 = run_op('beam_search',
                  {'PreIds': [pre_ids2], 'PreScores': [pre_scores],
                   'Scores': [scores]},
                  {'beam_size': 2, 'end_id': 0})
    ids2 = np.asarray(out2['SelectedIds'][0])
    sc2 = np.asarray(out2['SelectedScores'][0])
    assert ids2[0, 0] == 0 and abs(sc2[0, 0] - (-1.0)) < 1e-6

    # gather_tree backtrace
    ids_t = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], 'int64')   # [T=3,B=1,K=2]
    parents = np.array([[[0, 0]], [[1, 0]], [[1, 0]]], 'int64')
    out3 = run_op('gather_tree', {'Ids': [ids_t], 'Parents': [parents]})
    got = np.asarray(out3['Out'][0])
    # beam 0 at t2: id 5, parent 1 -> t1 id 4, parent(t1,beam1)=0 -> t0 id 1
    assert list(got[:, 0, 0]) == [1, 4, 5]
    # beam 1 at t2: id 6, parent 0 -> t1 id 3, parent(t1,beam0)=1 -> t0 id 2
    assert list(got[:, 0, 1]) == [2, 3, 6]


def test_hsigmoid_power_of_two_code():
    # label + num_classes landing on an exact power of two must keep the
    # full path (float log2 is off by one ulp there)
    rng = np.random.RandomState(8)
    num_classes = 20
    x = rng.randn(1, 4).astype('float32')
    w = rng.randn(num_classes - 1, 4).astype('float32')
    label = np.array([12], 'int64')        # code = 32 = 2^5
    out = run_op('hierarchical_sigmoid',
                 {'X': [x], 'W': [w], 'Label': [label]},
                 {'num_classes': num_classes})
    got = float(np.asarray(out['Out'][0]).ravel()[0])
    code = 32
    want = 0.0
    for b in range(5):                     # length = floor(log2(32)) = 5
        node = (code >> (b + 1)) - 1
        bit = (code >> b) & 1
        z = float(x[0] @ w[node])
        want += np.log1p(np.exp(z)) - bit * z
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_edit_distance_ignored_tokens():
    hyp = np.array([[0, 1, 0, 2, 3]], 'int64')     # ignoring 0 -> [1,2,3]
    ref = np.array([[1, 3, 3, 0, 0]], 'int64')     # ignoring 0 -> [1,3,3]
    out = run_op('edit_distance', {'Hyps': [hyp], 'Refs': [ref]},
                 {'normalized': False, 'ignored_tokens': [0]})
    d = float(np.asarray(out['Out'][0]).ravel()[0])
    assert d == 1.0   # substitute 2->3


def test_multilevel_lod_feed_fails_loudly():
    """Round-5 VERDICT #9: a >=2-level LoDTensor reaching a level-1
    (padded+mask) sequence lowering must raise a clear error, not
    silently compute dense (reference nested-LoD semantics,
    framework/lod_tensor.h:219).  A 1-level LoD feed stays accepted."""
    import numpy as np
    import pytest
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4, 3], dtype='float32',
                              append_batch_size=False)
        x.lod_level = 1
        out = fluid.layers.sequence_pool(x, 'sum')

    data = np.arange(24, dtype='float32').reshape(2, 4, 3)
    two_level = fluid.core.LoDTensor(
        data, lod=[[0, 1, 2], [0, 2, 4, 6, 8]])
    one_level = fluid.core.LoDTensor(data, lod=[[0, 4, 8]])

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        with pytest.raises(RuntimeError, match='2-level LoD'):
            exe.run(main, feed={'x': two_level}, fetch_list=[out])
        # level-1 feeds keep working
        r, = exe.run(main, feed={'x': one_level}, fetch_list=[out])
        assert np.asarray(r).shape[0] == 2


def test_multilevel_lod_guard_traces_transitive_consumers():
    """The guard follows dataflow: embedding(ids) -> sequence_pool is
    the common nested-sequence pattern, and the sequence op consumes
    the embedding OUTPUT, not the feed name."""
    import numpy as np
    import pytest
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data('ids', shape=[4, 1], dtype='int64',
                                append_batch_size=False)
        emb = fluid.layers.embedding(ids, size=[50, 8])
        out = fluid.layers.sequence_pool(emb, 'sum')

    data = np.zeros((2, 4, 1), 'int64')
    two_level = fluid.core.LoDTensor(
        data, lod=[[0, 1, 2], [0, 2, 4, 6, 8]])
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        with pytest.raises(RuntimeError, match='2-level LoD'):
            exe.run(main, feed={'ids': two_level}, fetch_list=[out])
