"""Dygraph (eager) mode: tape autodiff, layers, optimizer, save/load.

Mirrors reference tests test_imperative_basic.py / test_imperative_mnist
(python/paddle/fluid/tests/unittests/).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.dygraph import (Linear, Conv2D, Pool2D, BatchNorm,
                                      to_variable)


def test_eager_autodiff_basic():
    with fluid.dygraph.guard():
        x = to_variable(np.ones((2, 3), 'float32'))
        x.stop_gradient = False
        y = x * 2.0 + 1.0
        z = y * y
        from paddle_tpu.fluid.framework import _dygraph_tracer
        loss_vals = _dygraph_tracer().trace_op(
            'mean', {'X': [z]})['Out'][0]
        loss_vals.backward()
        # d/dx mean((2x+1)^2) = 2*(2x+1)*2/6 = 4*(2x+1)/6 = 2 at x=1
        np.testing.assert_allclose(x.gradient(),
                                   np.full((2, 3), 2.0), rtol=1e-5)


def test_grad_accumulation_shared_weight():
    """A weight used twice gets the SUM of both paths' grads (not 2x)."""
    with fluid.dygraph.guard():
        w = to_variable(np.ones((2, 2), 'float32'))
        w.stop_gradient = False
        x1 = to_variable(np.full((2, 2), 2.0, 'float32'))
        x2 = to_variable(np.full((2, 2), 3.0, 'float32'))
        y = w * x1 + w * x2
        from paddle_tpu.fluid.framework import _dygraph_tracer
        s = _dygraph_tracer().trace_op('reduce_sum', {'X': [y]},
                                       attrs={'reduce_all': True})
        s['Out'][0].backward()
        np.testing.assert_allclose(w.gradient(),
                                   np.full((2, 2), 5.0), rtol=1e-5)


class MNISTNet(fluid.dygraph.Layer):
    def __init__(self):
        super(MNISTNet, self).__init__()
        self.conv = Conv2D(1, 8, 3, padding=1)
        self.bn = BatchNorm(8, act='relu')
        self.pool = Pool2D(2, 'max', 2)
        self.fc = Linear(8 * 14 * 14, 10)

    def forward(self, x):
        h = self.pool(self.bn(self.conv(x)))
        from paddle_tpu.fluid.framework import _dygraph_tracer
        h = _dygraph_tracer().trace_op(
            'reshape2', {'X': [h]},
            attrs={'shape': [0, 8 * 14 * 14]})['Out'][0]
        return self.fc(h)


def test_dygraph_mnist_trains():
    rng = np.random.RandomState(0)
    with fluid.dygraph.guard():
        net = MNISTNet()
        opt = fluid.optimizer.Adam(1e-3)
        from paddle_tpu.fluid.framework import _dygraph_tracer
        losses = []
        x_np = rng.randn(16, 1, 28, 28).astype('float32') * 0.1
        y_np = rng.randint(0, 10, (16, 1)).astype('int64')
        for l in y_np[:, 0]:
            x_np[int(l) % 16, 0, :8, :8] += float(l) * 0.1
        for step in range(20):
            x = to_variable(x_np)
            y = to_variable(y_np)
            logits = net(x)
            tr = _dygraph_tracer()
            ce = tr.trace_op('softmax_with_cross_entropy',
                             {'Logits': [logits], 'Label': [y]})
            loss = tr.trace_op('mean', {'X': [ce['Loss'][0]]})['Out'][0]
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            net.clear_gradients()
            losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses


def test_dygraph_state_dict_roundtrip(tmp_path):
    with fluid.dygraph.guard():
        net = MNISTNet()
        sd = net.state_dict()
        fluid.dygraph.save_dygraph(sd, str(tmp_path / 'model'))
        loaded, _ = fluid.dygraph.load_dygraph(str(tmp_path / 'model'))
        net2 = MNISTNet()
        net2.set_dict({k: v for k, v in zip(
            [p.name for p in net2.parameters()],
            [loaded[p.name] for p in net.parameters()])})
        for p, q in zip(net.parameters(), net2.parameters()):
            np.testing.assert_allclose(p.numpy(), q.numpy())


def test_traced_layer_roundtrip(tmp_path):
    from paddle_tpu.fluid.dygraph import TracedLayer
    rng = np.random.RandomState(0)
    with fluid.dygraph.guard():
        net = fluid.dygraph.Linear(6, 3, act='relu')
        x = to_variable(rng.randn(4, 6).astype('float32'))
        eager_out = net(x)
        out, traced = TracedLayer.trace(net, [x])
        static_out = traced([x])[0]
        np.testing.assert_allclose(eager_out.numpy(), static_out,
                                   rtol=1e-5)
        traced.save_inference_model(str(tmp_path))
    from paddle_tpu.inference import AnalysisConfig, \
        create_paddle_predictor
    pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
    out2 = pred.run([rng.randn(2, 6).astype('float32')])
    assert out2[0].as_ndarray().shape == (2, 3)


def test_model_average():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(pred)
        fluid.optimizer.SGD(0.1).minimize(loss)
        ma = fluid.optimizer.ModelAverage(0.15)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        vals = []
        pname = main.all_parameters()[0].name
        for _ in range(5):
            exe.run(main, feed={'x': np.ones((4, 2), 'float32')},
                    fetch_list=[loss])
            vals.append(np.asarray(scope.find_var(pname)).copy())
        expected_avg = np.mean(vals, axis=0)
        with ma.apply(exe):
            avg_now = np.asarray(scope.find_var(pname))
            np.testing.assert_allclose(avg_now, expected_avg,
                                       rtol=1e-5)
        restored = np.asarray(scope.find_var(pname))
        np.testing.assert_allclose(restored, vals[-1], rtol=1e-6)


def test_py_func_host_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[3], dtype='float32')
        h = fluid.layers.scale(x, scale=2.0)
        out = main.global_block().create_var(
            name='pyfunc_out', shape=(-1, 3), dtype='float32')
        fluid.layers.py_func(lambda a: a + 1.0, h, out)
        final = fluid.layers.scale(out, scale=3.0)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        r, = exe.run(main, feed={'x': np.ones((2, 3), 'float32')},
                     fetch_list=[final])
    np.testing.assert_allclose(r, np.full((2, 3), 9.0), rtol=1e-6)
