"""Inference predictor, transpiler shims, nan/inf flag, launch CLI."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _train_and_save(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 8, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype('float32')
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(30):
            xs = rng.randn(16, 4).astype('float32')
            exe.run(main, feed={'x': xs, 'y': xs @ W},
                    fetch_list=[loss])
        fluid.io.save_inference_model(str(tmp_path), ['x'], [pred], exe,
                                      main_program=main)
        xs = rng.randn(5, 4).astype('float32')
        expect, = exe.run(main, feed={'x': xs, 'y': xs @ W},
                          fetch_list=[pred])
    return xs, expect


def test_predictor_roundtrip(tmp_path):
    xs, expect = _train_and_save(tmp_path)
    from paddle_tpu.inference import AnalysisConfig, \
        create_paddle_predictor, PaddleTensor
    cfg = AnalysisConfig(str(tmp_path))
    predictor = create_paddle_predictor(cfg)
    assert predictor.get_input_names() == ['x']
    outs = predictor.run([PaddleTensor(xs)])
    np.testing.assert_allclose(outs[0].as_ndarray(), expect, rtol=1e-5,
                               atol=1e-6)
    # params were pinned to the device at load (one upload, not one
    # per call), and the async serving path returns device arrays
    import jax
    assert any(isinstance(v, jax.Array)
               for v in predictor._scope._vars.values())
    out2, = predictor.run_dict({'x': xs}, return_numpy=False)
    assert isinstance(out2, jax.Array)
    np.testing.assert_allclose(np.asarray(out2), expect, rtol=1e-5,
                               atol=1e-6)


def test_feed_shape_mismatch_is_named_in_error():
    """When a mis-shaped feed makes a segment fail, the error must name
    the diverging feed and its declared spec (PEP 678 note), not just
    dump a raw XLA shape error (reference: data_feeder/enforce)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.fc(x, 2)
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        try:
            exe.run(main, feed={'x': np.zeros((3, 5), 'float32')},
                    fetch_list=[y])
            raise AssertionError('mis-shaped feed did not fail')
        except AssertionError:
            raise
        except Exception as e:
            notes = '\n'.join(getattr(e, '__notes__', []))
            assert "feed 'x': shape (3, 5), declared (-1, 4)" in notes, \
                notes
        # -1 batch dim accepts any size
        out, = exe.run(main, feed={'x': np.zeros((7, 4), 'float32')},
                       fetch_list=[y])
        assert np.asarray(out).shape == (7, 2)


def test_segment_auto_layout_flag():
    """FLAGS_segment_auto_layout=1 compiles executor segments with
    XLA-chosen boundary layouts (jax.experimental.layout AUTO) —
    training must run and match the default-layout path exactly."""
    def train(auto):
        fluid.set_flags({'FLAGS_segment_auto_layout': auto})
        try:
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 11
            with fluid.program_guard(main, startup):
                x = fluid.layers.data('x', shape=[8], dtype='float32')
                y = fluid.layers.data('y', shape=[1], dtype='float32')
                pred = fluid.layers.fc(fluid.layers.fc(x, 16,
                                                       act='relu'), 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            rng = np.random.RandomState(0)
            xs = rng.randn(64, 8).astype('float32')
            ys = rng.randn(64, 1).astype('float32')
            out = []
            with fluid.scope_guard(fluid.Scope()):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for _ in range(5):
                    l, = exe.run(main, feed={'x': xs, 'y': ys},
                                 fetch_list=[loss])
                    out.append(float(np.asarray(l).ravel()[0]))
            return out
        finally:
            fluid.set_flags({'FLAGS_segment_auto_layout': False})

    ref = train(False)
    got = train(True)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    assert got[-1] < got[0]


def test_check_nan_inf_flag():
    fluid.set_flags({'FLAGS_check_nan_inf': True})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[2], dtype='float32')
            y = fluid.layers.log(x)  # log of negatives -> nan
            out = fluid.layers.reduce_sum(y)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            with pytest.raises(FloatingPointError):
                exe.run(main,
                        feed={'x': -np.ones((3, 2), 'float32')},
                        fetch_list=[out])
    finally:
        fluid.set_flags({'FLAGS_check_nan_inf': False})


def test_transpiler_nccl2_marks_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        fluid.layers.fc(x, 2)
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = 'nccl2'
    t = fluid.DistributeTranspiler(config=cfg)
    t.transpile(trainer_id=0, program=main, trainers=2)
    assert getattr(main, '_collective_dp', False)
    # embedded PS runtime: pserver programs are explicit no-ops now
    # (round 2: transpiler PS mode routes to host-sharded tables)
    pserver = t.get_pserver_program('127.0.0.1:6174')
    assert getattr(pserver, '_embedded_ps', False)
    assert not pserver.global_block().ops


def test_grad_allreduce_transpiler_rewrite():
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    n_before = len(main.global_block().ops)
    # reference (v1.6) rewrite shape with the collective planner off:
    # one flat c_allreduce_sum + scale per grad (the planned default
    # coalesces grads into fused buckets — tests/test_comms_plan.py)
    prev = fluid.get_flags(['FLAGS_comms_plan'])
    fluid.set_flags({'FLAGS_comms_plan': False})
    try:
        GradAllReduce().transpile(startup, main, rank=0,
                                  endpoints=['a', 'b'],
                                  current_endpoint='a')
    finally:
        fluid.set_flags(prev)
    ops = [op.type for op in main.global_block().ops]
    assert ops.count('c_allreduce_sum') == 2  # w and b grads
    assert len(ops) == n_before + 4
    # rewritten program still runs (under shard_map mode)
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        l, = exe.run(main, feed={'x': rng.randn(16, 4).astype('float32'),
                                 'y': rng.randn(16, 1).astype('float32')},
                     fetch_list=[loss])
        assert np.isfinite(l).all()


def test_launch_cli_single_node(tmp_path):
    import subprocess, sys, os
    script = tmp_path / 'train.py'
    script.write_text(
        'import os\n'
        'print("RANK", os.environ["PADDLE_TRAINER_ID"],\n'
        '      os.environ["PADDLE_TRAINERS_NUM"])\n')
    out = subprocess.run(
        [sys.executable, '-m', 'paddle_tpu.distributed.launch',
         str(script)],
        capture_output=True, text=True, cwd='/root/repo',
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert 'RANK 0 1' in out.stdout, out.stdout + out.stderr


def test_training_is_deterministic_across_runs():
    """Same program + seeds + feeds -> bit-identical loss curves and
    final params across two independent runs (the reference's
    cpu_deterministic contract; here step-seeded RNG + XLA give it
    unconditionally on one device)."""
    def run_once():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 77
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[6], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            h = fluid.layers.fc(x, 12, act='relu')
            h = fluid.layers.dropout(
                h, 0.3, dropout_implementation='upscale_in_train')
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(fluid.layers.fc(h, 1),
                                               y))
            fluid.optimizer.Adam(0.01).minimize(loss)
        rng = np.random.RandomState(0)
        xb = rng.randn(32, 6).astype('float32')
        yb = rng.randn(32, 1).astype('float32')
        losses = []
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(10):
                l, = exe.run(main, feed={'x': xb, 'y': yb},
                             fetch_list=[loss])
                losses.append(np.asarray(l).copy())
            from paddle_tpu.fluid import core
            pname = main.all_parameters()[0].name
            final = np.asarray(core.as_array(
                core.global_scope().find_var(pname)))
        return losses, final

    # run in fresh scopes; dropout must draw the same step-seeded masks
    l1, p1 = run_once()
    l2, p2 = run_once()
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(p1, p2)
