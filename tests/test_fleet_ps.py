"""Parameter-server fleet frontend (Downpour/PSLib analog): async
bounded-staleness training through the embedded server converges.

Reference fixture: test_dist_fleet_base.py (PS fleet init_worker/
run_server/stop_worker lifecycle + async trainer convergence).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.incubate.fleet.parameter_server import fleet
from paddle_tpu.fluid.incubate.fleet.base import role_maker
from paddle_tpu.fluid.transpiler import DistributeTranspilerConfig


def test_async_ps_fleet_trains():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(fluid.layers.fc(x, 16, act='relu'), 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))

    fleet.init(role_maker.PaddleCloudRoleMaker())
    config = DistributeTranspilerConfig()
    config.sync_mode = False
    with fluid.program_guard(main, startup):
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.05),
                                          config)
        opt.minimize(loss)

    # async trainer program must carry no optimizer ops
    assert not any(op.type == 'sgd' for op in main.global_block().ops)

    fleet.run_server()
    fleet.init_worker()
    rng = np.random.RandomState(2)
    w = rng.randn(8, 1).astype('float32')
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for i in range(60):
            xb = rng.randn(32, 8).astype('float32')
            l, = exe.run(main, feed={'x': xb, 'y': xb @ w},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    fleet.stop_worker()
    assert np.isfinite(losses).all()
    # bounded-staleness training converges
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.5, (
        losses[:5], losses[-5:])


def test_async_ps_fleet_trains_with_adam():
    """Server-side adam: the reference pserver runs arbitrary optimize
    sub-blocks (listen_and_serv_op.cc:110); async PS must not be
    SGD-only."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(fluid.layers.fc(x, 16, act='relu'), 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))

    fleet.init(role_maker.PaddleCloudRoleMaker())
    config = DistributeTranspilerConfig()
    config.sync_mode = False
    with fluid.program_guard(main, startup):
        opt = fleet.distributed_optimizer(fluid.optimizer.Adam(5e-3),
                                          config)
        opt.minimize(loss)
    assert not any(op.type == 'adam' for op in main.global_block().ops)

    fleet.run_server()
    fleet.init_worker()
    rng = np.random.RandomState(4)
    w = rng.randn(8, 1).astype('float32')
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for i in range(80):
            xb = rng.randn(32, 8).astype('float32')
            l, = exe.run(main, feed={'x': xb, 'y': xb @ w},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    fleet.stop_worker()
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.5, (
        losses[:5], losses[-5:])


def test_async_ps_rejects_unsupported_optimizer():
    """Rules the server can't apply (e.g. Ftrl) are rejected loudly —
    silent degradation to SGD would corrupt training."""
    import pytest
    config = DistributeTranspilerConfig()
    config.sync_mode = False
    fleet.init(role_maker.PaddleCloudRoleMaker())
    with pytest.raises(ValueError, match='sgd/momentum/adam'):
        fleet.distributed_optimizer(
            fluid.optimizer.Ftrl(1e-3), config)


def test_local_fs_ops(tmp_path):
    """LocalFS surface (reference framework/io/fs.h localfs ops +
    hdfs.py split_files trainer sharding)."""
    from paddle_tpu.fluid.incubate.fleet.utils import LocalFS
    fs = LocalFS()
    d = str(tmp_path / 'data')
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = d + '/part-0'
    fs.touch(f)
    assert fs.is_file(f) and fs.ls_dir(d) == ['part-0']
    with open(f, 'w') as fh:
        fh.write('hello')
    assert fs.cat(f) == 'hello'
    fs.rename(f, d + '/part-1')
    assert fs.ls_dir(d) == ['part-1']
    files = ['a', 'b', 'c', 'd', 'e']
    assert fs.split_files(files, 0, 2) == ['a', 'c', 'e']
    assert fs.split_files(files, 1, 2) == ['b', 'd']
    fs.delete(d)
    assert not fs.is_exist(d)


def test_hdfs_client_without_hadoop_errors_clearly(monkeypatch):
    from paddle_tpu.fluid.incubate.fleet.utils import HDFSClient, \
        ExecuteError
    monkeypatch.delenv('HADOOP_HOME', raising=False)
    c = HDFSClient()
    import pytest as _pytest
    with _pytest.raises(ExecuteError, match='no hadoop client'):
        c.ls('hdfs://x/y')


def test_async_ps_through_compiled_pipeline():
    """CompiledPipeline must run the async-PS post-step hooks (grad
    push / param pull) exactly like Executor.run — training through
    the pipeline converges the same way."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(fluid.layers.fc(x, 16, act='relu'), 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))

    fleet.init(role_maker.PaddleCloudRoleMaker())
    config = DistributeTranspilerConfig()
    config.sync_mode = False
    with fluid.program_guard(main, startup):
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.05),
                                          config)
        opt.minimize(loss)
    assert getattr(main, '_ps_async', None)

    fleet.run_server()
    fleet.init_worker()
    rng = np.random.RandomState(2)
    w = rng.randn(8, 1).astype('float32')
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        pipe = exe.compile(main, feed_names=('x', 'y'),
                           fetch_names=(loss.name,), allow_host=True)
        for i in range(60):
            xb = rng.randn(32, 8).astype('float32')
            l, = pipe({'x': xb, 'y': xb @ w}, scope=scope)
            losses.append(float(np.asarray(l).ravel()[0]))
    fleet.stop_worker()
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.5, (
        losses[:5], losses[-5:])
