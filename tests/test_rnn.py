"""LSTM/GRU ops + an IMDB-style sentiment model (book test analog:
python/paddle/fluid/tests/book/test_understand_sentiment.py)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_lstm_op_numpy_parity():
    from paddle_tpu.ops import registry
    rng = np.random.RandomState(0)
    B, T, H = 2, 5, 4
    x = rng.randn(B, T, 4 * H).astype('float32')
    w = rng.randn(H, 4 * H).astype('float32') * 0.2
    out = registry.get('lstm').fn(registry.LowerCtx(0),
                                  {'Input': [x], 'Weight': [w]}, {})
    hs = np.asarray(out['Hidden'][0])

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))

    hp = np.zeros((B, H)); cp = np.zeros((B, H))
    for t in range(T):
        gates = x[:, t] + hp @ w
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sigmoid(f) * cp + sigmoid(i) * np.tanh(g)
        hp = sigmoid(o) * np.tanh(c)
        cp = c
        np.testing.assert_allclose(hs[:, t], hp, rtol=1e-4, atol=1e-5)


def test_lstm_mask_freezes_state():
    from paddle_tpu.ops import registry
    rng = np.random.RandomState(1)
    B, T, H = 2, 4, 3
    x = rng.randn(B, T, 4 * H).astype('float32')
    w = rng.randn(H, 4 * H).astype('float32') * 0.2
    mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)
    out = registry.get('lstm').fn(
        registry.LowerCtx(0),
        {'Input': [x], 'Weight': [w], 'Mask': [mask]}, {})
    hs = np.asarray(out['Hidden'][0])
    np.testing.assert_allclose(hs[0, 2], hs[0, 1], rtol=1e-6)
    np.testing.assert_allclose(hs[0, 3], hs[0, 1], rtol=1e-6)
    last = np.asarray(out['LastH'][0])
    np.testing.assert_allclose(last[0], hs[0, 1], rtol=1e-6)


def test_sentiment_lstm_trains():
    vocab, emb_dim, hid = 200, 16, 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        words = fluid.layers.data('words', shape=[20], dtype='int64')
        mask = fluid.layers.data('mask', shape=[20], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(words, size=[vocab, emb_dim])
        proj = fluid.layers.fc(emb, size=4 * hid, num_flatten_dims=2)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=4 * hid,
                                              mask=mask)
        pooled = fluid.layers.sequence_pool(hidden, 'max', mask=mask)
        pred = fluid.layers.fc(pooled, size=2, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(5e-3).minimize(loss)
    rng = np.random.RandomState(0)
    # synthetic: label = whether token 7 appears early
    def batch(n=32):
        w = rng.randint(0, vocab, (n, 20)).astype('int64')
        lens = rng.randint(5, 21, n)
        m = (np.arange(20)[None] < lens[:, None]).astype('float32')
        y = (w[:, :5] == 7).any(1).astype('int64')[:, None]
        w[y[:, 0] == 1, 2] = 7
        return {'words': w, 'mask': m, 'label': y}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(30):
            l, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_gru_runs():
    from paddle_tpu.ops import registry
    rng = np.random.RandomState(2)
    x = rng.randn(2, 5, 9).astype('float32')
    w = rng.randn(3, 9).astype('float32') * 0.2
    out = registry.get('gru').fn(registry.LowerCtx(0),
                                 {'Input': [x], 'Weight': [w]}, {})
    assert out['Hidden'][0].shape == (2, 5, 3)
