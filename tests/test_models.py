"""Model-zoo integration tests (reference book-tests style: train a few
steps on synthetic data, assert the loss decreases)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import models


def _train(build_fn, batch_fn, opt, steps=15):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        feeds, _, loss = build_fn()
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(steps):
            l, = exe.run(main, feed=batch_fn(rng), fetch_list=[loss])
            losses.append(float(l))
    return losses


def test_bert_tiny_trains():
    cfg = models.bert.TINY
    losses = _train(
        lambda: models.bert.build_pretrain(cfg, seq_len=32),
        lambda rng: models.bert.synthetic_batch(cfg, 8, 32, rng),
        fluid.optimizer.Adam(1e-3))
    assert losses[-1] < losses[0], losses


def test_transformer_tiny_trains():
    # fixed batch (memorization): with fresh random token batches every
    # step the loss signal is below the dropout noise floor at 15 steps
    cfg = models.transformer.TINY
    cache = {}

    def batch_fn(rng):
        if 'b' not in cache:
            cache['b'] = models.transformer.synthetic_batch(
                cfg, 8, 16, 16, rng)
        return cache['b']

    losses = _train(
        lambda: models.transformer.build(cfg, src_len=16, tgt_len=16),
        batch_fn, fluid.optimizer.Adam(1e-3))
    assert losses[-1] < losses[0], losses


def test_wide_deep_trains():
    cfg = models.wide_deep.TINY
    losses = _train(
        lambda: models.wide_deep.build(cfg),
        lambda rng: models.wide_deep.synthetic_batch(cfg, 32, rng),
        fluid.optimizer.Adam(5e-3), steps=25)
    assert losses[-1] < losses[0], losses


def test_word2vec_trains():
    fixed = {}

    def batch(rng):
        # memorize one fixed batch: reliable loss decrease in few steps
        if not fixed:
            fixed.update(models.word2vec.synthetic_batch(200, 32, rng))
        return fixed

    losses = _train(lambda: models.word2vec.build(vocab_size=200),
                    batch, fluid.optimizer.Adam(5e-3), steps=25)
    assert losses[-1] < losses[0], losses


def test_resnet18_cifar_trains():
    def build():
        feeds_logits = models.resnet.build(image_shape=(3, 32, 32),
                                           class_dim=10, depth=18)
        feeds, logits, loss, acc = feeds_logits
        return feeds, logits, loss

    def batch(rng):
        x = rng.randn(8, 3, 32, 32).astype('float32')
        y = rng.randint(0, 10, (8, 1)).astype('int64')
        return {'image': x, 'label': y}

    losses = _train(build, batch, fluid.optimizer.Momentum(0.01, 0.9),
                    steps=10)
    # random labels: just require a finite, stable optimization
    assert np.isfinite(losses).all()
    assert losses[-1] < 15.0, losses


def test_resnet50_builds():
    """Full ResNet-50 graph builds with correct shapes (compile check is
    bench/graft territory)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, logits, loss, acc = models.resnet.build()
    assert tuple(logits.shape) == (-1, 1000)
    n_params = len(main.all_parameters())
    # 53 convs + 53 BN(scale+bias) + fc(w+b) and BN means/vars are
    # parameters too in this design
    assert n_params > 150, n_params


def test_gpt_lm_learns_pattern_and_generates():
    """Decoder-only causal LM (models/gpt.py): trains on a deterministic
    +3 (mod V) token sequence, loss collapses, and greedy decoding
    continues the pattern — exercising causal attention masks through
    training AND the host-driven generation loop."""
    from paddle_tpu.models import gpt

    cfg = gpt.GptConfig(vocab_size=23, hidden=32, layers=2, heads=4,
                        max_pos=16, dropout=0.0)
    seq = 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        feeds, logits, loss = gpt.build_lm(cfg, seq)
        infer_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(3e-3).minimize(loss)

    rng = np.random.RandomState(0)

    def batch(n=32):
        starts = rng.randint(0, cfg.vocab_size, (n, 1))
        ids = (starts + 3 * np.arange(seq)) % cfg.vocab_size
        return gpt.lm_batch(ids)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(120):
            l, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < 0.1, (losses[0], losses[-1])

        toks = gpt.greedy_generate(exe, infer_prog, logits, [5, 8, 11],
                                   steps=6, cfg=cfg)
    want = [(5 + 3 * i) % cfg.vocab_size for i in range(9)]
    assert toks == want, (toks, want)


def test_gpt_flash_path_matches_naive():
    """The causal flash dispatch (seq >= flash_min_len) produces the
    same logits as the naive masked chain — model-level wiring check
    for fused_multihead_attention(causal=True)."""
    from paddle_tpu.models import gpt

    def logits_with(use_flash):
        cfg = gpt.GptConfig(vocab_size=31, hidden=32, layers=1,
                            heads=4, max_pos=32, dropout=0.0,
                            use_flash=use_flash)
        cfg.flash_min_len = 16   # force the flash path at seq 32
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 23
        with fluid.program_guard(main, startup):
            feeds, logits, loss = gpt.build_lm(cfg, 32, is_test=True)
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 31, (2, 32))
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            out, = exe.run(main, feed=gpt.lm_batch(ids),
                           fetch_list=[logits])
        return np.asarray(out)

    naive = logits_with(False)
    flash = logits_with(True)
    np.testing.assert_allclose(flash, naive, rtol=2e-3, atol=2e-3)
