"""Steady-state step fast path: argument binders, device-resident
scope bindings, batched async H2D feed staging, donation safety,
async fetch handles, and use_program_cache semantics."""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor


def _tiny_train_program(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 4, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _xs(n=4):
    return np.random.RandomState(0).randn(n, 8).astype('float32')


def test_steady_state_binder_hits_and_staged_h2d():
    """After the 2-step warmup (step 0 resolves, step 0's output
    write-back invalidates once) every step must bind through the
    cached tables, and each host feed must cross H2D exactly once per
    step through the batched async device_put."""
    main, startup, loss = _tiny_train_program()
    xs = _xs()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed={'x': xs}, fetch_list=[])
        f0 = monitor.flat()
        steps = 5
        for _ in range(steps):
            exe.run(main, feed={'x': xs}, fetch_list=[])
        f1 = monitor.flat()
    assert f1['executor/fastpath_hits'] - \
        f0['executor/fastpath_hits'] == steps
    assert f1.get('executor/scope_lookups', 0.0) == \
        f0.get('executor/scope_lookups', 0.0)
    # one async H2D batch per step, exactly the feed's bytes
    assert f1['executor/h2d_bytes_async'] - \
        f0['executor/h2d_bytes_async'] == steps * xs.nbytes
    assert f1['executor/bind_seconds/count'] > \
        f0['executor/bind_seconds/count']


def test_donation_safety_caller_fed_state():
    """A caller-fed jax.Array bound to a DONATED state slot must
    survive the step (the executor copies caller-owned buffers; only
    runtime-staged buffers pass by pointer)."""
    import jax
    main, startup, loss = _tiny_train_program()
    params = {p.name: p for p in main.all_parameters()}
    assert len(params) == 2  # fc weight + bias
    xs = _xs()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fed = {n: jax.device_put(np.full(
            tuple(int(d) for d in p.shape), 0.5, 'float32'))
            for n, p in params.items()}
        outs = []
        for _ in range(3):
            feed = dict({'x': xs}, **fed)
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            outs.append(float(np.asarray(l).ravel()[0]))
        # the fed buffers are still alive and unchanged after the
        # donated steps
        for v in fed.values():
            np.testing.assert_array_equal(np.asarray(v), 0.5)
        # every step restarted from the SAME fed weights -> same loss
        assert outs[0] == outs[1] == outs[2]


def test_async_fetch_matches_return_numpy():
    """FetchHandles must resolve to bit-identical values vs the
    blocking return_numpy=True path, on the same training trajectory."""
    main, startup, loss = _tiny_train_program()
    xs = _xs()

    def run(mode):
        vals = []
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(4):
                l, = exe.run(main, feed={'x': xs}, fetch_list=[loss],
                             return_numpy=mode)
                vals.append(l)
        return [np.asarray(v) for v in vals]

    sync = run(True)
    handles = run('async')
    for s, a in zip(sync, handles):
        np.testing.assert_array_equal(s, a)


def test_async_fetch_handle_api():
    main, startup, loss = _tiny_train_program()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        h, = exe.run(main, feed={'x': _xs()}, fetch_list=[loss],
                     return_numpy='async')
    from paddle_tpu.fluid.executor import FetchHandle
    assert isinstance(h, FetchHandle)
    first = h.as_numpy()
    assert h.as_numpy() is first          # resolution is cached
    assert np.asarray(h).shape == first.shape
    import jax
    assert isinstance(h.value, jax.Array)  # raw device value exposed


def test_device_resident_roundtrip_run_pipeline_saveload(tmp_path):
    """Device-resident state must survive the full loop: train via
    run(), save through the 'save' host op (reads the jax.Array from
    the scope), clobber, reload through 'load' (writes numpy back),
    and keep training — binders must absorb the numpy->device
    transition without wrong values."""
    import jax
    main, startup, loss = _tiny_train_program()
    pname = main.all_parameters()[0].name
    path = str(tmp_path / 'w_ckpt')
    save_p = fluid.Program()
    save_p.global_block().append_op(
        'save', inputs={'X': [pname]}, attrs={'file_path': path})
    load_p = fluid.Program()
    load_p.global_block().append_op(
        'load', outputs={'Out': [pname]}, attrs={'file_path': path})
    xs = _xs()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed={'x': xs}, fetch_list=[])
        # steady state: the param is device-resident
        assert isinstance(scope.find_var(pname), jax.Array)
        w_trained = np.asarray(scope.find_var(pname))
        exe.run(save_p)
        assert os.path.exists(path + '.npy')
        scope.set_var(pname, np.zeros((8, 4), 'float32'))
        exe.run(load_p)
        np.testing.assert_array_equal(
            np.asarray(fluid.core.as_array(scope.find_var(pname))),
            w_trained)
        l, = exe.run(main, feed={'x': xs}, fetch_list=[loss])
        assert np.isfinite(np.asarray(l)).all()

    # the same round-trip through a mid-plan host op (CompiledPipeline)
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x2 = layers.data('x', shape=[4], dtype='float32')
        y2 = layers.scale(x2, scale=2.0)
        out_v = main2.current_block().create_var(
            name='py_out', shape=[-1, 4], dtype='float32')
        layers.py_func(lambda a: a + 1.0, y2, out_v)
        z2 = layers.scale(out_v, scale=3.0)
    exe2 = fluid.Executor(fluid.XLAPlace(0))
    xv = np.ones((2, 4), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        pipe = exe2.compile(main2, feed_names=('x',),
                            fetch_names=(z2.name,), allow_host=True)
        for _ in range(3):
            got, = pipe({'x': xv})
        np.testing.assert_allclose(got, (xv * 2 + 1) * 3, rtol=1e-6)
        h, = pipe({'x': xv}, return_numpy='async')
        np.testing.assert_allclose(h.as_numpy(), (xv * 2 + 1) * 3,
                                   rtol=1e-6)


def test_binder_invalidation_on_scope_and_plan_change():
    """Cached bindings must refresh when the scope layout changes (a
    child scope shadowing a param) or when the plan changes (different
    feed keyset) — stale tables would silently read the old owner."""
    main, startup, loss = _tiny_train_program()
    params = main.all_parameters()
    pname = params[0].name
    xs = np.ones((2, 8), 'float32')
    parent = fluid.Scope()
    with fluid.scope_guard(parent):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(3):
            base, = exe.run(main, feed={'x': xs}, fetch_list=[loss])
        # shadow ALL state in a child scope (a partially-shadowing
        # child would let the donated step invalidate parent buffers —
        # the long-standing sub-scope contract): the binder serving
        # the parent must re-resolve onto the child's dict
        kid = parent.new_scope()
        for p in params:
            kid.set_var(p.name, np.zeros(
                tuple(int(d) for d in p.shape), 'float32'))
        w_parent = np.asarray(
            fluid.core.as_array(parent.find_var(pname)))
        zl, = exe.run(main, feed={'x': xs}, fetch_list=[loss],
                      scope=kid)
        assert float(np.asarray(zl).ravel()[0]) == 0.0  # relu(0)=0
        # back on the parent: its buffers were untouched by the child
        # run and rebinding lands on the parent's (trained) values
        np.testing.assert_array_equal(
            np.asarray(fluid.core.as_array(parent.find_var(pname))),
            w_parent)
        again, = exe.run(main, feed={'x': xs}, fetch_list=[loss])
        assert np.isfinite(np.asarray(again)).all()
        # a NEW plan (param fed explicitly -> different feed keyset)
        # builds its own binding table and binds correctly
        import jax
        w = jax.device_put(np.full((8, 4), 0.25, 'float32'))
        fed, = exe.run(main, feed={'x': xs, pname: w},
                       fetch_list=[loss])
        assert np.isfinite(np.asarray(fed)).all()


def test_use_program_cache_false_bypasses_plan_cache():
    main, startup, loss = _tiny_train_program()
    xs = _xs()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        n_bypass0 = monitor.counter_value('executor/plan_cache_bypass')
        a, = exe.run(main, feed={'x': xs}, fetch_list=[loss],
                     use_program_cache=False)
        plan_keys = [k for k in main._exec_cache if k[0] == 'plan']
        assert not plan_keys  # nothing cached for the main program
        b, = exe.run(main, feed={'x': xs}, fetch_list=[loss],
                     use_program_cache=False)
        assert monitor.counter_value('executor/plan_cache_bypass') == \
            n_bypass0 + 2
        # same program state evolution as the cached path would give
        assert np.isfinite(np.asarray(a)).all()
        assert np.asarray(b).ravel()[0] < np.asarray(a).ravel()[0]
        c, = exe.run(main, feed={'x': xs}, fetch_list=[loss])
        assert [k for k in main._exec_cache if k[0] == 'plan']
        assert np.asarray(c).ravel()[0] < np.asarray(b).ravel()[0]


def test_check_nan_inf_device_verdict():
    """The nan/inf sweep computes its reduction on device and still
    names the poisoned var; clean programs pass."""
    fluid.set_flags({'FLAGS_check_nan_inf': True})
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = layers.data('a', shape=[2], dtype='float32')
            b = layers.log(a)
            out = layers.reduce_sum(b)
        exe = fluid.Executor(fluid.XLAPlace(0))
        with fluid.scope_guard(fluid.Scope()):
            with pytest.raises(FloatingPointError,
                               match=out.name):
                exe.run(main, feed={'a': -np.ones((3, 2), 'float32')},
                        fetch_list=[out])
            got, = exe.run(main,
                           feed={'a': np.ones((3, 2), 'float32')},
                           fetch_list=[out])
            assert np.isfinite(np.asarray(got)).all()
    finally:
        fluid.set_flags({'FLAGS_check_nan_inf': False})


def test_compiled_pipeline_records_run_counters():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.scale(x, scale=2.0)
        layers.Print(y)
        z = layers.scale(y, scale=3.0)
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        pipe = exe.compile(main, feed_names=('x',),
                           fetch_names=(z.name,), allow_host=True)
        calls0 = monitor.counter_value('executor/run_calls')
        secs0 = (monitor.histogram_value('executor/run_seconds')
                 or {'count': 0})['count']
        pipe({'x': np.ones((2, 4), 'float32')})
        pipe({'x': np.ones((2, 4), 'float32')})
        assert monitor.counter_value('executor/run_calls') == calls0 + 2
        assert monitor.histogram_value(
            'executor/run_seconds')['count'] == secs0 + 2


def test_fed_state_shared_across_segments_survives_donation():
    """A fed state var consumed by TWO device segments (split by a
    host op) must not be pointer-donated to the first one: the second
    segment — and the scope, which host plans publish feeds into —
    still reference the buffer.  Regression test for the staged-feed
    ownership claim being plan-wide instead of per-consumer; the
    pre-fast-path executor's value semantics (feed precedence: each
    segment binding a fed name starts from the FED value, so the
    second increment sees 0, not segment 1's write-back) must hold."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = layers.data('c', shape=[4], dtype='float32')
        c.stop_gradient = True
        layers.increment(c, value=1.0)          # segment 1: c state
        probe = main.current_block().create_var(
            name='host_probe', shape=[-1, 4], dtype='float32')
        layers.py_func(lambda a: a, c, probe)   # host op cuts the plan
        layers.increment(c, value=2.0)          # segment 2: c state
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        for _ in range(2):
            out, = exe.run(main, feed={'c': np.zeros((1, 4),
                                                     'float32')},
                           fetch_list=[c])
        np.testing.assert_array_equal(np.asarray(out), 2.0)


def test_reader_batch_reuse_is_donation_safe():
    """Reader-staged batches are handed to USER code — re-feeding one
    (overfit-one-batch loops, train+eval on the same batch) must never
    hit a donated buffer: reader buffers stay caller-owned and the
    executor copies them before donating."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        c = layers.data('c', shape=[4], dtype='float32')
        c.stop_gradient = True
        layers.increment(c, value=1.0)   # fed name is segment STATE
    exe = fluid.Executor(fluid.XLAPlace(0))

    def gen():
        yield {'c': np.zeros((1, 4), 'float32')}

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[c], capacity=2, use_double_buffer=True)
    loader.set_batch_generator(gen)
    with fluid.scope_guard(fluid.Scope()):
        batch = next(iter(loader))
        for _ in range(2):   # second use would read a donated buffer
            out, = exe.run(main, feed=batch, fetch_list=[c])
            np.testing.assert_array_equal(np.asarray(out), 1.0)
        np.testing.assert_array_equal(np.asarray(batch['c']), 0.0)


def test_host_only_feeds_stay_on_host():
    """A feed consumed ONLY by a host op must not be staged to the
    device (it would cross H2D and straight back every step): only the
    segment-consumed feed's bytes enter the async H2D counter."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        ids = layers.data('ids', shape=[1], dtype='int64')
        out_v = main.current_block().create_var(
            name='host_seen', shape=[-1, 1], dtype='int64')
        layers.py_func(lambda a: a, ids, out_v)
        y = layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.XLAPlace(0))
    xv = np.ones((2, 4), 'float32')
    idv = np.array([[1], [2]], 'int64')
    with fluid.scope_guard(fluid.Scope()):
        h2d0 = monitor.counter_value('executor/h2d_bytes_async')
        exe.run(main, feed={'x': xv, 'ids': idv}, fetch_list=[y])
        assert monitor.counter_value('executor/h2d_bytes_async') - \
            h2d0 == xv.nbytes
