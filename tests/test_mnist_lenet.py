"""MNIST LeNet end-to-end (BASELINE.json config[0]).

Mirrors the reference book test
python/paddle/fluid/tests/book/test_recognize_digits.py: build LeNet with
fluid.layers, train with Adam, assert the loss decreases and accuracy
rises on synthetic data.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def lenet(img, label):
    conv1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act='relu')
    conv2 = fluid.nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act='relu')
    prediction = fluid.layers.fc(input=conv2, size=10, act='softmax')
    loss = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return prediction, avg_loss, acc


def _synthetic_batch(batch_size, rng):
    """Classifiable synthetic digits: class k lights up a distinct patch."""
    label = rng.randint(0, 10, size=(batch_size, 1)).astype('int64')
    img = rng.randn(batch_size, 1, 28, 28).astype('float32') * 0.1
    for i, l in enumerate(label[:, 0]):
        r, c = divmod(int(l), 4)
        img[i, 0, 4 + r * 6:10 + r * 6, 2 + c * 6:8 + c * 6] += 1.0
    return img, label


def test_mnist_lenet_trains():
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 42
    with fluid.program_guard(main, startup):
        img = fluid.layers.data('img', shape=[1, 28, 28], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        pred, avg_loss, acc = lenet(img, label)
        opt = fluid.optimizer.Adam(learning_rate=0.001)
        opt.minimize(avg_loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(7)
        losses, accs = [], []
        for step in range(60):
            x, y = _synthetic_batch(32, rng)
            l, a = exe.run(main, feed={'img': x, 'label': y},
                           fetch_list=[avg_loss, acc])
            losses.append(float(l))
            accs.append(float(a))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert np.mean(accs[-10:]) > 0.7, np.mean(accs[-10:])


def test_lenet_test_program_clone():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data('img', shape=[1, 28, 28], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        pred, avg_loss, acc = lenet(img, label)
        test_program = main.clone(for_test=True)
        opt = fluid.optimizer.SGD(learning_rate=0.01)
        opt.minimize(avg_loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(3)
        x, y = _synthetic_batch(16, rng)
        l1, = exe.run(test_program, feed={'img': x, 'label': y},
                      fetch_list=[avg_loss])
        # eval run must not mutate params: same loss twice
        l2, = exe.run(test_program, feed={'img': x, 'label': y},
                      fetch_list=[avg_loss])
        np.testing.assert_allclose(l1, l2, rtol=1e-6)
