"""Op sweep part 3: behavioral coverage for the ops no other test
exercises — comparisons/logicals, fill-likes, indexing, linalg,
optimizer update rules vs numpy reference math, quantization helpers,
streaming AUC, detection host ops, save/load_combine, collective
variants inside shard_map, and the BoxPS/distributed sparse-table ops.

Reference model: the per-op OpTest discipline
(python/paddle/fluid/tests/unittests/test_*_op.py) — every op's
lowering validated through the real executor against a numpy oracle.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core
from op_test import OpTest

layers = fluid.layers
rng = np.random.RandomState(7)


# ---------------------------------------------------------------------------
# comparisons + logicals
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('op,ref', [
    ('greater_equal', np.greater_equal),
    ('less_equal', np.less_equal),
    ('not_equal', np.not_equal),
])
def test_comparison_ops(op, ref):
    t = OpTest()
    x = rng.randint(0, 4, (3, 4)).astype('float32')
    y = rng.randint(0, 4, (3, 4)).astype('float32')
    t.check_output(op, {'X': x, 'Y': y}, expect={'Out': ref(x, y)})


@pytest.mark.parametrize('op,ref', [
    ('logical_and', np.logical_and),
    ('logical_or', np.logical_or),
    ('logical_xor', np.logical_xor),
])
def test_logical_binary_ops(op, ref):
    t = OpTest()
    x = (rng.rand(3, 4) > 0.5)
    y = (rng.rand(3, 4) > 0.5)
    t.check_output(op, {'X': x, 'Y': y}, expect={'Out': ref(x, y)})


def test_logical_not():
    t = OpTest()
    x = (rng.rand(3, 4) > 0.5)
    t.check_output('logical_not', {'X': x},
                   expect={'Out': np.logical_not(x)})


# ---------------------------------------------------------------------------
# fill-likes / constants / misc tensor ops
# ---------------------------------------------------------------------------

def test_fill_any_like():
    t = OpTest()
    x = rng.randn(2, 3).astype('float32')
    t.check_output('fill_any_like', {'X': x}, attrs={'value': 2.5},
                   expect={'Out': np.full_like(x, 2.5)})


def test_fill_zeros_like():
    t = OpTest()
    x = rng.randn(2, 3).astype('float32')
    t.check_output('fill_zeros_like', {'X': x},
                   expect={'Out': np.zeros_like(x)})


def test_fill_constant_batch_size_like():
    t = OpTest()
    x = rng.randn(5, 3).astype('float32')
    t.check_output('fill_constant_batch_size_like', {'Input': x},
                   attrs={'shape': [1, 7], 'value': 3.0},
                   expect={'Out': np.full((5, 7), 3.0, 'float32')})


def test_assign_value():
    t = OpTest()
    vals = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = main.global_block().create_var(name='av_out', shape=(),
                                             dtype='float32')
        main.global_block().append_op(
            'assign_value', inputs={}, outputs={'Out': out},
            attrs={'shape': [2, 3], 'values': vals, 'dtype': 'float32'})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        got, = exe.run(main, feed={}, fetch_list=[out])
    np.testing.assert_allclose(
        got, np.asarray(vals, 'float32').reshape(2, 3))


def test_share_data_is_identity():
    t = OpTest()
    x = rng.randn(4, 2).astype('float32')
    t.check_output('share_data', {'X': x}, expect={'Out': x})


def test_is_empty():
    t = OpTest()
    t.check_output('is_empty', {'X': np.zeros((0, 3), 'float32')},
                   expect={'Out': np.asarray(True)})
    t.check_output('is_empty', {'X': np.ones((2, 3), 'float32')},
                   expect={'Out': np.asarray(False)})


def test_isnan_isinf():
    t = OpTest()
    x = np.array([1.0, np.nan, 2.0], 'float32')
    y = np.array([1.0, np.inf, 2.0], 'float32')
    t.check_output('isnan', {'X': x}, expect={'Out': np.asarray(True)})
    t.check_output('isnan', {'X': y}, expect={'Out': np.asarray(False)})
    t.check_output('isinf', {'X': y}, expect={'Out': np.asarray(True)})
    t.check_output('isinf', {'X': x}, expect={'Out': np.asarray(False)})


def test_one_hot_v2():
    t = OpTest()
    ids = np.array([[0], [2], [1]], 'int64')
    want = np.eye(4, dtype='float32')[[0, 2, 1]]
    t.check_output('one_hot_v2', {'X': ids}, attrs={'depth': 4},
                   expect={'Out': want})


def test_ceil():
    t = OpTest()
    x = rng.randn(3, 4).astype('float32') * 3
    t.check_output('ceil', {'X': x}, expect={'Out': np.ceil(x)})


# ---------------------------------------------------------------------------
# indexing / selection
# ---------------------------------------------------------------------------

def test_arg_min():
    t = OpTest()
    x = rng.randn(4, 5).astype('float32')
    t.check_output('arg_min', {'X': x}, attrs={'axis': 1},
                   expect={'Out': np.argmin(x, 1)})
    t.check_output('arg_min', {'X': x}, attrs={'axis': 0},
                   expect={'Out': np.argmin(x, 0)})


def test_gather_nd():
    t = OpTest()
    x = rng.randn(3, 4, 5).astype('float32')
    idx = np.array([[0, 1], [2, 3]], 'int64')
    t.check_output('gather_nd', {'X': x, 'Index': idx},
                   expect={'Out': x[[0, 2], [1, 3]]})
    t.check_grad('gather_nd', {'X': x, 'Index': idx})


def test_index_select():
    t = OpTest()
    x = rng.randn(4, 6).astype('float32')
    idx = np.array([3, 0, 0, 2], 'int64')
    t.check_output('index_select', {'X': x, 'Index': idx},
                   attrs={'dim': 0}, expect={'Out': x[idx]})
    t.check_output('index_select', {'X': x, 'Index': idx},
                   attrs={'dim': 1}, expect={'Out': x[:, idx]})
    t.check_grad('index_select', {'X': x, 'Index': idx},
                 attrs={'dim': 0})


def test_top_k_v2():
    t = OpTest()
    x = rng.randn(3, 8).astype('float32')
    got = t.run_op('top_k_v2', {'X': x}, attrs={'k': 3},
                   out_slots=('Out', 'Indices'))
    want = np.sort(x, axis=1)[:, ::-1][:, :3]
    np.testing.assert_allclose(got['Out'], want, rtol=1e-6)
    np.testing.assert_array_equal(
        np.take_along_axis(x, np.asarray(got['Indices'],
                                         'int64'), 1), want)


def test_reduce_any():
    t = OpTest()
    x = rng.rand(3, 4) > 0.7
    t.check_output('reduce_any', {'X': x}, attrs={'dim': [1]},
                   expect={'Out': x.any(1)})
    t.check_output('reduce_any', {'X': x}, attrs={'reduce_all': True},
                   expect={'Out': x.any()})


def test_unstack():
    main, startup = fluid.Program(), fluid.Program()
    x = rng.randn(3, 4).astype('float32')
    with fluid.program_guard(main, startup):
        xv = main.global_block().create_var(name='x', shape=(3, 4),
                                            dtype='float32')
        outs = [main.global_block().create_var(
            name='us_%d' % i, shape=(4,), dtype='float32')
            for i in range(3)]
        main.global_block().append_op('unstack', inputs={'X': xv},
                                      outputs={'Y': outs},
                                      attrs={'axis': 0, 'num': 3})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        got = exe.run(main, feed={'x': x}, fetch_list=list(outs))
    for i in range(3):
        np.testing.assert_allclose(got[i], x[i], rtol=1e-6)


# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------

def test_cholesky():
    t = OpTest()
    a = rng.randn(4, 4).astype('float32')
    spd = (a @ a.T + 4 * np.eye(4)).astype('float32')
    got = t.check_output('cholesky', {'X': spd},
                         expect={'Out': np.linalg.cholesky(spd)},
                         atol=1e-4)
    del got
    t.grad_rtol = 2e-2
    t.grad_atol = 2e-2
    t.check_grad('cholesky', {'X': spd})


def test_inverse():
    t = OpTest()
    a = rng.randn(3, 3).astype('float32')
    a = a + 3 * np.eye(3, dtype='float32')
    t.check_output('inverse', {'Input': a}, out_slots=['Output'],
                   expect={'Output': np.linalg.inv(a)}, atol=1e-4)
    t.check_grad('inverse', {'Input': a}, out_slot='Output')


# ---------------------------------------------------------------------------
# misc shape/value ops
# ---------------------------------------------------------------------------

def test_clip_by_norm():
    t = OpTest()
    x = rng.randn(3, 4).astype('float32') * 5
    norm = np.sqrt((x ** 2).sum())
    want = x * min(1.0, 2.0 / norm)
    t.check_output('clip_by_norm', {'X': x}, attrs={'max_norm': 2.0},
                   expect={'Out': want})
    t.check_grad('clip_by_norm', {'X': x}, attrs={'max_norm': 2.0})


def test_causal_mask_like():
    t = OpTest()
    x = rng.randn(2, 5, 8).astype('float32')
    got = t.run_op('causal_mask_like', {'X': x})['Out']
    assert got.shape == (1, 1, 5, 5)
    m = np.asarray(got)[0, 0]
    iu = np.triu_indices(5, 1)
    assert (m[iu] <= -1e8).all()
    assert (np.tril(m) == 0).all()


def test_sequence_reshape():
    t = OpTest()
    x = rng.randn(2, 6, 4).astype('float32')
    got = t.run_op('sequence_reshape', {'X': x},
                   attrs={'new_dim': 8})['Out']
    np.testing.assert_allclose(np.asarray(got),
                               x.reshape(2, 3, 8), rtol=1e-6)


def test_interp_nearest():
    t = OpTest()
    x = rng.randn(1, 2, 4, 4).astype('float32')
    got = t.run_op('interp_nearest', {'X': x},
                   attrs={'out_h': 8, 'out_w': 8})['Out']
    assert np.asarray(got).shape == (1, 2, 8, 8)
    # nearest upscale by 2: every 2x2 block equals the source pixel
    g = np.asarray(got)
    np.testing.assert_allclose(g[:, :, ::2, ::2], x, rtol=1e-6)


def test_random_crop():
    t = OpTest()
    x = np.arange(2 * 3 * 8 * 8, dtype='float32').reshape(2, 3, 8, 8)
    got = np.asarray(t.run_op('random_crop', {'X': x},
                              attrs={'shape': [5, 5]},
                              out_slots=('Out', 'SeedOut'))['Out'])
    assert got.shape == (2, 3, 5, 5)
    # each sample's crop must be a contiguous window of the source
    for b in range(2):
        found = any(
            np.array_equal(got[b], x[b, :, i:i + 5, j:j + 5])
            for i in range(4) for j in range(4))
        assert found, 'crop %d is not a window of the input' % b


def test_truncated_gaussian_random():
    t = OpTest()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = main.global_block().create_var(name='tgr', shape=(),
                                             dtype='float32')
        main.global_block().append_op(
            'truncated_gaussian_random', inputs={},
            outputs={'Out': out},
            attrs={'shape': [2000], 'mean': 1.0, 'std': 0.5})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        got, = exe.run(main, feed={}, fetch_list=[out])
    g = np.asarray(got)
    assert g.shape == (2000,)
    # truncation at 2 std
    assert g.min() >= 1.0 - 2 * 0.5 - 1e-5
    assert g.max() <= 1.0 + 2 * 0.5 + 1e-5
    assert abs(g.mean() - 1.0) < 0.05


# ---------------------------------------------------------------------------
# quantization helpers
# ---------------------------------------------------------------------------

def test_fake_dequantize_max_abs():
    t = OpTest()
    x = rng.randint(-127, 127, (3, 4)).astype('float32')
    scale = np.array([0.5], 'float32')
    t.check_output('fake_dequantize_max_abs',
                   {'X': x, 'Scale': scale},
                   attrs={'max_range': 127.0},
                   expect={'Out': x * 0.5 / 127.0})


def test_moving_average_abs_max_scale():
    t = OpTest()
    x = rng.randn(3, 4).astype('float32')
    in_scale = np.array([0.8], 'float32')
    got = t.run_op('moving_average_abs_max_scale',
                   {'X': x, 'InScale': in_scale},
                   attrs={'moving_rate': 0.9},
                   out_slots=('Out', 'OutScale'))
    np.testing.assert_allclose(got['Out'], x, rtol=1e-6)
    want = 0.9 * 0.8 + 0.1 * np.abs(x).max()
    np.testing.assert_allclose(got['OutScale'], [want], rtol=1e-5)


# ---------------------------------------------------------------------------
# optimizer update rules vs numpy reference math
# (reference operators/optimizers/*_op.h formulas)
# ---------------------------------------------------------------------------

def _opt_inputs(shape=(4, 3)):
    p = rng.randn(*shape).astype('float32')
    g = rng.randn(*shape).astype('float32')
    lr = np.array([0.1], 'float32')
    return p, g, lr


def test_adamw_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    m1 = rng.randn(4, 3).astype('float32') * 0.1
    m2 = np.abs(rng.randn(4, 3)).astype('float32') * 0.1
    b1p = np.array([0.9], 'float32')
    b2p = np.array([0.999], 'float32')
    got = t.run_op('adamw', {'Param': p, 'Grad': g, 'LearningRate': lr,
                             'Moment1': m1, 'Moment2': m2,
                             'Beta1Pow': b1p, 'Beta2Pow': b2p},
                   attrs={'coeff': 0.01},
                   out_slots=('ParamOut', 'Moment1Out', 'Moment2Out'))
    b1, b2, eps = 0.9, 0.999, 1e-8
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * np.sqrt(1 - b2p * b2) / (1 - b1p * b1)
    want = p - lr_t * m1n / (np.sqrt(m2n) + eps) - lr * 0.01 * p
    np.testing.assert_allclose(got['ParamOut'], want, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(got['Moment1Out'], m1n, rtol=1e-6)


def test_rmsprop_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    ms = np.abs(rng.randn(4, 3)).astype('float32')
    mom = rng.randn(4, 3).astype('float32') * 0.1
    got = t.run_op('rmsprop',
                   {'Param': p, 'Grad': g, 'LearningRate': lr,
                    'MeanSquare': ms, 'Moment': mom},
                   attrs={'decay': 0.95, 'epsilon': 1e-6,
                          'momentum': 0.9},
                   out_slots=('ParamOut', 'MomentOut', 'MeanSquareOut'))
    msn = 0.95 * ms + 0.05 * g * g
    momn = 0.9 * mom + lr * g / np.sqrt(msn + 1e-6)
    np.testing.assert_allclose(got['MeanSquareOut'], msn, rtol=1e-5)
    np.testing.assert_allclose(got['MomentOut'], momn, rtol=1e-5)
    np.testing.assert_allclose(got['ParamOut'], p - momn, rtol=1e-5)


def test_rmsprop_centered_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    ms = np.abs(rng.randn(4, 3)).astype('float32')
    mg = rng.randn(4, 3).astype('float32') * 0.1
    mom = np.zeros((4, 3), 'float32')
    got = t.run_op('rmsprop',
                   {'Param': p, 'Grad': g, 'LearningRate': lr,
                    'MeanSquare': ms, 'MeanGrad': mg, 'Moment': mom},
                   attrs={'decay': 0.95, 'epsilon': 1e-6,
                          'momentum': 0.0, 'centered': True},
                   out_slots=('ParamOut', 'MeanGradOut'))
    msn = 0.95 * ms + 0.05 * g * g
    mgn = 0.95 * mg + 0.05 * g
    momn = lr * g / np.sqrt(msn - mgn * mgn + 1e-6)
    np.testing.assert_allclose(got['MeanGradOut'], mgn, rtol=1e-5)
    np.testing.assert_allclose(got['ParamOut'], p - momn, rtol=1e-5)


def test_ftrl_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    sq = np.abs(rng.randn(4, 3)).astype('float32')
    lin = rng.randn(4, 3).astype('float32') * 0.1
    l1, l2 = 0.1, 0.2
    got = t.run_op('ftrl',
                   {'Param': p, 'Grad': g, 'LearningRate': lr,
                    'SquaredAccumulator': sq, 'LinearAccumulator': lin},
                   attrs={'l1': l1, 'l2': l2, 'lr_power': -0.5},
                   out_slots=('ParamOut', 'SquaredAccumOut',
                              'LinearAccumOut'))
    new_sq = sq + g * g
    sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / lr
    lin_out = lin + g - sigma * p
    denom = np.sqrt(new_sq) / lr + 2 * l2
    pre = np.clip(lin_out, -l1, l1) - lin_out
    np.testing.assert_allclose(got['SquaredAccumOut'], new_sq,
                               rtol=1e-5)
    np.testing.assert_allclose(got['LinearAccumOut'], lin_out,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got['ParamOut'], pre / denom,
                               rtol=1e-4, atol=1e-6)


def test_lars_momentum_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    v = rng.randn(4, 3).astype('float32') * 0.1
    got = t.run_op('lars_momentum',
                   {'Param': p, 'Grad': g, 'LearningRate': lr,
                    'Velocity': v},
                   attrs={'mu': 0.9, 'lars_coeff': 0.001,
                          'lars_weight_decay': 0.0005},
                   out_slots=('ParamOut', 'VelocityOut'))
    pn = np.sqrt((p ** 2).sum())
    gn = np.sqrt((g ** 2).sum())
    local_lr = lr * 0.001 * pn / (gn + 0.0005 * pn)
    vn = 0.9 * v + local_lr * (g + 0.0005 * p)
    np.testing.assert_allclose(got['VelocityOut'], vn, rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(got['ParamOut'], p - vn, rtol=1e-4,
                               atol=1e-6)


def test_proximal_gd_rule():
    t = OpTest()
    p, g, lr = _opt_inputs()
    got = t.run_op('proximal_gd',
                   {'Param': p, 'Grad': g, 'LearningRate': lr},
                   attrs={'l1': 0.05, 'l2': 0.1},
                   out_slots=('ParamOut',))
    prox = p - lr * g
    want = (np.sign(prox) * np.maximum(np.abs(prox) - lr * 0.05, 0.0) /
            (1.0 + lr * 0.1))
    np.testing.assert_allclose(got['ParamOut'], want, rtol=1e-5,
                               atol=1e-6)


def test_dpsgd_clips_gradient():
    """sigma=0 isolates the clipping: update = lr * g * clip/||g||."""
    t = OpTest()
    p, g, lr = _opt_inputs()
    g = g * 100  # make ||g|| >> clip
    got = t.run_op('dpsgd', {'Param': p, 'Grad': g,
                             'LearningRate': lr},
                   attrs={'clip': 1.0, 'sigma': 0.0},
                   out_slots=('ParamOut',))
    gn = np.sqrt((g ** 2).sum())
    want = p - lr * g / (gn / 1.0)
    np.testing.assert_allclose(got['ParamOut'], want, rtol=1e-4,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# streaming AUC vs numpy
# ---------------------------------------------------------------------------

def test_auc_streaming():
    t = OpTest()
    n_thr = 255
    preds = rng.rand(200, 2).astype('float32')
    labels = (rng.rand(200) > 0.5).astype('int64').reshape(-1, 1)
    stat = np.zeros((n_thr + 1,), 'int64')
    got = t.run_op('auc', {'Predict': preds, 'Label': labels,
                           'StatPos': stat, 'StatNeg': stat.copy()},
                   attrs={'num_thresholds': n_thr},
                   out_slots=('AUC', 'StatPosOut', 'StatNegOut'))
    # numpy oracle: rank-sum AUC on the same bucketized scores
    bucket = np.clip((preds[:, 1] * n_thr).astype(int), 0, n_thr)
    pos = bucket[labels.ravel() > 0]
    neg = bucket[labels.ravel() == 0]
    wins = (pos[:, None] > neg[None, :]).sum() + \
        0.5 * (pos[:, None] == neg[None, :]).sum()
    want = wins / (len(pos) * len(neg))
    np.testing.assert_allclose(float(np.asarray(got['AUC'])), want,
                               atol=5e-3)
    assert int(np.asarray(got['StatPosOut']).sum()) == len(pos)
    assert int(np.asarray(got['StatNegOut']).sum()) == len(neg)


# ---------------------------------------------------------------------------
# detection ops
# ---------------------------------------------------------------------------

def test_bipartite_match():
    t = OpTest()
    dist = np.array([[0.9, 0.1, 0.3],
                     [0.2, 0.8, 0.2]], 'float32')
    got = t.run_op('bipartite_match', {'DistMat': dist},
                   out_slots=('ColToRowMatchIndices',
                              'ColToRowMatchDist'))
    np.testing.assert_array_equal(
        np.asarray(got['ColToRowMatchIndices']), [[0, 1, -1]])
    np.testing.assert_allclose(
        np.asarray(got['ColToRowMatchDist']), [[0.9, 0.8, 0.0]])


def test_box_decoder_and_assign():
    t = OpTest()
    prior = np.array([[0., 0., 4., 4.],
                      [2., 2., 6., 6.]], 'float32')
    n, c = 2, 3
    deltas = np.zeros((n, 4 * c), 'float32')  # zero deltas: box=prior
    scores = rng.rand(n, c + 1).astype('float32')
    got = t.run_op('box_decoder_and_assign',
                   {'PriorBox': prior, 'TargetBox': deltas,
                    'BoxScore': scores},
                   out_slots=('DecodeBox', 'OutputAssignBox'))
    ab = np.asarray(got['OutputAssignBox'])
    np.testing.assert_allclose(ab, prior, atol=1e-5)


def test_generate_proposals_sane():
    t = OpTest()
    N, A, H, W = 1, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype('float32')
    deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype('float32')
    im_info = np.array([[32., 32., 1.]], 'float32')
    base = np.array([[0., 0., 8., 8.], [2., 2., 10., 10.],
                     [4., 4., 12., 12.]], 'float32')
    anchors = np.tile(base[None, None], (H, W, 1, 1)).astype('float32')
    variances = np.ones_like(anchors) * 0.1
    got = t.run_op('generate_proposals',
                   {'Scores': scores, 'BboxDeltas': deltas,
                    'ImInfo': im_info, 'Anchors': anchors,
                    'Variances': variances},
                   attrs={'pre_nms_topN': 20, 'post_nms_topN': 8,
                          'nms_thresh': 0.7, 'min_size': 0.5},
                   out_slots=('RpnRois', 'RpnRoiProbs'))
    rois = np.asarray(got['RpnRois']).reshape(-1, 4)
    assert (rois[:, 0] >= -1e-3).all() and (rois[:, 2] <= 32 + 1e-3).all()
    probs = np.asarray(got['RpnRoiProbs']).ravel()
    assert ((probs >= 0) & (probs <= 1)).all()


def test_locality_aware_nms():
    t = OpTest()
    boxes = rng.rand(1, 5, 8).astype('float32') * 10
    scores = rng.rand(1, 1, 5).astype('float32')
    got = t.run_op('locality_aware_nms',
                   {'BBoxes': boxes, 'Scores': scores},
                   attrs={'keep_top_k': 3},
                   out_slots=('Out',))
    out = np.asarray(got['Out'])
    assert out.shape == (3, 6)
    # rows sorted by descending score
    assert (np.diff(out[:, 1]) <= 1e-6).all()


def test_retinanet_target_assign():
    t = OpTest()
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [0, 0, 9, 9]], 'float32')
    gt = np.array([[0, 0, 10, 10]], 'float32')
    got = t.run_op('retinanet_target_assign',
                   {'Anchor': anchors, 'GtBoxes': gt},
                   attrs={'rpn_positive_overlap': 0.7,
                          'rpn_negative_overlap': 0.3},
                   out_slots=('LocationIndex', 'ScoreIndex',
                              'TargetLabel', 'TargetBBox'))
    loc = np.asarray(got['LocationIndex']).ravel()
    assert 0 in loc  # the exact-match anchor is foreground
    lab = np.asarray(got['TargetLabel']).ravel()
    assert set(lab.tolist()) <= {0, 1}


def test_generate_proposal_labels_and_masks():
    t = OpTest()
    rois = np.array([[0, 0, 10, 10], [20, 20, 28, 28]], 'float32')
    gt_cls = np.array([2], 'int64')
    gt_box = np.array([[0, 0, 10, 10]], 'float32')
    got = t.run_op('generate_proposal_labels',
                   {'RpnRois': rois, 'GtClasses': gt_cls,
                    'GtBoxes': gt_box},
                   attrs={'batch_size_per_im': 4, 'fg_thresh': 0.5},
                   out_slots=('Rois', 'LabelsInt32', 'BboxTargets'))
    labels = np.asarray(got['LabelsInt32']).ravel()
    assert 2 in labels  # the matching roi gets the gt class
    out_rois = np.asarray(got['Rois'])
    got2 = t.run_op('generate_mask_labels', {'Rois': out_rois},
                    attrs={'resolution': 7},
                    out_slots=('MaskRois', 'RoiHasMaskInt32',
                               'MaskInt32'))
    assert np.asarray(got2['MaskInt32']).shape == (len(out_rois), 49)


def test_roi_perspective_transform():
    t = OpTest()
    x = np.arange(1 * 1 * 8 * 8, dtype='float32').reshape(1, 1, 8, 8)
    rois = np.array([[1, 1, 5, 1, 5, 5, 1, 5]], 'float32')  # quad
    got = t.run_op('roi_perspective_transform',
                   {'X': x, 'ROIs': rois},
                   attrs={'transformed_height': 4,
                          'transformed_width': 4},
                   out_slots=('Out',))
    out = np.asarray(got['Out'])
    assert out.shape == (1, 1, 4, 4)
    # values come from the roi's window of the source
    assert out.min() >= x[0, 0, 1:6, 1:6].min() - 1e-5
    assert out.max() <= x[0, 0, 1:6, 1:6].max() + 1e-5


# ---------------------------------------------------------------------------
# save/load_combine
# ---------------------------------------------------------------------------

def test_save_load_combine_roundtrip(tmp_path):
    path = str(tmp_path / 'combined')
    a = rng.randn(3, 4).astype('float32')
    b = rng.randn(2,).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        av = main.global_block().create_var(name='cv_a', shape=(3, 4),
                                            dtype='float32')
        bv = main.global_block().create_var(name='cv_b', shape=(2,),
                                            dtype='float32')
        main.global_block().append_op(
            'save_combine', inputs={'X': [av, bv]}, outputs={},
            attrs={'file_path': path})
    load_prog = fluid.Program()
    with fluid.program_guard(load_prog, fluid.Program()):
        a2 = load_prog.global_block().create_var(
            name='cv_a', shape=(3, 4), dtype='float32')
        b2 = load_prog.global_block().create_var(
            name='cv_b', shape=(2,), dtype='float32')
        load_prog.global_block().append_op(
            'load_combine', inputs={}, outputs={'Out': [a2, b2]},
            attrs={'file_path': path})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(main, feed={'cv_a': a, 'cv_b': b}, fetch_list=[])
        got_a, got_b = exe.run(load_prog, feed={},
                               fetch_list=['cv_a', 'cv_b'])
    np.testing.assert_allclose(got_a, a, rtol=1e-6)
    np.testing.assert_allclose(got_b, b, rtol=1e-6)


# ---------------------------------------------------------------------------
# collective variants inside shard_map (8-device CPU mesh)
# ---------------------------------------------------------------------------

def test_collective_variant_ops():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.ops import registry

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ('dp',))
    n = len(devs)
    x = (rng.rand(n, 4).astype('float32') + 0.5)

    def body(xs):
        ctx = registry.LowerCtx(0)

        def run(name, val, **attrs):
            return registry.get(name).fn(
                ctx, {'X': [val]},
                dict({'ring_id': 0}, **attrs))['Out'][0]
        mn = run('c_allreduce_min', xs)
        pr = run('c_allreduce_prod', xs)
        mp_sum = run('mp_allreduce_sum', xs)
        ident = run('c_identity', xs)
        cat = run('c_concat', xs)               # [1, n*4]
        sc1 = run('c_sync_calc_stream', xs)
        sp = run('c_split', cat, nranks=n)      # undo the concat
        return mn, pr, mp_sum, ident, cat, sc1, sp

    from paddle_tpu.compat import shard_map
    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P('dp'),),
        out_specs=(P(), P(), P(), P('dp'), P('dp'), P('dp'),
                   P('dp'))))
    mn, pr, mp_sum, ident, cat, sc1, sp = f(x)
    np.testing.assert_allclose(np.asarray(mn).reshape(4), x.min(0),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pr).reshape(4), x.prod(0),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(mp_sum).reshape(4), x.sum(0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ident), x, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sc1), x, rtol=1e-6)
    # c_concat: all_gather along last dim -> every shard sees all cols
    np.testing.assert_allclose(
        np.asarray(cat), np.tile(x.reshape(1, -1), (n, 1)), rtol=1e-6)
    # c_split of the gathered tensor gives back each shard's slice
    np.testing.assert_allclose(np.asarray(sp), x, rtol=1e-6)


def test_c_reducescatter():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.ops import registry

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ('dp',))
    n = len(devs)
    # each shard holds a local [n, 3] block; reduce-scatter leaves
    # every shard with its [1, 3] slice of the cross-shard sum
    x = rng.rand(n * n, 3).astype('float32')

    def body(xs):
        ctx = registry.LowerCtx(0)
        return registry.get('c_reducescatter').fn(
            ctx, {'X': [xs]}, {'ring_id': 0})['Out'][0]

    from paddle_tpu.compat import shard_map
    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P('dp'),),
                          out_specs=P('dp')))
    got = np.asarray(f(x))
    want = x.reshape(n, n, 3).sum(0)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_collective_init_ops_are_noops():
    main, startup = fluid.Program(), fluid.Program()
    x = rng.randn(2, 3).astype('float32')
    with fluid.program_guard(main, startup):
        xv = main.global_block().create_var(name='x', shape=(2, 3),
                                            dtype='float32')
        out = main.global_block().create_var(name='ci_out', shape=(),
                                             dtype='float32')
        for t in ('c_comm_init_all', 'c_gen_nccl_id', 'c_comm_init'):
            main.global_block().append_op(t, inputs={}, outputs={},
                                          attrs={})
        main.global_block().append_op('scale', inputs={'X': xv},
                                      outputs={'Out': out},
                                      attrs={'scale': 2.0})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        got, = exe.run(main, feed={'x': x}, fetch_list=[out])
    np.testing.assert_allclose(got, x * 2, rtol=1e-6)


# ---------------------------------------------------------------------------
# BoxPS / distributed sparse-table host ops
# ---------------------------------------------------------------------------

def test_box_sparse_and_distributed_lookup():
    from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding
    emb = HostShardedEmbedding('sweep3_box_emb', 50, 4, optimizer='sgd',
                               learning_rate=0.5, distributed=False)
    ids = np.array([3, 7, 3], 'int64')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        iv = blk.create_var(name='ids', shape=(3,), dtype='int64')
        ov = blk.create_var(name='emb_out', shape=(), dtype='float32')
        blk.append_op('pull_box_sparse', inputs={'Ids': [iv]},
                      outputs={'Out': [ov]},
                      attrs={'table': 'sweep3_box_emb'})
        o2 = blk.create_var(name='emb_out2', shape=(), dtype='float32')
        blk.append_op('distributed_lookup_table',
                      inputs={'Ids': [iv]}, outputs={'Outputs': [o2]},
                      attrs={'table': 'sweep3_box_emb'})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        pulled, pulled2 = exe.run(main, feed={'ids': ids},
                                  fetch_list=[ov, o2])
    want = emb._pull(ids)
    np.testing.assert_allclose(np.asarray(pulled), want, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pulled2), want, rtol=1e-6)

    # push: rows 3 and 7 move against the summed grads, others don't
    before = emb._pull(np.arange(50, dtype='int64')).copy()
    grad = np.ones((3, 4), 'float32')
    push_main = fluid.Program()
    with fluid.program_guard(push_main, fluid.Program()):
        blk = push_main.global_block()
        iv = blk.create_var(name='ids', shape=(3,), dtype='int64')
        gv = blk.create_var(name='emb_g', shape=(3, 4),
                            dtype='float32')
        blk.append_op('push_box_sparse',
                      inputs={'Ids': [iv], 'Out@GRAD': [gv]},
                      outputs={},
                      attrs={'table': 'sweep3_box_emb'})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(push_main, feed={'ids': ids, 'emb_g': grad},
                fetch_list=[])
    after = emb._pull(np.arange(50, dtype='int64'))
    assert not np.allclose(after[3], before[3])
    assert not np.allclose(after[7], before[7])
    mask = np.ones(50, bool)
    mask[[3, 7]] = False
    np.testing.assert_allclose(after[mask], before[mask])


def test_get_tensor_from_selected_rows():
    sr = core.SelectedRows(rows=np.array([1, 3], 'int64'),
                           value=rng.randn(2, 4).astype('float32'),
                           height=6)
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        blk = main.global_block()
        xv = blk.create_var(name='sr_in', shape=(), dtype='float32')
        ov = blk.create_var(name='sr_out', shape=(), dtype='float32')
        blk.append_op('get_tensor_from_selected_rows',
                      inputs={'X': xv}, outputs={'Out': ov}, attrs={})
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        scope.set_var('sr_in', sr)
        exe = fluid.Executor(fluid.XLAPlace(0))
        got, = exe.run(main, feed={}, fetch_list=[ov])
    np.testing.assert_allclose(np.asarray(got), np.asarray(sr.value),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# fused elementwise + activation
# ---------------------------------------------------------------------------

def test_fused_elemwise_activation():
    t = OpTest()
    x = rng.randn(3, 4).astype('float32')
    y = rng.randn(3, 4).astype('float32')
    got = t.run_op('fused_elemwise_activation', {'X': x, 'Y': y},
                   attrs={'functor_list': ['elementwise_add', 'relu']},
                   out_slots=('Out', 'IntermediateOut'))
    np.testing.assert_allclose(got['Out'], np.maximum(x + y, 0),
                               rtol=1e-6)
    np.testing.assert_allclose(got['IntermediateOut'], x + y,
                               rtol=1e-6)


def test_split_byref_matches_split():
    t = OpTest()
    x = rng.randn(4, 6).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        xv = blk.create_var(name='x', shape=(4, 6), dtype='float32')
        outs = [blk.create_var(name='sb_%d' % i, shape=(4, 2),
                               dtype='float32') for i in range(3)]
        blk.append_op('split_byref', inputs={'X': xv},
                      outputs={'Out': outs},
                      attrs={'num': 3, 'axis': 1})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        got = exe.run(main, feed={'x': x}, fetch_list=list(outs))
    for i in range(3):
        np.testing.assert_allclose(got[i], x[:, 2 * i:2 * i + 2],
                                   rtol=1e-6)


def test_continuous_value_model_aliases_cvm():
    t = OpTest()
    # cvm input convention: [N, D] with first two cols show/click
    x = np.abs(rng.randn(4, 6)).astype('float32') + 1.0
    from paddle_tpu.ops import registry as _reg
    ctx = _reg.LowerCtx(0)
    want = _reg.get('cvm').fn(ctx, {'X': [x]}, {'use_cvm': True})
    got = t.run_op('continuous_value_model', {'X': x},
                   attrs={'use_cvm': True}, out_slots=('Y',))
    np.testing.assert_allclose(np.asarray(got['Y']),
                               np.asarray(want['Y'][0]), rtol=1e-6)


def test_c_sync_comm_stream_passthrough():
    from paddle_tpu.ops import registry as _reg
    ctx = _reg.LowerCtx(0)
    xs = [rng.randn(2, 2).astype('float32'),
          rng.randn(3,).astype('float32')]
    out = _reg.get('c_sync_comm_stream').fn(ctx, {'X': xs}, {})['Out']
    for o, x in zip(out, xs):
        np.testing.assert_allclose(np.asarray(o), x, rtol=1e-6)
