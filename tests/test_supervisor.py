"""Self-healing supervisor (fluid/supervisor.py) + hung-step watchdog
+ serving deadline shedding + the rejoin-backoff satellite.

The decision-table tests drive the controller with SCRIPTED peer-view
sequences (injected heartbeat-loss signals) and call ``_tick()``
directly, so every decision is deterministic: a flap that recovers
under the miss threshold must not reshard; a death + rejoin race must
resolve to exactly ONE recovery action; checkpoint backpressure must
never overlap saves; a frozen controller (FLAGS_supervisor=0) must log
intents without acting."""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (elastic, faultinject, layers, monitor,
                              supervisor)

SUP_FLAGS = ('FLAGS_supervisor', 'FLAGS_supervisor_checkpoint_steps',
             'FLAGS_supervisor_rejoin_wait_s', 'FLAGS_step_timeout_s',
             'FLAGS_faultinject', 'FLAGS_elastic_checkpoint',
             'FLAGS_elastic_keep_generations', 'FLAGS_trace')


@pytest.fixture(autouse=True)
def _clean():
    prev = fluid.get_flags(list(SUP_FLAGS))
    monitor.reset()
    supervisor.reset()
    elastic.reset()
    faultinject.reset()
    yield
    fluid.set_flags(prev)
    supervisor.reset()
    faultinject.reset()
    elastic.reset()
    monitor.reset()


def _build(seed=7):
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[8], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            h = layers.fc(x, 16, act='relu')
            pred = layers.fc(h, 1)
            loss = layers.reduce_mean(layers.square(
                layers.elementwise_sub(pred, y)))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _batch(s, n=8):
    rng = np.random.RandomState(100 + s)
    x = rng.randn(n, 8).astype('float32')
    return x, (x.sum(1, keepdims=True) * 0.5).astype('float32')


def _f(val):
    return float(np.asarray(val).ravel()[0])


class _Peers(object):
    """Scripted peer view: a mutable {rank: state} the tests step
    through injected heartbeat-loss sequences."""

    def __init__(self, *ranks):
        self.state = {r: dict(up=True, ready=True, misses=0,
                              was_up=True, confirmed_down=False,
                              endpoint='scripted')
                      for r in ranks}

    def __call__(self):
        return {r: dict(v) for r, v in self.state.items()}

    def set(self, rank, **kw):
        self.state[rank].update(kw)


def _mk_sup(store, peers=None, price=None, **kw):
    """A Supervisor WITHOUT a controller thread: tests drive _tick()
    by hand so every decision lands deterministically."""
    kw.setdefault('checkpoint_steps', 0)
    sup = supervisor.Supervisor(store, peers=peers or _Peers('1'),
                                price=price, **kw)
    return sup


def _kinds(decs=None):
    return [(d['kind'], d['choice']) for d in
            (decs if decs is not None else supervisor.decisions())]


# ------------------------------------------------------ decision table
def test_flap_under_threshold_never_triggers_recovery():
    peers = _Peers('1')
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'), peers=peers)
    # injected loss sequence: two consecutive misses (threshold 3),
    # then recovery — the aggregator counts a flap, never a death
    for misses in (1, 2):
        peers.set('1', up=False, misses=misses)
        sup._tick()
    peers.set('1', up=True, misses=0)
    monitor.add('elastic/heartbeat_flaps')   # the aggregator's count
    sup._tick()
    kinds = _kinds()
    assert ('heartbeat_flap', 'tolerate') in kinds
    assert not any(k in ('death', 'recovery') for k, _c in kinds)
    assert sup._pending_recovery is None
    assert monitor.counter_value('supervisor/deaths_confirmed') == 0


def test_confirmed_death_cheap_reshard_degrades_immediately():
    peers = _Peers('1')
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'), peers=peers,
                  price=lambda: 0.001, rejoin_wait_s=5.0)
    peers.set('1', up=False, misses=3, confirmed_down=True)
    sup._tick()
    assert ('death', 'degrade_to_survivors') in _kinds()
    assert sup._pending_recovery is not None
    assert monitor.counter_value('supervisor/deaths_confirmed') == 1
    # further ticks with the worker still down do not re-decide
    sup._tick()
    sup._tick()
    assert monitor.counter_value('supervisor/deaths_confirmed') == 1


def test_death_rejoin_race_resolves_to_one_recovery_action():
    # pricing says the reshard costs MORE than the budget -> the
    # controller waits; the worker rejoins inside the budget -> the
    # ONLY recovery action is the readmission, never a reshard
    peers = _Peers('1')
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'), peers=peers,
                  price=lambda: 100.0, rejoin_wait_s=30.0)
    peers.set('1', up=False, misses=3, confirmed_down=True)
    sup._tick()
    assert ('death', 'wait_for_rejoin') in _kinds()
    assert sup.state == 'waiting_rejoin'
    assert sup._pending_recovery is None
    # the race: the worker answers again on the same tick the budget
    # would also be checked — readmission must win and close the
    # incident with exactly one action
    peers.set('1', up=True, misses=0, confirmed_down=False)
    sup._tick()
    kinds = _kinds()
    assert ('rejoin', 'readmit') in kinds
    assert ('death', 'degrade_after_wait') not in kinds
    assert ('death', 'degrade_to_survivors') not in kinds
    assert sup._pending_recovery is None
    assert sup.state == 'idle'
    # budget expiry later cannot fire a second action
    sup._wait_deadline = None
    sup._tick()
    recovery_actions = [k for k in _kinds()
                        if k in (('rejoin', 'readmit'),
                                 ('death', 'degrade_after_wait'))]
    assert recovery_actions == [('rejoin', 'readmit')]


def test_wait_budget_expiry_degrades_exactly_once():
    peers = _Peers('1')
    clock = [0.0]
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'), peers=peers,
                  price=lambda: 100.0, rejoin_wait_s=2.0,
                  clock=lambda: clock[0])
    peers.set('1', up=False, misses=3, confirmed_down=True)
    sup._tick()
    assert sup.state == 'waiting_rejoin'
    clock[0] = 5.0     # past the budget, worker still dead
    sup._tick()
    sup._tick()
    assert _kinds().count(('death', 'degrade_after_wait')) == 1
    assert sup._pending_recovery is not None


def test_frozen_controller_logs_intents_without_acting():
    fluid.set_flags({'FLAGS_supervisor': False})
    peers = _Peers('1')
    calls = []
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'), peers=peers,
                  price=lambda: 0.0, rejoin_wait_s=5.0,
                  checkpoint_steps=1,
                  save_fn=lambda *a: calls.append(a) or 1)
    peers.set('1', up=False, misses=3, confirmed_down=True)
    sup._tick()
    decs = supervisor.decisions()
    assert any(d['kind'] == 'death' for d in decs)
    assert all(d['acted'] is False and d['frozen'] for d in decs)
    assert sup._pending_recovery is None          # intent only
    assert monitor.counter_value('supervisor/frozen_intents') >= 1
    # checkpoint cadence: intent logged, no save executed
    import types
    sup.maybe_checkpoint(types.SimpleNamespace(_step=5))
    assert calls == []
    assert any(d['kind'] == 'checkpoint' and not d['acted']
               for d in supervisor.decisions())


# -------------------------------------------------- checkpoint plane
def test_checkpoint_backpressure_never_overlaps_saves():
    store = tempfile.mkdtemp(prefix='pt_sup_')
    inflight = [0]
    peak = [0]
    done = []

    def slow_save(dirname, program, scope, shim):
        inflight[0] += 1
        peak[0] = max(peak[0], inflight[0])
        time.sleep(0.15)
        inflight[0] -= 1
        done.append(shim._step)
        return len(done)

    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sup = supervisor.attach(store, program=main, executor=exe,
                                checkpoint_steps=1, save_fn=slow_save,
                                start=False)
        try:
            for s in range(8):
                x, y = _batch(s)
                exe.run(main, feed={'x': x, 'y': y},
                        fetch_list=[loss])
            t = sup._save_thread
            if t is not None:
                t.join(timeout=10)
        finally:
            supervisor.detach()
    assert peak[0] == 1, 'two saves overlapped'
    assert monitor.counter_value('supervisor/checkpoint_deferred') > 0
    assert any(d['kind'] == 'checkpoint' and
               d['choice'] == 'deferred_backpressure'
               for d in supervisor.decisions())
    assert len(done) >= 1


def test_cadence_stretches_when_save_wall_approaches_interval():
    store = tempfile.mkdtemp(prefix='pt_sup_')
    clock = [0.0]

    def slow_save(dirname, program, scope, shim):
        time.sleep(0.002)    # >> half the scripted 1e-3s trigger gap
        return 1

    sup = _mk_sup(store, checkpoint_steps=2, save_fn=slow_save,
                  clock=lambda: clock[0])
    main, startup, loss = _build()
    sup._program = main
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        sup._scope = fluid.global_scope()
        exe.run(startup)
        import types
        # first trigger establishes the reference wall; second arrives
        # only 1e-3 "seconds" later so even a fast save exceeds half
        # the gap -> the cadence must double
        sup.maybe_checkpoint(types.SimpleNamespace(_step=2))
        sup._save_thread.join(10)
        clock[0] = 1e-3
        sup.maybe_checkpoint(types.SimpleNamespace(_step=4))
        sup._save_thread.join(10)
    assert monitor.counter_value('supervisor/cadence_stretched') >= 1
    assert sup._cadence >= 4
    assert any(d['kind'] == 'cadence_stretched'
               for d in supervisor.decisions())


def test_torn_checkpoint_detected_and_resaved():
    store = tempfile.mkdtemp(prefix='pt_sup_')
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        x, y = _batch(0)
        exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
        sup = supervisor.attach(store, program=main, executor=exe,
                                checkpoint_steps=1, start=False)
        try:
            # tear the first shard of the FIRST generation: the
            # supervisor's post-save verification must catch the
            # digest mismatch and resave immediately
            faultinject.configure('elastic.shard_write:torn@1')
            sup.maybe_checkpoint(exe)
            sup._save_thread.join(30)
        finally:
            supervisor.detach()
    assert monitor.counter_value('supervisor/checkpoint_torn') == 1
    decs = supervisor.decisions()
    assert any(d['kind'] == 'checkpoint_torn' and
               d['choice'] == 'resave' and
               d.get('info', {}).get('shard') for d in decs)
    # the resaved generation is intact and loadable
    gen = elastic.latest_generation(store)
    elastic.verify_generation(store, gen)


def test_double_torn_checkpoint_gives_up_loudly():
    # the resave itself tears (persistent bitrot / open-ended torn
    # clause): the supervisor must SAY so, not log a good checkpoint
    store = tempfile.mkdtemp(prefix='pt_sup_')
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sup = supervisor.attach(store, program=main, executor=exe,
                                checkpoint_steps=1, start=False)
        try:
            faultinject.configure('elastic.shard_write:torn@1+')
            sup.maybe_checkpoint(exe)
            sup._save_thread.join(30)
        finally:
            supervisor.detach()
    assert monitor.counter_value('supervisor/checkpoint_torn') == 2
    kinds = _kinds()
    assert ('checkpoint_torn', 'resave') in kinds
    assert ('checkpoint_torn', 'gave_up') in kinds
    assert ('checkpoint', 'take') not in kinds


def test_hooks_pinned_to_attached_executor():
    # a second executor in the process (serving dispatcher, bench)
    # must neither drive the cadence nor execute a pending recovery
    store = tempfile.mkdtemp(prefix='pt_sup_')
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sup = supervisor.attach(store, program=main, executor=exe,
                                checkpoint_steps=1, start=False)
        try:
            other = fluid.Executor(fluid.XLAPlace(0))
            sup._pending_recovery = {'why': 'test'}
            x, y = _batch(0)
            # the UNattached executor's run must not recover or save
            other.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            assert sup._pending_recovery is not None
            assert monitor.counter_value(
                'supervisor/checkpoints_taken') == 0
            sup._pending_recovery = None
        finally:
            supervisor.detach()


def test_recovery_end_to_end_bounded_lost_work():
    # keep every generation: the replay below resumes the RECOVERY
    # generation by number after the soak wrote newer ones
    fluid.set_flags({'FLAGS_elastic_keep_generations': 32})
    store = tempfile.mkdtemp(prefix='pt_sup_')
    peers = _Peers('1')
    main, startup, loss = _build()
    cadence = 3
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sup = supervisor.attach(store, program=main, executor=exe,
                                checkpoint_steps=cadence, peers=peers,
                                price=lambda: 0.0, rejoin_wait_s=5.0,
                                start=False)
        try:
            losses = {}
            recovered = []
            target = 12
            while exe._step < target:
                s = exe._step
                x, y = _batch(s)
                try:
                    l, = exe.run(main, feed={'x': x, 'y': y},
                                 fetch_list=[loss])
                    losses[exe._step] = _f(l)
                except supervisor.Recovered as e:
                    recovered.append(e)
                    continue
                if exe._step == 8 and not recovered:
                    t = sup._save_thread
                    if t is not None:
                        t.join(10)
                    peers.set('1', up=False, misses=3,
                              confirmed_down=True)
                    sup._tick()     # controller confirms + schedules
            assert len(recovered) == 1
            e = recovered[0]
            assert e.lost_steps <= cadence
            assert exe._step >= target
            # detach BEFORE the replay: the replay executor must not
            # feed the same controller
            supervisor.detach()
            # post-recovery trajectory reproducible: resume the same
            # generation in a fresh scope and replay — bitwise equal
            replay = {}
            with fluid.scope_guard(fluid.Scope()):
                exe2 = fluid.Executor(fluid.XLAPlace(0))
                elastic.load_checkpoint(store, main, executor=exe2,
                                        generation=e.generation)
                while exe2._step < target:
                    s = exe2._step
                    x, y = _batch(s)
                    l, = exe2.run(main, feed={'x': x, 'y': y},
                                  fetch_list=[loss])
                    replay[exe2._step] = _f(l)
            for s in replay:
                assert np.float32(replay[s]).tobytes() == \
                    np.float32(losses[s]).tobytes(), \
                    'step %d diverged' % s
        finally:
            supervisor.detach()
    assert monitor.counter_value('supervisor/recoveries') == 1
    assert any(d['kind'] == 'recovery' and d['choice'] == 'recovered'
               for d in supervisor.decisions())


# ------------------------------------------------------------ watchdog
def test_guard_dispatch_times_out_with_named_segment():
    t0 = time.perf_counter()
    with pytest.raises(supervisor.StepTimeoutError) as ei:
        supervisor.guard_dispatch(lambda: time.sleep(3.0),
                                  'seg:fc_0.w_0', 0.2, step=7)
    wall = time.perf_counter() - t0
    assert wall < 0.4                      # < 2x the deadline
    assert ei.value.segment == 'seg:fc_0.w_0'
    assert 'fc_0.w_0' in str(ei.value)
    assert monitor.counter_value('executor/step_timeouts') == 1


def test_guard_dispatch_transparent_for_results_and_errors():
    assert supervisor.guard_dispatch(lambda: {'a': 1}, 's', 5.0) == \
        {'a': 1}
    with pytest.raises(KeyError):
        supervisor.guard_dispatch(lambda: {}['x'], 's', 5.0)
    assert monitor.counter_value('executor/step_timeouts') == 0


def test_injected_stall_converts_to_timeout_in_real_executor():
    # the watchdog acceptance: an injected dispatch stall becomes a
    # named StepTimeoutError + flight dump in < 2x the deadline
    fluid.set_flags({'FLAGS_step_timeout_s': 0.3, 'FLAGS_trace': True})
    from paddle_tpu.fluid import trace
    trace.enable()
    main, startup, loss = _build()
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            x, y = _batch(0)
            exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
            # arm AFTER warmup: which calls count as guarded
            # (site-consulting) dispatches depends on whether the AOT
            # compile plane is active in this process, so the clause
            # is configured once the next dispatch is steady-state
            # under either mode
            faultinject.configure('executor.dispatch:stall:5@1')
            t0 = time.perf_counter()
            with pytest.raises(supervisor.StepTimeoutError) as ei:
                exe.run(main, feed={'x': x, 'y': y},
                        fetch_list=[loss])
            wall = time.perf_counter() - t0
        assert wall < 0.6                   # < 2x FLAGS_step_timeout_s
        assert ei.value.dump_path and os.path.exists(
            ei.value.dump_path)
        assert monitor.counter_value('executor/step_timeouts') == 1
        assert faultinject.fired('executor.dispatch') == 1
    finally:
        fluid.set_flags({'FLAGS_step_timeout_s': 0.0,
                         'FLAGS_trace': False})
        trace.disable()


def test_collective_stall_converts_to_timeout_in_parallel_runner():
    # the satellite's named vehicle: 'collective.dispatch:stall' on a
    # dp2 CompiledProgram — a straggling collective blocked past the
    # deadline must become a StepTimeoutError, not a hang
    fluid.set_flags({'FLAGS_step_timeout_s': 0.4})
    main, startup, loss = _build()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name,
        places=[fluid.XLAPlace(i) for i in range(2)])
    x, y = _batch(0)
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.run(comp, feed={'x': x, 'y': y}, fetch_list=[loss])
            faultinject.configure('collective.dispatch:stall:5@1')
            t0 = time.perf_counter()
            with pytest.raises(supervisor.StepTimeoutError) as ei:
                exe.run(comp, feed={'x': x, 'y': y},
                        fetch_list=[loss])
            assert time.perf_counter() - t0 < 0.8   # < 2x deadline
        assert 'ops@' in ei.value.segment
        assert monitor.counter_value('executor/step_timeouts') == 1
        assert faultinject.fired('collective.dispatch') == 1
    finally:
        fluid.set_flags({'FLAGS_step_timeout_s': 0.0})


def test_hung_step_with_supervisor_recovers_from_last_good():
    store = tempfile.mkdtemp(prefix='pt_sup_')
    fluid.set_flags({'FLAGS_step_timeout_s': 0.3})
    main, startup, loss = _build()
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            sup = supervisor.attach(store, program=main, executor=exe,
                                    checkpoint_steps=2, start=False)
            faultinject.configure('executor.dispatch:stall:5@4')
            losses = 0
            recovered = []
            while exe._step < 8:
                x, y = _batch(exe._step)
                try:
                    exe.run(main, feed={'x': x, 'y': y},
                            fetch_list=[loss])
                    losses += 1
                except supervisor.StepTimeoutError:
                    continue    # next run() executes the recovery
                except supervisor.Recovered as e:
                    recovered.append(e)
                    continue
            assert recovered, 'timeout never converted to recovery'
            assert recovered[0].lost_steps <= 2
            assert any(d['kind'] == 'hung_step' for d in
                       supervisor.decisions())
    finally:
        supervisor.detach()
        fluid.set_flags({'FLAGS_step_timeout_s': 0.0})


# ----------------------------------------------- serving deadline shed
def test_serving_sheds_expired_requests_instead_of_dispatching():
    from paddle_tpu.fluid import serving
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4], dtype='float32')
            out = layers.fc(x, 4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    try:
        srv.add_program('t', main, ['x'], [out], scope=scope)
        # stall the dispatcher behind a lock-step: submit while the
        # dispatcher thread is NOT yet running, with an
        # already-expired deadline — _take_batch must shed it
        feed = {'x': np.ones((2, 4), 'float32')}
        fut = srv.submit('t', feed, deadline_s=1e-6)
        time.sleep(0.01)
        with pytest.raises(serving.DeadlineExpired):
            fut.result(timeout=10)
        assert monitor.counter_value('serving/shed_expired') == 1
        # an un-deadlined request still serves
        res = srv.submit('t', feed).result(timeout=30)
        assert res[0].shape == (2, 4)
        # requests served after the shed: the shed never wedged the
        # dispatcher or leaked into a batch
        assert monitor.counter_value('serving/requests') == 2
    finally:
        srv.close()


def test_serving_admission_rejects_expired_deadline():
    """A non-positive deadline fails fast AT ADMISSION — the request
    never queues, never reaches the dispatcher."""
    from paddle_tpu.fluid import serving
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4], dtype='float32')
            out = layers.fc(x, 4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    try:
        srv.add_program('t', main, ['x'], [out], scope=scope)
        feed = {'x': np.ones((2, 4), 'float32')}
        for dl in (0.0, -1.0):
            fut = srv.submit('t', feed, deadline_s=dl)
            assert fut.done()          # failed at admission, no queue
            with pytest.raises(serving.DeadlineExpired):
                fut.result(timeout=0)
        assert monitor.counter_value('serving/shed_expired') == 2
        # nothing was admitted: the tenant queue never saw them
        assert len(srv._tenants['t'].pending) == 0
        # a live deadline still serves
        res = srv.submit('t', feed, deadline_s=60.0).result(timeout=30)
        assert res[0].shape == (2, 4)
    finally:
        srv.close()


def test_serving_degraded_sheds_and_flips_readiness():
    from paddle_tpu.fluid import serving
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    from paddle_tpu.fluid import unique_name
    with unique_name.guard():
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4], dtype='float32')
            out = layers.fc(x, 4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
    srv = serving.ServingExecutor(max_batch=8, executor=exe)
    try:
        t = srv.add_program('t', main, ['x'], [out], scope=scope)
        t.warmed = True
        ready, reasons = serving.readiness()
        assert ready is True
        serving.enter_degraded('supervisor recovery: test')
        try:
            ready, reasons = serving.readiness()
            assert ready is False
            assert any('degraded' in r for r in reasons)
            fut = srv.submit('t', {'x': np.ones((2, 4), 'float32')})
            with pytest.raises(serving.ServingDegraded):
                fut.result(timeout=5)
            assert monitor.counter_value('serving/shed_degraded') == 1
        finally:
            serving.exit_degraded()
        ready, _ = serving.readiness()
        assert ready is True
    finally:
        srv.close()


# ------------------------------------------------- rejoin backoff fix
def test_rejoin_trainer_retries_transient_connection_refusal():
    # the aggregator/pserver restarts exactly when a trainer rejoins:
    # the first admission attempts are REFUSED, then the endpoint
    # comes back — rejoin_trainer must retry under its own timeout
    # through the rpc_ps backoff policy, not die on the first refusal
    from paddle_tpu.distributed import rpc_ps
    calls = {'n': 0}

    class FlakyHB(object):
        def __init__(self, endpoint, trainer_id, timeout=None,
                     interval=None):
            calls['n'] += 1
            if calls['n'] < 3:
                raise ConnectionRefusedError(
                    'injected: endpoint not listening yet')
            self.endpoint = endpoint
            self.trainer_id = trainer_id

        def stop(self):
            pass

    orig = rpc_ps.TrainerHeartbeat
    rpc_ps.TrainerHeartbeat = FlakyHB
    try:
        info, hb = elastic.rejoin_trainer('127.0.0.1:1', trainer_id=0,
                                          timeout=10.0)
        assert info is None and hb.trainer_id == 0
        assert calls['n'] == 3
        assert monitor.counter_value('elastic/rejoin_retries') == 2
        assert monitor.counter_value('elastic/readmissions') == 1
    finally:
        rpc_ps.TrainerHeartbeat = orig


def test_rejoin_trainer_raises_after_deadline():
    from paddle_tpu.distributed import rpc_ps

    class DeadHB(object):
        def __init__(self, *a, **k):
            raise ConnectionRefusedError('injected: still down')

    orig = rpc_ps.TrainerHeartbeat
    rpc_ps.TrainerHeartbeat = DeadHB
    try:
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError):
            elastic.rejoin_trainer('127.0.0.1:1', trainer_id=0,
                                   timeout=0.3)
        assert time.perf_counter() - t0 < 5.0
    finally:
        rpc_ps.TrainerHeartbeat = orig


# --------------------------------------------------------- observability
def test_statusz_supervisor_section_json_able():
    import json
    store = tempfile.mkdtemp(prefix='pt_sup_')
    peers = _Peers('1')
    sup = supervisor.attach(store, program=_build()[0], peers=peers,
                            price=lambda: 0.0, start=False)
    try:
        peers.set('1', up=False, misses=3, confirmed_down=True)
        sup._tick()
        from paddle_tpu.fluid import health
        doc = health.statusz()
        section = doc['supervisor']
        assert section is not None
        assert section['active'] is True
        assert section['controller']['store_dir'] == \
            os.path.abspath(store)
        assert any(d['kind'] == 'death' for d in section['decisions'])
        json.dumps(section)     # the HTTP handler's contract
    finally:
        supervisor.detach()


def test_decision_log_bounded():
    sup = _mk_sup(tempfile.mkdtemp(prefix='pt_sup_'))
    for i in range(supervisor._DECISIONS_CAP + 20):
        sup._decide('checkpoint', 'take', n=i)
    decs = supervisor.decisions()
    assert len(decs) == supervisor._DECISIONS_CAP
    assert decs[-1]['info']['n'] == supervisor._DECISIONS_CAP + 19


def test_disabled_watchdog_costs_one_flag_read():
    # FLAGS_step_timeout_s=0 must keep the plain dispatch path: no
    # guard threads are created
    main, startup, loss = _build()
    import threading as _th
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        x, y = _batch(0)
        exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
        before = {t.name for t in _th.enumerate()}
        for s in range(3):
            exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
        after = {t.name for t in _th.enumerate()}
    assert not any(n.startswith('pt_step_guard')
                   for n in after - before)
    assert monitor.counter_value('executor/step_timeouts') == 0
