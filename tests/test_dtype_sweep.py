"""bf16 dtype sweep: key ops run on bfloat16 inputs and track their
f32 oracle within bf16 tolerances.

Reference model: OpTest runs each op across dtypes/places
(python/paddle/fluid/tests/unittests/op_test.py _get_places /
check_output float16 variants); the TPU-relevant low-precision dtype
is bfloat16 — the AMP path computes MXU ops in it, so the op surface
must be numerically sane there, not just under f32.
"""

import ml_dtypes
import numpy as np
import pytest

from op_test import OpTest

BF16 = ml_dtypes.bfloat16
rng = np.random.RandomState(11)


def _bf16(x):
    return np.asarray(x, 'float32').astype(BF16)


def _check(op, inputs, attrs=None, out_slots=('Out',), rtol=3e-2,
           atol=3e-2, dtype=BF16):
    """Run `op` once on low-precision inputs and once on the SAME
    (rounded) values in f32; only compute precision differs, and
    outputs must agree within the dtype's tolerance."""
    t = OpTest()
    q = {k: np.asarray(v, 'float32').astype(dtype)
         for k, v in inputs.items()}
    lo = t.run_op(op, q, attrs, out_slots)
    hi = t.run_op(op, {k: v.astype('float32') for k, v in q.items()},
                  attrs, out_slots)
    for slot in out_slots:
        got = np.asarray(lo[slot], 'float32')
        want = np.asarray(hi[slot], 'float32')
        np.testing.assert_allclose(
            got, want, rtol=rtol, atol=atol,
            err_msg='%s[%s] %s vs f32' % (op, slot, np.dtype(dtype)))


@pytest.mark.parametrize('op', ['sigmoid', 'tanh', 'relu', 'gelu',
                                'exp', 'softplus', 'erf', 'swish'])
def test_bf16_activations(op):
    _check(op, {'X': rng.randn(4, 8)})


def test_bf16_matmul():
    _check('matmul', {'X': rng.randn(8, 16), 'Y': rng.randn(16, 8)},
           rtol=5e-2, atol=5e-1)


def test_bf16_softmax():
    _check('softmax', {'X': rng.randn(4, 16) * 2})


def test_bf16_layer_norm():
    x = rng.randn(4, 32)
    scale = rng.rand(32) + 0.5
    bias = rng.randn(32) * 0.1
    _check('layer_norm', {'X': x, 'Scale': scale, 'Bias': bias},
           attrs={'begin_norm_axis': 1},
           out_slots=('Y',), rtol=5e-2, atol=5e-2)


def test_bf16_elementwise():
    x, y = rng.randn(4, 8), rng.randn(4, 8)
    _check('elementwise_add', {'X': x, 'Y': y})
    _check('elementwise_mul', {'X': x, 'Y': y})


def test_bf16_reductions():
    x = rng.rand(6, 8)
    _check('reduce_sum', {'X': x}, attrs={'dim': [1]})
    _check('reduce_mean', {'X': x}, attrs={'dim': [0]})
    _check('reduce_max', {'X': x}, attrs={'dim': [1]}, rtol=0,
           atol=1e-2)


def test_bf16_conv2d():
    x = rng.randn(2, 4, 8, 8) * 0.5
    w = rng.randn(6, 4, 3, 3) * 0.3
    _check('conv2d', {'Input': x, 'Filter': w},
           attrs={'strides': [1, 1], 'paddings': [1, 1],
                  'dilations': [1, 1], 'groups': 1},
           out_slots=('Output',), rtol=5e-2, atol=3e-1)


def test_bf16_pool_and_transpose():
    x = rng.randn(2, 3, 8, 8)
    _check('pool2d', {'X': x},
           attrs={'pooling_type': 'max', 'ksize': [2, 2],
                  'strides': [2, 2], 'paddings': [0, 0]},
           rtol=0, atol=1e-2)
    _check('transpose', {'X': x}, attrs={'axis': [0, 2, 3, 1]},
           rtol=0, atol=0)


def test_bf16_cross_entropy_chain():
    """softmax_with_cross_entropy keeps labels int; logits bf16."""
    t = OpTest()
    logits = rng.randn(8, 10) * 2
    labels = rng.randint(0, 10, (8, 1)).astype('int64')
    lo = t.run_op('softmax_with_cross_entropy',
                  {'Logits': _bf16(logits), 'Label': labels},
                  out_slots=('Loss',))
    hi = t.run_op('softmax_with_cross_entropy',
                  {'Logits': logits.astype('float32'),
                   'Label': labels}, out_slots=('Loss',))
    np.testing.assert_allclose(np.asarray(lo['Loss'], 'float32'),
                               np.asarray(hi['Loss'], 'float32'),
                               rtol=5e-2, atol=5e-2)


def test_bf16_grads_flow():
    """Gradients through a bf16 matmul+activation chain exist, are
    finite, and track the f32 gradients loosely (the AMP contract:
    bf16 compute, usable grads)."""
    import paddle_tpu.fluid as fluid
    layers = fluid.layers
    # both runs see the same bf16-rounded values; only the compute
    # dtype differs
    xq = rng.randn(4, 8).astype('float32').astype(BF16)

    def grads(dtype):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 3
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[8], dtype=dtype)
            x.stop_gradient = False
            h = layers.fc(x, 16, act='tanh')
            loss = layers.reduce_mean(layers.square(h))
            fluid.backward.append_backward(loss)
        gmap = main._grad_name_map
        feed_x = xq if dtype == 'bfloat16' else xq.astype('float32')
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            g, = exe.run(main, feed={'x': feed_x},
                         fetch_list=[gmap['x']])
        return np.asarray(g, 'float32')

    g32 = grads('float32')
    g16 = grads('bfloat16')
    assert np.isfinite(g16).all()
    np.testing.assert_allclose(g16, g32, rtol=1e-1, atol=1e-2)


@pytest.mark.parametrize('op', ['sigmoid', 'tanh', 'relu', 'exp'])
def test_f16_activations(op):
    """float16 (the reference AMP dtype) works through the same ops;
    tolerance reflects f16's 10-bit mantissa."""
    _check(op, {'X': rng.randn(4, 8)}, dtype=np.float16,
           rtol=5e-3, atol=5e-3)


def test_f16_matmul_and_softmax():
    _check('matmul', {'X': rng.randn(8, 16), 'Y': rng.randn(16, 8)},
           dtype=np.float16, rtol=2e-2, atol=1e-1)
    _check('softmax', {'X': rng.randn(4, 16) * 2}, dtype=np.float16,
           rtol=1e-2, atol=1e-3)
