"""SE-ResNeXt (reference dist_se_resnext.py model) trains end-to-end."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.models import se_resnext


def test_se_resnext_tiny_trains():
    se_resnext.DEPTH_CFG[8] = [1, 1, 1, 1]  # tiny depth for CPU CI
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        feeds, out, loss, acc = se_resnext.build(
            image_shape=(3, 32, 32), class_dim=4, depth=8,
            cardinality=4, reduction_ratio=4,
            stage_filters=(8, 16, 16, 32))
        fluid.optimizer.Momentum(0.05, momentum=0.9).minimize(loss)

    rng = np.random.RandomState(0)
    losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for i in range(6):
            img = rng.rand(8, 3, 32, 32).astype('float32')
            # learnable rule: label from mean pixel intensity quartile
            lab = (img.mean(axis=(1, 2, 3)) * 4).astype('int64') % 4
            l, = exe.run(main, feed={'image': img,
                                     'label': lab.reshape(-1, 1)},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 1.5  # training is stable


def test_se_resnext_eval_deterministic():
    se_resnext.DEPTH_CFG[8] = [1, 1, 1, 1]
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        feeds, out, loss, acc = se_resnext.build(
            image_shape=(3, 32, 32), class_dim=4, depth=8,
            cardinality=4, reduction_ratio=4, is_test=True,
            stage_filters=(8, 16, 16, 32))
    rng = np.random.RandomState(1)
    img = rng.rand(4, 3, 32, 32).astype('float32')
    lab = np.zeros((4, 1), np.int64)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        o1, = exe.run(main, feed={'image': img, 'label': lab},
                      fetch_list=[out])
        o2, = exe.run(main, feed={'image': img, 'label': lab},
                      fetch_list=[out])
    np.testing.assert_allclose(o1, o2)
    np.testing.assert_allclose(np.asarray(o1).sum(axis=-1), 1.0,
                               rtol=1e-5)
