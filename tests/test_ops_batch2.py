"""OpTest-style checks for the batch-2 ops: losses, misc, vision/3D,
sequence extras (numpy references, torch cross-check where cheap)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import registry
from paddle_tpu.ops.registry import LowerCtx


def run_op(op_type, ins, attrs=None):
    d = registry.get(op_type)
    ctx = LowerCtx(step=jnp.asarray(0, jnp.int32), op_seed=3)
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return d.fn(ctx, ins, dict(attrs or {}))


def A(out, slot):
    return np.asarray(out[slot][0])


# ----------------------------------------------------------------- losses

def test_rank_margin_hinge_bpr_huber():
    rng = np.random.RandomState(0)
    left = rng.randn(6, 1).astype('f4')
    right = rng.randn(6, 1).astype('f4')
    lab = (rng.rand(6, 1) > 0.5).astype('f4')
    out = run_op('rank_loss', {'Label': [lab], 'Left': [left],
                               'Right': [right]})
    d = left - right
    np.testing.assert_allclose(A(out, 'Out'),
                               np.log1p(np.exp(d)) - lab * d, rtol=1e-5)

    lab_pm = np.sign(rng.randn(6, 1)).astype('f4')
    out = run_op('margin_rank_loss',
                 {'Label': [lab_pm], 'X1': [left], 'X2': [right]},
                 {'margin': 0.1})
    want = np.maximum(0, -lab_pm * (left - right) + 0.1)
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-5)

    out = run_op('hinge_loss', {'Logits': [left], 'Labels': [lab]})
    np.testing.assert_allclose(
        A(out, 'Loss'), np.maximum(0, 1 - (2 * lab - 1) * left), rtol=1e-5)

    x = rng.randn(4, 5).astype('f4')
    y = rng.randint(0, 5, (4, 1)).astype('i8')
    out = run_op('bpr_loss', {'X': [x], 'Label': [y]})
    want = np.zeros((4, 1), 'f4')
    for i in range(4):
        s = 0.0
        for j in range(5):
            if j == y[i, 0]:
                continue
            s += -np.log(1.0 + np.exp(x[i, j] - x[i, y[i, 0]]))
        want[i, 0] = -s / 4
    np.testing.assert_allclose(A(out, 'Y'), want, rtol=1e-4)

    pred = np.array([[-2.0], [-0.5], [0.5], [2.0]], 'f4')
    lab01 = np.array([[1.0], [1.0], [0.0], [1.0]], 'f4')
    out = run_op('modified_huber_loss', {'X': [pred], 'Y': [lab01]})
    val = (2 * lab01 - 1) * pred
    want = np.where(val < -1, -4 * val,
                    np.where(val < 1, (1 - val) ** 2, 0))
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-5)


def test_teacher_student_and_cvm_and_center():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 1).astype('f4')
    # the four label regimes: -2 (no q, clk 0), -1 (no q, clk 1),
    # 0.3 (q, clk 0), 1.7 (q, clk 1)
    lab = np.array([[-2.0], [-1.0], [0.3], [1.7]], 'f4')
    out = run_op('teacher_student_sigmoid_loss', {'X': [x], 'Label': [lab]})
    got = A(out, 'Y')

    def ce(xv, z):
        return max(xv, 0) - xv * z + np.log1p(np.exp(-abs(xv)))
    want = np.array([[ce(x[0, 0], 0)],
                     [ce(x[1, 0], 1)],
                     [ce(x[2, 0], 0) + ce(x[2, 0], 0.3)],
                     [ce(x[3, 0], 1) + ce(x[3, 0], 0.7)]], 'f4')
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    x = np.abs(rng.randn(3, 6)).astype('f4')
    out = run_op('cvm', {'X': [x]}, {'use_cvm': True})
    got = A(out, 'Y')
    np.testing.assert_allclose(got[:, 0], np.log(x[:, 0] + 1), rtol=1e-5)
    np.testing.assert_allclose(got[:, 1],
                               np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got[:, 2:], x[:, 2:])
    out = run_op('cvm', {'X': [x]}, {'use_cvm': False})
    assert A(out, 'Y').shape == (3, 4)

    feats = rng.randn(5, 3).astype('f4')
    labels = np.array([0, 1, 0, 2, 1], 'i8')
    centers = rng.randn(3, 3).astype('f4')
    out = run_op('center_loss',
                 {'X': [feats], 'Label': [labels], 'Centers': [centers],
                  'CenterUpdateRate': [np.array([0.5], 'f4')]})
    diff = feats - centers[labels]
    np.testing.assert_allclose(A(out, 'Loss'),
                               0.5 * (diff ** 2).sum(1, keepdims=True),
                               rtol=1e-5)
    new_c = A(out, 'CentersOut')
    for c in range(3):
        idx = labels == c
        want = centers[c] + 0.5 * diff[idx].sum(0) / (1 + idx.sum())
        np.testing.assert_allclose(new_c[c], want, rtol=1e-4, atol=1e-6)


def test_misc_ops():
    rng = np.random.RandomState(2)
    # fsp
    x = rng.randn(2, 3, 4, 5).astype('f4')
    y = rng.randn(2, 6, 4, 5).astype('f4')
    out = run_op('fsp', {'X': [x], 'Y': [y]})
    want = np.einsum('bchw,bdhw->bcd', x, y) / 20.0
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-4)
    # l1_norm
    out = run_op('l1_norm', {'X': [x]})
    np.testing.assert_allclose(A(out, 'Out'), [np.abs(x).sum()], rtol=1e-5)
    # mean_iou
    pred = np.array([0, 1, 1, 2, 2, 2], 'i4')
    lab = np.array([0, 1, 2, 2, 2, 1], 'i4')
    out = run_op('mean_iou', {'Predictions': [pred], 'Labels': [lab]},
                 {'num_classes': 3})
    # class0: i1 u1; class1: i1 u3; class2: i2 u4
    np.testing.assert_allclose(A(out, 'OutMeanIou')[0],
                               (1 + 1 / 3 + 0.5) / 3, rtol=1e-5)
    # shard_index
    ids = np.array([[0], [5], [9], [13]], 'i8')
    out = run_op('shard_index', {'X': [ids]},
                 {'index_num': 16, 'nshards': 2, 'shard_id': 1,
                  'ignore_value': -1})
    np.testing.assert_array_equal(A(out, 'Out'),
                                  [[-1], [-1], [1], [5]])
    # multiplex
    x1 = rng.randn(4, 3).astype('f4')
    x2 = rng.randn(4, 3).astype('f4')
    ids = np.array([[0], [1], [0], [1]], 'i4')
    out = run_op('multiplex', {'Ids': [ids], 'X': [x1, x2]})
    want = np.where(ids == 0, x1, x2)
    np.testing.assert_allclose(A(out, 'Out'), want)
    # bilinear_tensor_product
    xb = rng.randn(3, 4).astype('f4')
    yb = rng.randn(3, 5).astype('f4')
    wb = rng.randn(2, 4, 5).astype('f4')
    out = run_op('bilinear_tensor_product',
                 {'X': [xb], 'Y': [yb], 'Weight': [wb]})
    np.testing.assert_allclose(A(out, 'Out'),
                               np.einsum('bm,kmn,bn->bk', xb, wb, yb),
                               rtol=1e-4)
    # scatter_nd_add
    base = np.zeros((3, 4), 'f4')
    index = np.array([[0, 1], [2, 3], [0, 1]], 'i4')
    upd = np.array([1.0, 2.0, 3.0], 'f4')
    out = run_op('scatter_nd_add',
                 {'X': [base], 'Index': [index], 'Updates': [upd]})
    want = base.copy()
    want[0, 1] += 4.0
    want[2, 3] += 2.0
    np.testing.assert_allclose(A(out, 'Out'), want)
    # pad_constant_like
    big = np.zeros((3, 5), 'f4')
    small = np.ones((2, 3), 'f4')
    out = run_op('pad_constant_like', {'X': [big], 'Y': [small]},
                 {'pad_value': 9.0})
    got = A(out, 'Out')
    assert got.shape == (3, 5)
    assert (got[:2, :3] == 1).all() and (got[2] == 9).all()
    # size
    out = run_op('size', {'Input': [big]})
    assert int(A(out, 'Out')[0]) == 15


def test_spectral_and_data_norm_and_sampling():
    rng = np.random.RandomState(3)
    w = rng.randn(4, 6).astype('f4')
    u = rng.randn(4).astype('f4')
    v = rng.randn(6).astype('f4')
    out = run_op('spectral_norm', {'Weight': [w], 'U': [u], 'V': [v]},
                 {'power_iters': 30, 'dim': 0})
    got = A(out, 'Out')
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(got, w / sigma, rtol=1e-3, atol=1e-4)

    x = rng.randn(5, 3).astype('f4')
    bsize = np.full((3,), 10.0, 'f4')
    bsum = rng.randn(3).astype('f4') * 10
    bsqr = bsize * 1.0 + bsum ** 2 / 10.0   # variance 1
    out = run_op('data_norm', {'X': [x], 'BatchSize': [bsize],
                               'BatchSum': [bsum],
                               'BatchSquareSum': [bsqr]})
    means = bsum / 10.0
    np.testing.assert_allclose(A(out, 'Means'), means, rtol=1e-5)
    np.testing.assert_allclose(A(out, 'Y'), (x - means), rtol=1e-3,
                               atol=1e-3)

    probs = np.array([[1.0, 0.0, 0.0], [0.0, 0.0, 1.0]], 'f4')
    out = run_op('sampling_id', {'X': [probs]})
    np.testing.assert_array_equal(A(out, 'Out'), [0, 2])


def test_activations_new():
    x = np.array([-2.0, -0.5, 0.0, 0.7, 3.0], 'f4')
    out = run_op('selu', {'X': [x]})
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    want = scale * np.where(x > 0, x, alpha * np.expm1(x))
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-5)
    out = run_op('stanh', {'X': [x]}, {'scale_a': 0.67, 'scale_b': 1.7159})
    np.testing.assert_allclose(A(out, 'Out'), 1.7159 * np.tanh(0.67 * x),
                               rtol=1e-5)
    out = run_op('brelu', {'X': [x]}, {'t_min': -1.0, 't_max': 1.0})
    np.testing.assert_allclose(A(out, 'Out'), np.clip(x, -1, 1))
    out = run_op('logsigmoid', {'X': [x]})
    np.testing.assert_allclose(A(out, 'Out'),
                               -np.log1p(np.exp(-x)), rtol=1e-4)
    out = run_op('tanh_shrink', {'X': [x]})
    np.testing.assert_allclose(A(out, 'Out'), x - np.tanh(x), rtol=1e-5)


# ----------------------------------------------------------------- vision/3D

def test_conv3d_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 5, 6, 7).astype('f4')
    w = rng.randn(4, 3, 2, 3, 3).astype('f4')
    out = run_op('conv3d', {'Input': [x], 'Filter': [w]},
                 {'strides': [1, 2, 1], 'paddings': [1, 0, 1]})
    want = F.conv3d(torch.tensor(x), torch.tensor(w),
                    stride=(1, 2, 1), padding=(1, 0, 1)).numpy()
    np.testing.assert_allclose(A(out, 'Output'), want, rtol=2e-3,
                               atol=2e-4)


def test_conv3d_transpose_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(5)
    x = rng.randn(1, 3, 4, 4, 4).astype('f4')
    w = rng.randn(3, 2, 3, 3, 3).astype('f4')   # [in, out, k, k, k]
    out = run_op('conv3d_transpose', {'Input': [x], 'Filter': [w]},
                 {'strides': [2, 2, 2], 'paddings': [1, 1, 1]})
    want = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                              stride=2, padding=1).numpy()
    np.testing.assert_allclose(A(out, 'Output'), want, rtol=2e-3,
                               atol=2e-4)


def test_pool3d_and_trilinear():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 4, 6, 6).astype('f4')
    out = run_op('pool3d', {'X': [x]},
                 {'pooling_type': 'avg', 'ksize': [2, 2, 2],
                  'strides': [2, 2, 2]})
    want = F.avg_pool3d(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-4)

    out = run_op('trilinear_interp', {'X': [x]},
                 {'out_d': 8, 'out_h': 12, 'out_w': 12,
                  'align_corners': True})
    want = F.interpolate(torch.tensor(x), size=(8, 12, 12),
                         mode='trilinear', align_corners=True).numpy()
    np.testing.assert_allclose(A(out, 'Out'), want, rtol=1e-3, atol=1e-4)


def test_pixel_rearrange_ops():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(7)
    x = rng.randn(2, 8, 3, 4).astype('f4')
    out = run_op('pixel_shuffle', {'X': [x]}, {'upscale_factor': 2})
    want = F.pixel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(A(out, 'Out'), want)

    out = run_op('shuffle_channel', {'X': [x]}, {'group': 4})
    want = x.reshape(2, 4, 2, 3, 4).swapaxes(1, 2).reshape(2, 8, 3, 4)
    np.testing.assert_allclose(A(out, 'Out'), want)

    x2 = rng.randn(2, 3, 4, 6).astype('f4')
    out = run_op('space_to_depth', {'X': [x2]}, {'blocksize': 2})
    assert A(out, 'Out').shape == (2, 12, 2, 3)

    scale = rng.randn(3).astype('f4')
    bias = rng.randn(3).astype('f4')
    out = run_op('affine_channel', {'X': [x2], 'Scale': [scale],
                                    'Bias': [bias]})
    np.testing.assert_allclose(
        A(out, 'Out'), x2 * scale[None, :, None, None]
        + bias[None, :, None, None], rtol=1e-5)


def test_affine_grid_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(8)
    theta = rng.randn(2, 2, 3).astype('f4')
    out = run_op('affine_grid', {'Theta': [theta]},
                 {'output_shape': [2, 3, 4, 5]})
    want = F.affine_grid(torch.tensor(theta), (2, 3, 4, 5),
                         align_corners=True).numpy()
    np.testing.assert_allclose(A(out, 'Output'), want, rtol=1e-4,
                               atol=1e-5)


def test_unfold_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(9)
    x = rng.randn(2, 3, 6, 7).astype('f4')
    out = run_op('unfold', {'X': [x]},
                 {'kernel_sizes': [2, 3], 'strides': [2, 1],
                  'paddings': [1, 0], 'dilations': [1, 1]})
    want = F.unfold(torch.tensor(x), (2, 3), stride=(2, 1),
                    padding=(1, 0)).numpy()
    np.testing.assert_allclose(A(out, 'Y'), want, rtol=1e-5)


def test_crop_and_spp_and_roi_pool():
    rng = np.random.RandomState(10)
    x = rng.randn(2, 3, 6, 6).astype('f4')
    out = run_op('crop_tensor', {'X': [x]},
                 {'offsets': [0, 1, 2, 2], 'shape': [2, 2, 3, 3]})
    np.testing.assert_allclose(A(out, 'Out'), x[:, 1:3, 2:5, 2:5])

    out = run_op('spp', {'X': [x]}, {'pyramid_height': 2,
                                     'pooling_type': 'max'})
    got = A(out, 'Out')
    assert got.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(got[:, :3], x.max((2, 3)), rtol=1e-5)

    # roi_pool on a 1x1 grid == max over the roi box
    img = np.arange(36, dtype='f4').reshape(1, 1, 6, 6)
    rois = np.array([[0.0, 0.0, 2.0, 2.0]], 'f4')
    out = run_op('roi_pool', {'X': [img], 'ROIs': [rois]},
                 {'pooled_height': 1, 'pooled_width': 1,
                  'spatial_scale': 1.0})
    assert float(A(out, 'Out')[0, 0, 0, 0]) == img[0, 0, :3, :3].max()


def test_anchor_ops():
    feat = np.zeros((1, 8, 2, 3), 'f4')
    out = run_op('anchor_generator', {'Input': [feat]},
                 {'anchor_sizes': [64.0], 'aspect_ratios': [1.0],
                  'stride': [16.0, 16.0], 'offset': 0.5})
    anchors = A(out, 'Anchors')
    assert anchors.shape == (2, 3, 1, 4)
    # first cell center is (8, 8), box 64x64
    np.testing.assert_allclose(anchors[0, 0, 0], [-24, -24, 40, 40])

    img = np.zeros((1, 3, 32, 48), 'f4')
    out = run_op('density_prior_box', {'Input': [feat], 'Image': [img]},
                 {'fixed_sizes': [8.0], 'fixed_ratios': [1.0],
                  'densities': [2]})
    boxes = A(out, 'Boxes')
    assert boxes.shape == (2, 3, 4, 4)
    assert (boxes >= 0).all() and (boxes <= 1).all()

    b = np.array([[[-5.0, -5.0, 100.0, 100.0]]], 'f4')
    im_info = np.array([[32.0, 48.0, 1.0]], 'f4')
    out = run_op('box_clip', {'Input': [b], 'ImInfo': [im_info]})
    np.testing.assert_allclose(A(out, 'Output')[0, 0], [0, 0, 47, 31])


# ------------------------------------------------------------- sequence

def test_sequence_extras():
    rng = np.random.RandomState(11)
    x = np.array([[1, 2, 3, 0], [4, 5, 0, 0]], 'i4')
    mask = np.array([[1, 1, 1, 0], [1, 1, 0, 0]], 'f4')

    out = run_op('sequence_reverse', {'X': [x], 'Mask': [mask]})
    np.testing.assert_array_equal(A(out, 'Y'),
                                  [[3, 2, 1, 0], [5, 4, 0, 0]])

    out = run_op('sequence_erase', {'X': [x], 'Mask': [mask]},
                 {'tokens': [2, 4]})
    np.testing.assert_array_equal(A(out, 'Out'),
                                  [[1, 3, 0, 0], [5, 0, 0, 0]])

    out = run_op('sequence_enumerate', {'X': [x], 'Mask': [mask]},
                 {'win_size': 2, 'pad_value': -1})
    got = A(out, 'Out')
    np.testing.assert_array_equal(got[0], [[1, 2], [2, 3], [3, -1],
                                           [-1, -1]])

    xf = rng.randn(2, 4, 3).astype('f4')
    out = run_op('sequence_pad',
                 {'X': [xf], 'Mask': [mask],
                  'PadValue': [np.array([9.0], 'f4')]})
    got = A(out, 'Out')
    assert (got[0, 3] == 9).all() and (got[1, 2:] == 9).all()
    np.testing.assert_array_equal(A(out, 'Length'), [3, 2])

    out = run_op('sequence_unpad',
                 {'X': [xf], 'Length': [np.array([3, 2], 'i4')]})
    np.testing.assert_array_equal(A(out, 'Mask'), mask)

    a = np.array([[1, 2, 0], [3, 0, 0]], 'i4')
    am = np.array([[1, 1, 0], [1, 0, 0]], 'f4')
    b = np.array([[7, 8], [9, 0]], 'i4')
    bm = np.array([[1, 1], [1, 0]], 'f4')
    out = run_op('sequence_concat', {'X': [a, b], 'Mask': [am, bm]})
    np.testing.assert_array_equal(A(out, 'Out'),
                                  [[1, 2, 7, 8, 0], [3, 9, 0, 0, 0]])

    out = run_op('sequence_slice',
                 {'X': [x], 'Offset': [np.array([1, 0], 'i4')],
                  'Length': [np.array([2, 1], 'i4')]})
    np.testing.assert_array_equal(A(out, 'Out'),
                                  [[2, 3, 0, 0], [4, 0, 0, 0]])

    xv = rng.randn(2, 3).astype('f4')
    y = np.zeros((2, 4), 'f4')
    out = run_op('sequence_expand_as', {'X': [xv], 'Y': [y],
                                        'Mask': [mask]})
    got = A(out, 'Out')
    np.testing.assert_allclose(got[0, 2], xv[0])
    assert (got[0, 3] == 0).all()

    base = np.zeros((6,), 'f4')
    ids = np.array([[0, 2], [4, 0]], 'i4')
    upd = np.array([[1.0, 2.0], [3.0, 9.0]], 'f4')
    m2 = np.array([[1, 1], [1, 0]], 'f4')
    out = run_op('sequence_scatter', {'X': [base], 'Ids': [ids],
                                      'Updates': [upd], 'Mask': [m2]})
    np.testing.assert_allclose(A(out, 'Out'), [1, 0, 2, 0, 3, 0])

    out = run_op('lod_reset', {'X': [x], 'Y': [np.array([2, 4], 'i4')]})
    np.testing.assert_array_equal(A(out, 'Mask'),
                                  [[1, 1, 0, 0], [1, 1, 1, 1]])


def test_unique_with_counts_host():
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='int64')
        out = main.global_block().create_var(name='uniq', dtype='int64',
                                             shape=())
        idx = main.global_block().create_var(name='uidx', dtype='int32',
                                             shape=())
        cnt = main.global_block().create_var(name='ucnt', dtype='int32',
                                             shape=())
        main.global_block().append_op(
            'unique_with_counts', inputs={'X': x},
            outputs={'Out': out, 'Index': idx, 'Count': cnt})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        u, c = exe.run(main,
                       feed={'x': np.array([[3, 1, 3, 2, 1, 3, 7, 7]],
                                           'int64')},
                       fetch_list=[out, cnt])
    np.testing.assert_array_equal(np.asarray(u), [1, 2, 3, 7])
    np.testing.assert_array_equal(np.asarray(c), [2, 1, 3, 2])


def test_conv3d_layer_trains():
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2, 4, 6, 6], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.conv3d(x, 4, 3, padding=1, act='relu')
        h = fluid.layers.pool3d(h, 2, 'avg')
        h = fluid.layers.reshape(h, [-1, int(np.prod(h.shape[1:]))])
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    rng = np.random.RandomState(12)

    def batch(n=8):
        xs = rng.randn(n, 2, 4, 6, 6).astype('f4')
        return {'x': xs, 'y': xs.mean((1, 2, 3, 4), keepdims=False)
                .reshape(n, 1) * 3.0}

    with __import__('paddle_tpu').fluid.scope_guard(
            __import__('paddle_tpu').fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(25):
            l, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0], losses
