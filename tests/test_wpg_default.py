"""Whole-program autodiff as the DEFAULT backward (round 5).

FLAGS_whole_program_grad defaults ON: eligible train segments lower as
forward ops + ONE jax.vjp (executor._wpg_partition) with the per-op
grad replay as automatic fallback.  Reference semantics that must not
move: python/paddle/fluid/backward.py:1023 (append_backward).

These tests pin the round-5 eligibility widening:
  - while-loop (NMT-style) programs take the wpg path
  - multi-loss programs take it and match the per-op numerics
  - a print between forward and backward no longer splits the segment
    (read-only host ops defer past device ops they don't depend on)
  - RecomputeOptimizer programs DECLINE wpg (the vjp would keep all
    activations resident, defeating recompute's memory savings)
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import executor as executor_mod
from paddle_tpu.fluid.flags import get_flag, set_flags


def _segments(exe, program, feed_names, fetch_names):
    plan = exe._get_plan(program, tuple(sorted(feed_names)),
                         tuple(fetch_names))
    return [it for it in plan if isinstance(it, executor_mod._Segment)]


def _train(main, startup, loss, feeds, steps=6, wpg=None):
    old = get_flag('FLAGS_whole_program_grad')
    if wpg is not None:
        set_flags({'FLAGS_whole_program_grad': wpg})
    try:
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for feed in feeds:
                out, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(out).ravel()[0]))
            pname = main.all_parameters()[0].name
            param = np.asarray(scope.find_var(pname))
        return losses, param
    finally:
        set_flags({'FLAGS_whole_program_grad': old})


def test_flag_defaults_on():
    # the DEFAULT table, not the live value (other tests may have
    # toggled the runtime flag before this one runs)
    from paddle_tpu.fluid.flags import _DEFAULTS
    assert _DEFAULTS['FLAGS_whole_program_grad'] is True


def _mlp_program(seed, two_losses=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 16, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        if two_losses:
            aux = fluid.layers.mean(fluid.layers.abs(pred))
            total = [loss, aux]
        else:
            total = [loss]
    return main, startup, total


def _feeds(n, d=8, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.randn(4, d).astype('float32')
        out.append({'x': xb, 'y': xb.sum(1, keepdims=True)})
    return out


def test_simple_train_takes_wpg_by_default():
    main, startup, (loss,) = _mlp_program(3)
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.XLAPlace(0))
    segs = _segments(exe, main, ['x', 'y'], [loss.name])
    assert len(segs) == 1
    assert executor_mod._wpg_partition(segs[0]) is not None


def test_multi_loss_takes_wpg_and_matches_per_op():
    def build():
        main, startup, (loss, aux) = _mlp_program(5, two_losses=True)
        with fluid.program_guard(main, startup):
            pgs1 = fluid.backward.append_backward(loss)
            pgs2 = fluid.backward.append_backward(aux)
            # one optimizer applying both losses' grads (summed via the
            # vjp / via per-op sum ops)
            opt = fluid.optimizer.SGD(0.05)
            merged = {}
            for p, g in pgs1 + pgs2:
                merged.setdefault(p.name, (p, []))[1].append(g)
            pg = []
            for p, gs in merged.values():
                pg.append((p, gs[-1]))
            opt.apply_gradients(pg)
        return main, startup, loss

    m1, s1, l1 = build()
    exe = fluid.Executor(fluid.XLAPlace(0))
    segs = _segments(exe, m1, ['x', 'y'], [l1.name])
    assert len(segs) == 1
    part = executor_mod._wpg_partition(segs[0])
    assert part is not None
    assert len(part['seeds']) == 2

    feeds = _feeds(6, seed=1)
    wpg_losses, wpg_param = _train(m1, s1, l1, feeds, wpg=True)
    m2, s2, l2 = build()
    ref_losses, ref_param = _train(m2, s2, l2, feeds, wpg=False)
    np.testing.assert_allclose(wpg_losses, ref_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(wpg_param, ref_param, rtol=1e-5,
                               atol=1e-6)


def test_while_loop_program_takes_wpg():
    """An NMT-style bounded while loop trains through ONE jax.vjp."""
    layers = fluid.layers

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4, 8], dtype='float32',
                            append_batch_size=False)
            y = layers.data('y', shape=[4, 1], dtype='float32',
                            append_batch_size=False)
            w = layers.create_parameter(
                [8, 8], 'float32', name='rnn_w',
                default_initializer=fluid.initializer.Constant(0.1))
            i = layers.fill_constant([1], 'float32', 0)
            n = layers.fill_constant([1], 'float32', 3)
            h = layers.fill_constant([4, 8], 'float32', 0.0)
            cond = layers.less_than(i, n)
            wl = layers.While(cond, max_trip_count=4)
            with wl.block():
                h2 = layers.tanh(
                    layers.elementwise_add(layers.matmul(h, w), x))
                layers.assign(h2, h)
                layers.increment(i)
                layers.assign(layers.less_than(i, n), cond)
            pred = layers.reduce_mean(h, dim=[1], keep_dim=True)
            loss = layers.mean(layers.square(pred - y))
            fluid.optimizer.SGD(0.05).minimize(loss)
        return main, startup, loss

    main, startup, loss = build()
    exe = fluid.Executor(fluid.XLAPlace(0))
    segs = _segments(exe, main, ['x', 'y'], [loss.name])
    assert len(segs) == 1
    seg = segs[0]
    types = [op.type for op in seg.ops]
    assert 'while' in types and 'while_grad' in types
    assert executor_mod._wpg_partition(seg) is not None

    feeds = []
    rng = np.random.RandomState(2)
    for _ in range(5):
        xb = rng.randn(4, 8).astype('float32')
        feeds.append({'x': xb, 'y': xb.sum(1, keepdims=True)})
    wpg_losses, wpg_param = _train(main, startup, loss, feeds, wpg=True)
    m2, s2, l2 = build()
    ref_losses, ref_param = _train(m2, s2, l2, feeds, wpg=False)
    np.testing.assert_allclose(wpg_losses, ref_losses, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(wpg_param, ref_param, rtol=1e-4,
                               atol=1e-5)


def test_print_between_fwd_and_bwd_keeps_one_segment(capsys):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 16, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.layers.Print(loss, message='loss=')
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.XLAPlace(0))
    plan = exe._get_plan(main, ('x', 'y'), (loss.name,))
    segs = [it for it in plan if isinstance(it, executor_mod._Segment)]
    hosts = [it for it in plan if not isinstance(it, executor_mod._Segment)]
    # ONE fused device segment; the print deferred after it
    assert len(segs) == 1
    assert [h[1].type for h in hosts] == ['print']
    assert executor_mod._wpg_partition(segs[0]) is not None
    # and the printed value is the loss of THIS step (not stale)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        exe2.run(startup)
        feed = _feeds(1, seed=4)[0]
        out, = exe2.run(main, feed=feed, fetch_list=[loss])
    printed = capsys.readouterr().out
    assert 'loss=' in printed
    assert ('%.4f' % float(np.asarray(out).ravel()[0]))[:5] in printed or \
        str(np.asarray(out).ravel()[0])[:4] in printed


def test_param_save_before_update_is_not_deferred(tmp_path):
    """A save of a param that the optimizer later rewrites must run at
    its program point (pre-update values), not be deferred."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(x, 1, name='sv')
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
    p = main.all_parameters()[0]
    path = str(tmp_path / 'pre_update')
    with fluid.program_guard(main, startup):
        main.global_block().append_op(
            'save', inputs={'X': [p.name]}, outputs={},
            attrs={'file_path': path})
        fluid.optimizer.SGD(1.0).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        before = np.array(np.asarray(scope.find_var(p.name)))
        feed = _feeds(1, d=4, seed=5)[0]
        exe.run(main, feed=feed, fetch_list=[loss])
        after = np.asarray(scope.find_var(p.name))
    saved = np.load(path + '.npy')
    np.testing.assert_allclose(saved, before, rtol=0, atol=0)
    assert not np.allclose(after, before)  # lr=1.0 moved the param


def test_recompute_program_declines_wpg():
    """ADVICE r4 (medium): recompute re-emits forward spans with
    backward role; wpg must decline or activations stay resident."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h1 = fluid.layers.fc(x, 32, act='relu')
        h2 = fluid.layers.fc(h1, 32, act='relu')
        pred = fluid.layers.fc(h2, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.05))
        opt._set_checkpoints([h1])
        opt.minimize(loss)
    exe = fluid.Executor(fluid.XLAPlace(0))
    segs = _segments(exe, main, ['x', 'y'], [loss.name])
    assert len(segs) == 1
    assert executor_mod._wpg_partition(segs[0]) is None
