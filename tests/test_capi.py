"""Stable C API + C++ train demo, end to end.

Mirrors the reference's C API tests and C++ train demo
(reference: paddle/fluid/inference/capi/c_api.h,
paddle/fluid/train/demo/demo_trainer.cc,
paddle/fluid/train/test_train_recognize_digits.cc): build the shared
library, save a model from Python, then drive it from compiled C —
predict parity against the Python predictor, and a C++ training loop
whose loss must decrease.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI_DIR = os.path.join(ROOT, 'paddle_tpu', 'inference', 'capi')
LIB = os.path.join(CAPI_DIR, 'libpaddle_tpu_capi.so')

pytestmark = pytest.mark.skipif(
    shutil.which('g++') is None or shutil.which('python3-config') is None,
    reason='no native toolchain')


def _build_lib():
    subprocess.run(['make', '-C', CAPI_DIR], check=True,
                   capture_output=True)
    return LIB


def _compile(src, out):
    subprocess.run(
        ['g++', '-O1', src, '-o', out, '-L' + CAPI_DIR,
         '-lpaddle_tpu_capi', '-Wl,-rpath,' + CAPI_DIR],
        check=True, capture_output=True)


def _subprocess_env():
    env = dict(os.environ)
    env['PADDLE_TPU_ROOT'] = ROOT
    # the C process spawns a fresh embedded interpreter: pin it to the
    # host CPU backend like conftest does for in-process tests
    env['PADDLE_TPU_CAPI_PLATFORM'] = 'cpu'
    env['JAX_PLATFORMS'] = 'cpu'
    return env


def _save_fc_model(tmpdir):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        h = fluid.layers.fc(input=x, size=16, act='relu')
        out = fluid.layers.fc(input=h, size=3, act='softmax')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(tmpdir, ['x'], [out], exe, main)
        xv = ((np.arange(4 * 8) % 17) * 0.25 - 2.0) \
            .reshape(4, 8).astype('float32')
        expect, = exe.run(main, feed={'x': xv}, fetch_list=[out])
    return expect


def test_capi_predictor_matches_python(tmp_path):
    _build_lib()
    model_dir = str(tmp_path / 'model')
    expect = _save_fc_model(model_dir)
    driver = str(tmp_path / 'capi_predict_driver')
    _compile(os.path.join(ROOT, 'tests', 'capi_predict_driver.c'), driver)
    res = subprocess.run([driver, model_dir, '4', '8'],
                         capture_output=True, text=True,
                         env=_subprocess_env(), timeout=300)
    assert res.returncode == 0, res.stderr
    got = np.array([float(t) for t in res.stdout.split()],
                   dtype='float32').reshape(expect.shape)
    np.testing.assert_allclose(got, expect, rtol=2e-5, atol=2e-6)


def test_cpp_train_demo_loss_decreases(tmp_path):
    _build_lib()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[13], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    model_dir = str(tmp_path / 'train_model')
    fluid.io.save_train_model(model_dir, main, startup, ['x', 'y'], [loss])

    demo = str(tmp_path / 'demo_trainer')
    _compile(os.path.join(ROOT, 'paddle_tpu', 'train', 'demo',
                          'demo_trainer.cc'), demo)
    res = subprocess.run([demo, model_dir, '40'], capture_output=True,
                         text=True, env=_subprocess_env(), timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    # the C++ demo saved persistables back; they must load in Python
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        m2, s2, feeds, fetches = fluid.io.load_train_model(model_dir)
        exe.run(s2)
        fluid.io.load_persistables(exe, model_dir, m2)
        assert feeds == ['x', 'y']
