"""Gradient-check sweep, part 3 (round 4): scatter/gather family,
select ops, RNN step cells, structured losses, linear algebra, fused
elementwise, hierarchical softmax, tree conv, and image-to-sequence —
differentiable ops that parts 1-2 left to name-level coverage only.

Inputs live in each op's smooth region (away from kinks) and use an
ISOLATED RandomState so pytest -k deselection cannot shift which
values an op sees (the shared-rng flake fixed in part 2's
grid_sampler entry)."""

import numpy as np
import pytest

from op_test import OpTest


def R(seed):
    return np.random.RandomState(seed)


# op -> (inputs builder, attrs, out_slot, check_grad kwargs)
CASES = {
    'expand_as': (
        lambda: {'X': R(0).randn(2, 3),
                 'target_tensor': R(1).randn(4, 3)},
        {}, 'Out', {'grad_slots': ['X']}),
    'gather_nd': (
        lambda: {'X': R(2).randn(3, 4),
                 'Index': np.array([[0, 1], [2, 3]], 'int64')},
        {}, 'Out', {'grad_slots': ['X']}),
    'scatter': (
        lambda: {'X': R(3).randn(4, 3),
                 'Ids': np.array([1, 3], 'int64'),
                 'Updates': R(4).randn(2, 3)},
        {'overwrite': True}, 'Out', {'grad_slots': ['X', 'Updates']}),
    'scatter_add': (
        lambda: {'X': R(3).randn(4, 3),
                 'Ids': np.array([1, 1], 'int64'),
                 'Updates': R(4).randn(2, 3)},
        {'overwrite': False}, 'Out', {'grad_slots': ['X', 'Updates'],
                                      'op_name': 'scatter'}),
    'scatter_nd_add': (
        lambda: {'X': R(5).randn(3, 3),
                 'Index': np.array([[0], [2]], 'int64'),
                 'Updates': R(6).randn(2, 3)},
        {}, 'Out', {'grad_slots': ['X', 'Updates']}),
    'scatter_nd': (
        lambda: {'Index': np.array([[0], [2]], 'int64'),
                 'Updates': R(7).randn(2, 3)},
        {'shape': [4, 3]}, 'Out', {'grad_slots': ['Updates']}),
    'index_select': (
        lambda: {'X': R(8).randn(3, 4),
                 'Index': np.array([0, 2], 'int64')},
        {'dim': 0}, 'Out', {'grad_slots': ['X']}),
    'where': (
        lambda: {'Condition': np.array([[1, 0, 1], [0, 1, 0]], bool),
                 'X': R(9).randn(2, 3), 'Y': R(10).randn(2, 3)},
        {}, 'Out', {'grad_slots': ['X', 'Y']}),
    # val = (2y-1)x kinks at val in {-1, 1}: |x| <= 0.8 keeps clear
    'modified_huber_loss': (
        lambda: {'X': R(11).uniform(-0.8, 0.8, (3, 1)),
                 'Y': np.array([[0.0], [1.0], [1.0]])},
        {}, 'Out', {'grad_slots': ['X']}),
    # label branches switch at {-1, 0, 1}: pick labels inside regions
    'teacher_student_sigmoid_loss': (
        lambda: {'X': R(12).randn(3, 1),
                 'Label': np.array([[-2.0], [0.4], [1.6]])},
        {}, 'Y', {'grad_slots': ['X']}),
    'center_loss': (
        lambda: {'X': R(13).randn(3, 4),
                 'Label': np.array([0, 2, 2], 'int64'),
                 'Centers': R(14).randn(5, 4)},
        {'alpha': 0.1, 'need_update': False}, 'Loss',
        {'grad_slots': ['X']}),
    'inverse': (
        lambda: {'Input': 2.0 * np.eye(3) + 0.1 * R(18).randn(3, 3)},
        {}, 'Output', {'grad_slots': ['Input']}),
    'cholesky': (
        lambda: {'X': (lambda a: a @ a.T + 2 * np.eye(3))(
            R(19).randn(3, 3))},
        {}, 'Out', {'grad_slots': ['X'], 'atol': 2e-2, 'rtol': 2e-2}),
    # exact 2x nearest upscale: the source-pixel map is stable under
    # the finite-difference perturbation
    'interp_nearest': (
        lambda: {'X': R(20).randn(1, 2, 2, 2)},
        {'out_h': 4, 'out_w': 4}, 'Out', {'grad_slots': ['X']}),
    # distinct values so top-k membership is stable under perturbation
    'top_k': (
        lambda: {'X': np.arange(10.0).reshape(2, 5)
                 + R(21).uniform(0, 0.3, (2, 5))},
        {'k': 2}, 'Out', {'grad_slots': ['X']}),
    # add+relu: keep x+y away from the relu kink at 0
    'fused_elemwise_activation': (
        lambda: {'X': R(22).uniform(0.5, 1.5, (2, 3)),
                 'Y': R(23).uniform(0.5, 1.5, (2, 3))},
        {'functor_list': ['elementwise_add', 'relu']}, 'Out',
        {'grad_slots': ['X', 'Y']}),
    'gru_unit': (
        lambda: {'Input': R(24).randn(2, 9) * 0.5,
                 'HiddenPrev': R(25).randn(2, 3) * 0.5,
                 'Weight': R(26).randn(3, 9) * 0.5},
        {}, 'Hidden',
        {'grad_slots': ['Input', 'HiddenPrev', 'Weight']}),
    'lstm_unit': (
        lambda: {'X': R(27).randn(2, 8) * 0.5,
                 'C_prev': R(28).randn(2, 2) * 0.5},
        {'forget_bias': 0.0}, 'H', {'grad_slots': ['X', 'C_prev']}),
    'hierarchical_sigmoid': (
        lambda: {'X': R(29).randn(3, 4) * 0.5,
                 'W': R(30).randn(6, 4) * 0.5,
                 'Label': np.array([0, 3, 5], 'int64'),
                 'Bias': R(31).randn(6) * 0.5},
        {'num_classes': 7}, 'Out',
        {'grad_slots': ['X', 'W', 'Bias']}),
    'tree_conv': (
        lambda: {'NodesVector': R(32).randn(1, 4, 3) * 0.5,
                 'EdgeSet': np.array([[[0, 1], [0, 2], [1, 3]]],
                                     'int64'),
                 'Filter': R(33).randn(3, 3, 2, 2) * 0.5},
        {'max_depth': 2}, 'Out',
        {'grad_slots': ['NodesVector', 'Filter'],
         'atol': 2e-2, 'rtol': 2e-2}),
    'im2sequence': (
        lambda: {'X': R(34).randn(1, 2, 3, 3)},
        {'kernels': [2, 2], 'strides': [1, 1],
         'paddings': [0, 0, 0, 0]}, 'Out', {'grad_slots': ['X']}),
    'affine_grid': (
        lambda: {'Theta': R(35).randn(2, 2, 3) * 0.5},
        {'output_shape': [2, 1, 3, 3]}, 'Output',
        {'grad_slots': ['Theta']}),
    # indices as max_pool2d_with_index would emit them: one source
    # position per pooled cell, distinct within each (n, c) plane
    'unpool': (
        lambda: {'X': R(36).randn(1, 2, 2, 2),
                 'Indices': np.array(
                     [[[[0, 3], [9, 10]],
                       [[5, 6], [12, 15]]]], 'int64')},
        {'ksize': [2, 2], 'strides': [2, 2], 'paddings': [0, 0]},
        'Out', {'grad_slots': ['X']}),
    'sequence_expand': (
        lambda: {'X': R(37).randn(2, 3),
                 'Y': R(38).randn(2, 4, 3)},
        {}, 'Out', {'grad_slots': ['X']}),
    'sequence_slice': (
        lambda: {'X': R(39).randn(2, 5, 3),
                 'Offset': np.array([1, 0], 'int64'),
                 'Length': np.array([2, 3], 'int64')},
        {}, 'Out', {'grad_slots': ['X']}),
    # X/Y/Weight grads are sweep2's; only the Bias slot is new here
    'bilinear_tensor_product': (
        lambda: {'X': R(42).randn(2, 3) * 0.5,
                 'Y': R(43).randn(2, 4) * 0.5,
                 'Weight': R(44).randn(2, 3, 4) * 0.5,
                 'Bias': R(45).randn(2) * 0.5},
        {}, 'Out', {'grad_slots': ['Bias']}),
}


def test_spectral_norm_grad_frozen_uv_oracle():
    """spectral_norm stop-gradients u/v (reference buffers updated by
    power iteration out of the autodiff graph), so finite differences
    through the OP disagree by design.  Oracle: run the power
    iteration once to get (u*, v*), then jax.grad of w -> w/(u* M v*)
    with u*, v* FROZEN must equal the op's analytic gradient."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import registry

    rng = R(15)
    w = rng.randn(3, 4).astype('float32')
    u0 = rng.randn(3).astype('float32')
    v0 = rng.randn(4).astype('float32')
    attrs = {'power_iters': 1, 'dim': 0}
    ctx = registry.LowerCtx(0)

    def op_out(wv):
        return registry.get('spectral_norm').fn(
            ctx, {'Weight': [wv], 'U': [jnp.asarray(u0)],
                  'V': [jnp.asarray(v0)]}, attrs)['Out'][0]

    # frozen-uv oracle
    mat = jnp.asarray(w)
    v_ = mat.T @ jnp.asarray(u0)
    v_ = v_ / jnp.linalg.norm(v_)
    u_ = mat @ v_
    u_ = u_ / jnp.linalg.norm(u_)
    u_, v_ = jax.lax.stop_gradient((u_, v_))

    def oracle(wv):
        return wv / (u_ @ (wv @ v_))

    cot = R(16).randn(3, 4).astype('float32')
    g_op = jax.vjp(op_out, jnp.asarray(w))[1](jnp.asarray(cot))[0]
    g_or = jax.vjp(oracle, jnp.asarray(w))[1](jnp.asarray(cot))[0]
    np.testing.assert_allclose(np.asarray(g_op), np.asarray(g_or),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('case', sorted(CASES))
def test_sweep3_grad(case):
    gen, attrs, out_slot, kw = CASES[case]
    kw = dict(kw)
    op = kw.pop('op_name', case)
    ins = {}
    for k, v in gen().items():
        v = np.asarray(v)
        ins[k] = v if v.dtype.kind in 'iub' else v.astype('float32')
    OpTest().check_grad(op, ins, attrs, out_slot=out_slot, **kw)
