"""Async communicator / GeoSGD / heartbeat failure-detection tests
(reference: communicator_test.cc + heart_beat_monitor.h semantics)."""

import threading
import time

import numpy as np

from paddle_tpu.distributed import (AsyncCommunicator, GeoSgdCommunicator,
                                    HeartBeatMonitor, ParameterServerStore)
from paddle_tpu.distributed import heartbeat


def test_async_communicator_converges():
    """3 worker threads minimize ||w - target||^2 through the async
    send/recv path; bounded staleness must still converge."""
    rng = np.random.RandomState(0)
    target = rng.randn(8).astype('float32')
    server = ParameterServerStore(lr=0.05)
    server.init_var('w', np.zeros(8, 'float32'))
    comm = AsyncCommunicator(server, merge_num=4)
    comm.start()

    def worker():
        for _ in range(150):
            w = comm.recv('w')
            comm.send('w', 2.0 * (w - target))

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    comm.flush()
    comm.stop()
    w = server.get('w')
    assert np.abs(w - target).max() < 0.05, (w, target)


def test_geo_sgd_converges_two_trainers():
    """2 trainers do local SGD and ship deltas every k steps."""
    rng = np.random.RandomState(1)
    target = rng.randn(6).astype('float64')
    server = ParameterServerStore()
    server.init_var('w', np.zeros(6))
    comms = [GeoSgdCommunicator(server, trainers=2, geo_need_push_nums=5)
             for _ in range(2)]
    for c in comms:
        c.start()
    locals_ = [c.init_from_server('w') for c in comms]
    for it in range(300):
        for k, c in enumerate(comms):
            w = locals_[k]
            w = w - 0.05 * 2.0 * (w - target)     # local sgd step
            locals_[k] = c.step('w', w)
    for c in comms:
        c.stop()
    w = server.get('w')
    assert np.abs(w - target).max() < 0.05, (w, target)


def test_heartbeat_detects_lost_worker():
    lost = []
    mon = HeartBeatMonitor(workers=3, timeout=0.2, check_interval=0.05,
                           on_lost=lambda wid, age: lost.append(wid))
    mon.start()
    try:
        mon.update(0)
        mon.update(1)
        # worker 2 never reports: stays UNINITED, must NOT be flagged
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not mon.lost_workers():
            mon.update(1)                      # worker 1 keeps beating
            time.sleep(0.05)
        assert mon.lost_workers() == [0]       # worker 0 went silent
        assert lost == [0]
        assert mon.worker_status(2) == 'UNINITED'
        # recovery: a new heartbeat clears the lost mark
        mon.update(0)
        assert mon.lost_workers() == []
        mon.update(0, heartbeat.COMPLETED)
        mon.update(1, heartbeat.COMPLETED)
        mon.update(2, heartbeat.COMPLETED)
        assert mon.all_completed()
    finally:
        mon.stop()
