"""OpTest harness — the per-op validation backbone.

Reference: python/paddle/fluid/tests/unittests/op_test.py:174 (OpTest):
check_output runs the single op through the real executor on every place;
check_grad compares analytic gradients against centered finite differences
(get_numeric_gradient, op_test.py:57).  Here the 'place' is the XLA
device and the analytic grads come from the vjp-synthesized grad ops via
append_backward — so check_grad validates the whole autodiff pipeline,
not just the kernel.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core


class OpTest(object):
    """Subclass sets: op_type, inputs {slot: array | [(name, array),...]},
    attrs, and either expected outputs or a numpy reference fn."""

    atol = 1e-5
    rtol = 1e-4
    grad_atol = 5e-3
    grad_rtol = 5e-3
    fd_eps = 5e-3

    def _build(self, op_type, inputs, attrs, out_slots, stop_gradients=()):
        main = fluid.Program()
        startup = fluid.Program()
        feed = {}
        with fluid.program_guard(main, startup):
            in_vars = {}
            for slot, val in inputs.items():
                if isinstance(val, list):
                    row = []
                    for name, arr in val:
                        v = main.global_block().create_var(
                            name=name, shape=arr.shape,
                            dtype=str(arr.dtype),
                            stop_gradient=(slot in stop_gradients or
                                           not np.issubdtype(
                                               arr.dtype, np.floating)))
                        row.append(v)
                        feed[name] = arr
                    in_vars[slot] = row
                else:
                    name = 'in_' + slot
                    v = main.global_block().create_var(
                        name=name, shape=val.shape, dtype=str(val.dtype),
                        stop_gradient=(slot in stop_gradients or
                                       not np.issubdtype(val.dtype,
                                                         np.floating)))
                    in_vars[slot] = v
                    feed[name] = val
            out_vars = {}
            for slot in out_slots:
                ov = main.global_block().create_var(
                    name='out_' + slot, shape=(), dtype='float32')
                out_vars[slot] = ov
            main.global_block().append_op(op_type, inputs=in_vars,
                                          outputs=out_vars, attrs=attrs)
        return main, startup, feed, in_vars, out_vars

    def run_op(self, op_type, inputs, attrs=None, out_slots=('Out',),
               stop_gradients=()):
        main, startup, feed, _, out_vars = self._build(
            op_type, inputs, attrs or {}, out_slots, stop_gradients)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            fetches = [out_vars[s] for s in out_slots]
            res = exe.run(main, feed=feed, fetch_list=fetches)
        return dict(zip(out_slots, res))

    def check_output(self, op_type, inputs, attrs=None, expect=None,
                     out_slots=None, atol=None, rtol=None):
        expect = expect or {}
        out_slots = out_slots or list(expect.keys()) or ['Out']
        got = self.run_op(op_type, inputs, attrs, tuple(out_slots))
        for slot, want in expect.items():
            np.testing.assert_allclose(
                got[slot], np.asarray(want),
                atol=atol or self.atol, rtol=rtol or self.rtol,
                err_msg='%s output %s mismatch' % (op_type, slot))
        return got

    def check_grad(self, op_type, inputs, attrs=None, out_slot='Out',
                   grad_slots=None, stop_gradients=(), eps=None,
                   atol=None, rtol=None):
        """Compare analytic d(sum(w*out))/d(in) against central
        finite differences, like reference get_numeric_gradient."""
        import os
        audit = os.environ.get('PADDLE_TPU_GRAD_AUDIT')
        if audit:
            # dynamic FD-coverage accounting (tools/check_grad_coverage
            # .py): record every op that actually reaches an FD check
            with open(audit, 'a') as fh:
                fh.write(op_type + '\n')
        attrs = attrs or {}
        eps = eps or self.fd_eps
        grad_slots = grad_slots or [
            s for s, v in inputs.items()
            if s not in stop_gradients and np.issubdtype(
                (v if not isinstance(v, list) else v[0][1]).dtype,
                np.floating)]

        main, startup, feed, in_vars, out_vars = self._build(
            op_type, inputs, attrs, (out_slot,), stop_gradients)
        out_var = out_vars[out_slot]
        rng = np.random.RandomState(123)

        with fluid.program_guard(main, startup):
            w = rng.uniform(0.5, 1.5,
                            size=out_var.shape or ()).astype('float32')
            wv = fluid.layers.assign(w.astype('float32'))
            prod = fluid.layers.elementwise_mul(
                out_var, wv) if out_var.shape else out_var
            loss = fluid.layers.reduce_sum(prod)
            grads = {}
            pg = fluid.backward.append_backward(
                loss, parameter_list=None)
            del pg
            for slot in grad_slots:
                v = in_vars[slot]
                if isinstance(v, list):
                    # multi-var slot (concat/sum/stack X): one grad
                    # var per input var
                    row = []
                    for vi in v:
                        gname = main._grad_name_map.get(vi.name)
                        assert gname, 'no grad var for %s' % vi.name
                        row.append((vi.name, gname))
                    grads[slot] = row
                else:
                    gname = main._grad_name_map.get(v.name)
                    assert gname, 'no grad var for %s' % v.name
                    grads[slot] = gname

        # (slot, feed name, analytic grad var) triples — one per var,
        # expanding multi-var slots
        targets = []
        for slot in grad_slots:
            g = grads[slot]
            if isinstance(g, list):
                targets.extend((slot, name, gname) for name, gname in g)
            else:
                targets.append((slot, 'in_' + slot, g))

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            analytic = exe.run(main, feed=feed,
                               fetch_list=[g for _, _, g in targets])
            analytic = {name: a for (_, name, _), a
                        in zip(targets, analytic)}

            def eval_loss(fd):
                out, = exe.run(main, feed=fd, fetch_list=[loss])
                return float(out)

            for slot, name, _ in targets:
                base = feed[name].astype(np.float64)
                numeric = np.zeros_like(base)
                flat = base.reshape(-1)
                num_flat = numeric.reshape(-1)
                for i in range(flat.size):
                    orig = flat[i]
                    fd = dict(feed)
                    pert = base.copy().reshape(-1)
                    pert[i] = orig + eps
                    fd[name] = pert.reshape(base.shape).astype(
                        feed[name].dtype)
                    lp = eval_loss(fd)
                    pert[i] = orig - eps
                    fd[name] = pert.reshape(base.shape).astype(
                        feed[name].dtype)
                    lm = eval_loss(fd)
                    num_flat[i] = (lp - lm) / (2 * eps)
                np.testing.assert_allclose(
                    analytic[name], numeric,
                    atol=atol or self.grad_atol,
                    rtol=rtol or self.grad_rtol,
                    err_msg='%s grad wrt %s (%s) mismatch'
                    % (op_type, slot, name))
