/* C driver for the capi test: load an inference model, run one batch of
 * deterministic inputs, print the outputs.  Compiled and executed by
 * tests/test_capi.py; mirrors how a C deployment of the reference C API
 * looks (reference: paddle/fluid/inference/capi/c_api.h usage).
 *
 * Usage: capi_predict_driver <model_dir> <batch> <feat>
 * Prints: one output value per line, %.6f.
 */
#include <stdio.h>
#include <stdlib.h>

#include "../paddle_tpu/inference/capi/c_api.h"

int main(int argc, char** argv) {
  if (argc < 4) return 2;
  const char* model_dir = argv[1];
  int batch = atoi(argv[2]);
  int feat = atoi(argv[3]);

  PD_AnalysisConfig* cfg = PD_NewAnalysisConfig();
  PD_SetModel(cfg, model_dir, NULL);
  PD_Predictor* pred = PD_NewPredictor(cfg);
  if (!pred) {
    fprintf(stderr, "NewPredictor: %s\n", PD_GetLastError());
    return 1;
  }
  if (PD_GetInputNum(pred) != 1) {
    fprintf(stderr, "expected 1 input, got %d\n", PD_GetInputNum(pred));
    return 1;
  }

  float* x = (float*)malloc(sizeof(float) * batch * feat);
  for (int i = 0; i < batch * feat; ++i) x[i] = (i % 17) * 0.25f - 2.0f;

  PD_Tensor* in = PD_NewPaddleTensor();
  int shape[2];
  shape[0] = batch;
  shape[1] = feat;
  PD_SetPaddleTensorName(in, PD_GetInputName(pred, 0));
  PD_SetPaddleTensorDType(in, PD_FLOAT32);
  PD_SetPaddleTensorShape(in, shape, 2);
  PD_SetPaddleTensorData(in, x, sizeof(float) * batch * feat);

  PD_Tensor** outs = NULL;
  int n_out = 0;
  PD_Tensor* ins[1];
  ins[0] = in;
  if (!PD_PredictorRun(pred, ins, 1, &outs, &n_out)) {
    fprintf(stderr, "Run: %s\n", PD_GetLastError());
    return 1;
  }
  for (int i = 0; i < n_out; ++i) {
    size_t bytes = 0;
    const float* data = (const float*)PD_GetPaddleTensorData(outs[i],
                                                             &bytes);
    size_t cnt = bytes / sizeof(float);
    for (size_t j = 0; j < cnt; ++j) printf("%.6f\n", data[j]);
  }
  PD_DeleteTensorArray(outs, n_out);
  PD_DeletePaddleTensor(in);
  PD_DeletePredictor(pred);
  PD_DeleteAnalysisConfig(cfg);
  free(x);
  return 0;
}
