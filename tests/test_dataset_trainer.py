"""Native datafeed + Dataset + train_from_dataset.

Mirrors reference tests test_dataset.py / test_monitor.py
(python/paddle/fluid/tests/unittests/) for the C++ DataFeed/Dataset
runtime — here the native runtime is paddle_tpu/runtime/datafeed.cc.
"""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _write_ctr_file(path, n, rng, dense_dim=4, sparse_max=3, vocab=50):
    with open(path, 'w') as f:
        for _ in range(n):
            d = rng.rand(dense_dim)
            nids = rng.randint(1, sparse_max + 1)
            ids = rng.randint(0, vocab, nids)
            label = rng.randint(0, 2)
            f.write('%d %s %d %s 1 %d\n' % (
                dense_dim, ' '.join('%f' % x for x in d),
                nids, ' '.join(str(i) for i in ids), label))


def test_native_feed_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    p1 = str(tmp_path / 'a.txt')
    p2 = str(tmp_path / 'b.txt')
    _write_ctr_file(p1, 300, rng)
    _write_ctr_file(p2, 211, rng)
    from paddle_tpu.runtime import MultiSlotDataFeed
    feed = MultiSlotDataFeed(
        [p1, p2], [('dense', 'dense', 4), ('ids', 'sparse', 3),
                   ('label', 'sparse', 1)], batch_size=64, nthreads=3,
        shuffle_buffer=128, seed=1)
    total = 0
    for b in feed:
        total += b['dense'].shape[0]
        assert set(np.unique(b['label'])) <= {0, 1}
    assert total == 511
    feed.close()


def test_train_from_dataset(tmp_path):
    rng = np.random.RandomState(1)
    path = str(tmp_path / 'train.txt')
    _write_ctr_file(path, 640, rng)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.layers.data('dense', shape=[4], dtype='float32')
        ids = fluid.layers.data('ids', shape=[3], dtype='int64')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(ids, size=[50, 8])
        emb = fluid.layers.reshape(emb, [0, 24])
        h = fluid.layers.fc(fluid.layers.concat([dense, emb], axis=1),
                            32, act='relu')
        logit = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(
                logit, fluid.layers.cast(label, 'float32')))
        fluid.optimizer.Adam(1e-3).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    dataset.set_batch_size(64)
    dataset.set_thread(2)
    dataset.set_filelist([path])
    dataset.set_use_var([dense, ids, label])
    dataset.load_into_memory()
    dataset.local_shuffle()

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        steps = exe.train_from_dataset(main, dataset,
                                       fetch_list=[loss],
                                       print_period=5)
    assert steps == 10, steps


def test_infer_from_dataset_does_not_update_params(tmp_path):
    """Reference keeps separate entry points (executor.py:1115 region):
    infer_from_dataset over a TRAINING program must not touch the
    parameters (round-5 fix: the optimizer/backward ops are pruned)."""
    rng = np.random.RandomState(7)
    path = str(tmp_path / 'infer.txt')
    _write_ctr_file(path, 128, rng)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.layers.data('dense', shape=[4], dtype='float32')
        ids = fluid.layers.data('ids', shape=[3], dtype='int64')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(ids, size=[50, 8])
        emb = fluid.layers.reshape(emb, [0, 24])
        h = fluid.layers.fc(fluid.layers.concat([dense, emb], axis=1),
                            16, act='relu')
        logit = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(
                logit, fluid.layers.cast(label, 'float32')))
        fluid.optimizer.SGD(1.0).minimize(loss)  # lr=1: would move fast

    dataset = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    dataset.set_batch_size(64)
    dataset.set_filelist([path])
    dataset.set_use_var([dense, ids, label])
    dataset.load_into_memory()

    pnames = [p.name for p in main.all_parameters()]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        before = {n: np.array(np.asarray(scope.find_var(n)))
                  for n in pnames}
        steps = exe.infer_from_dataset(main, dataset, fetch_list=[loss],
                                       print_period=1)
        after = {n: np.asarray(scope.find_var(n)) for n in pnames}
    assert steps == 2, steps
    for n in pnames:
        np.testing.assert_array_equal(before[n], after[n])


def test_infer_from_dataset_reclones_after_mutation(tmp_path):
    """The cached inference clone is keyed on the program version: a
    mutation after the first infer (re-minimize, new layers) must
    re-clone, not run the stale pre-mutation graph."""
    rng = np.random.RandomState(9)
    path = str(tmp_path / 'reclone.txt')
    _write_ctr_file(path, 64, rng)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.layers.data('dense', shape=[4], dtype='float32')
        ids = fluid.layers.data('ids', shape=[3], dtype='int64')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        logit = fluid.layers.fc(dense, 1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(
                logit, fluid.layers.cast(label, 'float32')))
        fluid.optimizer.SGD(0.5).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    dataset.set_batch_size(64)
    dataset.set_filelist([path])
    dataset.set_use_var([dense, ids, label])
    dataset.load_into_memory()

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.infer_from_dataset(main, dataset)
        v1 = main._infer_clone
        # mutate: add a scaled fetch head (bumps the program version)
        with fluid.program_guard(main, startup):
            fluid.layers.scale(loss, scale=2.0)
        exe.infer_from_dataset(main, dataset)
        v2 = main._infer_clone
    assert v1[0] != v2[0] and v1[1] is not v2[1]
