"""Auto-sharding planner (parallel/plan.py): regex rule ->
PartitionSpec matching over an unannotated Program, cost-model-priced
candidate layouts, the memviz HBM gate, automatic weight-update
sharding through the existing ZeRO path, and the FLAGS_auto_shard
parity contract — an unannotated transformer block trains at loss
parity with both the single-device dense fallbacks and the hand-placed
sp/ep mesh config test_sp_ep_fluid exercises, with zero post-warmup
retraces and a deterministic plan digest."""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import comms_plan, health, layers, monitor
from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.parallel import plan

# B divides every dp x fsdp extent of the 8-device mesh — the planner
# judges candidate shardability on the BATCH dim (what
# _guard_local_batch actually shards), not the token product
B, T, H, D, E, FF = 8, 16, 4, 8, 4, 32
DIM = H * D

PLAN_FLAGS = ('FLAGS_auto_shard', 'FLAGS_memviz_budget_bytes',
              'FLAGS_comms_model_path')


@pytest.fixture(autouse=True)
def _clean():
    prev = fluid.get_flags(list(PLAN_FLAGS))
    monitor.reset()
    plan.reset()
    comms_plan.reset()
    yield
    fluid.set_flags(prev)
    monitor.reset()
    plan.reset()
    comms_plan.reset()


def _build_block(seed=5):
    """The test_sp_ep_fluid transformer-ish block, UNANNOTATED: qkv fc
    -> context-parallel causal attention -> proj -> residual -> MoE
    FFN -> residual -> mse+aux, Adam."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[T, DIM], dtype='float32')
        y = layers.data('y', shape=[T, DIM], dtype='float32')
        qkv = layers.fc(x, size=3 * DIM, num_flatten_dims=2,
                        bias_attr=False)
        q, k, v = layers.split(qkv, 3, dim=-1)
        q = layers.reshape(q, [-1, T, H, D])
        k = layers.reshape(k, [-1, T, H, D])
        v = layers.reshape(v, [-1, T, H, D])
        att = layers.context_parallel_attention(q, k, v, causal=True)
        att = layers.reshape(att, [-1, T, DIM])
        proj = layers.fc(att, size=DIM, num_flatten_dims=2,
                         bias_attr=False)
        h1 = layers.elementwise_add(x, proj)
        mo, aux = layers.moe(h1, num_experts=E, hidden_size=FF,
                             aux_weight=0.01)
        out = layers.elementwise_add(h1, mo)
        mse = layers.reduce_mean(
            layers.square(layers.elementwise_sub(out, y)))
        loss = layers.elementwise_add(mse, aux)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _run_losses(program, startup, loss, feed, steps, compiled=None):
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        target = compiled if compiled is not None else program
        out = []
        for _ in range(steps):
            l, = exe.run(target, feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(l).ravel()[0]))
    return out


def _feed(seed=0):
    rng = np.random.RandomState(seed)
    return {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}


# ------------------------------------------------------------- unit: rules
def test_default_rules_cover_gpt_style_params():
    from paddle_tpu import models
    cfg = models.gpt.GptConfig(vocab_size=96, hidden=64, layers=2,
                               heads=4, max_pos=32, dropout=0.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, logits, loss = models.gpt.build_lm(cfg, 16)
        fluid.optimizer.SGD(0.1).minimize(loss)
    params = [(p.name, tuple(p.shape)) for p in main.all_parameters()]
    sizes = {'dp': 2, 'fsdp': 2, 'mp': 2}
    specs = plan.match_partition_rules(plan.default_rules(), params,
                                       axis_sizes=sizes)
    by_name = dict(params)
    # the tied token embedding shards its vocab rows over fsdp x tp
    assert str(specs['gpt_wte']) == \
        str(plan.SpecLayout().embedding()), specs['gpt_wte']
    # every 2D fc weight is sharded; biases/norms replicate
    fc_specs = [specs[n] for n, s in params
                if n.startswith('fc_') and len(s) == 2 and
                min(s) > 1 and s[0] * s[1] * 4 >= plan.MIN_SHARD_BYTES]
    assert fc_specs and all(sp is not None for sp in fc_specs)
    for n, shape in params:
        if len(shape) == 1:
            assert specs[n] is None, (n, specs[n])
    # widening fc -> column-parallel (rows on fsdp, cols on tp);
    # narrowing fc -> row-parallel
    for n, shape in params:
        if n.startswith('fc_') and len(shape) == 2 and \
                specs[n] is not None:
            want = ('fsdp', 'mp') if shape[1] >= shape[0] \
                else ('mp', 'fsdp')
            assert tuple(specs[n]) == want, (n, shape, specs[n])
    assert by_name['gpt_wte'] == (96, 64)


def test_match_rules_scalars_and_first_match_win():
    from jax.sharding import PartitionSpec as P
    rules = [(r'^a\.', P('dp', None)), (r'.*', P(None, 'dp'))]
    specs = plan.match_partition_rules(
        rules, [('a.w', (8, 4)), ('b.w', (8, 4)), ('s', (1,)),
                ('scalar', ())])
    assert tuple(specs['a.w']) == ('dp', None)
    assert tuple(specs['b.w']) == (None, 'dp')
    assert specs['s'] is None and specs['scalar'] is None


def test_validate_spec_degrades_to_mesh_and_shape():
    from jax.sharding import PartitionSpec as P
    # absent axis drops; indivisible dim replicates; multi-axis tuples
    # filter to the present members
    assert plan.validate_spec(P('fsdp', 'mp'), (8, 6),
                              {'fsdp': 2, 'mp': 4}) is not None
    got = plan.validate_spec(P('fsdp', 'mp'), (8, 6),
                             {'fsdp': 2, 'mp': 4})
    assert tuple(got) == ('fsdp', None)       # 6 % 4 != 0
    assert plan.validate_spec(P('fsdp', 'mp'), (8, 8),
                              {'fsdp': 1, 'mp': 1}) is None
    got = plan.validate_spec(P(('fsdp', 'mp'), None), (8, 4),
                             {'fsdp': 2, 'mp': 1})
    assert tuple(got) == ('fsdp', None)


def test_enumerate_layouts_products_and_determinism():
    for n in (1, 2, 6, 8):
        layouts = plan.enumerate_layouts(n)
        assert all(dp * f * tp == n for dp, f, tp in layouts)
        assert layouts == plan.enumerate_layouts(n)
        assert len(set(layouts)) == len(layouts)
    assert plan.enumerate_layouts(8)[0] == (8, 1, 1)


# --------------------------------------------------------- plan + pricing
def test_plan_judges_shardability_on_batch_dim():
    """The runner shards ONLY dim 0 (_guard_local_batch): a batch of 4
    cannot split over a dp x fsdp extent of 8, so those candidates
    price at full replicated compute and lose to extent-4 layouts —
    the planner must never admit a split the execution would silently
    replicate."""
    main, startup, loss = _build_block()
    p = plan.build_plan(main, ndev=8,
                        feed_shapes={'x': (4, T, DIM),
                                     'y': (4, T, DIM)})
    by_layout = {tuple(c['layout']): c for c in p.candidates}
    assert not by_layout[(8, 1, 1)]['batch_shardable']
    assert by_layout[(4, 1, 2)]['batch_shardable']
    # the extent-8 candidate was priced at full replicated compute,
    # the extent-4 one at batch/4 per device
    assert by_layout[(8, 1, 1)]['compute_s'] > \
        by_layout[(4, 1, 2)]['compute_s']
    # whatever wins, the plan's batch_axes reflect EXECUTION: an
    # extent that does not divide the batch means a replicated batch
    dp, fsdp, tp = p.layout
    if 4 % (dp * fsdp) != 0:
        assert p.batch_axes == ()


def test_price_layout_fsdp_rs_term_uses_tp_shard_bytes():
    """A combined fsdp x tp layout reduce-scatters only each tp
    group's slice of the grad (nbytes/tp), not the full tensor —
    pricing the full bytes would penalize mixed layouts by tp x."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.fluid import comms
    lay = plan.SpecLayout()
    nbytes = 256 * 256 * 4
    inv = [('fc_0.w_0', (256, 256), nbytes, 4)]
    specs = {'fc_0.w_0': P('fsdp', 'mp')}
    r = plan._price_layout((1, 2, 4), inv, specs, 64, 64, 0, 0.0,
                           None, lay)
    shard_b = nbytes / 8.0
    w_ag = comms.wire_bytes('allgather', shard_b, 2)
    w_rs = comms.wire_bytes('reducescatter', shard_b * 2, 2)
    act_b = (64 / 2) * 256 * 4   # col-parallel: allgather downstream
    w_act = comms.wire_bytes('allgather', act_b / 4, 4)
    assert r['wire_bytes'] == pytest.approx(2 * w_ag + w_rs + w_act)


def test_build_plan_unconstrained_prefers_data_parallel():
    main, startup, loss = _build_block()
    p = plan.build_plan(main, ndev=8,
                        feed_shapes={'x': (B, T, DIM),
                                     'y': (B, T, DIM)})
    assert p.layout == (8, 1, 1)
    assert p.batch_axes == ('dp',)
    # weight-update sharding rides the dp axis when fsdp is absent
    assert p.update_axis == 'dp'
    assert len(p.candidates) == len(plan.enumerate_layouts(8))
    assert monitor.counter_value('parallel/plan_builds') == 1
    assert monitor.counter_value('parallel/plan_candidates') == \
        len(p.candidates)


def test_digest_determinism_and_sensitivity():
    main, startup, loss = _build_block()
    shapes = {'x': (B, T, DIM), 'y': (B, T, DIM)}
    p1 = plan.build_plan(main, ndev=8, feed_shapes=shapes)
    p2 = plan.build_plan(main, ndev=8, feed_shapes=shapes)
    assert p1.digest() == p2.digest()
    # a different chosen layout digests differently
    p3 = plan.build_plan(main, ndev=8, feed_shapes=shapes,
                         layouts=[(2, 2, 2)])
    assert p3.digest() != p1.digest()
    # the global fingerprint component: constant when off, sensitive
    # to the budget bucket when on
    fluid.set_flags({'FLAGS_auto_shard': False})
    assert plan.digest() == 'auto_shard(off)'
    fluid.set_flags({'FLAGS_auto_shard': True})
    d_on = plan.digest()
    assert d_on.startswith('auto_shard(on')
    assert plan.digest() == d_on
    fluid.set_flags({'FLAGS_memviz_budget_bytes': 1 << 30})
    assert plan.digest() != d_on


def test_digest_tracks_model_contents_not_just_names(tmp_path):
    """A recalibrated comms_model.json with the SAME collective names
    but new alpha/beta values must change the global digest — cached
    executables must not keep running a plan priced from stale
    numbers."""
    fluid.set_flags({'FLAGS_auto_shard': True})
    model = tmp_path / 'comms_model.json'
    entry = {'latency_s': 1e-5, 'inv_bw_s_per_byte': 1e-9}
    model.write_text(json.dumps({'collectives': {'allreduce': entry}}))
    fluid.set_flags({'FLAGS_comms_model_path': str(model)})
    d1 = plan.digest()
    entry2 = {'latency_s': 5e-5, 'inv_bw_s_per_byte': 2e-9}
    model.write_text(json.dumps({'collectives': {'allreduce':
                                                 entry2}}))
    comms_plan.reset()          # drop the (path, mtime, size) cache
    d2 = plan.digest()
    assert d1 != d2


def test_hbm_gate_rejects_over_budget_layouts():
    main, startup, loss = _build_block()
    shapes = {'x': (B, T, DIM), 'y': (B, T, DIM)}
    free = plan.build_plan(main, ndev=8, feed_shapes=shapes)
    repl_hbm = next(c['hbm_bytes'] for c in free.candidates
                    if tuple(c['layout']) == (8, 1, 1))
    # budget below the fully-replicated residency but above the best
    # sharded candidate: dp-only must be REJECTED before compiling,
    # and the chosen layout must fit
    budget = repl_hbm * 0.8
    plan.reset()
    monitor.reset()
    p = plan.build_plan(main, ndev=8, feed_shapes=shapes,
                        budget=budget)
    assert p.rejected > 0
    assert p.layout != (8, 1, 1)
    assert p.chosen['hbm_bytes'] <= budget
    assert monitor.counter_value('parallel/plan_hbm_rejected') \
        == p.rejected
    rejected_rows = [c for c in p.candidates if not c['admissible']]
    assert any(tuple(c['layout']) == (8, 1, 1) for c in rejected_rows)
    # every candidate over budget: the smallest footprint survives
    p2 = plan.build_plan(main, ndev=8, feed_shapes=shapes, budget=1.0)
    assert p2.rejected == len(p2.candidates)
    assert p2.chosen['hbm_bytes'] == min(c['hbm_bytes']
                                         for c in p2.candidates)


def test_partial_or_missing_model_degrades_to_byte_pricing(tmp_path):
    # absent model: plans fine, counts the honesty counter
    fluid.set_flags({'FLAGS_comms_model_path': str(tmp_path / 'no')})
    main, startup, loss = _build_block()
    p = plan.build_plan(main, ndev=8,
                        feed_shapes={'x': (B, T, DIM)})
    assert p.layout[0] >= 1
    assert monitor.counter_value('parallel/plan_unpriced') > 0
    # PARTIAL model (entries missing fields): predict_seconds answers
    # None instead of raising, and the planner still completes
    bad = tmp_path / 'comms_model.json'
    bad.write_text(json.dumps({'collectives': {
        'allreduce': {'latency_s': 'not-a-number'},
        'allgather': {}}}))
    fluid.set_flags({'FLAGS_comms_model_path': str(bad)})
    comms_plan.reset()
    assert comms_plan.predict_seconds('allreduce', 1 << 20) is None
    assert comms_plan.predict_seconds('allgather', 1 << 20) is None
    monitor.reset()
    plan.reset()
    p2 = plan.build_plan(main, ndev=8,
                         feed_shapes={'x': (B, T, DIM)})
    assert p2.chosen['cost_s'] > 0
    assert monitor.counter_value('parallel/plan_unpriced') > 0


# ------------------------------------------------------- executor parity
def test_auto_shard_matches_single_and_hand_placed_spep():
    """The acceptance contract: FLAGS_auto_shard=1 takes the
    UNANNOTATED block to a sharded mesh at loss parity with BOTH the
    single-device dense fallbacks and the hand-placed dp2 x sp2 x ep2
    config (the test_sp_ep_fluid posture)."""
    feed = _feed()
    main, startup, loss = _build_block()
    single = _run_losses(main, startup, loss, feed, 4)
    assert single[-1] < single[0]

    # hand-placed: the existing sp/ep mesh path
    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    m2, s2, l2 = _build_block()
    comp = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name).with_mesh(mesh)
    hand = _run_losses(m2, s2, l2, feed, 4, compiled=comp)
    np.testing.assert_allclose(hand, single, rtol=5e-3, atol=5e-4)

    # auto: no mesh, no rules, no axis names — just the flag
    fluid.set_flags({'FLAGS_auto_shard': True})
    m3, s3, l3 = _build_block()
    comp3 = fluid.CompiledProgram(m3).with_data_parallel(
        loss_name=l3.name)
    auto = _run_losses(m3, s3, l3, feed, 4, compiled=comp3)
    np.testing.assert_allclose(auto, single, rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(auto, hand, rtol=5e-3, atol=5e-4)
    assert getattr(comp3, '_auto_plan', None) is not None
    assert monitor.counter_value('parallel/plan_builds') >= 1
    assert monitor.gauge_value('parallel/plan_layout_dp') >= 1


def test_auto_shard_zero_post_warmup_retraces():
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        misses0 = monitor.counter_value('parallel/segment_cache_miss')
        for _ in range(5):
            exe.run(comp, feed=feed, fetch_list=[loss])
        assert monitor.counter_value('parallel/segment_cache_miss') \
            == misses0
        assert monitor.counter_value('parallel/segment_cache_hit') >= 5
        # the plan was built once and reused every step
        assert monitor.counter_value('parallel/plan_builds') == 1
        assert monitor.counter_value('parallel/plan_reused') >= 5


def test_auto_shard_tight_budget_shards_and_keeps_parity():
    """The HBM-rejection path end to end: a budget below the
    replicated residency forces a scattered layout — params actually
    shard, parity holds, the rejection is counted."""
    feed = _feed()
    main, startup, loss = _build_block()
    single = _run_losses(main, startup, loss, feed, 3)

    free = plan.build_plan(main, ndev=8,
                           feed_shapes={'x': (B, T, DIM),
                                        'y': (B, T, DIM)})
    repl_hbm = next(c['hbm_bytes'] for c in free.candidates
                    if tuple(c['layout']) == (8, 1, 1))
    plan.reset()
    monitor.reset()
    fluid.set_flags({'FLAGS_auto_shard': True,
                     'FLAGS_memviz_budget_bytes': repl_hbm * 0.8})
    m2, s2, l2 = _build_block()
    comp = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name)
    auto = _run_losses(m2, s2, l2, feed, 3, compiled=comp)
    np.testing.assert_allclose(auto, single, rtol=5e-3, atol=5e-4)
    assert monitor.counter_value('parallel/plan_hbm_rejected') > 0
    ap = comp._auto_plan
    assert ap.layout != (8, 1, 1)
    # the runner must execute the batch placement the plan priced: a
    # tp-only layout replicates the batch (batch_axes == ()), it does
    # NOT fall back to sharding over the mesh's first (tensor) axis
    assert ap.batch_axes == tuple(
        a for a, s in (('dp', ap.layout[0]), ('fsdp', ap.layout[1]))
        if s > 1)


def test_auto_weight_update_sharding_unifies_with_zero_path():
    """arXiv:2004.13336 through the EXISTING ZeRO rendering: the plan
    names an update axis, the runner applies it via
    _shard_opt_states_axis, and the Adam moments end up physically
    sharded over it (not replicated)."""
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[T, DIM], dtype='float32')
        y = layers.data('y', shape=[T, DIM], dtype='float32')
        h = layers.fc(x, size=DIM, num_flatten_dims=2)
        loss = layers.reduce_mean(
            layers.square(layers.elementwise_sub(h, y)))
        fluid.optimizer.Adam(0.01).minimize(loss)
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    params = set(p.name for p in main.all_parameters())
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        ax = comp._shard_opt_states_axis
        assert ax == comp._auto_plan.update_axis is not None
        sharded_accs = []
        for name in sc.local_var_names():
            if name in params or name not in main.global_block().vars:
                continue
            v = sc.find_var(name)
            spec = getattr(getattr(v, 'sharding', None), 'spec', None)
            if spec and any(e == ax for e in spec):
                sharded_accs.append(name)
        assert sharded_accs, 'no optimizer state sharded over %r' % ax


def test_auto_shard_on_hand_placed_mesh_degrades_to_its_axes():
    """FLAGS_auto_shard + an explicit with_mesh(dp/sp/ep): the plan's
    fsdp/mp specs must re-validate against the ACTUAL mesh (degrade to
    replication), not crash NamedSharding — and parity must hold."""
    feed = _feed()
    main, startup, loss = _build_block()
    single = _run_losses(main, startup, loss, feed, 3)
    # a tight budget makes the plan WANT scattered fsdp/tp specs
    free = plan.build_plan(main, ndev=8,
                           feed_shapes={'x': (B, T, DIM),
                                        'y': (B, T, DIM)})
    repl_hbm = next(c['hbm_bytes'] for c in free.candidates
                    if tuple(c['layout']) == (8, 1, 1))
    plan.reset()
    fluid.set_flags({'FLAGS_auto_shard': True,
                     'FLAGS_memviz_budget_bytes': repl_hbm * 0.8})
    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    m2, s2, l2 = _build_block()
    comp = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name).with_mesh(mesh)
    auto = _run_losses(m2, s2, l2, feed, 3, compiled=comp)
    np.testing.assert_allclose(auto, single, rtol=5e-3, atol=5e-4)


def test_auto_shard_reduce_strategy_on_dp_less_mesh():
    """ReduceStrategy.Reduce pre-sets the ZeRO axis to 'dp'; a
    planner-built dp=1 layout drops that axis from the mesh — the
    accumulator rule must re-home onto the plan's update axis instead
    of KeyError'ing on mesh.shape['dp']."""
    feed = _feed()
    main, startup, loss = _build_block()
    single = _run_losses(main, startup, loss, feed, 3)
    free = plan.build_plan(main, ndev=8,
                           feed_shapes={'x': (B, T, DIM),
                                        'y': (B, T, DIM)})
    # a budget only the dp=1 candidates satisfy
    dp1 = min(c['hbm_bytes'] for c in free.candidates
              if c['layout'][0] == 1)
    dp_more = min(c['hbm_bytes'] for c in free.candidates
                  if c['layout'][0] > 1)
    if dp1 >= dp_more:
        pytest.skip('no budget separates dp=1 from dp>1 layouts here')
    plan.reset()
    fluid.set_flags({'FLAGS_auto_shard': True,
                     'FLAGS_memviz_budget_bytes':
                         (dp1 + dp_more) / 2.0})
    bs = fluid.BuildStrategy()
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    m2, s2, l2 = _build_block()
    comp = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name, build_strategy=bs)
    auto = _run_losses(m2, s2, l2, feed, 3, compiled=comp)
    np.testing.assert_allclose(auto, single, rtol=5e-3, atol=5e-4)
    assert comp._auto_plan.layout[0] == 1


def test_budget_change_applies_to_programs_built_after():
    """The lowering-flag convention: a LIVE CompiledProgram keeps the
    plan (and mesh) it was traced with — its executable memo is keyed
    once — while the changed global digest() guarantees a program
    (re)built AFTER the change plans fresh and cannot reuse an
    executable traced under the old plan."""
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        first = comp._auto_plan
        assert first.layout == (8, 1, 1)
        d0 = plan.digest()
        mesh0 = comp._mesh
        repl_hbm = next(c['hbm_bytes'] for c in first.candidates
                        if tuple(c['layout']) == (8, 1, 1))
        fluid.set_flags({'FLAGS_memviz_budget_bytes': repl_hbm * 0.8})
        reused0 = monitor.counter_value('parallel/plan_reused')
        exe.run(comp, feed=feed, fetch_list=[loss])
        # the live program keeps its plan AND its planner-built mesh:
        # the cached executable was traced under them
        assert comp._auto_plan is first
        assert comp._mesh is mesh0
        assert monitor.counter_value('parallel/plan_reused') > reused0
        # ...but the global digest moved, so a REBUILT program's
        # segment fingerprints cannot collide with the stale executable
        assert plan.digest() != d0
    # a program built after the change plans under the new budget
    m2, s2, l2 = _build_block()
    comp2 = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=l2.name)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(s2)
        exe.run(comp2, feed=feed, fetch_list=[l2])
    ap = comp2._auto_plan
    assert ap.layout != (8, 1, 1)
    assert monitor.counter_value('parallel/plan_builds') >= 2
    # the new layout MATERIALIZES: the mesh was synthesized from the
    # new plan's axes (not inherited from the stale one, where every
    # new spec would degrade to replication) ...
    assert set(comp2._mesh.axis_names) == \
        set(a for a, s in ap.mesh_sizes().items() if s > 1)
    # ... and the new plan names params to shard on it
    assert any(sp is not None for sp in ap.specs.values())


def test_program_under_tight_budget_shards_scope_params():
    """The materialization half of the built-after contract, end to
    end: under a budget that rejects the replicated layout, a fresh
    program's planner-built mesh carries fsdp/tp axes and a sharded
    param's scope array is actually partitioned over them."""
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    probe = plan.build_plan(
        main, ndev=8,
        feed_shapes={n: v.shape for n, v in feed.items()})
    repl_hbm = next(c['hbm_bytes'] for c in probe.candidates
                    if tuple(c['layout']) == (8, 1, 1))
    plan.reset()
    fluid.set_flags({'FLAGS_memviz_budget_bytes': repl_hbm * 0.8})
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        ap = comp._auto_plan
        assert ap.layout != (8, 1, 1)
        sharded = [(n, sp) for n, sp in ap.specs.items()
                   if sp is not None]
        assert sharded
        name, spec = sharded[0]
        arr = sc.find_var(name)
        got = getattr(getattr(arr, 'sharding', None), 'spec', None)
        assert got is not None and any(e is not None for e in got), \
            (name, spec, got)


def test_moe_ep_hint_yields_to_plan_on_planner_mesh():
    """The 'ep'-stamped expert-weight hints fully degrade on a
    planner-built dp x fsdp x mp mesh; the experts must then execute
    under the plan's fsdp rule — the spec the candidate pricing and
    the HBM gate described — not pin replication.  (On a hand-placed
    mesh that HAS the hint's axis, hint-is-final still holds.)"""
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    p = plan.build_plan(
        main, ndev=8, layouts=[(2, 4, 1)],
        feed_shapes={n: v.shape for n, v in feed.items()})
    moe_sharded = [n for n in p.specs
                   if n.startswith('moe') and p.specs[n] is not None]
    # plan level: the degraded hint yields to the expert fsdp rule
    assert moe_sharded, p.specs
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    comp._auto_plan = p    # lifetime-cache seam: pin the fsdp layout
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        # execution level: what the plan says is what the scope holds
        for n in moe_sharded:
            arr = sc.find_var(n)
            got = getattr(getattr(arr, 'sharding', None), 'spec', None)
            assert got is not None and \
                any(e is not None for e in got), (n, p.specs[n], got)


def test_statusz_auto_shard_section():
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    _run_losses(main, startup, loss, feed, 2, compiled=comp)
    doc = health.statusz()
    sec = doc.get('auto_shard')
    assert sec and sec['enabled']
    assert sec['digest'].startswith('auto_shard(on')
    assert sec['programs']
    prog = next(iter(sec['programs'].values()))
    assert prog['layout']['dp'] * prog['layout']['fsdp'] * \
        prog['layout']['tp'] == 8
    assert prog['candidates'] and 'digest' in prog
    assert sec['counters']['plan_builds'] >= 1
    # the section JSON-serializes (it is served over HTTP)
    json.dumps(sec)


def test_stat_summary_autoshard_rollup(tmp_path, capsys):
    import importlib
    import os
    import sys
    fluid.set_flags({'FLAGS_auto_shard': True})
    feed = _feed()
    main, startup, loss = _build_block()
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    _run_losses(main, startup, loss, feed, 2, compiled=comp)
    p = str(tmp_path / 'run.jsonl')
    monitor.dump_jsonl(p)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), 'tools'))
    try:
        stat_summary = importlib.import_module('stat_summary')
        rc = stat_summary.main(['--autoshard', p])
    finally:
        sys.path.pop(0)
    out = capsys.readouterr().out
    assert rc == 0
    assert 'auto-sharding' in out
    assert 'dp=' in out and 'plan builds' in out
