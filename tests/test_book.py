"""Book tests: classic end-to-end workflows.

Reference: python/paddle/fluid/tests/book/ (fit_a_line, recognize_digits,
word2vec, ... with loss-decrease assertions) — exercising the full
dataset/reader/DataFeeder/executor/io pipeline.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.reader as preader
from paddle_tpu import dataset


def test_fit_a_line(tmp_path):
    """reference book/test_fit_a_line.py."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[13], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(avg_loss)

    train_reader = preader.batch(
        preader.shuffle(dataset.uci_housing.train(), buf_size=500),
        batch_size=20)
    place = fluid.XLAPlace(0)
    feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(place)
        exe.run(startup)
        first = last = None
        for epoch in range(6):
            for batch in train_reader():
                l, = exe.run(main, feed=feeder.feed(batch),
                             fetch_list=[avg_loss])
                if first is None:
                    first = float(l)
                last = float(l)
        assert last < first * 0.3, (first, last)
        # inference save/load roundtrip through the predictor
        fluid.io.save_inference_model(str(tmp_path), ['x'],
                                      [y_predict], exe, main)
    from paddle_tpu.inference import AnalysisConfig, \
        create_paddle_predictor
    pred = create_paddle_predictor(AnalysisConfig(str(tmp_path)))
    test_batch = list(dataset.uci_housing.test()())[:8]
    xs = np.stack([b[0] for b in test_batch])
    out = pred.run([xs])
    assert out[0].as_ndarray().shape == (8, 1)


def test_recognize_digits_reader_pipeline():
    """reference book/test_recognize_digits.py (mlp variant) with the
    mnist dataset reader + DataFeeder."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        img = fluid.layers.data('img', shape=[784], dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        h = fluid.layers.fc(img, 128, act='relu')
        pred = fluid.layers.fc(h, 10, act='softmax')
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        acc = fluid.layers.accuracy(pred, label)
        fluid.optimizer.Adam(1e-3).minimize(loss)
    reader = preader.batch(dataset.mnist.train(), batch_size=64,
                           drop_last=True)
    place = fluid.XLAPlace(0)
    feeder = fluid.DataFeeder(place=place, feed_list=[img, label])
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(place)
        exe.run(startup)
        accs = []
        for epoch in range(3):
            for batch in reader():
                _, a = exe.run(main, feed=feeder.feed(batch),
                               fetch_list=[loss, acc])
                accs.append(float(a))
    assert np.mean(accs[-10:]) > 0.9, np.mean(accs[-10:])


def test_reader_decorators():
    def base():
        return iter(range(10))

    b = preader.batch(base, 3)
    batches = list(b())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    s = list(preader.shuffle(base, 100)())
    assert sorted(s) == list(range(10))
    buf = list(preader.buffered(base, 2)())
    assert buf == list(range(10))
    m = list(preader.map_readers(lambda a: a * 2, base)())
    assert m == [i * 2 for i in range(10)]
    x = sorted(preader.xmap_readers(lambda a: a + 1, base, 2, 4)())
    assert x == [i + 1 for i in range(10)]
