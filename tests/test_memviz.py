"""Device-memory observability plane (fluid.memviz): per-(program,
segment) peak attribution summing back to memory_analysis() totals,
the live-HBM census classes, OOM forensics (incident schema, rate
limit, actionable note), the budget watermark detector, the Perfetto
counter track riding the merged timeline, and the collective
planner's per-program HBM headroom."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (comms, comms_plan, health, memviz,
                              monitor, trace)

MEMVIZ_FLAGS = ('FLAGS_memviz', 'FLAGS_memviz_sample_steps',
                'FLAGS_memviz_budget_bytes', 'FLAGS_memviz_watermark',
                'FLAGS_memviz_spike_factor',
                'FLAGS_memviz_dump_interval_s',
                'FLAGS_memviz_oom_interval_s',
                'FLAGS_comms_hbm_budget_bytes')


@pytest.fixture(autouse=True)
def _clean():
    from paddle_tpu.fluid import compile_cache
    prev = fluid.get_flags(list(MEMVIZ_FLAGS))
    # warmup() marks the PROCESS-WIDE compile plane warmed (the AOT
    # run path attribution rides): isolate it both ways so this module
    # neither inherits nor leaks the plane's warmed/cached state
    compile_cache.reset_plane()
    monitor.reset()
    memviz.reset()
    comms.reset()
    trace.disable()
    trace.reset()
    yield
    fluid.set_flags(prev)
    compile_cache.reset_plane()
    monitor.reset()
    memviz.reset()
    comms.reset()
    trace.disable()
    trace.reset()


def _build_mlp(width=16):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data('x', shape=[width], dtype='float32')
        h = fluid.layers.fc(x, width, act='relu')
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGD(0.05).minimize(loss)
    main_p._test_param_names = [p.name for p in main_p.all_parameters()]
    return main_p, startup, loss


def _run_steps(main_p, startup, loss, scope, steps=2, warm=True,
               width=16, batch=8):
    feed = {'x': np.ones((batch, width), 'float32')}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        if warm:
            # engage the AOT plane: attribution rides executable
            # resolution (compile / memory hit / disk hit)
            exe.warmup(main_p,
                       feed_shapes={'x': ((batch, width), 'float32')},
                       fetch_list=[loss], wait=True)
        for _ in range(steps):
            exe.run(main_p, feed=feed, fetch_list=[loss])
        return exe, feed


# ------------------------------------------------------ peak attribution
def test_peak_decomposition_sums_to_analysis_totals():
    main_p, startup, loss = _build_mlp()
    _run_steps(main_p, startup, loss, fluid.Scope())
    rows = memviz.report()
    assert rows, 'attribution must land on the AOT path'
    r = rows[0]
    # the named classes + alignment overhead reconstruct the
    # analysis's argument arena exactly — nothing is vibes
    named = sum(r['classes'].values())
    assert named + r['arg_overhead_bytes'] == \
        pytest.approx(r['argument_bytes'])
    # CPU XLA reports no peak: the live-set bound must be used
    assert r['peak_bytes'] == pytest.approx(
        r['argument_bytes'] + r['output_bytes'] + r['temp_bytes'])
    assert r['classes']['param'] > 0      # fc weights are attributed
    assert r['classes']['feed'] > 0       # the x feed is attributed
    # largest buffers are named and sorted descending
    tops = r['top_buffers']
    assert tops and all(tops[i]['bytes'] >= tops[i + 1]['bytes']
                        for i in range(len(tops) - 1))
    top_names = {c['name'] for c in tops}
    assert top_names & set(main_p._test_param_names)
    # outputs carry their originating op desc
    assert any(c['op'] for c in r['outputs'])
    assert monitor.counter_value('memviz/segments_attributed') >= 1


def test_peak_bytes_per_program_and_top_contributors():
    class FakeCompiled(object):
        def __init__(self, arg, out, temp):
            self._f = (arg, out, temp)

        def memory_analysis(self):
            class MA(object):
                pass
            ma = MA()
            ma.argument_size_in_bytes = self._f[0]
            ma.output_size_in_bytes = self._f[1]
            ma.temp_size_in_bytes = self._f[2]
            ma.generated_code_size_in_bytes = 10
            return ma

    memviz.record_segment('small', 'seg0', FakeCompiled(100, 50, 25),
                          {'w': np.zeros(25, 'float32')},
                          {'x': np.zeros(10, 'float32')})
    memviz.record_segment('big', 'seg0', FakeCompiled(1000, 500, 250),
                          {'w2': np.zeros(250, 'float32')}, {})
    assert memviz.peak_bytes('small') == 175
    assert memviz.peak_bytes('big') == 1750
    assert memviz.peak_bytes() == 1750
    assert memviz.peak_bytes('nonexistent') is None
    tops = memviz.top_contributors(2)
    assert tops[0]['name'] == 'w2' and tops[0]['program'] == 'big'


def test_analysis_unavailable_counted_not_silent():
    class Raises(object):
        def memory_analysis(self):
            raise RuntimeError('backend has no analysis')

    class ReturnsNone(object):
        def memory_analysis(self):
            return None

    assert comms.record_memory('bad', Raises()) is None
    assert memviz.record_segment('p', 's', ReturnsNone(), {}, {}) \
        is None
    assert monitor.counter_value('memviz/analysis_unavailable') == 2


def test_record_memory_partial_fields_tolerated():
    class Partial(object):
        def memory_analysis(self):
            class MA(object):
                argument_size_in_bytes = 128
                # no output/temp/peak fields at all
            return MA()

    row = comms.record_memory('partial', Partial())
    assert row is not None
    assert row['argument_bytes'] == 128
    assert row['peak_bytes'] == 128     # arg + 0 + 0 live-set bound
    assert monitor.counter_value('memviz/analysis_unavailable') == 0


# ----------------------------------------------------------- live census
def test_live_census_classifies_scope_and_exec_bytes():
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    _run_steps(main_p, startup, loss, scope)
    with fluid.scope_guard(scope):
        census = memviz.live_census(scope)
    classes = census['classes']
    assert census['total_bytes'] > 0
    assert classes['param'] > 0          # fc weights are scope-resident
    # every class is accounted, nothing negative
    assert all(v >= 0 for v in classes.values())
    # the classes cover the resident total exactly (live arrays +
    # generated executable code) — the stacked counter track sums
    assert sum(classes.values()) == pytest.approx(
        census['total_bytes'])


def test_sampler_gated_by_flag_and_stride():
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    _run_steps(main_p, startup, loss, scope, warm=False)
    assert monitor.counter_value('memviz/samples') == 0
    assert monitor.gauge_value('memviz/live_bytes_total', None) is None
    fluid.set_flags({'FLAGS_memviz': True})
    _run_steps(main_p, startup, loss, scope, steps=3, warm=False)
    assert monitor.counter_value('memviz/samples') >= 3
    assert monitor.gauge_value('memviz/live_bytes_total') > 0
    for cls in ('param', 'state', 'feed', 'exec', 'other'):
        assert ('memviz/live_bytes/%s' % cls) in monitor._gauges


# -------------------------------------------------------- OOM forensics
def _inject_alloc_failure(exe, main_p, loss):
    plan = exe._get_plan(main_p, ('x',), (loss.name,))
    seg = [it for it in plan if hasattr(it, 'ops')][0]

    def boom(*a, **k):
        raise RuntimeError('RESOURCE_EXHAUSTED: Out of memory while '
                           'trying to allocate 12345678 bytes')
    for k in list(seg.compiled):
        seg.compiled[k] = boom


def test_oom_incident_note_dump_schema_and_rate_limit(tmp_path):
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    trace.enable()
    exe, feed = _run_steps(main_p, startup, loss, scope)
    _inject_alloc_failure(exe, main_p, loss)
    with fluid.scope_guard(scope):
        with pytest.raises(RuntimeError) as ei:
            exe.run(main_p, feed=feed, fetch_list=[loss])
    notes = getattr(ei.value, '__notes__', [])
    text = str(ei.value) + '\n'.join(notes)
    assert 'device memory exhausted' in text
    assert 'live HBM' in text
    assert 'largest buffers' in text     # top contributors are NAMED
    assert monitor.counter_value('memviz/oom_incidents') == 1
    assert monitor.counter_value('memviz/oom_dumps') == 1
    # the flight dump embeds the memory snapshot
    path = [ln for ln in text.splitlines() if 'flight dump' in ln]
    assert path
    dump_path = path[0].split()[-1]
    with open(dump_path) as f:
        doc = json.load(f)
    inc = doc['ptIncident']
    assert inc['kind'] == 'oom'
    assert 'census' in inc and 'classes' in inc['census']
    assert 'segments' in inc and 'top_buffers' in inc
    assert 'serving_tenants' in inc
    os.unlink(dump_path)
    # rate limit: a second failure counts but does not dump again
    with fluid.scope_guard(scope):
        with pytest.raises(RuntimeError):
            exe.run(main_p, feed=feed, fetch_list=[loss])
    assert monitor.counter_value('memviz/oom_incidents') == 2
    assert monitor.counter_value('memviz/oom_dumps') == 1


def test_non_oom_failures_skip_the_memory_path():
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    exe, feed = _run_steps(main_p, startup, loss, scope, warm=False)
    plan = exe._get_plan(main_p, ('x',), (loss.name,))
    seg = [it for it in plan if hasattr(it, 'ops')][0]

    def boom(*a, **k):
        raise RuntimeError('some unrelated failure')
    for k in list(seg.compiled):
        seg.compiled[k] = boom
    with fluid.scope_guard(scope):
        with pytest.raises(RuntimeError):
            exe.run(main_p, feed=feed, fetch_list=[loss])
    assert monitor.counter_value('memviz/oom_incidents') == 0


# ---------------------------------------------------- budget watermarks
def test_budget_watermark_trip_dumps_before_oom():
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    trace.enable()
    fluid.set_flags({'FLAGS_memviz': True,
                     'FLAGS_memviz_budget_bytes': 64})   # tiny budget
    _run_steps(main_p, startup, loss, scope, warm=False)
    assert monitor.counter_value('memviz/watermark_trips') >= 1
    assert monitor.counter_value('memviz/detector_dumps') == 1
    assert monitor.gauge_value('memviz/budget_utilization') > 1.0
    pressure = memviz.memory_pressure()
    assert pressure['degraded'] is True
    # /healthz carries the degradation without flipping liveness
    st = health.status()
    assert st['memory']['degraded'] is True
    assert st['alive'] is True
    assert any('watermark' in r for r in st['reasons'])


def test_spike_detector_over_ema():
    fluid.set_flags({'FLAGS_memviz_spike_factor': 2.0,
                     'FLAGS_memviz_dump_interval_s': 0.0})
    trace.enable()
    memviz._state['ema'] = 10.0
    memviz._check_watermarks(1, {'total_bytes': 100.0, 'classes': {},
                                 'arrays': 0, 'tenants': {}})
    assert monitor.counter_value('memviz/spike_trips') == 1
    # EMA moved toward the spike
    assert memviz._state['ema'] > 10.0


# ------------------------------------------------------- counter track
def test_counter_track_in_dump_and_merged_timeline(tmp_path):
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    fluid.set_flags({'FLAGS_memviz': True})
    trace.enable()
    _run_steps(main_p, startup, loss, scope, steps=3, warm=False)
    path = trace.dump(str(tmp_path / 'dump.json'))
    with open(path) as f:
        doc = json.load(f)
    cs = [e for e in doc['traceEvents'] if e.get('ph') == 'C']
    assert cs, 'counter track must ride the chrome export'
    for e in cs:
        assert e['name'] == 'memviz/live_bytes'
        assert isinstance(e['ts'], float)
        assert set(e['args']) == {'param', 'state', 'feed', 'exec',
                                  'other'}
        assert all(isinstance(v, (int, float))
                   for v in e['args'].values())
    assert doc['ptCounters']
    # the device-trace merger keeps counters on the re-homed host pid
    merged = trace.merge_device_trace(
        [e for e in doc['traceEvents']],
        [{'ph': 'X', 'pid': 0, 'tid': 0, 'ts': 1.0, 'dur': 1.0,
          'name': 'devkernel'}])
    mc = [e for e in merged if e.get('ph') == 'C']
    assert mc and all(e['pid'] != 0 for e in mc)
    # and collect_job passes them through with shifted clocks
    job = trace.collect_job(workers=[('0', str(path))],
                            fetch=lambda p: open(p).read())
    assert [e for e in job['traceEvents'] if e.get('ph') == 'C']


# -------------------------------------------- planner headroom (per-program)
def test_hbm_headroom_is_per_program_with_gauge_fallback():
    fluid.set_flags({'FLAGS_comms_hbm_budget_bytes': 1 << 20})

    class FakeCompiled(object):
        def __init__(self, arg):
            self.arg = arg

        def memory_analysis(self):
            class MA(object):
                pass
            ma = MA()
            ma.argument_size_in_bytes = self.arg
            ma.output_size_in_bytes = 0
            ma.temp_size_in_bytes = 0
            return ma

    memviz.record_segment('hungry', 'seg0',
                          FakeCompiled((1 << 20) - 1024), {}, {})
    memviz.record_segment('lean', 'seg0', FakeCompiled(1024), {}, {})
    monitor.set_gauge('executor/segment_peak_bytes', (1 << 20) - 1024)
    # outside any program scope: the legacy global-max gauge governs
    assert comms_plan.hbm_headroom_bytes() == 1024
    # inside the lean program's scope its OWN peak governs — the big
    # resident program no longer suppresses its planning
    with memviz.program_scope('lean'):
        assert comms_plan.hbm_headroom_bytes() == (1 << 20) - 1024
    with memviz.program_scope('hungry'):
        assert comms_plan.hbm_headroom_bytes() == 1024
    # a program with no attribution rows falls back to the gauge
    with memviz.program_scope('unknown'):
        assert comms_plan.hbm_headroom_bytes() == 1024
    # the digest folds the ambient headroom: two programs with
    # materially different headroom plan (and fingerprint) apart
    with memviz.program_scope('lean'):
        d_lean = comms_plan.digest()
    with memviz.program_scope('hungry'):
        d_hungry = comms_plan.digest()
    assert d_lean != d_hungry


def test_parallel_runner_files_estimated_attribution():
    """The shared-jit runners expose no memory_analysis(): they file
    an ESTIMATED row (args + outputs) so per-program headroom is live
    on the data-parallel/collective path too."""
    from paddle_tpu.fluid.compiler import CompiledProgram
    main_p, startup, loss = _build_mlp()
    scope = fluid.Scope()
    feed = {'x': np.ones((8, 16), 'float32')}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        cp = CompiledProgram(main_p).with_data_parallel(
            loss_name=loss.name)
        exe.run(cp, feed=feed, fetch_list=[loss])
    label = main_p._memviz_label
    rows = [r for r in memviz.report() if r['program'] == label]
    assert rows and rows[0].get('estimated') is True
    assert rows[0]['peak_bytes'] > 0
    assert rows[0]['classes']['param'] > 0
    # the headroom gate resolves this program's own peak now
    with memviz.program_scope(label):
        assert memviz.peak_bytes(memviz.current_program()) == \
            rows[0]['peak_bytes']


# ------------------------------------------------------- status surfaces
def test_statusz_memory_table_names_contributors():
    main_p, startup, loss = _build_mlp()
    fluid.set_flags({'FLAGS_memviz': True})
    _run_steps(main_p, startup, loss, fluid.Scope())
    sz = health.statusz()
    mem = sz['memory']
    assert mem['attribution'], 'top-K table replaces the four scalars'
    row = mem['attribution'][0]
    assert row['top_buffers'] and row['classes']
    assert mem['top_buffers']
    assert mem['live'] is not None and 'classes' in mem['live']


def test_stat_summary_memory_rollup(tmp_path, capsys):
    main_p, startup, loss = _build_mlp()
    fluid.set_flags({'FLAGS_memviz': True})
    _run_steps(main_p, startup, loss, fluid.Scope())
    path = str(tmp_path / 'run.jsonl')
    monitor.dump_jsonl(path, step=1)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'stat_summary', os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            'tools', 'stat_summary.py'))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(['--memory', path]) == 0
    out = capsys.readouterr().out
    assert 'live HBM' in out
    assert 'param' in out
