"""Per-op profiler report (reference platform/profiler.h:166-175:
EnableProfiler/DisableProfiler print an Event table sorted by
sorted_key).  Round-4 VERDICT item 6: the table must name the
dominant op of a known program without opening Perfetto."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, profiler


def _build(big=1024):
    """One big matmul + a cheap elementwise tail: 'mul' must dominate."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[big], dtype='float32')
        h = layers.fc(x, size=big, bias_attr=False)
        out = layers.reduce_mean(h)
    return main, startup, out


def test_profiler_table_names_dominant_op(capsys, tmp_path):
    main, startup, out = _build()
    x = np.random.RandomState(0).randn(64, 1024).astype('float32')
    path = str(tmp_path / 'profile.txt')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        with profiler.profiler(sorted_key='total', profile_path=path):
            # warm-up compiles the per-op executables; reset so the
            # table reflects steady-state run time, not compile time
            exe.run(main, feed={'x': x}, fetch_list=[out])
            profiler.reset_profiler()
            for _ in range(3):
                exe.run(main, feed={'x': x}, fetch_list=[out])
        # outside the scope: records survive until reset
        recs = profiler.summary_records()
    assert 'mul' in recs and recs['mul']['calls'] == 3, recs
    assert 'reduce_mean' in recs
    # the big matmul dominates total time: first data row names it
    table = open(path).read().splitlines()
    assert table[0].startswith('Event')
    assert table[1].split()[0] == 'mul', table[:3]
    printed = capsys.readouterr().out
    assert 'mul' in printed and 'Total(ms)' in printed
    # ave * calls == total
    assert abs(recs['mul']['ave'] * 3 - recs['mul']['total']) < 1e-9


def test_profiler_sort_keys_and_reset():
    import pytest
    main, startup, out = _build(64)
    x = np.zeros((8, 64), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        profiler.start_profiler('All')
        exe.run(main, feed={'x': x}, fetch_list=[out])
        profiler.stop_profiler(sorted_key='calls')
    assert profiler.summary_records()
    # every documented sort key works; junk raises
    for k in ('calls', 'total', 'max', 'min', 'ave'):
        profiler.summary_string(k)
    with pytest.raises(ValueError):
        profiler.summary_string('bogus')
    with pytest.raises(ValueError):
        profiler.start_profiler('TPU-ish')
    profiler._enabled = False
    profiler.reset_profiler()
    assert not profiler.summary_records()


def test_profiler_off_keeps_segment_compilation():
    """With the profiler OFF the plan must stay the fused multi-op
    segment (one jit), not per-op pieces — profiling must not leak
    into normal execution."""
    main, startup, out = _build(64)
    x = np.zeros((8, 64), 'float32')
    profiler.reset_profiler()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': x}, fetch_list=[out])
        plan = exe._get_plan(main, ('x',), (out.name,))
    from paddle_tpu.fluid.executor import _Segment
    segs = [it for it in plan if isinstance(it, _Segment)]
    assert len(segs) == 1 and len(segs[0].ops) > 1
    assert not profiler.summary_records()


def test_attribute_trace_events_maps_kernels_to_ops():
    """Round-5 VERDICT item 4: per-op attribution of the REAL fused
    run.  The parser maps device-trace kernel events (tf_op = XLA
    op_metadata scope path) back to fluid op types, including
    whole-program-autodiff backward kernels whose scope is wrapped in
    transform names (transpose(jvp(op)))."""
    ev = [
        # forward kernels under plain scopes
        {'ph': 'X', 'name': 'fusion.1', 'dur': 800.0,
         'args': {'tf_op': 'jit_segment_mul_x12/mul/dot_general:'}},
        {'ph': 'X', 'name': 'fusion.2', 'dur': 100.0,
         'args': {'tf_op': 'jit_segment_mul_x12/relu/max:'}},
        # wpg backward: transform-wrapped scope components
        {'ph': 'X', 'name': 'fusion.3', 'dur': 700.0,
         'args': {'tf_op':
                  'jit_segment_wpg_mul_x12/transpose(jvp(mul))/'
                  'dot_general:'}},
        # second call of the mul kernel (another step)
        {'ph': 'X', 'name': 'fusion.1', 'dur': 820.0,
         'args': {'tf_op': 'jit_segment_mul_x12/mul/dot_general:'}},
        # unattributable copy
        {'ph': 'X', 'name': 'copy-start.4', 'dur': 5.0,
         'args': {'tf_op': 'jit_segment_mul_x12/copy'}},
        # non-X and arg-less events are ignored
        {'ph': 'M', 'name': 'process_name'},
        {'ph': 'X', 'name': 'jit_segment', 'dur': 9999.0},
    ]
    recs = profiler.attribute_trace_events(
        ev, op_types={'mul', 'relu', 'reduce_mean'})
    assert recs['mul'][0] == 3  # two fwd calls + one transposed bwd
    assert abs(recs['mul'][1] - (800 + 820 + 700) * 1e-6) < 1e-12
    assert recs['relu'][0] == 1
    assert 'unattributed/copy-start' in recs
    # dominant op of the known program is mul
    top = max(recs.items(), key=lambda kv: kv[1][1])[0]
    assert top == 'mul'


def test_attribute_trace_events_tolerates_malformed_events():
    """Real captures carry counter rows without dur, instant events,
    null args and non-string tf_op metadata — attribution must skip or
    zero-time them, never raise (surfaced while wiring the host+device
    timeline merger)."""
    ev = [
        # well-formed anchor
        {'ph': 'X', 'name': 'fusion.1', 'dur': 100.0,
         'args': {'tf_op': 'jit_seg/mul/dot_general:'}},
        # missing dur / null dur / junk dur -> zero-timed, still counted
        {'ph': 'X', 'name': 'fusion.2',
         'args': {'tf_op': 'jit_seg/mul/dot_general:'}},
        {'ph': 'X', 'name': 'fusion.3', 'dur': None,
         'args': {'tf_op': 'jit_seg/mul/dot_general:'}},
        {'ph': 'X', 'name': 'fusion.4', 'dur': 'n/a',
         'args': {'tf_op': 'jit_seg/mul/dot_general:'}},
        # non-string / non-dict metadata -> skipped
        {'ph': 'X', 'name': 'fusion.5', 'dur': 5.0,
         'args': {'tf_op': 123}},
        {'ph': 'X', 'name': 'fusion.6', 'dur': 5.0, 'args': 'oops'},
        # unknown op path + missing name -> unattributed bucket
        {'ph': 'X', 'dur': 7.0, 'args': {'tf_op': 'jit_seg/mystery'}},
        # non-dict rows in the list -> skipped
        None, 'garbage', 42,
    ]
    recs = profiler.attribute_trace_events(ev, op_types={'mul'})
    assert recs['mul'][0] == 4
    assert abs(recs['mul'][1] - 100e-6) < 1e-12
    assert recs['unattributed/?'][0] == 1


def test_profiler_default_mode_keeps_fused_plan():
    """tracer_option='Default' must NOT re-segment the program: the
    executor's plan stays the production (fused) one."""
    from paddle_tpu.fluid import executor as executor_mod
    main, startup, out = _build(256)
    x = np.random.RandomState(0).randn(8, 256).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        profiler.start_profiler(tracer_option='Default')
        try:
            assert not profiler.is_enabled()  # no per-op splitting
            exe.run(main, feed={'x': x}, fetch_list=[out])
            plan = exe._get_plan(main, ('x',), (out.name,))
            segs = [it for it in plan
                    if isinstance(it, executor_mod._Segment)]
            assert len(segs) == 1 and len(segs[0].ops) > 1
        finally:
            profiler.stop_profiler(profile_path=None)


def test_profiler_traced_table_on_device():
    """End-to-end trace-derived table from a REAL device run.  TPU
    backends emit per-kernel tf_op metadata; CPU hosts do not, so this
    integration leg runs only where a TPU is attached (the parser unit
    test above covers the attribution logic everywhere)."""
    import jax
    import pytest
    if jax.devices()[0].platform != 'tpu':
        pytest.skip('device-kernel tf_op metadata needs a TPU backend')
    main, startup, out = _build()
    x = np.random.RandomState(0).randn(64, 1024).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': x}, fetch_list=[out])  # compile
        with profiler.profiler(tracer_option='Default',
                               profile_path=None):
            for _ in range(3):
                exe.run(main, feed={'x': x}, fetch_list=[out])
        recs = profiler.summary_records()
    assert 'mul' in recs, recs
    top = max(recs.items(), key=lambda kv: kv[1]['total'])
    assert top[0] == 'mul', recs
