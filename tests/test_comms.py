"""Job-wide observability: fluid.comms collective telemetry, the
cross-worker trace collection (trace.collect_job + epoch anchors),
straggler/skew detection, per-segment XLA memory accounting, and the
comms cost model.

The two-subprocess test at the bottom is the acceptance path: a REAL
two-worker job (each a GradAllReduce program with a live status plane)
must collect into ONE schema-valid merged timeline with both ranks'
spans on a shared clock."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import comms, layers, monitor, trace
from paddle_tpu.fluid import health
from paddle_tpu.fluid.transpiler.collective import GradAllReduce


@pytest.fixture(autouse=True)
def _clean_registries():
    monitor.reset()
    comms.reset()
    trace.reset()
    trace.disable()
    yield
    monitor.reset()
    comms.reset()
    trace.reset()
    trace.disable()


# ------------------------------------------------------------ unit: comms
def test_wire_bytes_formulas():
    # ring allreduce moves 2(n-1)/n, allgather receives n-1 shards,
    # reduce-scatter (n-1)/n; n=1 moves nothing
    assert comms.wire_bytes('allreduce', 800, 8) == \
        pytest.approx(2 * 7 / 8 * 800)
    assert comms.wire_bytes('allgather', 800, 8) == \
        pytest.approx(7 * 800)
    assert comms.wire_bytes('reducescatter', 800, 8) == \
        pytest.approx(7 / 8 * 800)
    assert comms.wire_bytes('allreduce', 800, 1) == 0.0


def test_size_bucket_labels():
    assert comms.size_bucket(1024) == 'le4KiB'
    assert comms.size_bucket(5 << 10) == 'le64KiB'
    assert comms.size_bucket(2 << 20) == 'le16MiB'
    assert comms.size_bucket(1 << 30) == 'gt256MiB'


def test_record_trace_collecting_registry():
    # no ambient context: record_trace is a no-op
    assert comms.record_trace('allreduce', 100, participants=4) is None
    with comms.collecting('fp1'):
        rec = comms.record_trace('allreduce', 100, dtype='float32',
                                 axis='dp', participants=4)
        assert rec['wire_bytes'] == pytest.approx(2 * 3 / 4 * 100)
    recs = comms.records_for('fp1')
    assert len(recs) == 1 and recs[0]['axis'] == 'dp'
    # a re-entered context whose call skipped tracing (executable
    # reused) must not blank the registered profile
    with comms.collecting('fp1'):
        pass
    assert len(comms.records_for('fp1')) == 1
    assert comms.records_for(None) == ()


def test_account_dispatch_points_and_histograms():
    with comms.collecting('fp2'):
        comms.record_trace('allreduce', 1 << 20, dtype='float32',
                           axis='dp', participants=8)
    recs = comms.records_for('fp2')
    # compile run: bytes count, no bandwidth sample
    comms.account_dispatch(recs, 0.5, compile_run=True)
    assert monitor.counter_value('comms/bytes_on_wire') > 0
    assert comms.bw_samples() == {}
    # steady run: bandwidth histogram + raw samples
    comms.account_dispatch(recs, 0.01)
    key = 'comms/bw_gbps/allreduce/le1MiB'
    hist = monitor.histogram_value(key)
    assert hist and hist['count'] == 1
    samples = comms.bw_samples()[('allreduce', 'le1MiB')]
    expect = comms.wire_bytes('allreduce', 1 << 20, 8) / 0.01 / 1e9
    assert samples[0] == pytest.approx(expect)
    assert monitor.counter_value('comms/collective_calls') == 2.0


def test_phase_arms_feed_refit_pool():
    """rs_ag-armed records decompose into reducescatter + allgather
    phase refit points (the entries that price them), and quant
    records refit their own 'allreduce_quant' entry — neither pollutes
    the dense-allreduce fit."""
    from paddle_tpu.fluid import comms_plan
    comms.clear_dispatch_points()
    n, pl = 8, float(4 << 20)
    with comms.collecting('fp_phase'):
        comms.record_trace(
            'allreduce', pl, dtype='float32', axis='dp',
            participants=n, arm='rs_ag',
            wire=comms.wire_bytes('reducescatter', pl, n)
            + comms.wire_bytes('allgather', pl / n, n),
            dense_wire=comms.wire_bytes('allreduce', pl, n))
        comms.record_trace(
            'allreduce_quant', pl, dtype='float32', axis='dp',
            participants=n, arm='quant',
            wire=comms_plan.quant_wire_bytes(pl, 4, n),
            dense_wire=comms.wire_bytes('allreduce', pl, n))
        comms.record_trace('allreduce', pl, dtype='float32',
                           axis='dp', participants=n, arm='dense')
    comms.account_dispatch(comms.records_for('fp_phase'), 0.01)
    rs = comms.dispatch_points('reducescatter')
    ag = comms.dispatch_points('allgather')
    qt = comms.dispatch_points('allreduce_quant')
    dense = comms.dispatch_points('allreduce')
    assert len(rs) == len(ag) == len(qt) == len(dense) == 1
    # phase points carry the PHASE wire, not the composite
    assert rs[0][0] == pytest.approx(
        comms.wire_bytes('reducescatter', pl, n))
    assert ag[0][0] == pytest.approx(
        comms.wire_bytes('allgather', pl / n, n))
    assert dense[0][0] == pytest.approx(
        comms.wire_bytes('allreduce', pl, n))
    # wire-share attribution still reproduces the segment wall
    walls = sum(p[1] for p in rs + ag + qt + dense)
    assert walls == pytest.approx(0.01)
    comms.clear_dispatch_points()


def test_summarize_for_span_annotation():
    with comms.collecting('fp3'):
        comms.record_trace('allreduce', 100, axis='dp', participants=8)
        comms.record_trace('allgather', 50, axis='sp', participants=2)
    s = comms.summarize(comms.records_for('fp3'))
    assert s['collectives'] == 'allgather:1 allreduce:1'
    assert s['axes'] == 'dp,sp'
    assert s['participants'] == 8
    assert s['payload_bytes'] == 150.0


def test_cost_model_fit_and_predict():
    alpha, beta = 2e-4, 1e-9   # 200us latency, 1 GB/s
    rng = np.random.RandomState(0)
    pts = [(b, (alpha + beta * b) * rng.uniform(0.95, 1.05))
           for b in (1e4, 1e5, 1e6, 1e7, 1e8)]
    a, bta = comms.fit_linear(pts)
    entry = {'latency_s': a, 'inv_bw_s_per_byte': bta}
    for b, t in pts:
        pred = comms.model_predict(entry, b)
        assert max(pred / t, t / pred) < 2.0
    assert a == pytest.approx(alpha, rel=0.5)
    assert bta == pytest.approx(beta, rel=0.5)
    # degenerate inputs stay finite
    a, bta = comms.fit_linear([])
    assert bta > 0
    a, bta = comms.fit_linear([(1e6, 0.001)])
    assert bta > 0 and a == 0.0


# --------------------------------------------- real collective telemetry
def _allreduce_program(width=16):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 3
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[width], dtype='float32')
        h = layers.fc(x, width, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')
    return main_p, startup, loss


def test_collective_runner_records_comms():
    import jax
    ndev = len(jax.devices())
    main_p, startup, loss = _allreduce_program()
    exe = fluid.Executor(fluid.XLAPlace(0))
    feed = {'x': np.ones((8, 16), 'float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        trace.enable()
        for _ in range(3):
            exe.run(main_p, feed=feed, fetch_list=[loss])
    assert monitor.counter_value('comms/bytes_on_wire') > 0
    assert monitor.counter_value('comms/allreduce_calls') > 0
    # the traced records carry dtype/axis/participants
    seen = [r for recs in comms._BY_KEY.values() for r in recs]
    assert seen and all(r['participants'] == ndev for r in seen)
    assert all(r['axis'] == 'dp' for r in seen)
    # steady dispatches observed achieved bandwidth
    hists = [n for n in monitor._hists
             if n.startswith('comms/bw_gbps/allreduce/')]
    assert hists
    # the dispatch span is annotated with the collective profile
    annotated = [s for rec in trace.steps() for s in rec['spans']
                 if s[0] == 'dispatch' and s[5]
                 and 'wire_bytes' in s[5]]
    assert annotated
    args = annotated[-1][5]
    assert args['participants'] == ndev and args['axes'] == 'dp'


def test_ring_attention_op_records_ppermute():
    import jax
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.ops.parallel_ops import ring_attention_op
    if len(jax.devices()) < 2:
        pytest.skip('needs a multi-device mesh')
    ndev = len(jax.devices())
    mesh = pmesh.create_mesh(dp=ndev // 2, sp=2)
    rng = np.random.RandomState(0)
    q = rng.rand(1, 8, 2, 4).astype('float32')
    with pmesh.use_trace_mesh(mesh):
        with comms.collecting('ring_fp'):
            out = ring_attention_op(None, {'Q': [q], 'K': [q],
                                           'V': [q]}, {'axis': 'sp'})
    assert out['Out'][0].shape == q.shape
    recs = comms.records_for('ring_fp')
    assert len(recs) == 1 and recs[0]['kind'] == 'ppermute'
    assert recs[0]['participants'] == 2
    # one rotation (sp-1) of both K and V block shards
    hop = 2 * q.nbytes / 2
    assert recs[0]['wire_bytes'] == pytest.approx(hop)


# -------------------------------------------------------- skew detection
def _rollup(count, p50, p99, phases):
    return {'count': count, 'wall_p50_ms': p50, 'wall_p99_ms': p99,
            'wall_max_ms': p99, 'phases_ms': phases}


def test_job_skew_report_math():
    rep = trace.job_skew_report({
        '0': _rollup(10, 10.0, 12.0, {'dispatch': 80.0, 'bind': 10.0}),
        '1': _rollup(10, 30.0, 60.0, {'dispatch': 280.0, 'bind': 9.0}),
        '2': _rollup(10, 10.0, 11.0, {'dispatch': 82.0, 'bind': 11.0}),
    })
    assert rep['wall']['slowest_rank'] == '1'
    assert rep['wall']['skew_ratio'] == pytest.approx(3.0)
    assert rep['ranks']['1']['p99_over_p50'] == pytest.approx(2.0)
    ph = rep['phases']['dispatch']
    assert ph['slowest_rank'] == '1'
    assert ph['max_ms'] == pytest.approx(28.0)   # per step
    # reference is the median of the OTHER ranks' per-step phase time
    assert ph['ratio'] == pytest.approx(28.0 / 8.1)
    # empty / step-less rollups degrade to None
    assert trace.job_skew_report({}) is None
    assert trace.job_skew_report({'0': _rollup(0, 0, 0, {})}) is None
    # a zero reference with a nonzero straggler is UNBOUNDED skew (a
    # finite sentinel that trips any factor and stays JSON-safe), not
    # a masked 1.0 — e.g. a phase only the straggler runs
    rep = trace.job_skew_report({
        '0': _rollup(10, 10.0, 12.0, {'reader_wait': 50.0}),
        '1': _rollup(10, 0.0, 0.0, {}),
    })
    assert rep['wall']['skew_ratio'] == trace._SKEW_UNBOUNDED
    assert rep['phases']['reader_wait']['ratio'] == \
        trace._SKEW_UNBOUNDED
    json.dumps(rep)


def test_straggler_detector_autodump(tmp_path):
    fluid.set_flags({'FLAGS_straggler_factor': 2.0})
    try:
        agg = health._Aggregator('0', [('0', 'local')], 1000.0)
        agg.stop()
        trace.enable()
        with trace.step_span(1):
            pass
        # inject a straggling peer rollup and run one detector pass
        agg._peers['1'] = {
            'endpoint': 'x', 'up': True, 'ready': True, 'state': None,
            'status': None, 'error': None, 'ts': time.time(),
            'rollup': _rollup(5, 3000.0, 3600.0,
                              {'dispatch': 12000.0})}
        agg.workers = [('1', 'x')]
        rep = agg.check_skew()
        assert rep is not None and rep['wall']['slowest_rank'] == '1'
        assert monitor.gauge_value('comms/skew_ratio') >= 2.0
        assert monitor.counter_value('comms/straggler_trips') == 1.0
        assert monitor.counter_value('health/detector_dumps') == 1.0
        # rate limit: an immediate second trip must not dump again
        agg.check_skew()
        assert monitor.counter_value('comms/straggler_trips') == 2.0
        assert monitor.counter_value('health/detector_dumps') == 1.0
    finally:
        fluid.set_flags({'FLAGS_straggler_factor': 2.0})


# ------------------------------------------------------ memory accounting
def test_memory_gauges_from_real_executable():
    import jax
    compiled = jax.jit(lambda x: x @ x).lower(
        jax.ShapeDtypeStruct((64, 64), 'float32')).compile()
    row = comms.record_memory('test_seg', compiled)
    assert row is not None and row['argument_bytes'] > 0
    assert monitor.gauge_value('executor/segment_argument_bytes') > 0
    assert monitor.gauge_value('executor/segment_peak_bytes') >= \
        row['argument_bytes']
    rows = comms.memory_report()
    assert rows and rows[0]['segment'] == 'test_seg'
    # a backend without the analysis degrades to None, no gauges harmed
    class NoMa:
        def memory_analysis(self):
            raise NotImplementedError
    assert comms.record_memory('bad', NoMa()) is None


def test_executor_populates_memory_and_statusz_section(tmp_path):
    # the AOT compile plane is where memory_analysis runs: point it at
    # a scratch dir (the plane is off by default in the test env)
    prev = fluid.flags.get_flag('FLAGS_compile_cache_dir')
    fluid.set_flags({'FLAGS_compile_cache_dir': str(tmp_path)})
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        loss = layers.reduce_mean(layers.fc(x, 8))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.XLAPlace(0))
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(main_p, feed={'x': np.ones((4, 8), 'float32')},
                    fetch_list=[loss])
    finally:
        fluid.set_flags({'FLAGS_compile_cache_dir': prev})
    doc = health.statusz()
    mem = doc['memory']
    assert mem is not None and mem['segments']
    assert mem['segment_peak_bytes'] > 0
    json.dumps(doc, default=str)   # /statusz stays JSON-able


# ------------------------------------------------- collect_job (in-proc)
def _fake_dump(shift_us=0.0, rank='0'):
    trace.reset()
    trace.enable()
    for step in range(3):
        with trace.step_span(step):
            with trace.span('dispatch'):
                time.sleep(0.001)
    payload = json.loads(json.dumps(trace.dump_payload()))
    payload['ptRank'] = rank
    if shift_us:
        payload['ptClock']['export_us'] -= shift_us
        for e in payload['traceEvents']:
            if isinstance(e.get('ts'), (int, float)):
                e['ts'] -= shift_us
    trace.disable()
    trace.reset()
    return payload


def test_dump_carries_epoch_anchor():
    payload = _fake_dump()
    clock = payload['ptClock']
    assert abs(clock['unix_us'] - time.time() * 1e6) < 60e6
    assert abs(clock['unix_us'] - clock['export_us']) < 60e6
    assert payload['ptRank'] == '0'


def test_collect_job_rehomes_clocks_and_tracks():
    d0 = _fake_dump(rank='0')
    d1 = _fake_dump(shift_us=7e6, rank='1')   # 7s of NTP drift
    payloads = {'h0:1': json.dumps(d0), 'h1:2': json.dumps(d1)}
    doc = trace.collect_job(workers=[('0', 'h0:1'), ('1', 'h1:2')],
                            fetch=lambda ep: payloads[ep])
    assert not doc['ptJob']['skipped']
    meta = doc['ptJob']['workers']
    assert meta['0']['clock'] == 'anchored'
    # per-rank process tracks
    bands = {e['pid'] // 100 for e in doc['traceEvents']
             if e.get('ph') == 'X'}
    assert bands == {0, 1}
    # re-homed onto one clock: the 7s drift is gone
    t0 = [e['ts'] for e in doc['traceEvents']
          if e.get('ph') == 'X' and e['pid'] < 100]
    t1 = [e['ts'] for e in doc['traceEvents']
          if e.get('ph') == 'X' and e['pid'] >= 100]
    assert abs(min(t0) - min(t1)) < 5e6
    # rank-tagged steps + per-rank skew report computed
    assert {r['rank'] for r in doc['ptSteps']} == {'0', '1'}
    assert doc['ptJob']['skew']['wall']['skew_ratio'] >= 1.0
    # process names carry the rank
    names = [e['args']['name'] for e in doc['traceEvents']
             if e.get('ph') == 'M' and e.get('name') == 'process_name']
    assert any(n.startswith('rank 0 ') for n in names)
    assert any(n.startswith('rank 1 ') for n in names)


def test_collect_job_tolerates_bad_workers():
    d0 = _fake_dump(rank='0')
    payloads = {'good:1': json.dumps(d0),
                'trunc:2': json.dumps(d0)[:40],      # truncated JSON
                'empty:3': '{}'}                      # no traceEvents

    def fetch(ep):
        if ep == 'dead:4':
            raise OSError('connection refused')
        return payloads[ep]

    before = monitor.counter_value('trace/collect_skipped')
    doc = trace.collect_job(
        workers=[('0', 'good:1'), ('1', 'trunc:2'), ('2', 'empty:3'),
                 ('3', 'dead:4')], fetch=fetch)
    assert sorted(doc['ptJob']['skipped']) == ['1', '2', '3']
    assert monitor.counter_value('trace/collect_skipped') == before + 3
    # the healthy rank still collected
    assert doc['ptJob']['workers']['0']['events'] > 0


def test_collect_job_unanchored_fallback():
    d0 = _fake_dump(rank='0')
    d1 = _fake_dump(shift_us=3e6, rank='1')
    del d1['ptClock']   # pre-anchor dump
    payloads = {'a:1': json.dumps(d0), 'b:2': json.dumps(d1)}
    doc = trace.collect_job(workers=[('0', 'a:1'), ('1', 'b:2')],
                            fetch=lambda ep: payloads[ep])
    assert doc['ptJob']['workers']['1']['clock'] == 'aligned'
    assert monitor.counter_value('trace/collect_unanchored') == 1.0
    t0 = [e['ts'] for e in doc['traceEvents']
          if e.get('ph') == 'X' and e['pid'] < 100]
    t1 = [e['ts'] for e in doc['traceEvents']
          if e.get('ph') == 'X' and e['pid'] >= 100]
    # capture-start alignment: earliest events coincide
    assert abs(min(t0) - min(t1)) < 1e3


# ------------------------------------------------------- tools integration
def test_stat_summary_rank_filter(tmp_path, capsys):
    d0 = _fake_dump(rank='0')
    d1 = _fake_dump(rank='1')
    payloads = {'a:1': json.dumps(d0), 'b:2': json.dumps(d1)}
    doc = trace.collect_job(workers=[('0', 'a:1'), ('1', 'b:2')],
                            fetch=lambda ep: payloads[ep],
                            out_path=str(tmp_path / 'job.json'))
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), 'tools'))
    import importlib
    import stat_summary
    importlib.reload(stat_summary)
    rc = stat_summary.main(['--steps', str(tmp_path / 'job.json'),
                            '--rank', '1'])
    out = capsys.readouterr().out
    assert rc == 0 and out.startswith('rank 1:')
    assert 'steps: 3' in out
    rc = stat_summary.main(['--steps', str(tmp_path / 'job.json'),
                            '--rank', '9'])
    assert rc == 1


def test_metrics_json_carries_step_rollup():
    trace.enable()
    with trace.step_span(1):
        with trace.span('dispatch'):
            time.sleep(0.001)
    roll = trace.step_rollup()
    assert roll['count'] == 1 and 'dispatch' in roll['phases_ms']
    # the aggregator-facing scrape shape is json-able and compact
    json.dumps(roll)


# ---------------------------------------------- two-subprocess acceptance
def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _wait_ready(proc, url, deadline):
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError('worker died: rc=%d' % proc.returncode)
        try:
            code, _body = _get(url + '/healthz/local', timeout=2)
            if code == 200:
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError('worker at %s never became ready' % url)


def test_two_process_collect_job_merged_timeline():
    """Acceptance: a real two-worker collective job collects into ONE
    schema-valid merged trace with both ranks' spans on a shared
    clock, plus nonzero comms telemetry on every rank."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, 'comms_worker.py')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base_env = dict(os.environ)
    base_env.update({'JAX_PLATFORMS': 'cpu',
                     'PADDLE_TPU_STATUS_WORKERS': spec,
                     'FLAGS_health_heartbeat_seconds': '0.5',
                     'FLAGS_trace': '1'})
    env0 = dict(base_env, PADDLE_TRAINER_ID='0',
                PADDLE_TPU_STATUS_AGGREGATE='1')
    env1 = dict(base_env, PADDLE_TRAINER_ID='1',
                PADDLE_TPU_STATUS_AGGREGATE='0')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), '120'], env=env1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), '120'], env=env0,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        deadline = time.time() + 180
        agg = 'http://127.0.0.1:%d' % p0
        wrk = 'http://127.0.0.1:%d' % p1
        _wait_ready(procs[0], wrk, deadline)
        _wait_ready(procs[1], agg, deadline)
        time.sleep(1.5)     # a few steps on both ranks

        doc = trace.collect_job(workers=spec)
        assert not doc['ptJob']['skipped']
        assert sorted(doc['ptJob']['workers']) == ['0', '1']
        assert all(m['clock'] == 'anchored'
                   for m in doc['ptJob']['workers'].values())
        # schema: every span event complete, rank bands distinct
        bands = set()
        for e in doc['traceEvents']:
            assert isinstance(e, dict)
            if e.get('ph') == 'X':
                assert {'ts', 'dur', 'pid', 'name'} <= set(e)
                bands.add(e['pid'] // 100)
        assert bands == {0, 1}
        # shared clock: both ranks' windows overlap (they step
        # concurrently)
        w = {}
        for e in doc['traceEvents']:
            if e.get('ph') == 'X':
                band = w.setdefault(e['pid'] // 100, [1e30, 0])
                band[0] = min(band[0], e['ts'])
                band[1] = max(band[1], e['ts'] + e['dur'])
        assert w[0][0] < w[1][1] and w[1][0] < w[0][1]
        # rank-tagged step records feed the per-rank report
        assert {r['rank'] for r in doc['ptSteps']} == {'0', '1'}
        assert doc['ptJob']['skew'] is not None
        # comms telemetry populated on both ranks
        for url in (agg, wrk):
            code, body = _get(url + '/metrics.json')
            counters = json.loads(body)['state']['counters']
            assert counters.get('comms/bytes_on_wire', 0.0) > 0
        # aggregator /statusz carries per-rank liveness + skew
        code, body = _get(agg + '/statusz')
        job = json.loads(body)['job']
        assert sorted(job['workers']) == ['0', '1']
        assert all(v['up'] for v in job['workers'].values())
        assert job['skew'] is None or \
            job['skew']['wall']['skew_ratio'] >= 1.0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
