"""Gradient-check sweep, part 4 (round 5): the last lowerings the
dynamic audit (tools/check_grad_coverage.py) found with neither an FD
check nor a written waiver — multi-input aggregation ops (concat, sum,
stack, multiplex — the harness grew multi-var-slot support for these),
full RNN layers (gru/lstm/lstmp), sequence padding/scatter family,
sampled-geometry vision ops (deformable conv/roi, prroi), dense
detection losses (ssd_loss, yolov3_loss), and stragglers (cast,
lookup_table W-grad, diag, top_k_v2 values, max_pool3d, grouped
transpose conv, var_conv_2d).

Inputs live in each op's smooth region: bilinear-sampled ops get
fractional offsets away from integer grid crossings, pooling/top-k get
well-separated values, yolo stays under its ignore threshold so the
objectness mask is locally constant.  Isolated RandomStates per case
(the part-3 discipline)."""

import numpy as np
import pytest

from op_test import OpTest


def R(seed):
    return np.random.RandomState(seed)


def _distinct(seed, *shape):
    """Values with pairwise gaps >~0.3: argmax/top-k selections stay
    constant under the FD eps."""
    n = int(np.prod(shape))
    vals = np.arange(n, dtype='float64') * 0.5
    return R(seed).permutation(vals).reshape(shape).astype('float64')


# op -> (inputs builder, attrs, out_slot, check_grad kwargs)
CASES = {
    'cast': (
        lambda: {'X': R(0).randn(2, 3)},
        {'in_dtype': 'float32', 'out_dtype': 'float32'}, 'Out',
        {'grad_slots': ['X']}),
    'concat': (
        lambda: {'X': [('cc_a', R(1).randn(2, 3).astype('float32')),
                       ('cc_b', R(2).randn(2, 4).astype('float32'))]},
        {'axis': 1}, 'Out', {'grad_slots': ['X']}),
    'sum': (
        lambda: {'X': [('sm_a', R(3).randn(2, 3).astype('float32')),
                       ('sm_b', R(4).randn(2, 3).astype('float32')),
                       ('sm_c', R(5).randn(2, 3).astype('float32'))]},
        {}, 'Out', {'grad_slots': ['X']}),
    'stack': (
        lambda: {'X': [('st_a', R(6).randn(2, 3).astype('float32')),
                       ('st_b', R(7).randn(2, 3).astype('float32'))]},
        {'axis': 1}, 'Y', {'grad_slots': ['X']}),
    'multiplex': (
        lambda: {'X': [('mx_a', R(8).randn(3, 4).astype('float32')),
                       ('mx_b', R(9).randn(3, 4).astype('float32'))],
                 'Ids': np.array([[0], [1], [0]], 'int64')},
        {}, 'Out', {'grad_slots': ['X']}),
    'diag': (
        lambda: {'Diagonal': R(10).randn(4)},
        {}, 'Out', {'grad_slots': ['Diagonal']}),
    'top_k_v2': (
        lambda: {'X': _distinct(11, 2, 6)},
        {'k': 3}, 'Out', {'grad_slots': ['X']}),
    'lookup_table': (
        lambda: {'W': R(12).randn(5, 3),
                 'Ids': np.array([[0], [2], [2], [4]], 'int64')},
        {}, 'Out', {'grad_slots': ['W']}),
    'max_pool3d_with_index': (
        lambda: {'X': _distinct(13, 1, 1, 4, 4, 4)},
        {'ksize': [2, 2, 2], 'strides': [2, 2, 2],
         'paddings': [0, 0, 0]}, 'Out', {'grad_slots': ['X']}),
    'depthwise_conv2d_transpose': (
        lambda: {'Input': R(14).randn(1, 2, 3, 3) * 0.5,
                 'Filter': R(15).randn(2, 1, 3, 3) * 0.5},
        {'strides': [2, 2], 'groups': 2, 'paddings': [0, 0]}, 'Output',
        {'grad_slots': ['Input', 'Filter']}),
    'var_conv_2d': (
        lambda: {'X': R(16).randn(2, 1, 4, 4) * 0.5,
                 'W': R(17).randn(2, 9) * 0.5,
                 'Mask': (R(18).rand(2, 1, 4, 4) > 0.2).astype(
                     'float32')},
        {'output_channel': 2, 'input_channel': 1, 'kernel_h': 3,
         'kernel_w': 3}, 'Out',
        {'grad_slots': ['X', 'W'], 'stop_gradients': ('Mask',)}),
    # --- sequence family (padded + mask representation) ---
    'sequence_pad': (
        lambda: {'X': R(19).randn(2, 3, 2),
                 'Mask': np.array([[1, 1, 0], [1, 0, 0]], 'float32')},
        {'pad_value': 0.5}, 'Out',
        {'grad_slots': ['X'], 'stop_gradients': ('Mask',)}),
    'sequence_unpad': (
        lambda: {'X': R(20).randn(2, 3, 2),
                 'Length': np.array([2, 3], 'int64')},
        {}, 'Out', {'grad_slots': ['X']}),
    'sequence_reshape': (
        lambda: {'X': R(21).randn(2, 6)},
        {'new_dim': 3}, 'Out', {'grad_slots': ['X']}),
    'sequence_concat': (
        lambda: {'X': [('sq_a', R(22).randn(2, 2, 3).astype('float32')),
                       ('sq_b', R(23).randn(2, 3, 3).astype('float32'))]},
        {}, 'Out', {'grad_slots': ['X']}),
    'sequence_expand_as': (
        lambda: {'X': R(24).randn(2, 3),
                 'Y': R(25).randn(2, 4, 3)},
        {}, 'Out', {'grad_slots': ['X'], 'stop_gradients': ('Y',)}),
    'sequence_scatter': (
        lambda: {'X': R(26).randn(6),
                 'Ids': np.array([[0, 2], [3, 5]], 'int64'),
                 'Updates': R(27).randn(2, 2)},
        {}, 'Out', {'grad_slots': ['X', 'Updates']}),
    # --- full RNN layers (scan + gates; Input is pre-projected) ---
    'gru': (
        lambda: {'Input': R(28).randn(2, 3, 6) * 0.5,
                 'Weight': R(29).randn(2, 6) * 0.5,
                 'Mask': np.array([[1, 1, 1], [1, 1, 0]], 'float32')},
        {}, 'Hidden',
        {'grad_slots': ['Input', 'Weight'],
         'stop_gradients': ('Mask',)}),
    'lstm': (
        lambda: {'Input': R(30).randn(2, 3, 8) * 0.5,
                 'Weight': R(31).randn(2, 8) * 0.5,
                 'Mask': np.array([[1, 1, 0], [1, 1, 1]], 'float32')},
        {}, 'Hidden',
        {'grad_slots': ['Input', 'Weight'],
         'stop_gradients': ('Mask',)}),
    'lstmp': (
        lambda: {'Input': R(32).randn(2, 3, 8) * 0.5,
                 'Weight': R(33).randn(3, 8) * 0.5,
                 'ProjWeight': R(34).randn(2, 3) * 0.5},
        {}, 'Projection',
        {'grad_slots': ['Input', 'Weight', 'ProjWeight']}),
    # --- bilinear-sampled geometry: offsets fractional, away from
    #     integer crossings (kinks of bilinear interpolation) ---
    'deformable_conv_v1': (
        lambda: {'Input': R(35).randn(1, 2, 5, 5) * 0.5,
                 'Offset': (R(36).rand(1, 18, 5, 5) * 0.3 + 0.15
                            ).astype('float64'),
                 'Filter': R(37).randn(2, 2, 3, 3) * 0.3},
        {'strides': [1, 1], 'paddings': [1, 1], 'dilations': [1, 1],
         'groups': 1, 'deformable_groups': 1}, 'Output',
        {'grad_slots': ['Input', 'Offset', 'Filter']}),
    'deformable_roi_pooling': (
        lambda: {'X': R(38).randn(1, 2, 6, 6) * 0.5,
                 'ROIs': np.array([[0.7, 0.6, 4.3, 4.4]], 'float64'),
                 'Trans': (R(39).rand(1, 2, 2, 2) * 0.3 + 0.1
                           ).astype('float64')},
        {'pooled_height': 2, 'pooled_width': 2, 'spatial_scale': 1.0,
         'trans_std': 0.1}, 'Output',
        {'grad_slots': ['X', 'Trans'], 'stop_gradients': ('ROIs',)}),
    'prroi_pool': (
        lambda: {'X': R(40).randn(1, 2, 6, 6) * 0.5,
                 'ROIs': np.array([[0.65, 0.7, 4.3, 4.35]], 'float64')},
        {'pooled_height': 2, 'pooled_width': 2, 'spatial_scale': 1.0},
        'Out', {'grad_slots': ['X'], 'stop_gradients': ('ROIs',)}),
    # --- dense detection losses ---
    'ssd_loss': (
        lambda: {'Location': R(41).randn(1, 4, 4) * 0.1,
                 'Confidence': R(42).randn(1, 4, 3) * 0.5,
                 'GtBox': np.array([[[0.1, 0.1, 0.4, 0.4],
                                     [0.5, 0.5, 0.9, 0.9]]], 'float64'),
                 'GtLabel': np.array([[1, 2]], 'int64'),
                 'PriorBox': np.array([[0.1, 0.1, 0.45, 0.45],
                                       [0.5, 0.5, 0.85, 0.85],
                                       [0.0, 0.5, 0.3, 0.9],
                                       [0.6, 0.0, 0.95, 0.45]],
                                      'float64')},
        {'overlap_threshold': 0.5, 'neg_pos_ratio': 3.0}, 'Loss',
        {'grad_slots': ['Location', 'Confidence'],
         'stop_gradients': ('GtBox', 'PriorBox')}),
    'yolov3_loss': (
        # |X| small keeps every predicted box under ignore_thresh IoU,
        # so the objectness mask is locally constant and the loss is
        # smooth in X
        lambda: {'X': R(43).randn(1, 14, 2, 2) * 0.1,
                 'GTBox': np.array([[[0.4, 0.45, 0.3, 0.35]]],
                                   'float64'),
                 'GTLabel': np.array([[1]], 'int64')},
        {'anchors': [10, 13, 16, 30], 'anchor_mask': [0, 1],
         'class_num': 2, 'ignore_thresh': 0.7,
         'downsample_ratio': 32}, 'Loss', {'grad_slots': ['X']}),
}


@pytest.mark.parametrize('op', sorted(CASES))
def test_sweep4_grad(op):
    builder, attrs, out_slot, kwargs = CASES[op]
    kwargs = dict(kwargs)
    op_name = kwargs.pop('op_name', op)
    inputs = {}
    for slot, val in builder().items():
        if isinstance(val, list):
            inputs[slot] = val
        elif np.issubdtype(np.asarray(val).dtype, np.floating):
            inputs[slot] = np.asarray(val, 'float32')
        else:
            inputs[slot] = np.asarray(val)
    ot = OpTest()
    ot.grad_atol = 2e-2
    ot.grad_rtol = 2e-2
    ot.check_grad(op_name, inputs, attrs=attrs, out_slot=out_slot,
                  **kwargs)
