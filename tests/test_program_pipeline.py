"""Program-cutting pipeline: PipelineOptimizer cut_list validation +
GPipe execution of the cut program on the 'pp' mesh axis, parity vs
plain single-submission training."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def build(seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    cuts = []
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        h = x
        for i in range(4):
            h = fluid.layers.fc(h, 16, act='tanh')
            if i < 3:
                cuts.append(h.name)
        out = h
    return main, startup, out, cuts


def test_pipeline_optimizer_records_plan_and_validates():
    main, startup, out, cuts = build(3)
    with fluid.program_guard(main, startup):
        y = fluid.layers.data('y', shape=[16], dtype='float32')
        loss = fluid.layers.mean(fluid.layers.square(out - y))
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(0.1), cut_list=[[c] for c in cuts])
        opt.minimize(loss)
    assert main._pipeline_plan['cuts'] == cuts
    # the recorded program still trains via plain exe.run
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(5):
            xb = rng.randn(8, 16).astype('float32')
            l, = exe.run(main, feed={'x': xb, 'y': 0.5 * xb},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert losses[-1] < losses[0]


def test_program_cut_gpipe_parity():
    """The cut program trained through the GPipe schedule (pp=4) matches
    plain full-batch SGD training step-for-step."""
    import jax
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel.program_pipeline import build_train_step

    main, startup, out, cuts = build(7)
    rng = np.random.RandomState(1)
    batches = [(rng.randn(8, 16).astype('float32'),) for _ in range(4)]
    targets = [0.3 * x for (x,) in batches]

    def loss_fn(pred, y):
        import jax.numpy as jnp
        return jnp.mean((pred - y) ** 2)

    # reference: plain program training on the same init
    ref_main = main  # same program object; train a clone via exe
    with fluid.program_guard(main, startup):
        y = fluid.layers.data('y', shape=[16], dtype='float32')
        loss = fluid.layers.mean(fluid.layers.square(out - y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # snapshot init params for the pipeline run BEFORE training
        mesh = pmesh.create_mesh(pp=4, devices=jax.devices()[:4])
        step, params = build_train_step(
            main, scope, 'x', cuts, out.name, loss_fn, mesh,
            n_microbatches=4, learning_rate=0.05)
        ref_losses = []
        for (x,), t in zip(batches, targets):
            l, = exe.run(main, feed={'x': x, 'y': t},
                         fetch_list=[loss])
            ref_losses.append(float(np.asarray(l).ravel()[0]))

    pipe_losses = []
    for (x,), t in zip(batches, targets):
        l, params = step(params, x, t)
        pipe_losses.append(float(l))
    np.testing.assert_allclose(ref_losses, pipe_losses, rtol=1e-4,
                               atol=1e-5)


def test_cut_skip_connection_parity():
    """An activation produced in stage 0 and consumed in stage 2 rides
    the ring (multi-slot scope-queue analog) — training parity with the
    plain program.  Also exercises a MULTI-VAR cut group."""
    import jax
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel.program_pipeline import build_train_step

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        h1 = fluid.layers.fc(x, 12, act='relu')      # stage 0
        h1b = fluid.layers.fc(x, 16, act='tanh')     # stage 0 (skip src)
        h2 = fluid.layers.fc(h1, 16, act='relu')     # stage 1
        out = fluid.layers.elementwise_add(h2, h1b)  # stage 2 skip read
        out = fluid.layers.fc(out, 16)
    cuts = [[h1.name, h1b.name], [h2.name]]

    rng = np.random.RandomState(2)
    batches = [(rng.randn(8, 16).astype('float32'),) for _ in range(4)]
    targets = [0.2 * x for (x,) in batches]

    def loss_fn(pred, y):
        import jax.numpy as jnp
        return jnp.mean((pred - y) ** 2)

    with fluid.program_guard(main, startup):
        y = fluid.layers.data('y', shape=[16], dtype='float32')
        loss = fluid.layers.mean(fluid.layers.square(out - y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        mesh = pmesh.create_mesh(pp=3, devices=jax.devices()[:3])
        step, params = build_train_step(
            main, scope, 'x', cuts, out.name, loss_fn, mesh,
            n_microbatches=4, learning_rate=0.05)
        ref_losses = []
        for (xb,), t in zip(batches, targets):
            l, = exe.run(main, feed={'x': xb, 'y': t},
                         fetch_list=[loss])
            ref_losses.append(float(np.asarray(l).ravel()[0]))
    pipe_losses = []
    for (xb,), t in zip(batches, targets):
        l, params = step(params, xb, t)
        pipe_losses.append(float(l))
    np.testing.assert_allclose(ref_losses, pipe_losses, rtol=1e-4,
                               atol=1e-5)


def test_resnet_block_group_pipeline_parity():
    """ResNet block-group split (heterogeneous boundary shapes between
    stage groups) trains with exact parity — the VERDICT round-1 'done'
    criterion for generalized pipeline cutting."""
    import jax
    from paddle_tpu import models
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel.program_pipeline import build_train_step

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        img = fluid.layers.data('image', shape=[3, 16, 16],
                                dtype='float32')
        # frozen BN statistics (is_test=True): training-mode BN computes
        # batch stats per MICROBATCH inside a pipeline (2 samples) vs
        # per full batch outside — no pipeline implementation can give
        # exact parity there (the reference SectionWorker has the same
        # property); weights still train
        logits = models.resnet.resnet(img, class_dim=4, depth=18,
                                      is_test=True)
    block = main.global_block()
    # cut after the stage-2 and stage-3 block groups: batch_norm outputs
    # feeding the residual adds at channel-count changes (64->128->256)
    bn_outs = [op.output('Y')[0] for op in block.ops
               if op.type == 'batch_norm']
    adds = [op for op in block.ops if op.type == 'elementwise_add']
    # elementwise_add outputs mark residual-block exits; pick two
    cuts = [adds[3].output('Out')[0], adds[5].output('Out')[0]]
    assert bn_outs  # sanity: the net really has BN layers

    rng = np.random.RandomState(3)
    batches = [(0.1 * rng.randn(8, 3, 16, 16).astype('float32'),)
               for _ in range(3)]
    labels = [rng.randint(0, 4, (8,)).astype('int32')
              for _ in range(3)]

    def loss_fn(logits_v, y):
        import jax.numpy as jnp
        logp = jax.nn.log_softmax(logits_v.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

    with fluid.program_guard(main, startup):
        yv = fluid.layers.data('yv', shape=[1], dtype='int64')
        ce = fluid.layers.softmax_with_cross_entropy(logits, yv)
        loss = fluid.layers.mean(ce)
        fluid.optimizer.SGD(0.001).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        mesh = pmesh.create_mesh(pp=3, devices=jax.devices()[:3])
        step, params = build_train_step(
            main, scope, 'image', cuts, logits.name, loss_fn, mesh,
            n_microbatches=4, learning_rate=0.001)
        ref_losses = []
        for (xb,), y in zip(batches, labels):
            l, = exe.run(main, feed={'image': xb,
                                     'yv': y[:, None].astype('int64')},
                         fetch_list=[loss])
            ref_losses.append(float(np.asarray(l).ravel()[0]))
    pipe_losses = []
    for (xb,), y in zip(batches, labels):
        l, params = step(params, xb, y)
        pipe_losses.append(float(l))
    # step 1 matches to f32 rounding (forward equivalence); later steps
    # accumulate op-ordering rounding between the two autodiff
    # schedules (per-op vjp chain vs whole-pipeline jax.grad) amplified
    # through 18 layers of conv+BN
    np.testing.assert_allclose(ref_losses[:1], pipe_losses[:1],
                               rtol=1e-5)
    np.testing.assert_allclose(ref_losses, pipe_losses, rtol=5e-3)


def test_cut_rejects_cross_stage_weight_sharing():
    from paddle_tpu.parallel.program_pipeline import \
        split_program_stages
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        w = fluid.layers.create_parameter([8, 8], 'float32')
        h = fluid.layers.tanh(fluid.layers.matmul(x, w))
        out = fluid.layers.matmul(h, w)  # tied weight across the cut
    with pytest.raises(ValueError, match='weight sharing'):
        split_program_stages(main, 'x', [h.name], out.name)


def test_pipeline_optimizer_input_inference_ignores_label_order():
    """Labels declared before the input must not be mistaken for the
    pipeline input."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        y = fluid.layers.data('y', shape=[16], dtype='float32')  # first!
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        h = fluid.layers.fc(x, 16, act='tanh')
        out = fluid.layers.fc(h, 16)
        loss = fluid.layers.mean(fluid.layers.square(out - y))
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(0.1), cut_list=[[h.name]])
        opt.minimize(loss)
    assert main._pipeline_plan['input'] == 'x'
