"""Subprocess worker for the fluid.health aggregator tests: boots a
REAL executor on one tiny program, steps it in a loop, and serves the
status plane on the port given in argv[1] (the parent sets
PADDLE_TRAINER_ID / PADDLE_TPU_STATUS_WORKERS / aggregation env the
way distributed/launch.py would).  Prints READY once the first step
completed; runs until killed or the argv[2] deadline (seconds)."""

import os
import sys
import time


def main():
    port = int(sys.argv[1])
    run_for = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor

    fluid.set_flags({'FLAGS_status_port': port})
    rank = os.environ.get('PADDLE_TRAINER_ID', '0')
    # a per-rank marker counter: the parent asserts the AGGREGATED
    # /metrics carries every worker's series
    monitor.add('health/test_marker_rank%s' % rank)

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 3
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 8, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)

    exe = fluid.Executor(fluid.XLAPlace(0))  # starts the status server
    exe.run(startup)
    feed = {'x': np.ones((4, 8), 'float32')}
    exe.run(main_p, feed=feed, fetch_list=[loss])
    print('READY', flush=True)
    deadline = time.time() + run_for
    while time.time() < deadline:
        exe.run(main_p, feed=feed, fetch_list=[loss])
        time.sleep(0.05)


if __name__ == '__main__':
    main()
