"""SP/EP at the MODEL level: GPT with MoE FFN blocks (GShard top-1 via
layers.moe) and GPT/BERT-style context-parallel attention
(layers.context_parallel_attention) — the same fluid program trains on
one device (dense fallbacks) and on a dp x sp x ep mesh, with loss
parity between the two paths."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import models
from paddle_tpu.parallel import mesh as pmesh


def _build_moe_gpt(seq_len, use_cp=False):
    cfg = models.gpt.GptConfig(
        vocab_size=97, hidden=64, layers=2, heads=4, max_pos=seq_len,
        dropout=0.0, moe_experts=4, moe_hidden=128,
        use_context_parallel=use_cp)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        feeds, logits, loss = models.gpt.build_lm(cfg, seq_len)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return cfg, main, startup, loss


def _train(main, startup, loss, feed, steps, compiled=None):
    out = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(steps):
            l, = exe.run(compiled if compiled is not None else main,
                         feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(l).ravel()[0]))
    return out


def test_moe_gpt_trains_and_matches_on_ep_mesh():
    seq = 16
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 97, (4, seq)).astype('int64')
    feed = models.gpt.lm_batch(ids)

    cfg, main, startup, loss = _build_moe_gpt(seq)
    single = _train(main, startup, loss, feed, 4)
    assert single[-1] < single[0], single

    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    cfg2, main2, startup2, loss2 = _build_moe_gpt(seq)
    comp = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name).with_mesh(mesh)
    sharded = _train(main2, startup2, loss2, feed, 4, compiled=comp)
    np.testing.assert_allclose(sharded, single, rtol=5e-3, atol=5e-4)
    # the MoE expert weights actually shard over 'ep'
    w1 = next(p for p in main2.all_parameters()
              if tuple(p.shape) == (4, 64, 128))
    hints = main2._sharding_hints
    assert hints[w1.name][0] == 'ep'


def test_context_parallel_gpt_matches_standard_attention():
    """use_context_parallel single-device == standard attention path
    (dense fallback runs the identical math)."""
    seq = 16
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 97, (4, seq)).astype('int64')
    feed = models.gpt.lm_batch(ids)

    def build(use_cp):
        cfg = models.gpt.GptConfig(
            vocab_size=97, hidden=64, layers=2, heads=4, max_pos=seq,
            dropout=0.0, use_flash=False,
            use_context_parallel=use_cp)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 23
        with fluid.program_guard(main, startup):
            feeds, logits, loss = models.gpt.build_lm(cfg, seq)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    main_a, st_a, loss_a = build(False)
    main_b, st_b, loss_b = build(True)
    base = _train(main_a, st_a, loss_a, feed, 3)
    cp = _train(main_b, st_b, loss_b, feed, 3)
    np.testing.assert_allclose(cp, base, rtol=2e-4, atol=2e-5)

    # and the cp program runs sharded on an sp mesh with the same curve
    mesh = pmesh.create_mesh(dp=2, sp=4)
    main_c, st_c, loss_c = build(True)
    comp = fluid.CompiledProgram(main_c).with_data_parallel(
        loss_name=loss_c.name).with_mesh(mesh)
    sharded = _train(main_c, st_c, loss_c, feed, 3, compiled=comp)
    np.testing.assert_allclose(sharded, base, rtol=1e-3, atol=1e-4)


def test_context_parallel_rejects_masked_attention():
    import pytest
    cfg = models.bert.BertConfig(vocab_size=100, hidden=32, layers=1,
                                 heads=2, intermediate=64, max_pos=32,
                                 dropout=0.0)
    cfg.use_context_parallel = True
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with pytest.raises(ValueError, match='context_parallel'):
            models.bert.build_pretrain(cfg, 16)
