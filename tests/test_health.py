"""fluid.health — status plane, Prometheus correctness, NaN
provenance, tensor-health summaries, and the flight-recorder dump
paths of every runner.

The acceptance contract: /metrics lints clean and /healthz//statusz
are schema-stable JSON; a tripped NaN check names the exact OP (type +
output var) that first produced the non-finite value, reports EVERY
bad var of the step, and embeds the provenance in the flight-recorder
dump; health summaries record norms/ratios and their detectors
auto-dump; dispatch failures dump from the CompiledPipeline and the
parallel/collective runners — not just the plain executor; and a real
two-process job aggregates into one scrape target whose readiness
flips when a worker dies."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import health, layers, monitor, trace


@pytest.fixture(autouse=True)
def _clean_health():
    yield
    fluid.set_flags({'FLAGS_check_nan_inf': False,
                     'FLAGS_health_summaries': False,
                     'FLAGS_health_zero_update_steps': 3,
                     'FLAGS_health_spike_factor': 10.0})
    health.reset_state()
    health.stop()
    trace.disable()
    trace.reset()


def _build(lr=0.01, seed=1):
    # square loss: gradients stay nonzero over the whole test window
    # (a relu head can die in two SGD steps and zero them)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 8)
        loss = layers.reduce_mean(layers.square(h))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, loss


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode('utf-8')
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode('utf-8')


# ------------------------------------------------- prometheus lint
def test_prometheus_text_lints_clean():
    monitor.add('executor/some_counter', 3)
    monitor.set_gauge('reader/queue_depth', 4)
    monitor.observe('executor/run_seconds', 0.01)
    text = monitor.prometheus_text()
    assert health.prom_lint(text) == []
    # HELP + TYPE metadata present for a counter family
    assert '# HELP paddle_tpu_executor_some_counter' in text
    assert '# TYPE paddle_tpu_executor_some_counter counter' in text


def test_prom_lint_catches_scrape_breakers():
    bad = '\n'.join([
        '# TYPE m counter',
        'm 1',
        'm 2',                      # duplicate series
        'orphan 5',                 # no TYPE/HELP
        '# TYPE h histogram',
        '# HELP h h',
        'h_bucket{le="1"} 5',
        'h_bucket{le="+Inf"} 3',    # not cumulative, != _count
        'h_sum 1.0',
        'h_count 4',
    ]) + '\n'
    problems = health.prom_lint(bad)
    text = '\n'.join(problems)
    assert 'duplicate series' in text
    assert 'no TYPE metadata' in text
    assert 'not cumulative' in text
    assert '+Inf bucket' in text
    assert any('HELP' in p for p in problems)


def test_prom_escaping_label_and_help():
    assert monitor.prom_escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert monitor.prom_escape_help('x\\y\nz') == 'x\\\\y\\nz'
    line = monitor.prom_sample('m', [('worker', 'a"b')], 1.0)
    assert line == 'm{worker="a\\"b"} 1'


def test_render_merged_sums_counters_and_labels_gauges():
    s1 = {'counters': {'executor/run_calls': 3.0},
          'gauges': {'reader/queue_depth': 2.0},
          'hists': {'executor/run_seconds': {
              'edges': [0.1, 1.0], 'counts': [2, 1, 0],
              'sum': 0.5, 'count': 3}}}
    s2 = {'counters': {'executor/run_calls': 4.0,
                       'rpc/calls': 1.0},
          'gauges': {'reader/queue_depth': 7.0},
          'hists': {'executor/run_seconds': {
              'edges': [0.1, 1.0], 'counts': [1, 0, 1],
              'sum': 1.5, 'count': 2}}}
    text = health.render_merged([('0', s1), ('1', s2)])
    assert health.prom_lint(text) == []
    assert 'paddle_tpu_executor_run_calls 7' in text
    assert 'paddle_tpu_rpc_calls 1' in text
    # gauges keep worker identity instead of summing
    assert 'paddle_tpu_reader_queue_depth{worker="0"} 2' in text
    assert 'paddle_tpu_reader_queue_depth{worker="1"} 7' in text
    # histogram merged: counts sum, +Inf == _count
    assert 'paddle_tpu_executor_run_seconds_bucket{le="+Inf"} 5' in text
    assert 'paddle_tpu_executor_run_seconds_count 5' in text


# ------------------------------------------------- status endpoints
def test_status_endpoints_serve_and_validate():
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': np.ones((4, 8), 'float32')},
                fetch_list=[loss])
    srv = monitor.serve(port=0)   # monitor.serve delegates to health
    assert srv.port > 0
    try:
        code, text = _get(srv.url + '/metrics')
        assert code == 200
        assert health.prom_lint(text) == []
        assert 'paddle_tpu_executor_run_calls' in text

        code, body = _get(srv.url + '/healthz')
        doc = json.loads(body)
        assert code == 200 and doc['ready'] is True
        assert doc['alive'] and doc['steps'] >= 1
        assert doc['last_step_age_s'] is not None

        code, body = _get(srv.url + '/statusz')
        doc = json.loads(body)
        assert code == 200
        assert 'rollup' in doc['step_report']
        assert 'segment_cache_hit' in doc['caches']
        assert 'FLAGS_status_port' in doc['flags']
        assert doc['versions'].get('jax')

        code, body = _get(srv.url + '/metrics.json')
        doc = json.loads(body)
        assert code == 200
        assert 'counters' in doc['state'] and 'hists' in doc['state']

        trace.enable(buffer_steps=4)
        with trace.step_span(1):
            with trace.span('dispatch'):
                pass
        code, body = _get(srv.url + '/trace/dump')
        doc = json.loads(body)
        assert code == 200
        assert doc['ptSteps'] and os.path.exists(doc['ptDumpPath'])

        code, body = _get(srv.url + '/nope')
        assert code == 404 and 'paths' in json.loads(body)
    finally:
        srv.stop()
    assert health.server() is None


def test_healthz_not_ready_before_first_step():
    monitor.reset()
    from paddle_tpu.fluid import compile_cache
    compile_cache.reset_plane()
    st = health.status()
    assert st['ready'] is False and st['reasons']
    monitor.add('executor/run_calls')
    assert health.status()['ready'] is True


def test_aggregator_marks_unreachable_worker_down():
    # no process listens on this endpoint: one probe flips it down
    agg = health._Aggregator('0', [('1', '127.0.0.1:9')], 0.2)
    try:
        agg.probe_once()
        doc = agg.healthz()
        assert doc['aggregated'] is True
        assert doc['workers']['1']['up'] is False
        assert doc['ready'] is False
        assert monitor.gauge_value('health/worker_up/1') == 0.0
        # merged text still renders (self only) and lints clean
        assert health.prom_lint(agg.metrics_text()) == []
    finally:
        agg.stop()


# ------------------------------------------------- NaN provenance
def test_nan_error_names_op_and_dumps_provenance():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        h = layers.scale(x, scale=2.0)
        y = layers.log(h)          # log(0) -> -inf: the culprit op
        z = layers.scale(y, scale=3.0)
    fluid.set_flags({'FLAGS_check_nan_inf': True})
    trace.enable(buffer_steps=4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        with pytest.raises(FloatingPointError) as ei:
            exe.run(main, feed={'x': np.zeros((2, 4), 'float32')},
                    fetch_list=[z])
    msg = str(ei.value)
    assert 'op [log]' in msg                   # exact op type
    assert y.name in msg                       # its output var
    assert 'nonfinite=100.0%' in msg           # output stats
    assert 'min=0.0' in msg                    # input stats
    assert 'dumped to' in msg                  # flight recorder path
    path = msg.rsplit('dumped to ', 1)[1].strip()
    doc = json.load(open(path))
    inc = doc['ptIncident']
    assert inc['kind'] == 'nan_check'
    assert inc['provenance']['op_type'] == 'log'
    assert inc['provenance']['outputs'] == [y.name]
    assert monitor.counter_value('health/nan_trips') >= 1.0


def test_nan_check_reports_every_bad_var():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y1 = layers.log(x)                     # -inf
        y2 = layers.scale(y1, scale=2.0)       # still -inf
    fluid.set_flags({'FLAGS_check_nan_inf': True})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        with pytest.raises(FloatingPointError) as ei:
            exe.run(main, feed={'x': np.zeros((2, 4), 'float32')},
                    fetch_list=[y1, y2])
    first = str(ei.value).splitlines()[0]
    assert '2 var(s)' in first
    assert y1.name in first and y2.name in first


def test_nan_replay_flag_off_still_reports_vars():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.log(x)
    fluid.set_flags({'FLAGS_check_nan_inf': True,
                     'FLAGS_nan_replay': False})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            with pytest.raises(FloatingPointError) as ei:
                exe.run(main,
                        feed={'x': np.zeros((2, 4), 'float32')},
                        fetch_list=[y])
        assert y.name in str(ei.value)
        assert 'produced by op' not in str(ei.value)
    finally:
        fluid.set_flags({'FLAGS_nan_replay': True})


# ------------------------------------------------- tensor health
def test_health_summaries_record_norms_and_ratios():
    fluid.set_flags({'FLAGS_health_summaries': True})
    monitor.reset()
    health.reset_state()
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(4):
            exe.run(main, feed={'x': np.ones((4, 8), 'float32')},
                    fetch_list=[loss])
    assert monitor.counter_value('health/summary_steps') >= 4.0
    assert monitor.counter_value('health/summary_errors') == 0.0
    gh = monitor.histogram_value('health/grad_norm')
    assert gh and gh['count'] >= 4      # param grads surfaced
    uh = monitor.histogram_value('health/update_ratio')
    assert uh and uh['count'] >= 4
    assert monitor.histogram_value('health/global_grad_norm')['count'] \
        >= 4
    assert monitor.gauge_value('health/last_global_grad_norm') > 0.0
    # an SGD step with lr>0 and nonzero grads must NOT look dead
    assert monitor.counter_value('health/zero_update_trips') == 0.0
    # and a healthy run must not false-positive the spike detector
    # (the grad-free startup program must not seed the EMA at zero)
    assert monitor.counter_value('health/grad_spikes') == 0.0


def test_zero_update_detector_dumps_flight_recorder():
    fluid.set_flags({'FLAGS_health_summaries': True,
                     'FLAGS_health_zero_update_steps': 2})
    monitor.reset()
    health.reset_state()
    trace.enable(buffer_steps=4)
    main, startup, loss = _build(lr=0.0)   # frozen optimizer
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(4):
            exe.run(main, feed={'x': np.ones((4, 8), 'float32')},
                    fetch_list=[loss])
    assert monitor.counter_value('health/zero_update_trips') == 1.0
    assert monitor.counter_value('health/detector_dumps') >= 1.0


def test_grad_spike_detector():
    fluid.set_flags({'FLAGS_health_summaries': True,
                     'FLAGS_health_spike_factor': 5.0})
    monitor.reset()
    health.reset_state()
    trace.enable(buffer_steps=4)
    main, startup, loss = _build(lr=1e-4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        small = {'x': np.ones((4, 8), 'float32') * 0.01}
        for _ in range(3):
            exe.run(main, feed=small, fetch_list=[loss])
        huge = {'x': np.ones((4, 8), 'float32') * 1e6}
        exe.run(main, feed=huge, fetch_list=[loss])
    assert monitor.counter_value('health/grad_spikes') >= 1.0
    assert monitor.counter_value('health/detector_dumps') >= 1.0


def test_summaries_off_costs_nothing():
    assert not fluid.flags.get_flag('FLAGS_health_summaries')
    monitor.reset()
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={'x': np.ones((4, 8), 'float32')},
                    fetch_list=[loss])
    assert monitor.counter_value('health/summary_steps') == 0.0
    assert monitor.histogram_value('health/grad_norm') is None


# ---------------------------------------- dispatch-failure dump paths
def test_pipeline_dispatch_failure_dumps_flight_recorder():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 8, act='relu')
        mid = main.current_block().create_var(
            name='hmid', shape=[-1, 8], dtype='float32')
        layers.py_func(lambda a: a, h, mid)   # host op: pipeline plan
        h2 = layers.fc(mid, 4)
        loss = layers.reduce_mean(h2)
    trace.enable(buffer_steps=4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        pipe = exe.compile(main, feed_names=['x'],
                           fetch_names=[loss.name], allow_host=True)
        d0 = monitor.counter_value('trace/dumps_written')
        with pytest.raises(Exception):
            # inner dim 7 violates the fc weights: segment fails
            pipe(feed={'x': np.ones((4, 7), 'float32')})
        assert monitor.counter_value('trace/dumps_written') == d0 + 1


def test_parallel_runner_dispatch_failure_dumps():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 8)
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    trace.enable(buffer_steps=4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        d0 = monitor.counter_value('trace/dumps_written')
        with pytest.raises(Exception):
            exe.run(cp, feed={'x': np.ones((8, 7), 'float32')},
                    fetch_list=[loss])
        assert monitor.counter_value('trace/dumps_written') == d0 + 1


def test_collective_runner_dispatch_failure_dumps():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 8)
        loss = layers.reduce_mean(h)
    main._collective_dp = True    # fleet GradAllReduce posture
    trace.enable(buffer_steps=4)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        d0 = monitor.counter_value('trace/dumps_written')
        with pytest.raises(Exception):
            exe.run(main, feed={'x': np.ones((8, 7), 'float32')},
                    fetch_list=[loss])
        assert monitor.counter_value('trace/dumps_written') == d0 + 1


# ------------------------------------------------- two-process job
def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_ready(proc, url, deadline):
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError('worker died: rc=%d' % proc.returncode)
        try:
            code, _body = _get(url + '/healthz/local', timeout=2)
            if code == 200:
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError('worker at %s never became ready' % url)


def test_two_process_aggregated_metrics_and_failover():
    """Acceptance: rank 0's aggregated /metrics carries both workers'
    counters; killing one worker flips aggregated /healthz readiness
    within one heartbeat interval."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, 'health_worker.py')
    p0, p1 = _free_port(), _free_port()
    spec = '0=127.0.0.1:%d,1=127.0.0.1:%d' % (p0, p1)
    base_env = dict(os.environ)
    base_env.update({'JAX_PLATFORMS': 'cpu',
                     'PADDLE_TPU_STATUS_WORKERS': spec,
                     'FLAGS_health_heartbeat_seconds': '0.5'})
    env0 = dict(base_env, PADDLE_TRAINER_ID='0',
                PADDLE_TPU_STATUS_AGGREGATE='1')
    env1 = dict(base_env, PADDLE_TRAINER_ID='1',
                PADDLE_TPU_STATUS_AGGREGATE='0')
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p1), '120'], env=env1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(p0), '120'], env=env0,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        deadline = time.time() + 180
        agg = 'http://127.0.0.1:%d' % p0
        wrk = 'http://127.0.0.1:%d' % p1
        _wait_ready(procs[0], wrk, deadline)
        _wait_ready(procs[1], agg, deadline)

        # aggregated readiness: both workers up within a heartbeat
        doc = None
        for _ in range(40):
            code, body = _get(agg + '/healthz')
            doc = json.loads(body)
            if code == 200:
                break
            time.sleep(0.25)
        assert doc['aggregated'] is True
        assert doc['workers']['0']['ready'] is True
        assert doc['workers']['1']['up'] is True

        # merged /metrics: BOTH workers' marker counters in one blob
        code, text = _get(agg + '/metrics')
        assert code == 200
        assert health.prom_lint(text) == []
        assert 'paddle_tpu_health_test_marker_rank0 1' in text
        assert 'paddle_tpu_health_test_marker_rank1 1' in text
        # run_calls merged = sum of both workers (> either alone)
        code, body = _get(wrk + '/metrics.json')
        w1_calls = json.loads(body)['state']['counters'][
            'executor/run_calls']
        merged = dict(
            line.rsplit(' ', 1)
            for line in text.splitlines()
            if line and not line.startswith('#') and '{' not in line)
        assert float(merged['paddle_tpu_executor_run_calls']) > \
            w1_calls
        assert 'paddle_tpu_health_agg_worker_up{worker="1"' in text

        # kill worker 1: readiness flips within one heartbeat interval
        procs[0].kill()
        procs[0].wait(timeout=10)
        flipped = False
        for _ in range(20):        # 0.5s heartbeat + slack
            time.sleep(0.25)
            code, body = _get(agg + '/healthz')
            if code == 503:
                doc = json.loads(body)
                assert doc['workers']['1']['up'] is False
                flipped = True
                break
        assert flipped, 'aggregated readiness never flipped after kill'
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass
