"""Gradients through while / conditional_block sub-blocks.

Reference behavior: WhileGradOp and ConditionalBlockGradOp
(/root/reference/paddle/fluid/operators/controlflow/while_op.cc,
conditional_block_op.cc), wired by
/root/reference/python/paddle/fluid/backward.py:876.  TPU-native
re-design: the forward op saves its carry ENTRY values; the grad op
re-runs the sub-block functionally under jax.vjp (loops as a bounded
masked lax.scan — hence While(max_trip_count=N) — branches as lax.cond).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _run(main, startup, feed, fetch):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


def _build_while_prog(max_trip_count=4):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[2, 4], dtype='float32',
                        append_batch_size=False)
        x.stop_gradient = False
        w = layers.create_parameter(
            [4], 'float32', name='w_loop',
            default_initializer=fluid.initializer.Constant(1.5))
        i = layers.fill_constant([1], 'float32', 0)
        n = layers.fill_constant([1], 'float32', 3)
        acc = layers.fill_constant([2, 4], 'float32', 0.0)
        cond = layers.less_than(i, n)
        wh = layers.While(cond, max_trip_count=max_trip_count)
        with wh.block():
            t = layers.elementwise_mul(acc, w)
            t2 = layers.elementwise_add(t, x)
            layers.assign(t2, acc)
            layers.increment(i)
            layers.assign(layers.less_than(i, n), cond)
        loss = layers.mean(acc)
    return main, startup, x, w, acc, loss


def test_while_grad_analytic():
    # acc_{k+1} = acc_k * w + x, acc_0 = 0, 3 trips:
    #   acc_3 = x * (w^2 + w + 1)
    #   dloss/dx = (w^2 + w + 1) / N,  dloss/dw = sum_b x * (2w + 1) / N
    main, startup, x, w, acc, loss = _build_while_prog()
    pg = fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    assert any(p.name == 'w_loop' for p, g in pg)
    wgrad = dict((p.name, g.name) for p, g in pg)['w_loop']

    rng = np.random.RandomState(0)
    xv = rng.randn(2, 4).astype('float32')
    out = _run(main, startup, {'x': xv}, [loss, gmap['x'], wgrad])
    lossv, dx, dw = out
    wv = 1.5
    N = 8.0
    acc3 = xv * (wv ** 2 + wv + 1)
    np.testing.assert_allclose(lossv, acc3.mean(), rtol=1e-5)
    np.testing.assert_allclose(
        dx, np.full((2, 4), (wv ** 2 + wv + 1) / N), rtol=1e-5)
    # d acc3/dw = x * (2w + 1)
    np.testing.assert_allclose(
        dw, (xv * (2 * wv + 1)).sum(0) / N, rtol=1e-4)


def test_while_grad_numeric():
    main, startup, x, w, acc, loss = _build_while_prog()
    fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 4).astype('float32')
    lossv, dx = _run(main, startup, {'x': xv}, [loss, gmap['x']])
    eps = 1e-3
    for idx in [(0, 0), (1, 2)]:
        xp, xm = xv.copy(), xv.copy()
        xp[idx] += eps
        xm[idx] -= eps
        lp, = _run(main, startup, {'x': xp}, [loss])
        lm, = _run(main, startup, {'x': xm}, [loss])
        num = (lp - lm) / (2 * eps)
        np.testing.assert_allclose(dx[idx], num, rtol=2e-2, atol=1e-4)


def test_while_grad_unbounded_auto_bucket():
    """Round 3: While WITHOUT max_trip_count differentiates — the
    executor counts trips on the host, buckets to the next power of
    two, and compiles the masked scan at that bucket (the reference's
    WhileGradOp handles dynamic trip counts by replaying step scopes,
    while_op.cc).  Gradients match the bounded build exactly."""
    main, startup, x, w, acc, loss = _build_while_prog(
        max_trip_count=None)
    pg = fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    wgrad = dict((p.name, g.name) for p, g in pg)['w_loop']
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 4).astype('float32')
    lossv, dx, dw = _run(main, startup, {'x': xv},
                         [loss, gmap['x'], wgrad])
    wv, N = 1.5, 8.0
    np.testing.assert_allclose(
        lossv, (xv * (wv ** 2 + wv + 1)).mean(), rtol=1e-5)
    np.testing.assert_allclose(
        dx, np.full((2, 4), (wv ** 2 + wv + 1) / N), rtol=1e-5)
    np.testing.assert_allclose(
        dw, (xv * (2 * wv + 1)).sum(0) / N, rtol=1e-4)


def test_while_grad_unbounded_data_dependent_trips():
    """Trip count depends on a FED value: the same compiled program
    serves different trip counts; counts in one power-of-two bucket
    reuse one executable, the truncation NaN guard never fires because
    the bucket always covers the measured count."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[2, 4], dtype='float32',
                        append_batch_size=False)
        x.stop_gradient = False
        n = layers.data('n', shape=[1], dtype='float32',
                        append_batch_size=False)
        i = layers.fill_constant([1], 'float32', 0)
        acc = layers.fill_constant([2, 4], 'float32', 0.0)
        cond = layers.less_than(i, n)
        wh = layers.While(cond)  # no bound
        with wh.block():
            layers.assign(layers.elementwise_add(
                layers.scale(acc, scale=0.5), x), acc)
            layers.increment(i)
            layers.assign(layers.less_than(i, n), cond)
        loss = layers.mean(acc)
    fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    rng = np.random.RandomState(1)
    xv = rng.randn(2, 4).astype('float32')

    def expect(trips):
        # acc_T = x * sum_{j<T} 0.5^j; dloss/dx = that sum / 8
        s = sum(0.5 ** j for j in range(trips))
        return (xv * s).mean(), np.full((2, 4), s / 8.0, 'float32')

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for trips in (3, 4, 7, 2, 16):
            nv = np.array([float(trips)], 'float32')
            lossv, dx = exe.run(main, feed={'x': xv, 'n': nv},
                                fetch_list=[loss, gmap['x']])
            want_l, want_dx = expect(trips)
            np.testing.assert_allclose(
                float(np.asarray(lossv).ravel()[0]), want_l,
                rtol=1e-5, err_msg='trips=%d' % trips)
            np.testing.assert_allclose(np.asarray(dx), want_dx,
                                       rtol=1e-5,
                                       err_msg='trips=%d' % trips)


def test_while_grad_unbounded_write_only_carry():
    """An unbounded loop whose body WRITES a parent var it never reads
    (assign into a pre-initialized output): the trip-count pass must
    seed that carry from the scope and the segment DCE must keep its
    initializer alive (executor._op_dep_reads)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[2, 4], dtype='float32',
                        append_batch_size=False)
        x.stop_gradient = False
        i = layers.fill_constant([1], 'float32', 0)
        n = layers.fill_constant([1], 'float32', 3)
        acc = layers.fill_constant([2, 4], 'float32', 0.0)
        y = layers.fill_constant([2, 4], 'float32', 0.0)
        cond = layers.less_than(i, n)
        wh = layers.While(cond)  # no bound -> auto-bucket
        with wh.block():
            layers.assign(layers.elementwise_add(acc, x), acc)
            # y is written from the loop state but never read inside
            layers.assign(layers.scale(acc, scale=2.0), y)
            layers.increment(i)
            layers.assign(layers.less_than(i, n), cond)
        loss = layers.elementwise_add(layers.mean(acc), layers.mean(y))
    fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    rng = np.random.RandomState(2)
    xv = rng.randn(2, 4).astype('float32')
    # acc_3 = 3x, y = 2*acc_3 = 6x -> loss = 9*mean(x), dx = 9/8
    lossv, dx = _run(main, startup, {'x': xv}, [loss, gmap['x']])
    np.testing.assert_allclose(float(np.asarray(lossv).ravel()[0]),
                               9 * xv.mean(), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx),
                               np.full((2, 4), 9 / 8.0, 'float32'),
                               rtol=1e-5)


def test_unbounded_while_compile_refusal_names_the_cause():
    """Executor.compile on a program whose only cut is an auto-bucketed
    unbounded while must name the loop (not claim 'host ops'), and
    allow_host=True must compile a working pipeline with no host ops
    reported."""
    main, startup, x, w, acc, loss = _build_while_prog(
        max_trip_count=None)
    fluid.backward.append_backward(loss)
    exe = fluid.Executor(fluid.XLAPlace(0))
    with pytest.raises(ValueError, match='max_trip_count'):
        exe.compile(main, feed_names=('x',), fetch_names=(loss.name,))
    pipe = exe.compile(main, feed_names=('x',),
                       fetch_names=(loss.name,), allow_host=True)
    assert pipe.host_op_types == []
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 4).astype('float32')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step0 = exe._step
        got, = pipe({'x': xv}, scope=scope)
        assert exe._step == step0 + 1  # pipeline advances the RNG step
    wv = 1.5
    np.testing.assert_allclose(float(np.asarray(got).ravel()[0]),
                               (xv * (wv ** 2 + wv + 1)).mean(),
                               rtol=1e-5)


def test_while_early_exit_masking():
    # max_trip_count=8 > 3 actual trips: masked iterations must not
    # contribute to values or gradients
    main, startup, x, w, acc, loss = _build_while_prog(max_trip_count=8)
    fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    xv = np.ones((2, 4), np.float32)
    lossv, dx = _run(main, startup, {'x': xv}, [loss, gmap['x']])
    wv = 1.5
    np.testing.assert_allclose(lossv, (wv ** 2 + wv + 1), rtol=1e-5)
    np.testing.assert_allclose(
        dx, np.full((2, 4), (wv ** 2 + wv + 1) / 8.0), rtol=1e-5)


def _build_cond_prog(pred_value):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[2, 4], dtype='float32',
                        append_batch_size=False)
        x.stop_gradient = False
        w = layers.create_parameter(
            [4], 'float32', name='w_cond',
            default_initializer=fluid.initializer.Constant(2.0))
        pred = layers.fill_constant([1], 'bool', pred_value)
        out = layers.cond(
            pred,
            lambda: layers.elementwise_mul(
                layers.scale(x, scale=3.0), w),
            lambda: layers.elementwise_mul(x, w))
        loss = layers.mean(out)
    return main, startup, x, loss


@pytest.mark.parametrize('pred_value', [True, False])
def test_cond_grad(pred_value):
    # loss = mean(3*x*w) if pred else mean(x*w); dloss/dx = 3w/N or w/N
    main, startup, x, loss = _build_cond_prog(pred_value)
    fluid.backward.append_backward(loss)
    gmap = main._grad_name_map
    rng = np.random.RandomState(2)
    xv = rng.randn(2, 4).astype('float32')
    lossv, dx = _run(main, startup, {'x': xv}, [loss, gmap['x']])
    wv, N = 2.0, 8.0
    k = 3.0 if pred_value else 1.0
    np.testing.assert_allclose(lossv, (k * xv * wv).mean(), rtol=1e-5)
    np.testing.assert_allclose(dx, np.full((2, 4), k * wv / N),
                               rtol=1e-5)


def test_while_training_parity_with_unrolled():
    """A layers.While training loop reaches the same losses as the
    identical unrolled program (VERDICT round-1 'done' criterion)."""
    T = 3

    def build(use_while):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[4, 6], dtype='float32',
                                append_batch_size=False)
            y = layers.data('y', shape=[4, 6], dtype='float32',
                                append_batch_size=False)
            w = layers.create_parameter(
                [6, 6], 'float32', name='w_rnn',
                default_initializer=fluid.initializer.Constant(0.05))
            if use_while:
                i = layers.fill_constant([1], 'float32', 0)
                n = layers.fill_constant([1], 'float32', T)
                h = layers.fill_constant([4, 6], 'float32', 0.0)
                cond = layers.less_than(i, n)
                wh = layers.While(cond, max_trip_count=T + 1)
                with wh.block():
                    hn = layers.tanh(
                        layers.elementwise_add(layers.matmul(h, w), x))
                    layers.assign(hn, h)
                    layers.increment(i)
                    layers.assign(layers.less_than(i, n), cond)
            else:
                h = layers.fill_constant([4, 6], 'float32', 0.0)
                for _ in range(T):
                    h = layers.tanh(
                        layers.elementwise_add(layers.matmul(h, w), x))
            d = layers.elementwise_sub(h, y)
            loss = layers.mean(layers.square(d))
            fluid.optimizer.SGD(0.5).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(7)
    xv = rng.randn(4, 6).astype('float32')
    yv = rng.randn(4, 6).astype('float32')

    curves = []
    for use_while in (True, False):
        main, startup, loss = build(use_while)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            losses = []
            for _ in range(5):
                l, = exe.run(main, feed={'x': xv, 'y': yv},
                             fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        curves.append(losses)
    np.testing.assert_allclose(curves[0], curves[1], rtol=1e-5)
    assert curves[0][-1] < curves[0][0]


def test_while_truncation_poisons_with_nan():
    """If max_trip_count underestimates the real trip count, the loop
    must fail LOUDLY (NaN outputs) instead of silently computing the
    truncated recurrence."""
    main, startup, x, w, acc, loss = _build_while_prog(max_trip_count=2)
    fluid.backward.append_backward(loss)
    xv = np.ones((2, 4), np.float32)
    lossv, = _run(main, startup, {'x': xv}, [loss])
    assert not np.isfinite(np.asarray(lossv)).all(), lossv
