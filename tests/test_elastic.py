"""Elastic resilience plane (fluid/elastic.py + fluid/faultinject.py
+ the rpc/heartbeat retry satellites): crash-consistent manifest-led
checkpoints (kill -9 mid-save leaves a loadable last-good generation,
torn shards refused BY NAME), cross-topology resharding (dp4 -> dp2,
dp2 -> fsdp2 x tp1 on the CPU mesh, parameters bitwise-preserved,
resumed loss trajectories at parity), bounded retry/backoff with
per-call deadlines, and heartbeat miss tolerance."""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import elastic, faultinject, layers, monitor
from paddle_tpu.parallel import plan as ashard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ELASTIC_FLAGS = ('FLAGS_elastic_checkpoint', 'FLAGS_auto_shard',
                 'FLAGS_faultinject', 'FLAGS_elastic_keep_generations',
                 'FLAGS_rpc_backoff_ms', 'FLAGS_rpc_backoff_max_ms')


@pytest.fixture(autouse=True)
def _clean():
    prev = fluid.get_flags(list(ELASTIC_FLAGS))
    monitor.reset()
    elastic.reset()
    faultinject.reset()
    ashard.reset()
    yield
    fluid.set_flags(prev)
    faultinject.reset()
    elastic.reset()
    ashard.reset()
    monitor.reset()


def _build(seed=7, hidden=32, optimizer='adam'):
    from paddle_tpu.fluid import unique_name
    # unique_name.guard(): deterministic param names (fc_0.w_0, ...)
    # regardless of what earlier tests built in this process — the
    # manifest names must match across the save/load (and subprocess)
    # boundary, and the missing-var guard rightly refuses otherwise
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[16], dtype='float32')
            h = layers.fc(x, hidden, act='relu')
            h2 = layers.fc(h, 16)
            loss = layers.reduce_mean(h2)
            if optimizer == 'adam':
                fluid.optimizer.Adam(0.01).minimize(loss)
            else:
                fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed(seed=3, n=8):
    return {'x': np.random.RandomState(seed).randn(n, 16)
            .astype('float32')}


def _f(val):
    return float(np.asarray(val).ravel()[0])


# ------------------------------------------------------------ faultinject
def test_faultinject_spec_parse_and_determinism():
    faultinject.configure('a.site:delay:0.001@2;b.site:torn@3+')
    assert faultinject.armed()
    # clause fires on exactly the 2nd hit of a.site
    assert faultinject.check('a.site') is None
    assert faultinject.check('a.site') is None   # delay executed inline
    assert faultinject.fired('a.site') == 1
    assert faultinject.check('a.site') is None
    assert faultinject.fired('a.site') == 1      # @2 exact, not @2+
    # @3+ fires on the 3rd and every later hit, returning the clause
    assert faultinject.check('b.site') is None
    assert faultinject.check('b.site') is None
    c = faultinject.check('b.site')
    assert c is not None and c['action'] == 'torn'
    assert faultinject.check('b.site')['action'] == 'torn'
    assert faultinject.fired('b.site') == 2
    rep = faultinject.report()
    assert rep['armed'] and rep['hits']['a.site'] == 3
    with pytest.raises(ValueError):
        faultinject.configure('missing-action-clause')
    with pytest.raises(ValueError):
        faultinject.configure('site:explode')
    faultinject.reset()
    assert not faultinject.armed()
    assert faultinject.check('a.site') is None


def test_faultinject_exact_clause_beats_open_ended():
    """'rpc.call:delay@1+;rpc.call:fail@3' — the documented combined
    spec: the one-shot exact clause must fire on its hit even though
    an open-ended clause also matches every hit."""
    faultinject.configure('s:delay:0.0@1+;s:fail@3')
    assert faultinject.check('s') is None          # hit 1: delay
    assert faultinject.check('s') is None          # hit 2: delay
    with pytest.raises(ConnectionError):
        faultinject.check('s')                     # hit 3: fail@3
    assert faultinject.check('s') is None          # hit 4: delay again


def test_faultinject_fail_action_raises_transport_error():
    faultinject.configure('x.y:fail@1')
    with pytest.raises(ConnectionError):
        faultinject.check('x.y')
    faultinject.configure('x.y:raise@1')
    with pytest.raises(faultinject.FaultInjected):
        faultinject.check('x.y')


# ----------------------------------------------------- save/load roundtrip
def test_save_load_roundtrip_bitwise_with_adam_state():
    main, startup, loss = _build()
    feed = _feed()
    d = tempfile.mkdtemp(prefix='pt_el_')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        gen = elastic.save_checkpoint(d, main, executor=exe)
        step_at_save = exe._step
        ref = [_f(exe.run(main, feed=feed, fetch_list=[loss])[0])
               for _ in range(3)]
    assert gen == 1 and elastic.latest_generation(d) == 1
    # fresh process-state: new scope + executor; Adam moments are
    # persistable, so the resumed trajectory must be BITWISE identical
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        info = elastic.load_checkpoint(d, main, executor=exe2)
        assert info['generation'] == 1
        assert exe2._step == step_at_save
        got = [_f(exe2.run(main, feed=feed, fetch_list=[loss])[0])
               for _ in range(3)]
    assert got == ref, (got, ref)
    # same topology: every param keeps its grid (zero-wire schedule)
    assert set(info['reshard']['by_kind']) == {'keep'}
    assert info['reshard']['wire_bytes'] == 0


def test_io_wiring_flag_save_and_autodetect_load():
    main, startup, loss = _build(optimizer='sgd')
    feed = _feed()
    d = tempfile.mkdtemp(prefix='pt_el_')
    fluid.set_flags({'FLAGS_elastic_checkpoint': True})
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        fluid.io.save_persistables(exe, d, main)
        ref = _f(exe.run(main, feed=feed, fetch_list=[loss])[0])
    assert elastic.is_elastic_store(d)
    # load_persistables detects the store even with the flag OFF
    fluid.set_flags({'FLAGS_elastic_checkpoint': False})
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        fluid.io.load_persistables(exe2, d, main)
        got = _f(exe2.run(main, feed=feed, fetch_list=[loss])[0])
    assert got == ref


def test_native_save_stays_default_and_atomic():
    """Flag off: save_persistables keeps the one-.npz native format,
    published atomically (no tmp debris)."""
    main, startup, loss = _build(optimizer='sgd')
    d = tempfile.mkdtemp(prefix='pt_el_')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fluid.io.save_persistables(exe, d, main)
    assert os.path.exists(os.path.join(d, '__model_params__.npz'))
    assert not elastic.is_elastic_store(d)
    assert not [e for e in os.listdir(d) if '.tmp' in e]


# --------------------------------------------------- crash consistency
_CHILD = r'''
import os, sys
import numpy as np
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import elastic, faultinject, layers
main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 7
with fluid.program_guard(main, startup):
    x = layers.data('x', shape=[16], dtype='float32')
    h = layers.fc(x, 32, act='relu')
    h2 = layers.fc(h, 16)
    loss = layers.reduce_mean(h2)
    fluid.optimizer.Adam(0.01).minimize(loss)
feed = {'x': np.random.RandomState(3).randn(8, 16).astype('float32')}
exe = fluid.Executor(fluid.XLAPlace(0))
exe.run(startup)
exe.run(main, feed=feed, fetch_list=[loss])
d = sys.argv[1]
elastic.save_checkpoint(d, main, executor=exe)        # gen 1: clean
exe.run(main, feed=feed, fetch_list=[loss])
faultinject.configure(sys.argv[2])
elastic.save_checkpoint(d, main, executor=exe)        # gen 2: injected
print('SURVIVED')
'''


def _run_child(d, spec):
    return subprocess.run(
        [sys.executable, '-c', _CHILD, d, spec], capture_output=True,
        text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))


def test_kill9_mid_save_leaves_loadable_last_good():
    d = tempfile.mkdtemp(prefix='pt_el_')
    p = _run_child(d, 'elastic.shard_write:die@3')
    assert p.returncode == 9, (p.returncode, p.stderr[-1500:])
    assert 'SURVIVED' not in p.stdout
    # the torn save never published: only staging debris, gen 1 intact
    assert elastic.list_generations(d) == [1]
    assert elastic.latest_generation(d) == 1
    elastic.verify_generation(d, 1)
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        info = elastic.load_checkpoint(d, main, executor=exe)
    assert info['generation'] == 1


def test_torn_published_generation_refused_by_name():
    d = tempfile.mkdtemp(prefix='pt_el_')
    p = _run_child(d, 'elastic.shard_write:torn@2')
    assert p.returncode == 0, p.stderr[-1500:]
    assert elastic.list_generations(d) == [1, 2]
    # explicit load of the torn generation names the shard
    with pytest.raises(elastic.ElasticCheckpointError) as ei:
        elastic.verify_generation(d, 2)
    assert ei.value.reason == 'torn_shard'
    assert ei.value.shard and ei.value.shard.endswith('.npy')
    assert ei.value.shard in str(ei.value)
    # default load refuses gen 2 (counted + recorded) and falls back
    main, startup, loss = _build()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        info = elastic.load_checkpoint(d, main, executor=exe)
    assert info['generation'] == 1
    assert monitor.counter_value('elastic/refused_generations') == 1.0
    rep = elastic.report()
    assert rep['refusals'][-1]['reason'] == 'torn_shard'
    assert rep['refusals'][-1]['shard'] == ei.value.shard


def test_every_generation_torn_raises_no_generation():
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        elastic.save_checkpoint(d, main, executor=exe)
    # tear the only generation by hand
    gdir = os.path.join(d, 'gen-00000001')
    shard = [e for e in os.listdir(gdir) if e.endswith('.npy')][0]
    with open(os.path.join(gdir, shard), 'r+b') as f:
        f.truncate(8)
    with pytest.raises(elastic.ElasticCheckpointError) as ei:
        with fluid.scope_guard(fluid.Scope()):
            elastic.load_checkpoint(d, main)
    assert ei.value.reason == 'no_generation'


def test_stale_latest_pointer_neither_wedges_saves_nor_hides_newest():
    """A crash between a generation's rename and the LATEST update
    leaves a stale pointer: saves must keep numbering from the newest
    PUBLISHED generation (not collide), and loads must prefer it."""
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    feed = _feed()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        elastic.save_checkpoint(d, main, executor=exe)
        exe.run(main, feed=feed, fetch_list=[loss])
        elastic.save_checkpoint(d, main, executor=exe)
    with open(os.path.join(d, 'LATEST'), 'w') as f:
        f.write('1')                     # the stale pointer
    assert elastic.latest_generation(d) == 2
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        info = elastic.load_checkpoint(d, main, executor=exe2)
        assert info['generation'] == 2   # newest, not the pointer
        gen = elastic.save_checkpoint(d, main, executor=exe2)
    assert gen == 3                      # no collision with gen-2


def test_missing_persistable_refused_loudly():
    """A program persistable absent from the checkpoint (optimizer
    switched after the save) must raise, not silently train from
    fresh init — the native load_vars guard, kept."""
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        elastic.save_checkpoint(d, main, executor=exe)
    main2, startup2, loss2 = _build(optimizer='adam')  # adds moments
    with pytest.raises(elastic.ElasticCheckpointError) as ei:
        with fluid.scope_guard(fluid.Scope()):
            exe2 = fluid.Executor(fluid.XLAPlace(0))
            elastic.load_checkpoint(d, main2, executor=exe2)
    assert ei.value.reason == 'missing_var'
    assert 'moment' in str(ei.value)


def test_generations_pruned_to_keep_limit():
    fluid.set_flags({'FLAGS_elastic_keep_generations': 2})
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(4):
            elastic.save_checkpoint(d, main, executor=exe)
    assert elastic.list_generations(d) == [3, 4]
    assert elastic.latest_generation(d) == 4


def test_prune_never_evicts_last_intact_generation():
    """Torn NEWER generations must not count toward the keep limit:
    after two torn saves over one good generation, the good one
    survives pruning and still loads."""
    fluid.set_flags({'FLAGS_elastic_keep_generations': 2})
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        elastic.save_checkpoint(d, main, executor=exe)       # gen 1
        faultinject.configure('elastic.shard_write:torn@1+')
        elastic.save_checkpoint(d, main, executor=exe)       # torn 2
        elastic.save_checkpoint(d, main, executor=exe)       # torn 3
        faultinject.reset()
    assert 1 in elastic.list_generations(d)
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        info = elastic.load_checkpoint(d, main, executor=exe2)
    assert info['generation'] == 1
    assert monitor.counter_value('elastic/refused_generations') >= 2


# -------------------------------------------------- cross-topology reshard
def _params_bytes(names, scope):
    return {n: np.asarray(scope.find_var(n)).tobytes() for n in names}


def _run_layout(main, startup, loss, feed, layout, ndev, steps,
                ckpt=None, save_at=None, save_dir=None):
    """Train `steps` under an injected auto-shard plan; optionally
    load `ckpt` first / save at step `save_at`.  Returns (losses,
    param bytes AT SAVE TIME (else at end), plan)."""
    plan = ashard.build_plan(main, ndev=ndev, layouts=[layout])
    losses = []
    names = [p.name for p in main.all_parameters()]
    param_bytes = None
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        comp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name,
            places=[fluid.XLAPlace(i) for i in range(ndev)])
        comp._auto_plan = plan
        if ckpt is not None:
            elastic.load_checkpoint(ckpt, main, executor=exe,
                                    plan=plan)
        else:
            exe.run(startup)
        for i in range(steps):
            l, = exe.run(comp, feed=feed, fetch_list=[loss])
            losses.append(_f(l))
            if save_at is not None and i + 1 == save_at:
                elastic.save_checkpoint(save_dir, main, executor=exe)
                param_bytes = _params_bytes(names,
                                            fluid.global_scope())
        if param_bytes is None:
            param_bytes = _params_bytes(names, fluid.global_scope())
    return losses, param_bytes, plan


def test_reshard_dp4_to_dp2_loss_parity():
    fluid.set_flags({'FLAGS_auto_shard': True})
    main, startup, loss = _build()
    feed = _feed(n=8)           # 8 divides every dp extent used here
    d = tempfile.mkdtemp(prefix='pt_el_')
    pre, saved_params, _ = _run_layout(
        main, startup, loss, feed, (4, 1, 1), 4, 4, save_at=2,
        save_dir=d)
    # resume at dp2: parameters bitwise-preserved through the reshard,
    # trajectory at parity with the dp4 continuation (float summation
    # order differs across device counts), and bitwise-REPRODUCIBLE —
    # two resumes from the same generation agree exactly
    got1, p1, _ = _run_layout(main, startup, loss, feed, (2, 1, 1), 2,
                              2, ckpt=d)
    got2, p2, _ = _run_layout(main, startup, loss, feed, (2, 1, 1), 2,
                              2, ckpt=d)
    assert got1 == got2
    assert p1.keys() == p2.keys()
    np.testing.assert_allclose(got1, pre[2:], rtol=2e-5, atol=1e-7)
    # the loaded (pre-training) params equal the saved ones bitwise:
    # verify via a zero-step load
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        plan2 = ashard.build_plan(main, ndev=2, layouts=[(2, 1, 1)])
        elastic.load_checkpoint(d, main, executor=exe, plan=plan2)
        loaded = _params_bytes(saved_params.keys(),
                               fluid.global_scope())
    assert loaded == saved_params


def test_reshard_dp2_to_fsdp2_tp1_loss_parity():
    fluid.set_flags({'FLAGS_auto_shard': True})
    main, startup, loss = _build(hidden=64)
    feed = _feed(n=8)
    d = tempfile.mkdtemp(prefix='pt_el_')
    pre, saved_params, _ = _run_layout(
        main, startup, loss, feed, (2, 1, 1), 2, 4, save_at=2,
        save_dir=d)
    got1, p1, plan_b = _run_layout(main, startup, loss, feed,
                                   (1, 2, 1), 2, 2, ckpt=d)
    got2, p2, _ = _run_layout(main, startup, loss, feed, (1, 2, 1), 2,
                              2, ckpt=d)
    assert plan_b.layout == (1, 2, 1)
    assert any(s is not None for s in plan_b.specs.values())
    assert got1 == got2                      # bitwise-reproducible
    np.testing.assert_allclose(got1, pre[2:], rtol=2e-5, atol=1e-7)
    # reshard preserved every parameter bitwise.  The dp2 source is
    # genuinely sharded (ZeRO moments + the dp-propagated param
    # updates live split over 'dp'), so the synthesized schedule
    # includes real collective steps: row-halves -> column-halves is
    # the general ppermute re-cut, moments coarsen via allgather
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        planb = ashard.build_plan(main, ndev=2, layouts=[(1, 2, 1)])
        info = elastic.load_checkpoint(d, main, executor=exe,
                                       plan=planb)
        loaded = _params_bytes(saved_params.keys(),
                               fluid.global_scope())
    assert loaded == saved_params
    kinds = set(info['reshard']['by_kind'])
    assert kinds <= {'keep', 'slice', 'allgather', 'ppermute'}
    assert info['src_layout'] == {'dp': 2}
    assert monitor.counter_value('elastic/reshard_params') > 0


def test_reshard_fsdp4_to_fsdp2_allgather_schedule():
    """A genuinely sharded source coarsening onto fewer shards: the
    schedule names allgather steps with nonzero wire bytes, predicted
    seconds are recorded, and values stay bitwise."""
    fluid.set_flags({'FLAGS_auto_shard': True})
    main, startup, loss = _build(hidden=64)
    feed = _feed(n=8)
    d = tempfile.mkdtemp(prefix='pt_el_')
    _pre, saved_params, _ = _run_layout(
        main, startup, loss, feed, (1, 4, 1), 4, 3, save_at=3,
        save_dir=d)
    m = elastic.read_manifest(d, 1)
    assert any(len(r['shards']) == 4 for r in m['params'].values())
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        plan2 = ashard.build_plan(main, ndev=2, layouts=[(1, 2, 1)])
        info = elastic.load_checkpoint(d, main, executor=exe,
                                       plan=plan2)
        loaded = _params_bytes(saved_params.keys(),
                               fluid.global_scope())
    assert loaded == saved_params
    assert info['reshard']['by_kind'].get('allgather', 0) > 0
    assert info['reshard']['wire_bytes'] > 0
    assert info['reshard']['measured_s'] > 0
    assert monitor.gauge_value(
        'elastic/reshard_measured_seconds') > 0


def test_resume_warms_compile_cache_zero_retraces():
    """resume() drives Executor.warmup through the persistent compile
    cache: steps after the warmup lower nothing."""
    main, startup, loss = _build(optimizer='sgd')
    feed = _feed()
    d = tempfile.mkdtemp(prefix='pt_el_')
    cache = tempfile.mkdtemp(prefix='pt_el_cc_')
    fluid.set_flags({'FLAGS_compile_cache_dir': cache})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            elastic.save_checkpoint(d, main, executor=exe)
            ref = _f(exe.run(main, feed=feed, fetch_list=[loss])[0])
        with fluid.scope_guard(fluid.Scope()):
            exe2 = fluid.Executor(fluid.XLAPlace(0))
            info = elastic.resume(
                exe2, d, main,
                feed_shapes={'x': feed['x']}, fetch_list=[loss])
            assert info.get('warmed')
            lowered = monitor.counter_value('executor/segments_lowered')
            got = _f(exe2.run(main, feed=feed, fetch_list=[loss])[0])
            assert monitor.counter_value(
                'executor/segments_lowered') == lowered
        assert got == ref
    finally:
        fluid.set_flags({'FLAGS_compile_cache_dir': ''})
        from paddle_tpu.fluid import compile_cache
        compile_cache.reset_plane()


# ------------------------------------------------------- retry/backoff
def test_retry_backoff_and_deadline():
    from paddle_tpu.distributed.rpc_ps import PsClient, \
        RpcDeadlineError
    import socket
    # a port with nothing listening: connect fails fast; the client
    # must retry with backoff and raise RpcDeadlineError
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    fluid.set_flags({'FLAGS_rpc_backoff_ms': 10,
                     'FLAGS_rpc_backoff_max_ms': 40})
    before = monitor.counter_value('rpc/retries')
    c = PsClient('127.0.0.1:%d' % port, deadline_ms=300, retry_times=2)
    t0 = time.monotonic()
    with pytest.raises(RpcDeadlineError):
        c.pull_dense('w')
    wall = time.monotonic() - t0
    assert monitor.counter_value('rpc/retries') - before == 2
    h = monitor.histogram_value('rpc/backoff_seconds')
    assert h and h['count'] >= 2 and h['sum'] > 0
    # bounded: two backoffs capped at 40ms each + fast connect refusals
    assert wall < 5.0
    assert monitor.counter_value('rpc/deadline_errors') >= 1


def test_backoff_bounds_and_jitter():
    from paddle_tpu.distributed.rpc_ps import _backoff_seconds
    fluid.set_flags({'FLAGS_rpc_backoff_ms': 100,
                     'FLAGS_rpc_backoff_max_ms': 400})
    for attempt, cap in ((1, 0.1), (2, 0.2), (3, 0.4), (9, 0.4)):
        for _ in range(16):
            b = _backoff_seconds(attempt)
            assert 0.5 * cap <= b <= cap, (attempt, b, cap)
    fluid.set_flags({'FLAGS_rpc_backoff_ms': 0})
    assert _backoff_seconds(5) == 0.0


def test_faultinject_rpc_delay_counts_against_deadline():
    """An injected per-call delay exercises the real deadline path:
    the call still completes (delay < deadline) and the injection is
    counted."""
    pytest.importorskip('ctypes')
    from paddle_tpu.distributed.rpc_ps import PsServer, PsClient
    try:
        srv = PsServer()
    except Exception:
        pytest.skip('native runtime unavailable')
    try:
        faultinject.configure('rpc.call:delay:0.05@1')
        c = PsClient(srv.endpoint)
        w = np.ones(4, 'float32')
        t0 = time.monotonic()
        c.init_dense('w', w)
        assert time.monotonic() - t0 >= 0.05
        assert faultinject.fired('rpc.call') == 1
        np.testing.assert_allclose(c.pull_dense('w'), w)
        c.close()
    finally:
        srv.stop()


def test_rejoin_trainer_readmission():
    from paddle_tpu.distributed.rpc_ps import PsServer
    try:
        srv = PsServer()
    except Exception:
        pytest.skip('native runtime unavailable')
    d = tempfile.mkdtemp(prefix='pt_el_')
    main, startup, loss = _build(optimizer='sgd')
    feed = _feed()
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[loss])
            elastic.save_checkpoint(d, main, executor=exe)
            ref = _f(exe.run(main, feed=feed, fetch_list=[loss])[0])
        # the restarted trainer re-registers the slot and resumes
        # from the last-good generation
        with fluid.scope_guard(fluid.Scope()):
            exe2 = fluid.Executor(fluid.XLAPlace(0))
            info, hb = elastic.rejoin_trainer(
                srv.endpoint, trainer_id=0, dirname=d, program=main,
                executor=exe2, timeout=5.0, interval=0.05)
            assert info is not None and info['generation'] == 1
            got = _f(exe2.run(main, feed=feed, fetch_list=[loss])[0])
            hb.stop()
        assert got == ref
        assert monitor.counter_value('elastic/readmissions') >= 1
        from paddle_tpu.distributed.rpc_ps import PsClient
        c = PsClient(srv.endpoint)
        assert 0 in c.query_trainers()
        c.close()
    finally:
        srv.stop()


# --------------------------------------------------- heartbeat tolerance
def test_heartbeat_requires_consecutive_misses():
    from paddle_tpu.distributed.heartbeat import HeartBeatMonitor
    lost = []
    mon = HeartBeatMonitor(workers=1, timeout=0.08, check_interval=0.03,
                           misses=3,
                           on_lost=lambda w, a: lost.append(w))
    mon.start()
    try:
        mon.update(0)
        # one expired check is NOT death: beat again right after the
        # timeout first elapses -> flap, not loss
        time.sleep(0.13)
        mon.update(0)
        assert mon.lost_workers() == []
        # silence long enough for >= 3 consecutive expired checks
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not mon.lost_workers():
            time.sleep(0.03)
        assert mon.lost_workers() == [0]
        assert lost == [0]
        # re-admission: a restarted worker's first beat reclaims the
        # slot and is counted
        before = monitor.counter_value('elastic/readmissions')
        mon.update(0)
        assert mon.lost_workers() == []
        assert monitor.counter_value('elastic/readmissions') == \
            before + 1
        assert monitor.counter_value('elastic/heartbeat_flaps') >= 1
    finally:
        mon.stop()


def test_heartbeat_misses_flag_default():
    from paddle_tpu.distributed.heartbeat import HeartBeatMonitor
    mon = HeartBeatMonitor(workers=1, timeout=1.0)
    assert mon.misses == int(
        fluid.get_flags(['FLAGS_heartbeat_misses'])
        ['FLAGS_heartbeat_misses'])


# ------------------------------------------------------------- /statusz
def test_statusz_elastic_section_and_report():
    from paddle_tpu.fluid import health
    main, startup, loss = _build(optimizer='sgd')
    feed = _feed()
    d = tempfile.mkdtemp(prefix='pt_el_')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        elastic.save_checkpoint(d, main, executor=exe)
    with fluid.scope_guard(fluid.Scope()):
        exe2 = fluid.Executor(fluid.XLAPlace(0))
        elastic.load_checkpoint(d, main, executor=exe2)
    sz = health.statusz()
    sec = sz['elastic']
    assert sec is not None
    assert sec['last_generation'] == 1.0
    assert sec['last_save']['generation'] == 1
    assert sec['last_load']['generation'] == 1
    rs = sec['last_load']['reshard']
    for k in ('by_kind', 'predicted_s', 'measured_s',
              'pred_over_measured', 'staging_waves'):
        assert k in rs, rs
    assert 'retries' in sec['rpc']
    assert 'armed' in sec['faultinject']
    json.dumps(sz)              # the whole report stays JSON-able


def test_spec_jsonable_roundtrip():
    from jax.sharding import PartitionSpec as P
    for spec in (None, P('dp'), P(('fsdp', 'mp'), None),
                 P(None, 'mp')):
        doc = elastic.spec_to_jsonable(spec)
        json.dumps(doc)
        back = elastic.spec_from_jsonable(doc)
        assert (back is None and spec is None) or \
            tuple(back) == tuple(spec)
