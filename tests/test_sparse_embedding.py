"""Host-sharded embedding (parameter-server analog) end-to-end."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel.sparse_embedding import HostShardedEmbedding


def test_host_embedding_trains():
    vocab, dim = 10000, 8
    emb = HostShardedEmbedding('test_emb', vocab, dim,
                               optimizer='adagrad', learning_rate=0.1,
                               seed=3)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data('ids', shape=[5], dtype='int64')
        label = fluid.layers.data('label', shape=[1], dtype='float32')
        rows = emb.lookup(ids)                      # host pull-sparse
        feat = fluid.layers.reshape(rows, [0, 5 * dim])
        pred = fluid.layers.fc(feat, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(0.1).minimize(loss)
        emb.apply_gradients(main)                   # host push-sparse

    rng = np.random.RandomState(0)
    table0 = emb.table.copy()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        # memorize a small id set -> loss must drop and only touched
        # rows may change
        ids_np = rng.randint(0, 200, (16, 5)).astype('int64')
        y_np = rng.rand(16, 1).astype('float32')
        for _ in range(40):
            l, = exe.run(main, feed={'ids': ids_np, 'label': y_np},
                         fetch_list=[loss])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    touched = np.unique(ids_np)
    changed = np.where(np.abs(emb.table - table0).sum(1) > 0)[0]
    assert set(changed) <= set(touched.tolist())
    assert len(changed) > 0


def test_host_embedding_duplicate_ids_accumulate():
    emb = HostShardedEmbedding('dup_emb', 10, 2, optimizer='sgd',
                               learning_rate=1.0)
    emb.table[:] = 0
    ids = np.array([[1, 1, 2]])
    grad = np.ones((1, 3, 2), 'float32')
    emb._push(ids, grad)
    np.testing.assert_allclose(emb.table[1], [-2.0, -2.0])
    np.testing.assert_allclose(emb.table[2], [-1.0, -1.0])
