"""fluid.autopilot — closed-loop recalibration and knob tuning.

The acceptance contract: a drifted fabric (measured dispatch walls
far from the one-shot model's predictions) triggers exactly one refit
whose repriced predictions converge back onto the measured walls — an
honest model triggers nothing (the honesty-band guard); the refit is
pending-vs-adopted generation-split so the planner digest moves only
at explicit re-plan points (zero retrace churn post-warmup) and is
coefficient-content-addressed (a restart onto the same refit never
retraces); degenerate fit inputs return the prior with a count, never
a singular-matrix extrapolation; the serving loop drops never-hit
ladder rungs and pre-warms hot natural shapes BEFORE they are
admissible (the serving path stays zero-retrace) and adapts
batch-close deadlines from occupancy; freeze mode
(``FLAGS_autopilot=0``) logs intents acted=False and leaves every
knob bit-identical; the decision log is bounded and the whole
/statusz section JSON-serializable; and ``revert()`` is one call back
to the static configuration."""

import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (autopilot, comms, comms_plan, layers,
                              monitor, serving, slo, timeseries)

# the synthetic "true fabric": T(b) = ALPHA + BETA * b
ALPHA, BETA = 2e-4, 2e-9
SIZES = (1 << 20, 4 << 20, 16 << 20)


@pytest.fixture(autouse=True)
def _clean():
    yield
    fluid.set_flags({'FLAGS_autopilot': True,
                     'FLAGS_autopilot_interval_s': 2.0,
                     'FLAGS_autopilot_honesty_band': 1.5,
                     'FLAGS_autopilot_min_points': 4,
                     'FLAGS_autopilot_refit_path': '',
                     'FLAGS_autopilot_skew_high': 1.5,
                     'FLAGS_autopilot_ladder_min_batches': 16,
                     'FLAGS_autopilot_ladder_hits': 8,
                     'FLAGS_autopilot_close_wait_max_s': 0.02,
                     'FLAGS_autopilot_occupancy_low': 0.5,
                     'FLAGS_comms_model_path': '',
                     'FLAGS_comms_bucket_bytes': 4 << 20,
                     'FLAGS_timeseries': False})
    autopilot.reset()
    comms_plan.reset()
    comms.reset()
    timeseries.reset()
    slo.reset()
    monitor.reset()


def _write_model(path, alpha, beta):
    with open(str(path), 'w') as f:
        json.dump({'collectives': {
            'allreduce': {'latency_s': alpha,
                          'inv_bw_s_per_byte': beta}}}, f)
    fluid.set_flags({'FLAGS_comms_model_path': str(path)})


def _drive_dispatch(rounds=2, honest=False):
    """Synthetic planned-allreduce traffic: predicted_s frozen from
    the CURRENT model (what a trace would freeze), measured wall from
    the true fabric (or from the prediction itself when honest)."""
    for _ in range(rounds):
        for size in SIZES:
            wall = ALPHA + BETA * size
            pred = comms_plan.predict_seconds('allreduce', size)
            rec = {'kind': 'allreduce', 'payload_bytes': float(size),
                   'wire_bytes': float(size), 'dtype': 'float32',
                   'axis': 'dp', 'participants': 8,
                   'bucket': comms.size_bucket(size), 'arm': 'dense',
                   'dense_wire_bytes': float(size),
                   'predicted_s': float(pred)}
            comms.account_dispatch([rec], pred if honest else wall)


class TestRefitLoop:
    def test_drift_triggers_refit_that_reconverges(self, tmp_path):
        # stale one-shot model predicts a fabric ~100x faster than
        # the walls actually measured
        _write_model(tmp_path / 'm.json', ALPHA / 100, BETA / 100)
        _drive_dispatch()
        assert autopilot.engage()
        autopilot.tick(now=1000.0)

        st = comms_plan.refit_state()
        assert st['pending'] and not st['adopted']
        assert monitor.counter_value('autopilot/refits') == 1
        recs = [d for d in autopilot.decisions() if d['kind'] == 'refit']
        assert recs and recs[-1]['choice'] == 'installed'
        assert recs[-1]['acted'] and not recs[-1]['frozen']
        assert 'allreduce' in recs[-1]['info']['kinds']
        # atomically persisted to the sidecar, NEVER the model itself
        sidecar = str(tmp_path / 'm.json.refit.json')
        assert os.path.exists(sidecar)
        with open(str(tmp_path / 'm.json')) as f:
            stale = json.load(f)['collectives']['allreduce']
        assert stale['latency_s'] == ALPHA / 100

        # repriced predictions reproduce the measured walls: honesty
        # ratio back inside a few % of 1.0, with no retrace
        for size in SIZES:
            rec = {'kind': 'allreduce', 'wire_bytes': float(size),
                   'payload_bytes': float(size), 'participants': 8,
                   'arm': 'dense'}
            live = comms_plan.reprice_record(rec)
            wall = ALPHA + BETA * size
            assert live == pytest.approx(wall, rel=0.05)

    def test_honest_model_never_refits(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA, BETA)
        _drive_dispatch(honest=True)
        autopilot.engage()
        autopilot.tick(now=1000.0)
        assert not comms_plan.refit_state()['pending']
        assert monitor.counter_value('autopilot/refits') == 0
        assert not [d for d in autopilot.decisions()
                    if d['kind'] == 'refit']

    def test_persisted_refit_survives_restart(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA / 100, BETA / 100)
        _drive_dispatch()
        autopilot.engage()
        autopilot.tick(now=1000.0)
        adopted_digest_before = None
        comms_plan.adopt_refit()
        adopted_digest_before = comms_plan.refit_state()['adopted_digest']
        # "restart": drop the in-memory plane, re-engage from disk
        autopilot.reset()
        comms_plan.reset()
        autopilot.engage()
        st = comms_plan.refit_state()
        assert st['adopted']
        # coefficient-content-addressed: the same persisted refit
        # yields the same digest — a restart never retraces onto it
        assert st['adopted_digest'] == adopted_digest_before

    def test_frozen_mode_logs_intent_and_touches_nothing(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA / 100, BETA / 100)
        _drive_dispatch()
        fluid.set_flags({'FLAGS_autopilot': False})
        monitor.set_gauge('comms/skew_ratio', 4.0)
        autopilot.engage()
        before = fluid.get_flags(['FLAGS_comms_bucket_bytes'])
        autopilot.tick(now=1e9)
        recs = [d for d in autopilot.decisions()
                if d['kind'] in ('refit', 'bucket_bytes')]
        assert recs
        assert all(not d['acted'] and d['frozen'] for d in recs)
        assert not comms_plan.refit_state()['pending']
        assert not os.path.exists(str(tmp_path / 'm.json.refit.json'))
        assert fluid.get_flags(['FLAGS_comms_bucket_bytes']) == before
        assert monitor.counter_value('autopilot/frozen_intents') >= 2

    def test_slo_firing_freezes_adaptation(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA / 100, BETA / 100)
        _drive_dispatch()
        autopilot.engage()
        name = slo.declare('comms/bytes_on_wire > 1e30')
        obj = [o for o in slo._objectives.values()
               if o.name == name][0]
        obj.state = 'firing'
        autopilot.tick(now=1000.0)
        assert not comms_plan.refit_state()['pending']
        assert monitor.counter_value('autopilot/slo_frozen') == 1
        recs = [d for d in autopilot.decisions() if d['kind'] == 'refit']
        assert recs and not recs[-1]['acted']


class TestDigestChurn:
    def test_refit_moves_digest_only_at_adoption(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA, BETA)
        d0 = comms_plan.digest()
        model = {'collectives': {'allreduce': {
            'latency_s': ALPHA * 2, 'inv_bw_s_per_byte': BETA * 2}}}
        comms_plan.install_refit(model)
        # pending refit reprices telemetry but NEVER the digest: an
        # installed-but-unadopted refit cannot retrace anything
        assert comms_plan.digest() == d0
        assert comms_plan.adopt_refit() is not None
        d1 = comms_plan.digest()
        assert d1 != d0
        # adopting again is a no-op; re-adopting identical
        # coefficients is digest-stable (content-addressed)
        assert comms_plan.adopt_refit() is None
        assert comms_plan.digest() == d1
        comms_plan.install_refit(json.loads(json.dumps(model)))
        comms_plan.adopt_refit()
        assert comms_plan.digest() == d1
        # one-call revert: back to the static digest
        assert comms_plan.clear_refit()
        assert comms_plan.digest() == d0

    def test_adopted_refit_prices_planning_without_disk(self, tmp_path):
        _write_model(tmp_path / 'm.json', ALPHA, BETA)
        comms_plan.install_refit({'collectives': {'allreduce': {
            'latency_s': 0.5, 'inv_bw_s_per_byte': 0.0}}})
        # pending: planning still prices from the on-disk model
        assert comms_plan.predict_seconds('allreduce', 1 << 20) == \
            pytest.approx(ALPHA + BETA * (1 << 20))
        comms_plan.adopt_refit()
        os.remove(str(tmp_path / 'm.json'))   # no disk read per call
        assert comms_plan.predict_seconds('allreduce', 1 << 20) == 0.5


class TestFitLinear:
    def test_degenerate_single_bucket_returns_prior(self):
        prior = (1e-4, 3e-9)
        n0 = monitor.counter_value('autopilot/refit_degenerate')
        pts = [(1024.0, 5e-4)] * 6     # one wire size: unidentifiable
        assert comms.fit_linear(pts, prior=prior) == prior
        assert comms.fit_linear([], prior=prior) == prior
        assert monitor.counter_value('autopilot/refit_degenerate') \
            == n0 + 2

    def test_legacy_no_prior_paths_unchanged(self):
        assert comms.fit_linear([]) == (0.0, 1e-12)
        a, b = comms.fit_linear([(1e6, 1e-3)])
        assert a == 0.0 and b == pytest.approx(1e-9)
        a, b = comms.fit_linear(
            [(s, ALPHA + BETA * s) for s in SIZES])
        assert a == pytest.approx(ALPHA, rel=1e-6)
        assert b == pytest.approx(BETA, rel=1e-6)


class TestBucketLoop:
    def test_high_skew_shrinks_low_skew_widens(self):
        autopilot.engage()
        monitor.set_gauge('comms/skew_ratio', 3.0)
        autopilot.tick(now=1e9)
        assert fluid.get_flags(['FLAGS_comms_bucket_bytes']) == \
            {'FLAGS_comms_bucket_bytes': 2 << 20}
        rec = [d for d in autopilot.decisions()
               if d['kind'] == 'bucket_bytes'][-1]
        assert rec['acted'] and \
            rec['info']['why'] == 'latency_dominated_skew'
        # settle window: an immediate second tick must NOT move again
        monitor.set_gauge('comms/skew_ratio', 3.0)
        autopilot.tick(now=1e9 + 2.0)
        assert fluid.get_flags(['FLAGS_comms_bucket_bytes']) == \
            {'FLAGS_comms_bucket_bytes': 2 << 20}
        # bandwidth-bound skew widens again after the settle window
        monitor.set_gauge('comms/skew_ratio', 1.0)
        autopilot.tick(now=1e9 + 100.0)
        assert fluid.get_flags(['FLAGS_comms_bucket_bytes']) == \
            {'FLAGS_comms_bucket_bytes': 4 << 20}
        # revert restores the engage-time static value
        autopilot.revert()
        assert fluid.get_flags(['FLAGS_comms_bucket_bytes']) == \
            {'FLAGS_comms_bucket_bytes': 4 << 20}


def _build_mlp(width=16, seed=5, in_w=8):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = seed
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[in_w], dtype='float32')
        h = layers.fc(x, width, act='relu')
        y = layers.fc(h, 6, act='softmax')
    return main_p, startup, y


class TestServingLoop:
    def test_ladder_drop_prewarm_close_wait_and_revert(self):
        fluid.set_flags({'FLAGS_autopilot_ladder_min_batches': 3,
                         'FLAGS_autopilot_ladder_hits': 3})
        exe = fluid.Executor(fluid.XLAPlace(0))
        main_p, startup, y = _build_mlp()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
        srv = serving.ServingExecutor(max_batch=8, executor=exe)
        # rung 1 will never be hit; rows=2 pads to 8 (natural bucket 2)
        srv.add_program('t', main_p, ['x'], [y], scope=scope,
                        bucket_ladder=[1, 8])
        try:
            srv.warmup(wait=True)
            rng = np.random.RandomState(0)
            xs = [rng.randn(2, 8).astype('float32') for _ in range(4)]
            outs = [np.asarray(srv.submit('t', {'x': xv}).result(120)[0])
                    for xv in xs]
            rep = srv.resident_report()['tenants'][0]
            assert rep['bucket_hits'] == {'8': 4}
            assert rep['natural_miss_hits'] == {'2': 4}

            autopilot.engage()
            retraces0 = rep['retraces']
            autopilot.tick(now=1e9)
            rep = srv.resident_report()['tenants'][0]
            # never-hit rung 1 dropped, hot natural shape 2 joined
            # pre-warmed (largest rung 8 is not droppable)
            assert rep['bucket_ladder'] == [2, 8]
            assert monitor.counter_value('serving/bucket_dropped') == 1
            assert monitor.counter_value('serving/bucket_prewarmed') == 1
            # occupancy 2/8 < 0.5 -> a batch-close deadline appears
            assert rep['close_wait_s'] == pytest.approx(0.02 / 4)
            kinds = {d['kind'] for d in autopilot.decisions()}
            assert {'ladder', 'close_wait'} <= kinds
            assert monitor.gauge_value('serving/pad_waste_ratio') > 0

            # the adapted rung serves bitwise-identically with ZERO
            # retraces (it was compiled before becoming admissible)
            out2 = np.asarray(srv.submit('t', {'x': xs[0]}).result(120)[0])
            assert np.array_equal(out2, outs[0])
            rep = srv.resident_report()['tenants'][0]
            assert rep['retraces'] == retraces0
            assert rep['bucket_hits'].get('2') == 1

            # one-call revert: registered ladder and deadline restored
            autopilot.revert()
            rep = srv.resident_report()['tenants'][0]
            assert rep['bucket_ladder'] == [1, 8]
            assert rep['close_wait_s'] is None
        finally:
            srv.stop()

    def test_adapt_ladder_never_drops_largest(self):
        exe = fluid.Executor(fluid.XLAPlace(0))
        main_p, startup, y = _build_mlp()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
        srv = serving.ServingExecutor(max_batch=4, executor=exe)
        srv.add_program('t', main_p, ['x'], [y], scope=scope,
                        bucket_ladder=[2, 4])
        try:
            srv.warmup(wait=True)
            assert srv.adapt_ladder('t', drop=[2, 4]) == (4,)
        finally:
            srv.stop()


class TestSurface:
    def test_decision_log_bounded_and_statusz_jsonable(self, tmp_path):
        autopilot.engage()
        for i in range(300):
            autopilot._decide('probe', {'i': i}, acted=False)
        assert len(autopilot.decisions()) == 256
        assert autopilot.decisions(last=5)[-1]['choice']['i'] == 299
        rep = autopilot.report()
        assert rep['engaged'] and rep['decisions_total'] == 301
        json.dumps(rep)             # the /statusz contract
        from paddle_tpu.fluid import health
        json.dumps(health.statusz())

    def test_maybe_tick_interval_and_disengage(self):
        assert not autopilot.maybe_tick(now=10.0)   # not engaged
        autopilot.engage()
        assert autopilot.maybe_tick(now=10.0)
        assert not autopilot.maybe_tick(now=10.5)   # inside interval
        assert autopilot.maybe_tick(now=13.0)
        assert autopilot.disengage()
        assert not autopilot.maybe_tick(now=20.0)

    def test_tick_rides_timeseries_sampling(self):
        fluid.set_flags({'FLAGS_timeseries': True})
        autopilot.engage()
        timeseries.sample(now=100.0)
        assert monitor.counter_value('autopilot/ticks') == 1
        timeseries.sample(now=100.5)    # throttled by the interval
        assert monitor.counter_value('autopilot/ticks') == 1
        timeseries.sample(now=103.0)
        assert monitor.counter_value('autopilot/ticks') == 2
