"""QAT fake-quant ops + program rewrite pass."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import registry


def test_fake_quant_abs_max():
    x = np.array([[-1.0, 0.5], [0.25, 1.0]], 'float32') * 4
    out = registry.get('fake_quantize_abs_max').fn(
        registry.LowerCtx(0), {'X': [x]}, {'bit_length': 8})
    q = np.asarray(out['Out'][0])
    s = float(np.asarray(out['OutScale'][0]))
    assert s == 4.0
    # max error bounded by one quant step
    assert np.abs(q - x).max() <= s / 127 + 1e-6


def test_fake_quant_ste_gradient():
    import jax, jax.numpy as jnp

    def f(x):
        out = registry.get('fake_quantize_abs_max').fn(
            registry.LowerCtx(0), {'X': [x]}, {'bit_length': 8})
        return jnp.sum(out['Out'][0] ** 2)

    x = jnp.asarray(np.array([[0.3, -0.7]], 'float32'))
    g = jax.grad(f)(x)
    # straight-through: grad ~ 2*q(x) but nonzero and finite
    assert np.isfinite(np.asarray(g)).all()
    assert (np.asarray(g) != 0).all()


def test_qat_rewrite_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import \
        quantize_program
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 16, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        quantize_program(main, startup)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert types.count('fake_channel_wise_quantize_abs_max') == 2
    assert types.count(
        'fake_quantize_dequantize_moving_average_abs_max') == 2
    rng = np.random.RandomState(0)
    W = rng.randn(8, 1).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(80):
            xs = rng.randn(32, 8).astype('float32')
            l, = exe.run(main, feed={'x': xs, 'y': xs @ W},
                         fetch_list=[loss])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
