"""QAT fake-quant ops + program rewrite pass."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops import registry


def test_fake_quant_abs_max():
    x = np.array([[-1.0, 0.5], [0.25, 1.0]], 'float32') * 4
    out = registry.get('fake_quantize_abs_max').fn(
        registry.LowerCtx(0), {'X': [x]}, {'bit_length': 8})
    q = np.asarray(out['Out'][0])
    s = float(np.asarray(out['OutScale'][0]))
    assert s == 4.0
    # max error bounded by one quant step
    assert np.abs(q - x).max() <= s / 127 + 1e-6


def test_fake_quant_ste_gradient():
    import jax, jax.numpy as jnp

    def f(x):
        out = registry.get('fake_quantize_abs_max').fn(
            registry.LowerCtx(0), {'X': [x]}, {'bit_length': 8})
        return jnp.sum(out['Out'][0] ** 2)

    x = jnp.asarray(np.array([[0.3, -0.7]], 'float32'))
    g = jax.grad(f)(x)
    # straight-through: grad ~ 2*q(x) but nonzero and finite
    assert np.isfinite(np.asarray(g)).all()
    assert (np.asarray(g) != 0).all()


def test_qat_rewrite_trains():
    from paddle_tpu.fluid.contrib.slim.quantization import \
        quantize_program
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = fluid.layers.fc(x, 16, act='relu')
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        quantize_program(main, startup)
        fluid.optimizer.Adam(5e-3).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert types.count('fake_channel_wise_quantize_abs_max') == 2
    assert types.count(
        'fake_quantize_dequantize_moving_average_abs_max') == 2
    rng = np.random.RandomState(0)
    W = rng.randn(8, 1).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(80):
            xs = rng.randn(32, 8).astype('float32')
            l, = exe.run(main, feed={'x': xs, 'y': xs @ W},
                         fetch_list=[loss])
            losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def _train_lenet_blobs(seed=0, steps=40):
    """Tiny conv net on separable 2-class 8x8 'images'; returns
    (inference program, feed name, logits name, scope, eval batches,
    accuracy fn)."""
    import paddle_tpu.fluid as fluid
    layers = fluid.layers

    rng = np.random.RandomState(seed)

    def make_batch(n=64):
        y = rng.randint(0, 2, n)
        x = rng.randn(n, 1, 8, 8).astype('float32') * 0.5
        x[y == 1, :, 2:6, 2:6] += 1.5   # class-1 blob in the center
        return x, y.astype('int64').reshape(-1, 1)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = layers.data('img', shape=[1, 8, 8], dtype='float32')
        lab = layers.data('lab', shape=[1], dtype='int64')
        c = layers.conv2d(img, num_filters=4, filter_size=3,
                          padding=1, act='relu')
        p = layers.pool2d(c, pool_size=2, pool_stride=2)
        f = layers.fc(p, size=16, act='relu')
        logits = layers.fc(f, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, lab))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(5e-3).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(steps):
            xb, yb = make_batch()
            exe.run(main, feed={'img': xb, 'lab': yb}, fetch_list=[])

    eval_batches = [make_batch(128) for _ in range(3)]

    def accuracy(program, sc):
        good = tot = 0
        with fluid.scope_guard(sc):
            exe2 = fluid.Executor(fluid.XLAPlace(0))
            for xb, yb in eval_batches:
                out, = exe2.run(program, feed={'img': xb},
                                fetch_list=[logits.name])
                good += (np.argmax(np.asarray(out), 1) ==
                         yb.ravel()).sum()
                tot += len(yb)
        return good / tot

    infer = main.clone(for_test=True)
    infer = fluid.io._prune_for_inference(infer, ['img'],
                                          [logits.name]) \
        if hasattr(fluid.io, '_prune_for_inference') else infer
    return infer, 'img', logits.name, scope, eval_batches, accuracy


def test_post_training_quantization_accuracy_budget():
    """VERDICT r4 #8: PTQ — calibrate activation ranges on real
    batches, emit a quantized inference program, accuracy within a
    stated budget (here: <= 3 points of the fp32 baseline on a
    comfortably-separable task)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.contrib.slim.quantization import \
        PostTrainingQuantization

    infer, feed_name, out_name, scope, eval_batches, accuracy = \
        _train_lenet_blobs()
    base_acc = accuracy(infer, scope)
    assert base_acc > 0.9, base_acc   # the task is easy by design

    calib = [{feed_name: xb} for xb, _ in eval_batches[:2]]
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        ptq = PostTrainingQuantization(exe, infer, [feed_name], calib,
                                       scope=scope)
        quant = ptq.quantize()

    # the quantized program carries the static-scale quant-dequant ops
    types = [op.type for op in quant.global_block().ops]
    assert 'fake_quantize_dequantize_moving_average_abs_max' in types
    assert ptq.activation_scales, 'calibration collected no scales'
    # weights are 8-bit grids: <= 255 distinct values per channel
    for op in quant.global_block().ops:
        for n in op.input_arg_names:
            if n.endswith('.ptq'):
                arr = np.asarray(scope.find_var(n))
                ch0 = arr.reshape(arr.shape[0], -1)[0]
                assert len(np.unique(ch0)) <= 255
    q_acc = accuracy(quant, scope)
    assert q_acc >= base_acc - 0.03, (base_acc, q_acc)
    # determinism: the pinned scales make eval repeatable
    assert accuracy(quant, scope) == q_acc


def test_sensitive_prune_strategy_respects_budget():
    """VERDICT r4 #8: magnitude pruning driven by a sensitivity scan —
    per-param ratios chosen so the eval metric stays within max_drop."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.contrib.slim.prune import \
        SensitivePruneStrategy

    infer, feed_name, out_name, scope, eval_batches, accuracy = \
        _train_lenet_blobs(seed=3)
    base_acc = accuracy(infer, scope)
    assert base_acc > 0.9, base_acc

    strat = SensitivePruneStrategy(
        eval_fn=lambda: accuracy(infer, scope), max_drop=0.02,
        params=[p.name for p in infer.all_parameters()
                if len(p.shape) > 1])   # weights only, skip biases
    chosen = strat.prune(infer, scope)
    assert chosen and any(r > 0 for r in chosen.values()), chosen
    final_acc = accuracy(infer, scope)
    assert final_acc >= base_acc - 0.02 - 1e-9, (base_acc, final_acc,
                                                 chosen)
    # pruning really zeroed weights at the chosen ratios
    for name, r in chosen.items():
        if r > 0:
            arr = np.asarray(scope.find_var(name))
            frac0 = float((arr == 0).mean())
            assert frac0 >= r * 0.9, (name, r, frac0)
