"""Functional smoke tests over the API-audit long tail: every wrapper
added to reach reference API parity runs through the real executor
(tools/check_api_coverage.py guards presence; these guard behavior).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

rng = np.random.RandomState(0)


def run_prog(build, feed=None, n_fetch=1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        fetches = build()
        if not isinstance(fetches, (list, tuple)):
            fetches = [fetches]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        return exe.run(main, feed=feed or {}, fetch_list=list(fetches))


def test_norm_layers():
    def build():
        x = fluid.layers.data('x', shape=[6, 4, 4], dtype='float32')
        a = fluid.layers.instance_norm(x)
        b = fluid.layers.group_norm(x, groups=2)
        return fluid.layers.reduce_mean(a), fluid.layers.reduce_mean(b)
    x = rng.rand(2, 6, 4, 4).astype('float32')
    a, b = run_prog(lambda: build(), {'x': x})
    assert np.isfinite(a).all() and np.isfinite(b).all()
    assert abs(float(a)) < 0.2  # normalized


def test_spectral_norm_scales_weight():
    def build():
        w = fluid.layers.create_parameter([4, 6], 'float32')
        return fluid.layers.spectral_norm(w, dim=0)
    out, = run_prog(build)
    # largest singular value of the normalized weight ~ 1
    s = np.linalg.svd(np.asarray(out), compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=0.2)


def test_detection_pipeline():
    def build():
        loc = fluid.layers.data('loc', shape=[8, 4], dtype='float32')
        conf = fluid.layers.data('conf', shape=[8, 3], dtype='float32')
        pb = fluid.layers.data('pb', shape=[8, 4], dtype='float32',
                               append_batch_size=False)
        out = fluid.layers.detection_output(
            loc, conf, pb, [0.1, 0.1, 0.2, 0.2], keep_top_k=4,
            nms_top_k=8)
        return out
    loc = rng.rand(1, 8, 4).astype('float32') * 0.1
    conf = rng.rand(1, 8, 3).astype('float32')
    pb = np.stack([np.linspace(0, .8, 8), np.linspace(0, .8, 8),
                   np.linspace(.2, 1, 8), np.linspace(.2, 1, 8)],
                  axis=1).astype('float32')
    out, = run_prog(build, {'loc': loc, 'conf': conf, 'pb': pb})
    assert np.asarray(out).shape[-1] == 6  # [label, score, 4 coords]


def test_iou_and_box_coder():
    def build():
        x = fluid.layers.data('bx', shape=[4], dtype='float32')
        y = fluid.layers.data('by', shape=[4], dtype='float32')
        return fluid.layers.iou_similarity(x, y)
    bx = np.array([[0, 0, 1, 1], [0, 0, 0.5, 0.5]], 'float32')
    by = np.array([[0, 0, 1, 1], [0.5, 0.5, 1, 1]], 'float32')
    iou, = run_prog(build, {'bx': bx, 'by': by})
    np.testing.assert_allclose(np.asarray(iou)[0, 0], 1.0, rtol=1e-5)


def test_rnn_cells_and_decode():
    def build():
        x = fluid.layers.data('x', shape=[4, 8], dtype='float32')
        cell = fluid.layers.GRUCell(hidden_size=8)
        h0 = fluid.layers.fill_constant([2, 8], 'float32', 0.0)
        step_in = fluid.layers.slice(x, axes=[1], starts=[0], ends=[1])
        step_in = fluid.layers.squeeze(step_in, axes=[1])
        out, new_h = cell.call(step_in, h0)
        return fluid.layers.reduce_mean(out)
    x = rng.rand(2, 4, 8).astype('float32')
    out, = run_prog(build, {'x': x})
    assert np.isfinite(out).all()


def test_lstm_fused_layer():
    def build():
        x = fluid.layers.data('x', shape=[5, 6], dtype='float32')
        h, last_h, last_c = fluid.layers.lstm(
            x, None, None, max_len=5, hidden_size=8)
        return fluid.layers.reduce_mean(h)
    x = rng.rand(3, 5, 6).astype('float32')
    out, = run_prog(build, {'x': x})
    assert np.isfinite(out).all()


def test_distributions():
    def build():
        u = fluid.layers.Uniform(0.0, 2.0)
        n = fluid.layers.Normal(0.0, 1.0)
        n2 = fluid.layers.Normal(1.0, 2.0)
        return (u.sample([64]), u.entropy(), n.kl_divergence(n2),
                n.entropy())
    s, ent, kl, nent = run_prog(build)
    s = np.asarray(s)
    assert (s >= 0).all() and (s < 2).all()
    np.testing.assert_allclose(float(np.asarray(ent).ravel()[0]),
                               np.log(2.0), rtol=1e-5)
    assert float(np.asarray(kl).ravel()[0]) > 0
    np.testing.assert_allclose(float(np.asarray(nent).ravel()[0]),
                               0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-5)


def test_lookahead_and_decayed_adagrad_train():
    for make in (lambda: fluid.optimizer.DecayedAdagrad(0.1),
                 lambda: fluid.optimizer.LookaheadOptimizer(
                     fluid.optimizer.SGD(0.1), alpha=0.5, k=2)):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[8], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(fluid.layers.square(pred - y))
            make().minimize(loss)
        w = rng.randn(8, 1).astype('float32')
        scope = fluid.Scope()
        losses = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(12):
                xb = rng.randn(32, 8).astype('float32')
                l, = exe.run(main, feed={'x': xb, 'y': xb @ w},
                             fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert losses[-1] < losses[0], losses


def test_eye_and_tensor_array_to_tensor():
    def build():
        e = fluid.layers.eye(3)
        arr = fluid.layers.create_array('float32')
        i0 = fluid.layers.fill_constant([1], 'int64', 0)
        x = fluid.layers.fill_constant([2, 2], 'float32', 1.5)
        fluid.layers.array_write(x, i0, arr)
        t, _ = fluid.layers.tensor_array_to_tensor(arr, axis=0)
        return e, t
    e, t = run_prog(build)
    np.testing.assert_allclose(np.asarray(e), np.eye(3), rtol=1e-6)
    assert np.asarray(t).shape[0] >= 2


def test_misc_nn_tail():
    def build():
        x = fluid.layers.data('x', shape=[4, 8, 8], dtype='float32')
        m = fluid.layers.maxout(x, groups=2)
        p = fluid.layers.pad2d(x, paddings=[1, 1, 2, 2])
        sr = fluid.layers.soft_relu(x)
        t = fluid.layers.temporal_shift(x, seg_num=2)
        return (fluid.layers.reduce_mean(m), fluid.layers.reduce_mean(p),
                fluid.layers.reduce_mean(sr),
                fluid.layers.reduce_mean(t))
    x = rng.rand(2, 4, 8, 8).astype('float32')
    outs = run_prog(build, {'x': x})
    assert all(np.isfinite(o).all() for o in outs)


def test_ifelse_merges_rows():
    def build():
        x = fluid.layers.data('x', shape=[1], dtype='float32')
        zero = fluid.layers.fill_constant([1], 'float32', 0.0)
        from paddle_tpu.fluid.layers import ops as _ops
        cond = _ops.greater_than(x, zero)
        ie = fluid.layers.IfElse(cond)
        with ie.true_block():
            xi = ie.input(x)
            ie.output(fluid.layers.scale(xi, scale=2.0))
        with ie.false_block():
            xi = ie.input(x)
            ie.output(fluid.layers.scale(xi, scale=-1.0))
        out, = ie()
        return out
    x = np.array([[1.0], [-2.0], [3.0]], 'float32')
    out, = run_prog(build, {'x': x})
    np.testing.assert_allclose(np.asarray(out).ravel(), [2.0, 2.0, 6.0],
                               rtol=1e-5)


def test_dygraph_new_layers():
    from paddle_tpu.fluid.dygraph import (GroupNorm, PRelu, GRUUnit,
                                          BilinearTensorProduct,
                                          to_variable)
    with fluid.dygraph.guard():
        np.random.seed(3)
        x = to_variable(rng.rand(2, 4, 4, 4).astype('float32'))
        gn = GroupNorm(4, 2)
        out = gn(x)
        assert np.isfinite(np.asarray(out.value)).all()
        pr = PRelu('all')
        out = pr(to_variable(rng.randn(2, 3).astype('float32')))
        assert np.isfinite(np.asarray(out.value)).all()
        gu = GRUUnit(3 * 6)
        h = gu(to_variable(rng.rand(2, 18).astype('float32')),
               to_variable(np.zeros((2, 6), 'float32')))[0]
        assert np.asarray(h.value).shape == (2, 6)
        bl = BilinearTensorProduct(3, 4, 5)
        out = bl(to_variable(rng.rand(2, 3).astype('float32')),
                 to_variable(rng.rand(2, 4).astype('float32')))
        assert np.asarray(out.value).shape == (2, 5)


def test_dygraph_lr_schedulers():
    from paddle_tpu.fluid.dygraph import (NoamDecay, PiecewiseDecay,
                                          CosineDecay, PolynomialDecay)
    noam = NoamDecay(d_model=512, warmup_steps=10, begin=1)
    lrs = [noam() for _ in range(20)]
    assert max(lrs) == lrs[9]  # peak at warmup end
    pw = PiecewiseDecay([5, 10], [1.0, 0.5, 0.1], begin=0)
    vals = [pw() for _ in range(12)]
    assert vals[0] == 1.0 and vals[6] == 0.5 and vals[-1] == 0.1
    poly = PolynomialDecay(1.0, 10, end_learning_rate=0.0)
    vals = [poly() for _ in range(11)]
    assert vals[0] == 1.0 and vals[-1] <= 0.11
    cos = CosineDecay(1.0, step_each_epoch=1, epochs=10)
    vals = [cos() for _ in range(10)]
    assert vals[0] == 1.0 and vals[-1] < 0.1


def test_pyreader_feeds_training():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.py_reader(
            capacity=8, shapes=[(-1, 4), (-1, 1)],
            dtypes=['float32', 'float32'], name='r1')
        x, y = reader.feed_vars
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.fc(x, 1) - y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    def gen():
        r = np.random.RandomState(1)
        for _ in range(4):
            xb = r.rand(8, 4).astype('float32')
            yield {x.name: xb, y.name: xb.sum(1, keepdims=True)}
    reader.decorate_batch_generator(gen)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        reader.start()
        losses = []
        while True:
            try:
                batch = reader.next()
            except StopIteration:
                break
            l, = exe.run(main, feed=batch, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert len(losses) == 4 and np.isfinite(losses).all()


def test_metrics_edit_distance_and_map():
    from paddle_tpu.fluid.metrics import EditDistance, DetectionMAP
    ed = EditDistance()
    ed.update(np.array([1.0, 0.0, 2.0]), 3)
    d, err = ed.eval()
    np.testing.assert_allclose(d, 1.0)
    np.testing.assert_allclose(err, 2.0 / 3)
    m = DetectionMAP(class_num=2, background_label=-1)
    m.update([[0, 0.9, 0, 0, 1, 1]], [[0, 0, 1, 1]], [0])
    assert m.eval() == 1.0
    # background class is excluded from mAP
    m2 = DetectionMAP(class_num=2, background_label=0)
    m2.update([[1, 0.9, 0, 0, 1, 1]], [[0, 0, 1, 1]], [1])
    assert m2.eval() == 1.0


def test_ssd_loss_functional():
    def build():
        loc = fluid.layers.data('loc', shape=[6, 4], dtype='float32')
        conf = fluid.layers.data('conf', shape=[6, 3], dtype='float32')
        gtb = fluid.layers.data('gtb', shape=[2, 4], dtype='float32')
        gtl = fluid.layers.data('gtl', shape=[2], dtype='int64')
        pb = fluid.layers.data('pb', shape=[6, 4], dtype='float32',
                               append_batch_size=False)
        return fluid.layers.ssd_loss(loc, conf, gtb, gtl, pb)
    loc = np.zeros((2, 6, 4), 'float32')
    conf = rng.rand(2, 6, 3).astype('float32')
    gtb = np.tile(np.array([[[0, 0, .5, .5], [.5, .5, 1, 1]]],
                           'float32'), (2, 1, 1))
    gtl = np.ones((2, 2), 'int64')
    pb = np.array([[0, 0, .5, .5], [.5, .5, 1, 1], [0, .5, .5, 1],
                   [.5, 0, 1, .5], [0, 0, 1, 1], [.2, .2, .4, .4]],
                  'float32')
    out, = run_prog(build, {'loc': loc, 'conf': conf, 'gtb': gtb,
                            'gtl': gtl, 'pb': pb})
    out = np.asarray(out)
    assert out.shape[0] == 2 and np.isfinite(out).all() and \
        (out > 0).all()


def test_beam_search_decoder_beams_diverge():
    V, H, K = 7, 6, 3

    def build():
        import paddle_tpu.fluid.layers as L

        class ToyCell(L.RNNCell):
            hidden_size = H

            def call(self, inputs, states):
                # state-independent fixed logits would make all beams
                # tie; mix in the (distinct) input ids
                h = L.fc(L.cast(inputs, 'float32'), H)
                return h, h

        cell = ToyCell()
        dec = L.BeamSearchDecoder(
            cell, start_token=0, end_token=V - 1, beam_size=K,
            output_fn=lambda h: L.fc(h, V))
        init = L.fill_constant([2, H], 'float32', 0.0)
        out, _ = L.dynamic_decode(dec, init, max_step_num=4)
        return out
    out, = run_prog(build)
    out = np.asarray(out).reshape(2, K, -1)
    # beams within a batch entry are NOT all identical
    assert not (out[0] == out[0][0]).all(), out[0]


def test_lstm_bidirectional_width():
    def build():
        x = fluid.layers.data('x', shape=[5, 6], dtype='float32')
        h, lh, lc = fluid.layers.lstm(x, None, None, max_len=5,
                                      hidden_size=4, is_bidirec=True)
        return h
    x = rng.rand(2, 5, 6).astype('float32')
    h, = run_prog(build, {'x': x})
    assert np.asarray(h).shape == (2, 5, 8)  # 2H concat
