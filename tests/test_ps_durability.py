"""Parameter-server durability + robustness (protocol v2).

Reference behaviors reproduced:
- checkpoint_notify_op.cc:28 / recv_save_op.cc — trainer-triggered
  pserver snapshot incl. optimizer state, restore in a FRESH process;
- rpc_deadline / rpc_retry_times flags
  (python/paddle/fluid/__init__.py:190-198) — dead/hung server raises
  within the deadline instead of hanging forever;
- enforce-with-message discipline on the wire — protocol errors get an
  error frame, not a silent connection drop;
- heart_beat_monitor.h:38-104 — the pserver detects and reports lost
  trainers;
- listen_and_serv optimize sub-blocks (listen_and_serv_op.cc:110) —
  server-side momentum/adam, dense and per-row.
"""

import os
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed import (PsServer, PsClient,
                                    RpcParameterServerStore,
                                    PsServerError, RpcDeadlineError,
                                    TrainerHeartbeat)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# error frames / protocol robustness

def test_error_frames_keep_connection_alive():
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        # push before init: an error MESSAGE, and the connection
        # survives for the next (valid) request
        with pytest.raises(PsServerError, match='not initialized'):
            c.push_dense_grad('ghost', np.ones(3, 'float32'))
        c.init_dense('w', np.zeros(3, 'float32'))
        np.testing.assert_allclose(c.pull_dense('w'), np.zeros(3))
        # size mismatch: diagnosed, connection still alive
        with pytest.raises(PsServerError, match='elements'):
            c.push_dense_grad('w', np.ones(5, 'float32'))
        # unknown pull -> KeyError (not a silent empty array)
        with pytest.raises(KeyError):
            c.pull_dense('never_created')
        # unknown sparse table
        with pytest.raises(PsServerError, match='unknown sparse'):
            c.pull_rows('ghost_table', np.array([0], 'int64'), 4)
        # unknown op code
        with pytest.raises(PsServerError, match='unknown op'):
            c._call(77, 'x')
        assert 'w' in c.list_vars()  # connection still works
        c.close()
    finally:
        srv.stop()


def test_overflow_sized_count_is_rejected():
    """A huge element count whose byte-size wraps u64 must be rejected
    by the division-based bounds check, not read out of bounds."""
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_dense('w', np.zeros(4, 'float32'))
        # n chosen so n * 4 wraps to a tiny number in u64
        wrap_n = (1 << 62) + 1
        with pytest.raises(PsServerError, match='shorter than count'):
            c._call(2, 'w', struct.pack('<Q', wrap_n) + b'\0' * 4)
        # sparse ids leg too
        c.init_sparse('t', rows=10, dim=2, optimizer='sgd', lr=1.0)
        wrap_k = (1 << 61) + 1  # k * 8 wraps
        with pytest.raises(PsServerError, match='shorter than count'):
            c._call(5, 't', struct.pack('<Q', wrap_k) + b'\0' * 8)
        assert 'w' in c.list_vars()  # server alive and sane
        c.close()
    finally:
        srv.stop()


def test_deadline_on_hung_server():
    """A server that accepts but never replies: the call returns an
    error within (retries+1) * deadline instead of hanging forever."""
    silent = socket.socket()
    silent.bind(('127.0.0.1', 0))
    silent.listen(1)
    port = silent.getsockname()[1]
    try:
        c = PsClient('127.0.0.1:%d' % port, deadline_ms=300,
                     retry_times=1)
        t0 = time.monotonic()
        with pytest.raises(RpcDeadlineError, match='after 2 attempts'):
            c.pull_dense('w')
        assert time.monotonic() - t0 < 5.0
    finally:
        silent.close()


def test_deadline_on_dead_server():
    """Connection-refused endpoint: bounded retries then a clear
    error."""
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    c = PsClient('127.0.0.1:%d' % port, deadline_ms=200, retry_times=2)
    t0 = time.monotonic()
    with pytest.raises(RpcDeadlineError):
        c.list_vars()
    assert time.monotonic() - t0 < 5.0


def test_named_barriers_are_independent():
    """Two barrier groups must not share a counter: an arrival in group
    'b' cannot release a waiter in group 'a' (the v1 global-counter
    bug)."""
    import threading
    srv = PsServer()
    try:
        released = []

        def waiter():
            cw = PsClient(srv.endpoint)
            cw.barrier(2, group='a')
            released.append(time.monotonic())
            cw.close()

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        c = PsClient(srv.endpoint)
        c.barrier(1, group='b')   # would wrongly release 'a' pre-fix
        time.sleep(0.3)
        assert not released       # 'a' still parked
        c.barrier(2, group='a')   # second arrival releases both
        t.join(timeout=10)
        assert released
        with pytest.raises(PsServerError, match='>= 1'):
            c.barrier(0)
        c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# server-side optimizer rules

def _np_adam_steps(w, grads, lr, b1=0.9, b2=0.999, eps=1e-8):
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    return w


def test_dense_momentum_and_adam_rules():
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        rng = np.random.RandomState(0)
        w0 = rng.randn(6).astype('float32')
        grads = [rng.randn(6).astype('float32') for _ in range(5)]

        c.init_dense('wm', w0)
        c.conf_dense('wm', optimizer='momentum', lr=0.1, momentum=0.9)
        for g in grads:
            c.push_dense_grad('wm', g)
        w, vel = w0.copy(), np.zeros_like(w0)
        for g in grads:
            vel = 0.9 * vel + g
            w = w - 0.1 * vel
        np.testing.assert_allclose(c.pull_dense('wm'), w, rtol=1e-5)

        c.init_dense('wa', w0)
        c.conf_dense('wa', optimizer='adam', lr=0.05)
        for g in grads:
            c.push_dense_grad('wa', g)
        np.testing.assert_allclose(
            c.pull_dense('wa'),
            _np_adam_steps(w0.astype(np.float64), grads, 0.05),
            rtol=1e-4)
        c.close()
    finally:
        srv.stop()


def test_sparse_adam_rows():
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_sparse('e', rows=50, dim=3, optimizer='adam', lr=0.05)
        ids = np.array([7, 20], 'int64')
        w0 = np.arange(6, dtype='float32').reshape(2, 3)
        c.set_rows('e', ids, w0)
        grads = [np.full((2, 3), 0.5, 'float32') * (i + 1)
                 for i in range(4)]
        for g in grads:
            c.push_rows('e', ids, g)
        np.testing.assert_allclose(
            c.pull_rows('e', ids, 3),
            _np_adam_steps(w0.astype(np.float64), grads, 0.05),
            rtol=1e-4)
        # untouched rows have untouched (t=0) state
        np.testing.assert_allclose(
            c.pull_rows('e', np.array([0], 'int64'), 3),
            np.zeros((1, 3)))
        c.close()
    finally:
        srv.stop()


def test_embedded_store_rules_match_rpc_server():
    """ParameterServerStore (embedded) and the native server apply
    identical rules — fleet code may swap one for the other."""
    from paddle_tpu.distributed import ParameterServerStore
    srv = PsServer()
    try:
        remote = RpcParameterServerStore(srv.endpoint, optimizer='adam',
                                         lr=0.02)
        local = ParameterServerStore()
        rng = np.random.RandomState(1)
        w0 = rng.randn(4, 2).astype('float32')
        remote.init_var('p', w0)
        local.init_var('p', w0)
        local.conf_var('p', optimizer='adam', lr=0.02)
        for _ in range(6):
            g = rng.randn(4, 2).astype('float32')
            remote.apply_grad('p', g)
            local.apply_grad('p', g)
        np.testing.assert_allclose(remote.get('p'), local.get('p'),
                                   rtol=1e-5, atol=1e-6)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# checkpoint / restore

def test_save_load_roundtrip_fresh_process(tmp_path):
    """Snapshot carries values AND optimizer state: a restored server
    continues the update sequence bit-for-bit like an uninterrupted
    one."""
    path = str(tmp_path / 'snap.ptps')
    rng = np.random.RandomState(2)
    w0 = rng.randn(8).astype('float32')
    grads = [rng.randn(8).astype('float32') for _ in range(6)]

    srv = PsServer()
    c = PsClient(srv.endpoint)
    c.init_dense('w', w0)
    c.conf_dense('w', optimizer='adam', lr=0.1)
    c.init_sparse('e', rows=20, dim=2, optimizer='adagrad', lr=0.5)
    ids = np.array([3, 11], 'int64')
    c.set_rows('e', ids, np.ones((2, 2), 'float32'))
    for g in grads[:3]:
        c.push_dense_grad('w', g)
        c.push_rows('e', ids, np.ones((2, 2), 'float32'))
    c.save(path)

    # uninterrupted continuation
    for g in grads[3:]:
        c.push_dense_grad('w', g)
        c.push_rows('e', ids, np.ones((2, 2), 'float32'))
    w_ref = c.pull_dense('w')
    e_ref = c.pull_rows('e', ids, 2)
    c.close()
    srv.stop()  # "crash"

    srv2 = PsServer()  # fresh process state
    try:
        c2 = PsClient(srv2.endpoint)
        c2.load(path)
        assert sorted(c2.list_vars()) == ['e', 'w']
        for g in grads[3:]:
            c2.push_dense_grad('w', g)
            c2.push_rows('e', ids, np.ones((2, 2), 'float32'))
        np.testing.assert_allclose(c2.pull_dense('w'), w_ref,
                                   rtol=1e-6)
        np.testing.assert_allclose(c2.pull_rows('e', ids, 2), e_ref,
                                   rtol=1e-6)
        c2.close()
    finally:
        srv2.stop()


def test_load_while_pushing_is_safe(tmp_path):
    """LOAD must not free table objects other threads still hold: a
    concurrent pusher sees either old or new state, and the server
    survives (the use-after-free regression)."""
    import threading
    path = str(tmp_path / 'live.ptps')
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_dense('w', np.zeros(16, 'float32'))
        c.init_sparse('e', rows=100, dim=4, optimizer='adagrad', lr=0.1)
        c.save(path)
        stop = threading.Event()
        errs = []

        def pusher():
            cp = PsClient(srv.endpoint)
            ids = np.arange(8, dtype='int64')
            g = np.ones((8, 4), 'float32')
            try:
                while not stop.is_set():
                    cp.push_dense_grad('w', np.ones(16, 'float32'))
                    cp.push_rows('e', ids, g)
            except (PsServerError, ConnectionError):
                pass  # transient shape/kind mismatch mid-swap is fine
            except Exception as exc:
                errs.append(exc)
            finally:
                cp.close()

        threads = [threading.Thread(target=pusher) for _ in range(3)]
        for t in threads:
            t.start()
        for _ in range(30):
            c.load(path)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        # server still sane after 30 live reloads under push load
        assert sorted(c.list_vars()) == ['e', 'w']
        assert c.pull_dense('w').shape == (16,)
        c.close()
    finally:
        srv.stop()


def test_conf_dense_rule_change_resets_state():
    """momentum -> adam reconfigure must not leave a sized m with an
    empty v (out-of-bounds write regression)."""
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_dense('w', np.zeros(8, 'float32'))
        c.conf_dense('w', optimizer='momentum', lr=0.1, momentum=0.9)
        c.push_dense_grad('w', np.ones(8, 'float32'))
        c.conf_dense('w', optimizer='adam', lr=0.1)
        c.push_dense_grad('w', np.ones(8, 'float32'))  # crashed pre-fix
        got = c.pull_dense('w')
        assert np.isfinite(got).all()
        # fresh adam state: first step moves by ~lr exactly
        np.testing.assert_allclose(got, -0.1 - 0.1 / (1 + 1e-8),
                                    rtol=1e-4)
        c.close()
    finally:
        srv.stop()


def test_state_dict_with_zero_row_shard():
    """vocab < n_servers: the empty shard must not break state_dict."""
    from paddle_tpu.parallel.sparse_embedding import (
        RpcShardedEmbedding, HostShardedEmbedding)
    name = 'tiny_emb'
    servers = [PsServer() for _ in range(4)]
    try:
        emb = RpcShardedEmbedding(name, 3, 4,
                                  [s.endpoint for s in servers],
                                  optimizer='adagrad',
                                  learning_rate=0.1, seed=1)
        emb._push(np.array([0, 2], 'int64'), np.ones((2, 4), 'float32'))
        sd = emb.state_dict()
        assert sd[name + '.table'].shape == (3, 4)
        assert sd[name + '.acc'].shape == (3,)
    finally:
        HostShardedEmbedding._REGISTRY.pop(name, None)
        for s in servers:
            s.stop()


def test_save_error_paths(tmp_path):
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        with pytest.raises(PsServerError, match='cannot open'):
            c.save('/nonexistent_dir_xyz/snap.ptps')
        with pytest.raises(PsServerError, match='cannot open'):
            c.load(str(tmp_path / 'missing.ptps'))
        bad = tmp_path / 'garbage.ptps'
        bad.write_bytes(b'not a snapshot')
        with pytest.raises(PsServerError, match='bad snapshot'):
            c.load(str(bad))
        c.close()
    finally:
        srv.stop()


def test_rpc_embedding_kill_restart_loss_parity(tmp_path):
    """THE durability criterion: train over RPC shards, checkpoint,
    KILL the server processes, restart fresh ones, restore, continue —
    loss trajectory matches an uninterrupted run exactly."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.sparse_embedding import (
        RpcShardedEmbedding, HostShardedEmbedding)

    name = 'dur_emb'
    rng = np.random.RandomState(0)
    feeds = [(rng.randint(0, 200, (16, 5)).astype('int64'),
              rng.rand(16, 1).astype('float32')) for _ in range(30)]

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data('ids', shape=[5], dtype='int64')
            label = fluid.layers.data('label', shape=[1],
                                      dtype='float32')
            rows = HostShardedEmbedding._REGISTRY[name].lookup(ids)
            feat = fluid.layers.reshape(rows, [0, 5 * 8])
            pred = fluid.layers.fc(
                feat, 1, param_attr=fluid.ParamAttr(name='dur_fc_w'),
                bias_attr=fluid.ParamAttr(name='dur_fc_b'))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, label))
            fluid.optimizer.SGD(0.1).minimize(loss)
            HostShardedEmbedding._REGISTRY[name].apply_gradients(main)
        return main, startup, loss

    def run_steps(main, startup_or_none, loss, scope, feed_list,
                  dense_init=None):
        out = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            if startup_or_none is not None:
                exe.run(startup_or_none)
            if dense_init:
                for k, v in dense_init.items():
                    scope.set_var(k, v)
            for ids_np, y_np in feed_list:
                l, = exe.run(main, feed={'ids': ids_np, 'label': y_np},
                             fetch_list=[loss])
                out.append(float(np.asarray(l).ravel()[0]))
        return out

    srv1, srv2 = PsServer(), PsServer()
    try:
        emb = RpcShardedEmbedding(name, 200, 8,
                                  [srv1.endpoint, srv2.endpoint],
                                  optimizer='adagrad',
                                  learning_rate=0.1, seed=5)
        main, startup, loss = build()
        scope = fluid.Scope()
        run_steps(main, startup, loss, scope, feeds[:10])
        # checkpoint: server-side snapshot + trainer-side dense params
        paths = emb.checkpoint(str(tmp_path))
        assert all(os.path.exists(p) for p in paths)
        dense_snap = {
            n: np.array(fluid.core.as_array(scope.find_var(n)),
                        copy=True)
            for n in ('dur_fc_w', 'dur_fc_b')}
        # uninterrupted continuation = the reference trajectory
        ref = run_steps(main, None, loss, scope, feeds[10:])

        # ---- crash: kill both pservers ----
        srv1.stop()
        srv2.stop()
        HostShardedEmbedding._REGISTRY.pop(name, None)

        srv1b, srv2b = PsServer(), PsServer()
        try:
            emb2 = RpcShardedEmbedding(
                name, 200, 8, [srv1b.endpoint, srv2b.endpoint],
                optimizer='adagrad', learning_rate=0.1,
                initializer_scale=0)
            emb2.restore(str(tmp_path))
            scope2 = fluid.Scope()
            # fresh process: run startup for aux vars (lr), then load
            # the checkpointed dense params over the random init
            got = run_steps(main, startup, loss, scope2, feeds[10:],
                            dense_init=dense_snap)
            np.testing.assert_allclose(got, ref, rtol=1e-5)
        finally:
            srv1b.stop()
            srv2b.stop()
    finally:
        HostShardedEmbedding._REGISTRY.pop(name, None)
        srv1.stop()
        srv2.stop()


def test_rpc_embedding_state_dict_roundtrip():
    """Pull-all fallback: state_dict reassembles the full table on the
    trainer; load_state_dict pushes it into a different server set."""
    from paddle_tpu.parallel.sparse_embedding import (
        RpcShardedEmbedding, HostShardedEmbedding)
    name = 'sd_emb'
    srv1, srv2 = PsServer(), PsServer()
    srv3 = PsServer()
    try:
        emb = RpcShardedEmbedding(name, 101, 4,
                                  [srv1.endpoint, srv2.endpoint],
                                  optimizer='adagrad',
                                  learning_rate=0.1, seed=9)
        ids = np.array([0, 1, 50, 100], 'int64')
        emb._push(ids, np.ones((4, 4), 'float32'))
        sd = emb.state_dict()
        assert sd[name + '.table'].shape == (101, 4)
        assert sd[name + '.acc'].shape == (101,)
        want = emb._pull(ids)

        HostShardedEmbedding._REGISTRY.pop(name, None)
        # single-shard target: different sharding layout, same content
        emb2 = RpcShardedEmbedding(name, 101, 4, [srv3.endpoint],
                                   optimizer='adagrad',
                                   learning_rate=0.1,
                                   initializer_scale=0)
        emb2.load_state_dict(sd)
        np.testing.assert_allclose(emb2._pull(ids), want, rtol=1e-6)
        # optimizer state travelled too: one more identical push on
        # both sides stays identical
        emb2._push(ids, np.ones((4, 4), 'float32'))
        emb._push(ids, np.ones((4, 4), 'float32'))
        np.testing.assert_allclose(emb2._pull(ids), emb._pull(ids),
                                   rtol=1e-6)
    finally:
        HostShardedEmbedding._REGISTRY.pop(name, None)
        for s in (srv1, srv2, srv3):
            s.stop()


def test_attach_mismatch_raises():
    from paddle_tpu.parallel.sparse_embedding import (
        RpcShardedEmbedding, HostShardedEmbedding)
    name = 'mm_emb'
    srv = PsServer()
    try:
        RpcShardedEmbedding(name, 100, 8, [srv.endpoint],
                            optimizer='adagrad', learning_rate=0.1)
        HostShardedEmbedding._REGISTRY.pop(name, None)
        with pytest.raises(ValueError, match='incompatible'):
            RpcShardedEmbedding(name, 100, 16, [srv.endpoint],
                                optimizer='adagrad', learning_rate=0.1)
        with pytest.raises(ValueError, match='incompatible'):
            RpcShardedEmbedding(name, 100, 8, [srv.endpoint],
                                optimizer='sgd', learning_rate=0.1)
    finally:
        HostShardedEmbedding._REGISTRY.pop(name, None)
        srv.stop()


def test_save_persistables_includes_ps_tables(tmp_path):
    """fluid.io.save_persistables on a program with a PS-resident
    table saves (and load restores) the table state too — the
    distributed-aware save of reference io.py:393."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.sparse_embedding import (
        RpcShardedEmbedding, HostShardedEmbedding)
    name = 'iosave_emb'
    srv = PsServer()
    try:
        emb = RpcShardedEmbedding(name, 60, 4, [srv.endpoint],
                                  optimizer='adagrad',
                                  learning_rate=0.1, seed=2)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data('ids', shape=[3], dtype='int64')
            rows = emb.lookup(ids)
            out = fluid.layers.reduce_sum(rows)
        probe = np.array([1, 5, 59], 'int64')
        before = emb._pull(probe)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            fluid.io.save_persistables(exe, str(tmp_path), main)
            assert os.path.exists(
                os.path.join(str(tmp_path), '__dist_tables__.npz'))
            # clobber the server rows, then restore
            emb._push(probe, np.full((3, 3, 4), 9.0, 'float32')[0])
            fluid.io.load_persistables(exe, str(tmp_path), main)
        np.testing.assert_allclose(emb._pull(probe), before, rtol=1e-6)
    finally:
        HostShardedEmbedding._REGISTRY.pop(name, None)
        srv.stop()


# ---------------------------------------------------------------------------
# heartbeat wired to the server

def test_server_detects_lost_trainer():
    """heart_beat_monitor.h end-to-end: a trainer that stops pinging
    is marked LOST by the SERVER's monitor; a completing trainer is
    COMPLETED."""
    srv = PsServer()
    try:
        admin = PsClient(srv.endpoint)
        hb0 = TrainerHeartbeat(srv.endpoint, trainer_id=0, timeout=0.6)
        hb1 = TrainerHeartbeat(srv.endpoint, trainer_id=1, timeout=0.6)
        time.sleep(0.3)
        st = admin.query_trainers()
        assert st[0]['status'] == 'RUNNING'
        assert st[1]['status'] == 'RUNNING'
        hb1.complete()          # clean shutdown
        hb0.stop()              # silent death: stops pinging
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = admin.query_trainers()
            if st[0]['status'] == 'LOST':
                break
            time.sleep(0.2)
        assert st[0]['status'] == 'LOST', st
        assert st[1]['status'] == 'COMPLETED', st
        admin.close()
    finally:
        srv.stop()


def test_killed_trainer_subprocess_detected():
    """Real process death: a trainer SUBPROCESS registers, heartbeats,
    then is SIGKILLed; the server reports it lost."""
    trainer_code = '''
import sys, time
sys.path.insert(0, %r)
from paddle_tpu.distributed import TrainerHeartbeat
hb = TrainerHeartbeat('127.0.0.1:' + sys.argv[1], trainer_id=7,
                      timeout=0.8)
print('UP', flush=True)
time.sleep(60)
'''
    srv = PsServer()
    try:
        env = dict(os.environ, PYTHONPATH=REPO)
        proc = subprocess.Popen(
            [sys.executable, '-c', trainer_code % REPO,
             str(srv.port)], stdout=subprocess.PIPE, text=True,
            env=env)
        try:
            assert proc.stdout.readline().strip() == 'UP'
            admin = PsClient(srv.endpoint)
            assert admin.query_trainers()[7]['status'] == 'RUNNING'
            proc.kill()
            proc.wait()
            deadline = time.monotonic() + 15
            status = None
            while time.monotonic() < deadline:
                status = admin.query_trainers()[7]['status']
                if status == 'LOST':
                    break
                time.sleep(0.2)
            assert status == 'LOST'
            admin.close()
        finally:
            proc.kill()
    finally:
        srv.stop()
