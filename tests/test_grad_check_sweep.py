"""Systematic gradient-check sweep: analytic (vjp-synthesized) grads vs
centered finite differences across the differentiable op surface.

This is the reference's per-op test backbone
(python/paddle/fluid/tests/unittests/test_*_op.py check_grad over
op_test.py:57 get_numeric_gradient) applied wholesale: every op here
validates BOTH its lowering and the autodiff pipeline end-to-end through
the real executor.

Inputs are chosen inside each op's smooth region (away from kinks like
relu@0, |x|@0, domain edges of log/sqrt/acos) — the same discipline the
reference tests use when picking OpTest inputs.
"""

import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(42)


def smooth_away_from(x, bad, margin=0.15):
    """Nudge entries within `margin` of any kink point in `bad`."""
    x = np.array(x)
    for b in bad:
        close = np.abs(x - b) < margin
        x[close] = b + margin * np.sign(x[close] - b + 1e-8) * 2
    return x


# op -> (input generator, attrs)
UNARY = {
    'sigmoid': (lambda: rng.randn(2, 3), {}),
    'logsigmoid': (lambda: rng.randn(2, 3), {}),
    'tanh': (lambda: rng.randn(2, 3), {}),
    'relu': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]), {}),
    'gelu': (lambda: rng.randn(2, 3), {}),
    'elu': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]), {'alpha': 1.0}),
    'selu': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]), {}),
    'softplus': (lambda: rng.randn(2, 3), {}),
    'softsign': (lambda: rng.randn(2, 3), {}),
    'sqrt': (lambda: rng.rand(2, 3) + 0.5, {}),
    'rsqrt': (lambda: rng.rand(2, 3) + 0.5, {}),
    'square': (lambda: rng.randn(2, 3), {}),
    'exp': (lambda: rng.randn(2, 3) * 0.5, {}),
    'log': (lambda: rng.rand(2, 3) + 0.5, {}),
    'log1p': (lambda: rng.rand(2, 3) + 0.5, {}),
    'abs': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]), {}),
    'cos': (lambda: rng.randn(2, 3), {}),
    'sin': (lambda: rng.randn(2, 3), {}),
    'acos': (lambda: rng.uniform(-0.7, 0.7, (2, 3)), {}),
    'asin': (lambda: rng.uniform(-0.7, 0.7, (2, 3)), {}),
    'atan': (lambda: rng.randn(2, 3), {}),
    'sinh': (lambda: rng.randn(2, 3) * 0.5, {}),
    'cosh': (lambda: rng.randn(2, 3) * 0.5, {}),
    'erf': (lambda: rng.randn(2, 3), {}),
    'mish': (lambda: rng.randn(2, 3), {}),
    'swish': (lambda: rng.randn(2, 3), {'beta': 1.0}),
    'hard_sigmoid': (lambda: rng.uniform(-0.15, 0.15, (2, 3)),
                     {'slope': 0.2, 'offset': 0.5}),
    'hard_swish': (lambda: smooth_away_from(rng.randn(2, 3),
                                            [-3.0, 3.0]), {}),
    'leaky_relu': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]),
                   {'alpha': 0.1}),
    'softshrink': (lambda: smooth_away_from(rng.randn(2, 3) * 2,
                                            [-0.5, 0.5]), {'lambda': 0.5}),
    'hard_shrink': (lambda: smooth_away_from(rng.randn(2, 3) * 2,
                                             [-0.5, 0.5]),
                    {'threshold': 0.5}),
    'tanh_shrink': (lambda: rng.randn(2, 3), {}),
    'thresholded_relu': (lambda: smooth_away_from(rng.randn(2, 3) * 2,
                                                  [1.0]),
                         {'threshold': 1.0}),
    'stanh': (lambda: rng.randn(2, 3), {}),
    'relu6': (lambda: smooth_away_from(rng.randn(2, 3) * 3, [0.0, 6.0]),
              {}),
    'brelu': (lambda: smooth_away_from(rng.randn(2, 3) * 5,
                                       [1.0, 4.0]),
              {'t_min': 1.0, 't_max': 4.0}),
    'pow': (lambda: rng.rand(2, 3) + 0.5, {'factor': 2.5}),
    'scale': (lambda: rng.randn(2, 3), {'scale': 3.0, 'bias': 1.0}),
    'reciprocal': (lambda: rng.rand(2, 3) + 0.5, {}),
    'softmax': (lambda: rng.randn(2, 4), {}),
    'log_softmax': (lambda: rng.randn(2, 4), {}),
    'reduce_sum': (lambda: rng.randn(2, 3), {'reduce_all': True}),
    'reduce_mean': (lambda: rng.randn(2, 3), {'dim': [1]}),
    'reduce_prod': (lambda: rng.rand(2, 3) + 0.5, {'reduce_all': True}),
    'transpose': (lambda: rng.randn(2, 3), {'axis': [1, 0]}),
    'reshape': (lambda: rng.randn(2, 3), {'shape': [3, 2]}),
    'squeeze': (lambda: rng.randn(2, 1, 3), {'axes': [1]}),
    'unsqueeze': (lambda: rng.randn(2, 3), {'axes': [1]}),
    'clip': (lambda: smooth_away_from(rng.randn(2, 3) * 2,
                                      [-1.0, 1.0]),
             {'min': -1.0, 'max': 1.0}),
    'squared_l2_norm': (lambda: rng.randn(2, 3), {}),
    'l1_norm': (lambda: smooth_away_from(rng.randn(2, 3), [0.0]), {}),
    'mean': (lambda: rng.randn(2, 3), {}),
    'pad': (lambda: rng.randn(2, 3), {'paddings': [0, 1, 1, 0],
                                      'pad_value': 0.0}),
    'flatten': (lambda: rng.randn(2, 3), {'axis': 1}),
}

BINARY = {
    'elementwise_add': (lambda: (rng.randn(2, 3), rng.randn(2, 3)), {}),
    'elementwise_sub': (lambda: (rng.randn(2, 3), rng.randn(2, 3)), {}),
    'elementwise_mul': (lambda: (rng.randn(2, 3), rng.randn(2, 3)), {}),
    'elementwise_div': (lambda: (rng.randn(2, 3),
                                 rng.rand(2, 3) + 0.5), {}),
    'elementwise_pow': (lambda: (rng.rand(2, 3) + 0.5,
                                 rng.rand(2, 3) + 0.5), {}),
    'elementwise_max': (lambda: (rng.randn(2, 3),
                                 rng.randn(2, 3) + 5.0), {}),
    'elementwise_min': (lambda: (rng.randn(2, 3),
                                 rng.randn(2, 3) + 5.0), {}),
    'matmul': (lambda: (rng.randn(2, 3), rng.randn(3, 4)), {}),
    'mul': (lambda: (rng.randn(2, 3), rng.randn(3, 4)),
            {'x_num_col_dims': 1, 'y_num_col_dims': 1}),
    'dot': (lambda: (rng.randn(4), rng.randn(4)), {}),
    'cos_sim': (lambda: (rng.randn(2, 4), rng.randn(2, 4)), {}),
    'bilinear_tensor_product': None,  # needs Weight slot; covered elsewhere
    'mse_loss': None,
}


@pytest.mark.parametrize('op', sorted(UNARY))
def test_unary_grad(op):
    gen, attrs = UNARY[op]
    x = gen().astype('float32')
    t = OpTest()
    try:
        t.check_grad(op, {'X': x}, attrs)
    except AssertionError as e:
        if 'no grad var' in str(e):
            pytest.skip('%s: non-differentiable lowering' % op)
        raise


@pytest.mark.parametrize('op', sorted(k for k, v in BINARY.items() if v))
def test_binary_grad(op):
    gen, attrs = BINARY[op]
    x, y = gen()
    t = OpTest()
    t.check_grad(op, {'X': x.astype('float32'),
                      'Y': y.astype('float32')}, attrs)


def test_layer_norm_grad():
    t = OpTest()
    t.check_grad('layer_norm',
                 {'X': rng.randn(2, 6).astype('float32'),
                  'Scale': (rng.rand(6) + 0.5).astype('float32'),
                  'Bias': rng.randn(6).astype('float32')},
                 {'epsilon': 1e-5, 'begin_norm_axis': 1},
                 out_slot='Y')


def test_conv2d_grad():
    t = OpTest()
    t.check_grad('conv2d',
                 {'Input': rng.randn(1, 2, 5, 5).astype('float32'),
                  'Filter': rng.randn(3, 2, 3, 3).astype('float32')},
                 {'strides': [1, 1], 'paddings': [1, 1],
                  'dilations': [1, 1], 'groups': 1},
                 out_slot='Output')


def test_depthwise_conv2d_grad():
    t = OpTest()
    t.check_grad('depthwise_conv2d',
                 {'Input': rng.randn(1, 3, 5, 5).astype('float32'),
                  'Filter': rng.randn(3, 1, 3, 3).astype('float32')},
                 {'strides': [1, 1], 'paddings': [1, 1],
                  'dilations': [1, 1], 'groups': 3},
                 out_slot='Output')


def test_pool2d_avg_grad():
    t = OpTest()
    t.check_grad('pool2d', {'X': rng.randn(1, 2, 6, 6).astype('float32')},
                 {'pooling_type': 'avg', 'ksize': [2, 2],
                  'strides': [2, 2], 'paddings': [0, 0]})


def test_batch_norm_grad():
    t = OpTest()
    t.check_grad('batch_norm',
                 {'X': rng.randn(4, 3, 2, 2).astype('float32') + 1.0,
                  'Scale': (rng.rand(3) + 0.5).astype('float32'),
                  'Bias': rng.randn(3).astype('float32'),
                  'Mean': np.zeros(3, 'float32'),
                  'Variance': np.ones(3, 'float32')},
                 {'epsilon': 1e-5, 'is_test': False,
                  'momentum': 0.9},
                 out_slot='Y',
                 grad_slots=['X', 'Scale', 'Bias'],
                 stop_gradients=('Mean', 'Variance'))


def test_softmax_with_cross_entropy_grad():
    t = OpTest()
    t.check_grad('softmax_with_cross_entropy',
                 {'Logits': rng.randn(4, 5).astype('float32'),
                  'Label': rng.randint(0, 5, (4, 1)).astype('int64')},
                 {'soft_label': False},
                 out_slot='Loss', grad_slots=['Logits'])


def test_lookup_table_grad():
    t = OpTest()
    t.check_grad('lookup_table_v2',
                 {'W': rng.randn(7, 4).astype('float32'),
                  'Ids': rng.randint(0, 7, (3, 2)).astype('int64')},
                 {}, grad_slots=['W'])


def test_gather_grad():
    t = OpTest()
    t.check_grad('gather',
                 {'X': rng.randn(6, 3).astype('float32'),
                  'Index': np.array([0, 2, 5], 'int32')},
                 {}, grad_slots=['X'])


def test_while_grad_without_bound_auto_buckets():
    """Gradients through an UNBOUNDED while work via the executor's
    trip-count auto-bucketing (round 3): v doubles until >= 10, so the
    trip count is data-dependent and dout/dx = 2^trips.  See
    tests/test_control_flow_grad.py for the full coverage."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[1], dtype='float32')
        x.stop_gradient = False
        ten = fluid.layers.fill_constant([1], 'float32', 10.0)
        out, = fluid.layers.while_loop(
            lambda v: fluid.layers.less_than(v, ten),
            lambda v: fluid.layers.elementwise_mul(
                v, fluid.layers.fill_constant([1], 'float32', 2.0)),
            [fluid.layers.elementwise_add(
                x, fluid.layers.fill_constant([1], 'float32', 0.0))])
        loss = fluid.layers.reduce_sum(out)
        fluid.backward.append_backward(loss)
    gname = main._grad_name_map['x']
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for x0, trips in ((1.0, 4), (3.0, 2), (0.2, 6)):
            xv = np.array([[x0]], 'float32')
            outv, dx = exe.run(main, feed={'x': xv},
                               fetch_list=[out.name, gname])
            np.testing.assert_allclose(
                np.asarray(outv).ravel()[0], x0 * 2 ** trips,
                rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(dx).ravel()[0], 2 ** trips, rtol=1e-6)


def test_cond_grad_differentiates_taken_branch():
    """cond() gradients follow the branch actually taken at runtime —
    NOT the always-computed false branch (the false branch only gives
    the outputs their shapes; conditional_block_grad re-runs the true
    branch under lax.cond's vjp)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[1], dtype='float32')
        x.stop_gradient = False
        zero = fluid.layers.fill_constant([1], 'float32', 0.0)
        from paddle_tpu.fluid.layers import ops as _ops
        pred = _ops.greater_than(fluid.layers.reduce_sum(x), zero)
        y = fluid.layers.cond(pred,
                              lambda: fluid.layers.scale(x, scale=2.0),
                              lambda: fluid.layers.scale(x, scale=3.0))
        loss = fluid.layers.mean(y)
        fluid.backward.append_backward(loss)
    gname = main._grad_name_map['x']
    for xv, want in ((np.array([[5.0]], np.float32), 2.0),
                     (np.array([[-5.0]], np.float32), 3.0)):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            dx, = exe.run(main, feed={'x': xv}, fetch_list=[gname])
        np.testing.assert_allclose(np.asarray(dx).ravel()[0], want,
                                   rtol=1e-6)


def test_nested_cond_in_while_grad():
    """A conditional_block nested inside a bounded while
    differentiates: the while grad's scan-vjp traces the nested branch
    as lax.cond.  acc doubles 3x (pred always true): dloss/dx = 8."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.layers.control_flow import ConditionalBlock
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[1], dtype='float32')
        x.stop_gradient = False
        acc = fluid.layers.elementwise_add(
            x, fluid.layers.fill_constant([1], 'float32', 0.0))
        i = fluid.layers.fill_constant([1], 'float32', 0.0)
        three = fluid.layers.fill_constant([1], 'float32', 3.0)
        cond_v = fluid.layers.less_than(i, three)
        w = fluid.layers.While(cond_v, max_trip_count=4)
        with w.block():
            from paddle_tpu.fluid.layers import ops as _ops
            pred = _ops.greater_than(
                acc, fluid.layers.fill_constant([1], 'float32', -1e9))
            cb = ConditionalBlock(pred)
            with cb.block():
                fluid.layers.assign(
                    fluid.layers.scale(acc, scale=2.0), acc)
            fluid.layers.assign(
                fluid.layers.elementwise_add(
                    i, fluid.layers.fill_constant([1], 'float32', 1.0)),
                i)
            fluid.layers.assign(fluid.layers.less_than(i, three), cond_v)
        loss = fluid.layers.mean(acc)
        fluid.backward.append_backward(loss)
    gname = main._grad_name_map['x']
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        xv = np.array([[1.0]], np.float32)
        dx, loss_v = exe.run(main, feed={'x': xv},
                             fetch_list=[gname, loss])
    np.testing.assert_allclose(np.asarray(loss_v).ravel()[0], 8.0,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx).ravel()[0], 8.0,
                               rtol=1e-6)
