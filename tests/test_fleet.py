"""fluid.fleet: SLO-aware serving fleet over N ServingExecutor
replicas.

Covers the fleet-plane contract: router placement is sticky (a
tenant's warmed ladder keeps paying off), a firing SLO objective on
one class sheds the OTHER classes while the protected class keeps
serving, eviction picks the priced-cheapest candidate with the whole
candidate table in the decision log, migration lands bitwise-equal on
the target with zero post-warmup retraces, the freeze/revert contract
(FLAGS_fleet=0 logs intents without acting; revert() restores the
as-registered placements even frozen), and the /statusz fleet section
is JSON-able."""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (fleet, health, layers, memviz, monitor,
                              serving, slo, timeseries)


@pytest.fixture(autouse=True)
def _clean():
    yield
    fluid.set_flags({'FLAGS_fleet': True,
                     'FLAGS_fleet_interval_s': 1.0,
                     'FLAGS_fleet_imbalance_depth': 8,
                     'FLAGS_fleet_shed_mode': 'shed',
                     'FLAGS_fleet_defer_close_wait_s': 0.02,
                     'FLAGS_fleet_rewarmup_default_s': 1.0,
                     'FLAGS_slo_hysteresis': 3,
                     'FLAGS_timeseries': False})
    fleet.reset()
    timeseries.reset()
    slo.reset()
    monitor.reset()


@pytest.fixture
def exe():
    return fluid.Executor(fluid.XLAPlace(0))


def _build_mlp(width=16, seed=5, in_w=8):
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = seed
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[in_w], dtype='float32')
        h = layers.fc(x, width, act='relu')
        y = layers.fc(h, 6, act='softmax')
    return main_p, startup, y


def _make_fleet(exe, replicas=2, tenants=(('a', 16, 'interactive'),
                                          ('b', 24, 'batch'))):
    fl = fleet.Fleet()
    for i in range(replicas):
        fl.add_replica('r%d' % i,
                       serving.ServingExecutor(max_batch=4,
                                               executor=exe))
    built = {}
    for i, (name, width, cls) in enumerate(tenants):
        mp, sp, y = _build_mlp(width=width, seed=5 + i)
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(sp)
        fl.register_tenant(name, mp, ['x'], [y], scope=sc,
                           slo_class=cls)
        built[name] = (mp, sc, y)
    return fl, built


class TestRouter:
    def test_placement_spreads_and_sticks(self, exe):
        fl, _ = _make_fleet(exe, replicas=2)
        fl.warmup(wait=True)
        # the second tenant lands on the emptier replica (scored, not
        # first-fit)
        placed = fl.placement()
        assert set(placed.values()) == {'r0', 'r1'}
        # sticky: repeated submits never move the tenant
        rng = np.random.RandomState(0)
        before = dict(placed)
        for _ in range(6):
            rows = int(rng.randint(1, 4))
            xv = rng.randn(rows, 8).astype('float32')
            fl.submit('a', {'x': xv}).result(120)
        assert fl.placement() == before
        # every request was served by the placed replica
        rep = fl.replica(before['a']).resident_report()
        trep = [t for t in rep['tenants'] if t['tenant'] == 'a'][0]
        assert trep['requests_served'] == 6
        assert monitor.counter_value('fleet/routed_requests') == 6
        # every placement decision logged the per-replica signals
        places = [d for d in fleet.decisions() if d['kind'] == 'place']
        assert len(places) == 2
        for d in places:
            assert set(d['info']['signals']) == {'r0', 'r1'}
            assert d['acted']

    def test_unplaced_tenant_rejected(self, exe):
        fl, _ = _make_fleet(exe, replicas=1)
        with pytest.raises(KeyError):
            fl.submit('nope', {'x': np.zeros((1, 8), 'float32')})


class TestClassPolicy:
    def _fire(self, fl):
        """Drive a declared objective to 'firing' through the real
        sampling cadence (the fleet tick rides the same sample)."""
        fluid.set_flags({'FLAGS_slo_hysteresis': 1,
                         'FLAGS_fleet_interval_s': 0.0})
        slo.declare('fleet/_test_breach value < 1', name='fleet-obj')
        fl.protect_class('interactive', 'fleet-obj')
        monitor.add('fleet/_test_breach', 100)
        timeseries.sample(now=1000.0)   # sample -> slo eval -> tick
        timeseries.sample(now=1002.0)
        assert [o['state'] for o in slo.objectives()] == ['firing']

    def test_firing_objective_sheds_other_class_only(self, exe):
        fl, _ = _make_fleet(exe, replicas=1)
        fl.warmup(wait=True)
        self._fire(fl)
        xv = np.random.RandomState(0).randn(2, 8).astype('float32')
        # the batch class fails fast; interactive keeps serving
        with pytest.raises(serving.ServingDegraded):
            fl.submit('b', {'x': xv}).result(10)
        out, = fl.submit('a', {'x': xv}).result(120)
        assert np.asarray(out).shape == (2, 6)
        assert monitor.counter_value('serving/shed_class') >= 1
        sheds = [d for d in fleet.decisions()
                 if d['kind'] == 'class_shed']
        assert sheds and sheds[-1]['choice']['class'] == 'batch'
        # resolution restores the shed class
        slo.clear()
        fl.tick(now=2000.0)
        out, = fl.submit('b', {'x': xv}).result(120)
        assert np.asarray(out).shape == (2, 6)
        assert any(d['kind'] == 'class_restore'
                   for d in fleet.decisions())
        assert monitor.counter_value('fleet/class_restored') == 1

    def test_defer_mode_widens_close_wait_instead(self, exe):
        fluid.set_flags({'FLAGS_fleet_shed_mode': 'defer',
                         'FLAGS_fleet_defer_close_wait_s': 0.5})
        fl, _ = _make_fleet(exe, replicas=1)
        fl.warmup(wait=True)
        self._fire(fl)
        srv = fl.replica(fl.placement('b'))
        assert srv._tenants['b'].close_wait_s == 0.5
        assert srv._tenants['a'].close_wait_s is None
        # deferred, not shed: the batch class still serves
        xv = np.random.RandomState(0).randn(2, 8).astype('float32')
        out, = fl.submit('b', {'x': xv}).result(120)
        assert np.asarray(out).shape == (2, 6)
        slo.clear()
        fl.tick(now=2000.0)
        assert srv._tenants['b'].close_wait_s is None

    def test_frozen_class_policy_logs_intent_only(self, exe):
        fl, _ = _make_fleet(exe, replicas=1)
        fl.warmup(wait=True)
        fluid.set_flags({'FLAGS_slo_hysteresis': 1,
                         'FLAGS_fleet_interval_s': 0.0})
        slo.declare('fleet/_test_breach value < 1', name='fleet-obj')
        fl.protect_class('interactive', 'fleet-obj')
        monitor.add('fleet/_test_breach', 100)
        fluid.set_flags({'FLAGS_fleet': 0})   # freeze FIRST
        timeseries.sample(now=1000.0)         # fires the objective
        fl.tick(now=1002.0)
        sheds = [d for d in fleet.decisions()
                 if d['kind'] == 'class_shed']
        assert sheds and sheds[-1]['frozen'] \
            and not sheds[-1]['acted']
        # nothing actually shed
        xv = np.random.RandomState(0).randn(2, 8).astype('float32')
        out, = fl.submit('b', {'x': xv}).result(120)
        assert np.asarray(out).shape == (2, 6)


class TestPricedEviction:
    def test_evict_picks_cheapest_with_full_table(self, exe):
        # 'big' frees ~30x the residency of 'small' for the same
        # re-warmup wall: cheapest per byte freed, so churn evicts it
        fl, _ = _make_fleet(
            exe, replicas=1,
            tenants=(('small', 8, 'batch'), ('big', 256, 'batch')))
        memviz.live_census()        # pricing reads the newest census
        assert fl.price_move('big')['cost_per_byte'] \
            < fl.price_move('small')['cost_per_byte']
        assert fl.evict(why='test-churn') == 'big'
        assert monitor.counter_value('fleet/evictions') == 1
        d = [x for x in fleet.decisions() if x['kind'] == 'evict'][-1]
        # the whole candidate table is priced in the log
        table = {c['tenant']: c for c in d['info']['candidates']}
        assert set(table) == {'small', 'big'}
        assert all(c['residency_bytes'] > 0 and c['rewarmup_s'] > 0
                   for c in table.values())
        assert d['info']['why'] == 'test-churn'
        # the evicted tenant is gone from the route table
        assert fl.placement('big') is None
        with pytest.raises(KeyError):
            fl.submit('big', {'x': np.zeros((1, 8), 'float32')})

    def test_frozen_evict_is_intent_only(self, exe):
        fl, _ = _make_fleet(exe, replicas=1)
        fluid.set_flags({'FLAGS_fleet': 0})
        assert fl.evict(why='frozen') is None
        d = [x for x in fleet.decisions() if x['kind'] == 'evict'][-1]
        assert d['frozen'] and not d['acted']
        assert monitor.counter_value('fleet/frozen_intents') == 1
        assert set(fl.placement()) == {'a', 'b'}


class TestMigration:
    def test_migrate_bitwise_equal_zero_retrace(self, exe):
        fl, _ = _make_fleet(exe, replicas=2,
                            tenants=(('a', 16, 'interactive'),))
        fl.warmup(wait=True)
        src = fl.placement('a')
        rng = np.random.RandomState(1)
        feeds = [rng.randn(r, 8).astype('float32')
                 for r in (1, 3, 2, 4)]
        before = [np.asarray(fl.submit('a', {'x': xv}).result(120)[0])
                  for xv in feeds]
        tgt = fl.migrate('a', why='test')
        assert tgt is not None and tgt != src
        assert fl.placement('a') == tgt
        # post-migration traffic must not retrace: the target ladder
        # was pre-warmed through the persistent compile cache
        lowered0 = monitor.counter_value('executor/segments_lowered')
        after = [np.asarray(fl.submit('a', {'x': xv}).result(120)[0])
                 for xv in feeds]
        assert monitor.counter_value(
            'executor/segments_lowered') == lowered0
        rep = fl.replica(tgt).resident_report()
        trep = [t for t in rep['tenants'] if t['tenant'] == 'a'][0]
        assert trep['retraces'] == 0
        # bitwise: the scope moved with the tenant, the per-bucket
        # executables come from the same compile cache
        for b, a in zip(before, after):
            assert np.array_equal(b, a)
        # the source no longer holds the tenant
        assert all(t['tenant'] != 'a' for t in
                   fl.replica(src).resident_report()['tenants'])
        # priced and logged, with the measured warmup wall
        d = [x for x in fleet.decisions()
             if x['kind'] == 'migrate'][-1]
        assert d['acted']
        assert d['info']['priced']['measured_warmup_s'] >= 0
        assert d['info']['from'] == src and d['info']['to'] == tgt
        assert monitor.counter_value('fleet/migrations') == 1

    def test_frozen_migrate_is_intent_only(self, exe):
        fl, _ = _make_fleet(exe, replicas=2,
                            tenants=(('a', 16, 'interactive'),))
        fl.warmup(wait=True)
        src = fl.placement('a')
        fluid.set_flags({'FLAGS_fleet': 0})
        assert fl.migrate('a', why='frozen') is None
        assert fl.placement('a') == src
        d = [x for x in fleet.decisions()
             if x['kind'] == 'migrate'][-1]
        assert d['frozen'] and not d['acted']
        assert 'priced' in d['info']


class TestFreezeRevert:
    def test_frozen_placement_is_static(self, exe):
        fluid.set_flags({'FLAGS_fleet': 0})
        fl, _ = _make_fleet(exe, replicas=2)
        # frozen: everything lands on the static first replica, the
        # scored choice only logged
        assert set(fl.placement().values()) == {'r0'}
        places = [d for d in fleet.decisions() if d['kind'] == 'place']
        assert all(d['choice']['why'] == 'frozen_static'
                   for d in places)

    def test_revert_restores_base_placements(self, exe):
        fl, _ = _make_fleet(exe, replicas=2,
                            tenants=(('a', 16, 'interactive'),))
        fl.warmup(wait=True)
        base = fl.placement('a')
        fl.migrate('a', why='test')
        assert fl.placement('a') != base
        # revert works even frozen — it IS the escape hatch
        fluid.set_flags({'FLAGS_fleet': 0})
        restored = fl.revert()
        assert restored['migrations'] == 1
        assert fl.placement('a') == base
        assert monitor.counter_value('fleet/reverts') == 1
        # the reverted route still serves, zero-retrace
        lowered0 = monitor.counter_value('executor/segments_lowered')
        xv = np.random.RandomState(0).randn(2, 8).astype('float32')
        out, = fl.submit('a', {'x': xv}).result(120)
        assert np.asarray(out).shape == (2, 6)
        assert monitor.counter_value(
            'executor/segments_lowered') == lowered0


class TestSurface:
    def test_statusz_fleet_section_jsonable(self, exe):
        fl, _ = _make_fleet(exe, replicas=2)
        doc = health.statusz()
        sec = doc['fleet']
        assert sec is not None
        json.dumps(sec)          # JSON-able end to end
        body = sec['fleets'][0]
        assert set(body['replicas']) == {'r0', 'r1'}
        assert set(body['placements']) == {'a', 'b'}
        assert body['classes'] == {'a': 'interactive', 'b': 'batch'}
        assert sec['decisions_total'] == 2
        assert sec['enabled']
        # no fleet -> section withheld (a plain trainer pays nothing)
        fleet.reset()
        assert health.statusz()['fleet'] is None

    def test_tick_rides_sampling_cadence(self, exe):
        fl, _ = _make_fleet(exe, replicas=1)
        fluid.set_flags({'FLAGS_fleet_interval_s': 10.0})
        timeseries.sample(now=5000.0)
        assert monitor.counter_value('fleet/ticks') == 1
        timeseries.sample(now=5001.0)   # throttled
        assert monitor.counter_value('fleet/ticks') == 1
        timeseries.sample(now=5011.0)
        assert monitor.counter_value('fleet/ticks') == 2
        assert monitor.counter_value('fleet/tick_errors') == 0
