"""RecomputeOptimizer: activation checkpointing by program rewrite.

Reference: python/paddle/fluid/optimizer.py:3611 (RecomputeOptimizer) +
backward.py:618 (_append_backward_ops_with_checkpoints_).  Training with
recompute must match plain training exactly; the backward region must
contain the re-emitted forward spans behind recompute_barrier ops.
"""

import numpy as np

import paddle_tpu.fluid as fluid


def build(seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h1 = fluid.layers.fc(x, 32, act='relu')
        h2 = fluid.layers.fc(h1, 32, act='relu')
        h3 = fluid.layers.fc(h2, 32, act='relu')
        pred = fluid.layers.fc(h3, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
    return main, startup, loss, [h2]


def train(main, startup, loss, opt, steps=8):
    rng = np.random.RandomState(3)
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for _ in range(steps):
            xb = rng.randn(16, 16).astype('float32')
            yb = xb.sum(1, keepdims=True)
            l, = exe.run(main, feed={'x': xb, 'y': yb},
                         fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        p = main.all_parameters()[0].name
        param = np.asarray(scope.find_var(p))
    return losses, param


def test_recompute_matches_plain_training():
    m1, s1, l1, _ = build(7)
    with fluid.program_guard(m1, s1):
        fluid.optimizer.SGD(0.05).minimize(l1)
    ref_losses, ref_param = train(m1, s1, l1, None)

    m2, s2, l2, ckpts = build(7)
    with fluid.program_guard(m2, s2):
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.05))
        opt._set_checkpoints(ckpts)
        opt.minimize(l2)
    rc_losses, rc_param = train(m2, s2, l2, None)

    np.testing.assert_allclose(ref_losses, rc_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(ref_param, rc_param, rtol=1e-5, atol=1e-6)


def test_recompute_rewrites_program():
    m, s, loss, ckpts = build(11)
    with fluid.program_guard(m, s):
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.05))
        opt._set_checkpoints(ckpts)
        opt.minimize(loss)
    ops = m.global_block().ops
    types = [op.type for op in ops]
    assert 'recompute_barrier' in types
    # re-emitted forward ops write @RC twins in the backward region
    rc_outputs = [n for op in ops for n in op.output_arg_names
                  if n.endswith('@RC')]
    assert rc_outputs, 'expected recomputed forward activations'
    # recompute ops carry the backward role so eval clones prune them
    for op in ops:
        if op.type == 'recompute_barrier':
            assert op.attrs['__op_role__'] == 'backward'
