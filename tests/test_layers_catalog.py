"""Layer catalog: build + execute every long-tail wrapper through the
real executor (reference test_layers.py pattern — every layer in
fluid.layers must construct a runnable program)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

rng = np.random.RandomState(1)


def run(build, feed=None):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        outs = build()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outs = [o for o in outs if o is not None]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        return exe.run(main, feed=feed or {}, fetch_list=list(outs))


def test_shape_size_rank_sum():
    def b():
        x = fluid.layers.data('x', shape=[3, 4], dtype='float32')
        return (fluid.layers.shape(x), fluid.layers.size(x),
                fluid.layers.rank(x),
                fluid.layers.sum([x, x]))
    sh, sz, rk, sm = run(b, {'x': np.ones((2, 3, 4), 'float32')})
    assert list(np.asarray(sh)) == [2, 3, 4]
    assert int(np.asarray(sz)) == 24 and int(np.asarray(rk)) == 3
    np.testing.assert_allclose(np.asarray(sm), 2.0)


def test_crop_family_and_slices():
    def b():
        x = fluid.layers.data('x', shape=[6, 6], dtype='float32')
        c = fluid.layers.crop(x, shape=[-1, 3, 3], offsets=[0, 1, 1])
        ct = fluid.layers.crop_tensor(x, shape=[-1, 2, 2],
                                      offsets=[0, 0, 0])
        ss = fluid.layers.strided_slice(x, axes=[1], starts=[0],
                                        ends=[6], strides=[2])
        return c, ct, ss
    c, ct, ss = run(b, {'x': rng.rand(2, 6, 6).astype('float32')})
    assert np.asarray(c).shape == (2, 3, 3)
    assert np.asarray(ct).shape == (2, 2, 2)
    assert np.asarray(ss).shape == (2, 3, 6)


def test_expand_as_and_elementwise_int():
    def b():
        x = fluid.layers.data('x', shape=[1, 4], dtype='float32')
        t = fluid.layers.data('t', shape=[3, 4], dtype='float32')
        e = fluid.layers.expand_as(x, t)
        a = fluid.layers.data('a', shape=[4], dtype='int64')
        m = fluid.layers.elementwise_mod(
            a, fluid.layers.fill_constant([4], 'int64', 3))
        f = fluid.layers.elementwise_floordiv(
            a, fluid.layers.fill_constant([4], 'int64', 3))
        return e, m, f
    e, m, f = run(b, {'x': np.ones((2, 1, 4), 'float32'),
                      't': np.ones((2, 3, 4), 'float32'),
                      'a': np.arange(8).reshape(2, 4).astype('int64')})
    assert np.asarray(e).shape == (2, 3, 4)
    np.testing.assert_array_equal(np.asarray(m)[0], [0, 1, 2, 0])
    np.testing.assert_array_equal(np.asarray(f)[0], [0, 0, 0, 1])


def test_random_layers_shapes():
    def b():
        u = fluid.layers.uniform_random([4, 5], min=0.0, max=1.0)
        g = fluid.layers.gaussian_random([3, 2])
        x = fluid.layers.data('x', shape=[7], dtype='float32')
        ub = fluid.layers.uniform_random_batch_size_like(x, [-1, 6])
        gb = fluid.layers.gaussian_random_batch_size_like(x, [-1, 2])
        return u, g, ub, gb
    u, g, ub, gb = run(b, {'x': np.ones((5, 7), 'float32')})
    assert np.asarray(u).shape == (4, 5)
    assert (np.asarray(u) >= 0).all() and (np.asarray(u) < 1).all()
    assert np.asarray(g).shape == (3, 2)
    assert np.asarray(ub).shape == (5, 6)
    assert np.asarray(gb).shape == (5, 2)


def test_hash_unique_scatter_nd():
    def b():
        ids = fluid.layers.data('ids', shape=[4], dtype='int64')
        h = fluid.layers.hash(ids, hash_size=100, num_hash=2)
        u, idx = fluid.layers.unique(
            fluid.layers.reshape(ids, shape=[-1]))
        uo, ui, uc = fluid.layers.unique_with_counts(
            fluid.layers.reshape(ids, shape=[-1]))
        index = fluid.layers.data('index', shape=[2, 1], dtype='int32')
        upd = fluid.layers.data('upd', shape=[2], dtype='float32')
        sc = fluid.layers.scatter_nd(index, upd, [6])
        return h, u, uo, uc, sc
    h, u, uo, uc, sc = run(
        b, {'ids': np.array([[1, 2, 2, 9], [3, 1, 9, 9]], 'int64'),
            'index': np.array([[1], [4]], 'int32').reshape(1, 2, 1)[0],
            'upd': np.array([5.0, 7.0], 'float32')})
    assert np.asarray(h).shape[-1] == 8  # 2 hashes x 4 ids
    assert (np.asarray(h) < 100).all()
    assert sorted(np.asarray(u).tolist()) == [1, 2, 3, 9]
    assert np.asarray(uc).sum() == 8
    got = np.zeros(6); got[1] = 5; got[4] = 7
    np.testing.assert_allclose(np.asarray(sc), got)


def test_vision_wrappers():
    def b():
        x = fluid.layers.data('x', shape=[2, 8, 8], dtype='float32')
        rois = fluid.layers.data('rois', shape=[4], dtype='float32')
        ra = fluid.layers.roi_align(x, rois, pooled_height=2,
                                    pooled_width=2)
        pp = fluid.layers.prroi_pool(x, rois, pooled_height=2,
                                     pooled_width=2)
        g = fluid.layers.data('grid', shape=[4, 4, 2], dtype='float32')
        gs = fluid.layers.grid_sampler(x, g)
        ap = fluid.layers.adaptive_pool3d(
            fluid.layers.unsqueeze(x, axes=[1]), pool_size=[1, 2, 2],
            pool_type='avg')
        return ra, pp, gs, ap
    ra, pp, gs, ap = run(
        b, {'x': rng.rand(1, 2, 8, 8).astype('float32'),
            'rois': np.array([[0, 0, 4, 4]], 'float32'),
            'grid': np.zeros((1, 4, 4, 2), 'float32')})
    assert np.asarray(ra).shape[-2:] == (2, 2)
    assert np.asarray(pp).shape[-2:] == (2, 2)
    assert np.asarray(gs).shape == (1, 2, 4, 4)
    assert np.isfinite(np.asarray(ap)).all()


def test_deformable_wrappers():
    def b():
        x = fluid.layers.data('x', shape=[2, 6, 6], dtype='float32')
        # 2*dg*K offsets for a 3x3 kernel, dg=1 -> 18 channels
        off = fluid.layers.data('off', shape=[18, 6, 6],
                                dtype='float32')
        mask = fluid.layers.data('mask', shape=[9, 6, 6],
                                 dtype='float32')
        dc = fluid.layers.deformable_conv(x, off, mask, num_filters=4,
                                          filter_size=3, padding=1)
        rois = fluid.layers.data('rois', shape=[4], dtype='float32')
        trans = fluid.layers.data('trans', shape=[2, 2, 2],
                                  dtype='float32')
        dr = fluid.layers.deformable_roi_pooling(
            x, rois, trans, pooled_height=2, pooled_width=2)
        return dc, dr
    dc, dr = run(b, {'x': rng.rand(1, 2, 6, 6).astype('float32'),
                     'off': np.zeros((1, 18, 6, 6), 'float32'),
                     'mask': np.ones((1, 9, 6, 6), 'float32'),
                     'rois': np.array([[0, 0, 4, 4]], 'float32'),
                     'trans': np.zeros((1, 2, 2, 2), 'float32')})
    assert np.asarray(dc).shape == (1, 4, 6, 6)
    assert np.asarray(dr).shape[-2:] == (2, 2)


def test_detection_host_wrappers():
    def b():
        bbox_pred = fluid.layers.data('bp', shape=[4], dtype='float32')
        cls = fluid.layers.data('cl', shape=[1], dtype='float32')
        anchors = fluid.layers.data('an', shape=[4], dtype='float32',
                                    append_batch_size=False)
        gts = fluid.layers.data('gt', shape=[4], dtype='float32',
                                append_batch_size=False)
        out = fluid.layers.rpn_target_assign(
            bbox_pred, cls, anchors, None, gts)
        rois, restore = fluid.layers.distribute_fpn_proposals(
            gts, 2, 5, 4, 224)
        col = fluid.layers.collect_fpn_proposals(
            rois, [fluid.layers.fill_constant(
                [1], 'float32', 0.9)] * len(rois), 2, 5, 3)
        return (out[0], restore, col)
    loc_idx, restore, col = run(
        b, {'bp': np.zeros((1, 8, 4), 'float32'),
            'cl': np.zeros((1, 8, 1), 'float32'),
            'an': np.array([[0, 0, 10, 10], [20, 20, 40, 40],
                            [0, 0, 300, 300]], 'float32'),
            'gt': np.array([[0, 0, 9, 9], [100, 100, 280, 280]],
                           'float32')})
    assert np.asarray(loc_idx).ndim >= 1
    assert np.asarray(col).shape[-1] == 4


def test_sequence_misc_wrappers():
    def b():
        x = fluid.layers.data('x', shape=[6, 8], dtype='float32')
        ape = fluid.layers.add_position_encoding(x)
        rc = fluid.layers.row_conv(x, future_context_size=2)
        im = fluid.layers.data('im', shape=[1, 8, 8], dtype='float32')
        seq = fluid.layers.im2sequence(im, filter_size=4, stride=4)
        return ape, rc, seq
    ape, rc, seq = run(b, {'x': rng.rand(2, 6, 8).astype('float32'),
                           'im': rng.rand(2, 1, 8, 8).astype('float32')})
    assert np.asarray(ape).shape == (2, 6, 8)
    assert np.asarray(rc).shape == (2, 6, 8)
    assert np.isfinite(np.asarray(seq)).all()


def test_loss_wrappers():
    def b():
        p = fluid.layers.data('p', shape=[1], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        ll = fluid.layers.log_loss(p, y)
        hl = fluid.layers.huber_loss(p, y, delta=1.0)
        kl = fluid.layers.kldiv_loss(p, y, reduction='none')
        ms = fluid.layers.mse_loss(p, y)
        logits = fluid.layers.data('lg', shape=[50], dtype='float32')
        lab = fluid.layers.data('lb', shape=[1], dtype='int64')
        ss = fluid.layers.sampled_softmax_with_cross_entropy(
            logits, lab, num_samples=10)
        return ll, hl, kl, ms, ss
    outs = run(b, {'p': np.full((3, 1), 0.4, 'float32'),
                   'y': np.full((3, 1), 0.5, 'float32'),
                   'lg': rng.rand(3, 50).astype('float32'),
                   'lb': rng.randint(0, 50, (3, 1)).astype('int64')})
    assert all(np.isfinite(np.asarray(o)).all() for o in outs)


def test_step_counter_and_print():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        step = fluid.layers.autoincreased_step_counter()
        fluid.layers.Print(x, message='catalog')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        vals = []
        for _ in range(3):
            s, = exe.run(main, feed={'x': np.ones((1, 2), 'float32')},
                         fetch_list=[step])
            vals.append(int(np.asarray(s).ravel()[0]))
    assert vals == [1, 2, 3], vals


def test_misc_remaining():
    def b():
        x = fluid.layers.data('x', shape=[4, 6, 6], dtype='float32')
        sf = fluid.layers.similarity_focus(x, axis=1, indexes=[0])
        pb = fluid.layers.polygon_box_transform(
            fluid.layers.data('q', shape=[8, 4, 4], dtype='float32'))
        rk = fluid.layers.data('rk', shape=[1], dtype='int32',
                               append_batch_size=False)
        ro = fluid.layers.reorder_lod_tensor_by_rank(x, rk)
        return sf, pb, ro
    sf, pb, ro = run(b, {'x': rng.rand(2, 4, 6, 6).astype('float32'),
                         'q': rng.rand(2, 8, 4, 4).astype('float32'),
                         'rk': np.array([1, 0], 'int32')})
    assert set(np.unique(np.asarray(sf))) <= {0.0, 1.0}
    assert np.asarray(pb).shape == (2, 8, 4, 4)
    assert np.asarray(ro).shape == (2, 4, 6, 6)
