"""Runtime stats registry (fluid.monitor — platform/monitor.h
StatRegistry analog): always-on counters that observe the executor,
reader, PS and collective layers WITHOUT enabling the profiler (which
re-segments the program).

The acceptance contract: two Executor.run() calls of one program show
segment_cache_miss=N then segment_cache_hit=N, prometheus_text()
round-trips those counters in valid exposition format, and bench.py's
JSON carries the counter subset — all with the profiler off."""

import json
import os
import re
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, profiler

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build(width=32):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[width], dtype='float32')
        h = layers.fc(x, size=width, bias_attr=False)
        out = layers.reduce_mean(h)
    return main, startup, out


# ---------------------------------------------------------------- registry
def test_registry_primitives():
    monitor.reset()
    monitor.add('t/c')
    monitor.add('t/c', 2.5)
    assert monitor.counter_value('t/c') == 3.5
    monitor.set_gauge('t/g', 7)
    monitor.set_gauge('t/g', 4)
    assert monitor.gauge_value('t/g') == 4.0
    monitor.observe('t/h', 0.002, buckets=(0.001, 0.01, 0.1))
    monitor.observe('t/h', 0.5)  # later bucket args are ignored
    h = monitor.histogram_value('t/h')
    assert h['count'] == 2 and abs(h['sum'] - 0.502) < 1e-12
    assert h['buckets']['0.01'] == 1 and h['buckets']['+Inf'] == 2
    snap = monitor.snapshot()
    assert snap['t']['c'] == 3.5 and snap['t']['g'] == 4.0
    assert snap['t']['h']['count'] == 2
    flat = monitor.flat()
    assert flat['t/h/count'] == 2.0 and flat['t/c'] == 3.5
    monitor.reset()
    assert monitor.snapshot() == {}


def test_set_enabled_gates_recording():
    monitor.reset()
    prev = monitor.set_enabled(False)
    assert prev is True
    monitor.add('off/c')
    monitor.set_gauge('off/g', 1)
    monitor.observe('off/h', 1.0)
    assert monitor.snapshot() == {}
    monitor.set_enabled(True)
    monitor.add('off/c')
    assert monitor.counter_value('off/c') == 1.0
    monitor.reset()


# ------------------------------------------------- executor instrumentation
def test_segment_cache_miss_then_hit_without_profiler():
    """Acceptance: run #1 of a program misses the executable cache N
    times (N segments), run #2 hits N times — observed with the
    profiler OFF (the counters must not require re-segmentation)."""
    assert not profiler.is_enabled()
    main, startup, out = _build()
    x = np.random.RandomState(0).randn(8, 32).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        monitor.reset()
        exe.run(main, feed={'x': x}, fetch_list=[out])
        s1 = monitor.snapshot()['executor']
        n = s1['segment_cache_miss']
        assert n >= 1 and 'segment_cache_hit' not in s1
        assert s1['segments_lowered'] == n
        # compile latency histogram saw one sample per lowered segment
        assert s1['segment_compile_seconds']['count'] == n
        assert s1['segment_compile_seconds']['sum'] > 0
        exe.run(main, feed={'x': x}, fetch_list=[out])
        s2 = monitor.snapshot()['executor']
        assert s2['segment_cache_miss'] == n  # no new misses
        assert s2['segment_cache_hit'] == n
        # plan cache: one build, one reuse
        assert s2['plan_cache_miss'] == 1.0
        assert s2['plan_cache_hit'] == 1.0
        # volume + latency counters moved
        assert s2['feed_bytes'] == 2 * x.nbytes
        assert s2['fetch_bytes'] > 0
        assert s2['run_seconds']['count'] == 2
    assert not profiler.is_enabled()


def test_prometheus_text_round_trips_counters():
    main, startup, out = _build()
    x = np.zeros((4, 32), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        monitor.reset()
        exe.run(main, feed={'x': x}, fetch_list=[out])
        exe.run(main, feed={'x': x}, fetch_list=[out])
        snap = monitor.snapshot()['executor']
        text = monitor.prometheus_text()
    # every line is valid text exposition format (incl. HELP metadata)
    line_re = re.compile(
        r'^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* '
        r'(counter|gauge|histogram)'
        r'|# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*'
        r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})? -?[0-9.e+-]+'
        r'(inf)?)$')
    for line in text.strip().splitlines():
        assert line_re.match(line), line
    # and the lint-level contract holds (fluid.health.prom_lint is the
    # exhaustive check: HELP/TYPE per family, no duplicate series,
    # histogram bucket/_sum/_count consistency)
    from paddle_tpu.fluid import health
    assert health.prom_lint(text) == []
    # the cache counters round-trip by value
    parsed = {}
    for line in text.splitlines():
        if line.startswith('#') or '{' in line or not line:
            continue
        name, val = line.rsplit(' ', 1)
        parsed[name] = float(val)
    assert parsed['paddle_tpu_executor_segment_cache_hit'] == \
        snap['segment_cache_hit']
    assert parsed['paddle_tpu_executor_segment_cache_miss'] == \
        snap['segment_cache_miss']
    # histogram triplet present with consistent count
    assert parsed['paddle_tpu_executor_run_seconds_count'] == 2
    assert 'paddle_tpu_executor_run_seconds_sum' in parsed
    assert '# TYPE paddle_tpu_executor_run_seconds histogram' in text


def test_dump_jsonl_and_stat_summary_diff(tmp_path, capsys):
    main, startup, out = _build()
    x = np.zeros((4, 32), 'float32')
    p1, p2 = str(tmp_path / 'a.jsonl'), str(tmp_path / 'b.jsonl')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        monitor.reset()
        exe.run(main, feed={'x': x}, fetch_list=[out])
        monitor.dump_jsonl(p1, step=1)
        exe.run(main, feed={'x': x}, fetch_list=[out])
        monitor.dump_jsonl(p2, step=2, extra={'tag': 'second'})
    rec = json.loads(open(p2).read().splitlines()[-1])
    assert rec['step'] == 2 and rec['tag'] == 'second'
    assert rec['counters']['executor/segment_cache_hit'] >= 1
    assert rec['histograms']['executor/run_seconds']['count'] == 2
    sys.path.insert(0, os.path.join(ROOT, 'tools'))
    try:
        import stat_summary
    finally:
        sys.path.pop(0)
    assert stat_summary.main([p2]) == 0
    rendered = capsys.readouterr().out
    assert 'executor/segment_cache_hit' in rendered
    assert stat_summary.main([p1, p2]) == 0
    diffed = capsys.readouterr().out
    # between the dumps exactly one run happened: one cache hit
    m = re.search(r'executor/segment_cache_hit\s+(\S+)', diffed)
    assert m and float(m.group(1)) == \
        rec['counters']['executor/segment_cache_hit'] - \
        json.loads(open(p1).read())['counters'].get(
            'executor/segment_cache_hit', 0.0) + 0.0


def test_bench_json_carries_monitor_subset():
    """bench.py merges the counter subset into its JSON line; the
    helper must report the registry of the runs that just happened."""
    sys.path.insert(0, ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    main, startup, out = _build()
    x = np.zeros((4, 32), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        monitor.reset()
        exe.run(main, feed={'x': x}, fetch_list=[out])
        exe.run(main, feed={'x': x}, fetch_list=[out])
        fields = bench._monitor_fields()
    sub = fields['monitor']
    assert sub['segment_cache_miss'] >= 1
    assert sub['segment_cache_hit'] >= 1
    assert sub['compile_seconds'] > 0
    assert sub['feed_bytes'] == 2 * x.nbytes  # one feed var, two runs
    json.dumps(fields)  # must be JSON-serializable as emitted


# ------------------------------------------------------ reader / loader
def test_reader_pipeline_counters():
    from paddle_tpu.fluid.reader import _AsyncBatchIterator
    monitor.reset()
    batches = [{'x': np.zeros((2, 4), 'float32')} for _ in range(5)]

    def gen():
        for b in batches:
            yield b

    it = _AsyncBatchIterator(gen, capacity=2, device=None)
    got = list(it)
    assert len(got) == 5
    snap = monitor.snapshot()['reader']
    assert snap['batches_produced'] == 5.0
    assert snap['batches_consumed'] == 5.0
    assert 'queue_depth' in snap
    # the consumer blocked at least once waiting on the producer
    assert snap['consume_blocked_seconds']['count'] >= 1


def test_reader_staging_counts_bytes():
    import jax
    from paddle_tpu.fluid.reader import _AsyncBatchIterator
    monitor.reset()
    arr = np.ones((3, 4), 'float32')

    def gen():
        yield {'x': arr}

    it = _AsyncBatchIterator(gen, capacity=2, device=jax.devices()[0])
    batch = next(it)
    assert hasattr(batch['x'], 'devices')
    assert monitor.counter_value('reader/bytes_staged') == arr.nbytes


# ------------------------------------------------- PS / communicator plane
def test_communicator_counters():
    from paddle_tpu.distributed import (ParameterServerStore,
                                        AsyncCommunicator)
    monitor.reset()
    store = ParameterServerStore(lr=0.5)
    store.init_var('w', np.ones(4, 'float32'))
    comm = AsyncCommunicator(store)
    comm.start()
    g = np.full(4, 2.0, 'float32')
    comm.send('w', g)
    comm.send('w', g)
    comm.flush()
    comm.stop()
    snap = monitor.snapshot()['communicator']
    assert snap['sends'] == 2.0
    assert snap['send_bytes'] == 2.0 * g.nbytes
    assert snap['grads_merged'] == 2.0
    assert snap['server_applies'] >= 1.0


def test_collective_transpile_counters():
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.fc(x, size=1)
        loss = layers.reduce_mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    monitor.reset()
    # reference (v1.6) rewrite counters: one c_allreduce_sum per grad
    # (the planned default fuses the two small grads into ONE bucket
    # op and reports ops_inserted accordingly — test_comms_plan.py)
    prev = fluid.get_flags(['FLAGS_comms_plan'])
    fluid.set_flags({'FLAGS_comms_plan': False})
    try:
        GradAllReduce().transpile(startup, main, 0, ['127.0.0.1:6170'],
                                  '127.0.0.1:6170')
    finally:
        fluid.set_flags(prev)
    snap = monitor.snapshot()['collective']
    assert snap['transpile_calls'] == 1.0
    # fc weight + bias gradients each get one inserted c_allreduce_sum
    assert snap['allreduce_ops_inserted'] >= 2.0
    assert snap['allreduce_bytes_per_step'] >= 4 * 4  # w is [4,1] f32


# ------------------------------------------------------ profiler satellites
def test_stop_profiler_folds_table_into_monitor_and_returns_it():
    main, startup, out = _build()
    x = np.zeros((4, 32), 'float32')
    monitor.reset()
    profiler.reset_profiler()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        profiler.start_profiler('All')
        exe.run(main, feed={'x': x}, fetch_list=[out])
        table = profiler.stop_profiler(profile_path=None)
    assert isinstance(table, str) and table.startswith('Event')
    assert 'mul' in table
    prof = monitor.snapshot()['profiler']
    assert prof['mul']['calls'] == 1.0
    assert prof['mul']['total_seconds'] > 0
    # a second (defensive) stop must not re-fold the same records
    profiler.stop_profiler(profile_path=None)
    assert monitor.snapshot()['profiler']['mul']['calls'] == 1.0
    profiler.reset_profiler()


def test_stop_profiler_resets_stale_default_mode():
    """Satellite: a 'Default' capture must not leave _mode sticky —
    after stop, a bare start_profiler()/is_enabled() behaves exactly
    like a fresh process (Serial re-segmentation enabled)."""
    profiler.reset_profiler()
    # simulate the post-'Default' state without paying a jax trace
    profiler._mode = 'Default'
    profiler._enabled = True
    assert not profiler.is_enabled()  # Default never re-segments
    profiler.stop_profiler(profile_path=None)
    assert profiler._mode == 'Serial'
    profiler.start_profiler('All')
    try:
        assert profiler.is_enabled()
    finally:
        profiler.stop_profiler(profile_path=None)
        profiler.reset_profiler()


def test_start_trace_double_start_raises(tmp_path):
    profiler.start_trace(str(tmp_path / 't1'))
    try:
        with pytest.raises(RuntimeError, match='already active'):
            profiler.start_trace(str(tmp_path / 't2'))
    finally:
        profiler.stop_trace()
    # a 'Default' profiler capture owns the device tracer too
    profiler._prof_trace_dir = '/tmp/fake_prof_dir'
    try:
        with pytest.raises(RuntimeError, match='stop_profiler'):
            profiler.start_trace(str(tmp_path / 't3'))
    finally:
        profiler._prof_trace_dir = None


def test_attribute_trace_events_transform_wrapped_scopes():
    """Satellite: transform-wrapped scope components — the wpg backward
    wraps op scopes as transpose(jvp(op)), possibly nested — must
    attribute to the base op; kernels with no registered component land
    in per-HLO 'unattributed/…' buckets (folded keys stay one level)."""
    ev = [
        {'ph': 'X', 'name': 'fusion.9', 'dur': 50.0,
         'args': {'tf_op': 'jit_seg/transpose(jvp(relu))/max:'}},
        {'ph': 'X', 'name': 'fusion.10', 'dur': 30.0,
         'args': {'tf_op': 'jit_seg/jvp(relu)/max:'}},
        {'ph': 'X', 'name': 'convert.3', 'dur': 5.0,
         'args': {'tf_op': 'jit_seg/convert'}},
    ]
    recs = profiler.attribute_trace_events(ev, op_types={'relu'})
    assert recs['relu'][0] == 2
    assert abs(recs['relu'][1] - 80e-6) < 1e-12
    assert recs['unattributed/convert'][0] == 1
    # fold-in keeps the unattributed bucket one level deep
    monitor.reset()
    profiler.reset_profiler()
    profiler._records.update(recs)
    profiler._fold_into_monitor()
    prof = monitor.snapshot()['profiler']
    assert prof['relu']['calls'] == 2.0
    assert prof['unattributed:convert']['calls'] == 1.0
    profiler.reset_profiler()
    monitor.reset()
