"""Parity batch: ops added to close the registry gap vs the reference's
364 REGISTER_OPERATOR names (SURVEY.md §2.2).

Mirrors the reference OpTest pattern (tests/unittests/test_*_op.py):
numpy reference values where the math is checkable, shape/finiteness
and behavioural properties otherwise.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import registry

rng = np.random.RandomState(7)


def run(op, ins, attrs=None):
    return registry.get(op).fn(registry.LowerCtx(0, 5),
                               {k: (v if isinstance(v, list) else [v])
                                for k, v in ins.items()},
                               attrs or {})


# --------------------------- tensor / array -------------------------------


def test_squeeze_flatten_reverse_minus():
    x = rng.randn(2, 1, 3).astype('f4')
    assert run('squeeze', {'X': jnp.asarray(x)}, {'axes': []}
               )['Out'][0].shape == (2, 3)
    assert run('flatten', {'X': jnp.zeros((2, 3, 4))}, {'axis': 2}
               )['Out'][0].shape == (6, 4)
    r = run('reverse', {'X': jnp.arange(6).reshape(2, 3)}, {'axis': [1]})
    assert (np.asarray(r['Out'][0]) == [[2, 1, 0], [5, 4, 3]]).all()
    r = run('minus', {'X': jnp.ones(3), 'Y': jnp.full(3, 2.0)})
    assert (np.asarray(r['Out'][0]) == -1).all()


def test_coalesce_tensor():
    a, b = jnp.ones((2, 2)), jnp.zeros(3)
    r = run('coalesce_tensor', {'Input': [a, b]})
    assert r['FusedOutput'][0].shape == (7,)
    assert np.asarray(r['FusedOutput'][0]).sum() == 4


def test_shuffle_batch_is_permutation():
    x = jnp.arange(8.0).reshape(4, 2)
    r = run('shuffle_batch', {'X': x})
    got = sorted(np.asarray(r['Out'][0]).ravel().tolist())
    assert got == sorted(np.arange(8.0).tolist())


def test_tensor_array_ops():
    arr = jnp.zeros((4, 3))
    r = run('write_to_array', {'X': jnp.ones(3), 'I': jnp.asarray([1]),
                               'Array': arr})
    assert np.asarray(r['Out'][0])[1].sum() == 3
    r = run('read_from_array', {'X': jnp.arange(12.0).reshape(4, 3),
                                'I': jnp.asarray([2])})
    assert (np.asarray(r['Out'][0]) == [6, 7, 8]).all()
    # lod_tensor_to_array/back = time-major transpose roundtrip
    x = rng.randn(2, 5, 3).astype('f4')
    st = run('lod_tensor_to_array', {'X': jnp.asarray(x)})['Out'][0]
    assert st.shape == (5, 2, 3)
    back = run('array_to_lod_tensor', {'X': st})['Out'][0]
    np.testing.assert_allclose(np.asarray(back), x)


def test_shrink_rnn_memory_and_select():
    r = run('shrink_rnn_memory',
            {'X': jnp.ones((3, 2)), 'I': jnp.asarray([1]),
             'RankTable': jnp.asarray([3, 2, 1])})
    assert (np.asarray(r['Out'][0]).sum(1) == [2, 2, 0]).all()
    r = run('select_input', {'X': [jnp.zeros(3), jnp.ones(3)],
                             'Mask': jnp.asarray([1])})
    assert r['Out'][0].sum() == 3
    r = run('select_output', {'X': jnp.ones(3), 'Mask': jnp.asarray([0])},
            {'branches': 2})
    assert r['Out'][0].sum() == 3 and r['Out'][1].sum() == 0
    r = run('merge_lod_tensor',
            {'InTrue': jnp.ones((2, 2)), 'InFalse': jnp.zeros((2, 2)),
             'Mask': jnp.asarray([1, 0])})
    assert (np.asarray(r['Out'][0]).sum(1) == [2, 0]).all()
    r = run('split_lod_tensor',
            {'X': jnp.ones((2, 2)), 'Mask': jnp.asarray([1, 0])})
    assert np.asarray(r['OutTrue'][0]).sum() == 2
    assert np.asarray(r['OutFalse'][0]).sum() == 2


# ------------------------------- nn ---------------------------------------


def test_lrn_matches_loop_reference():
    x = rng.randn(2, 7, 3, 3).astype('f4')
    r = run('lrn', {'X': jnp.asarray(x)})
    ref = np.zeros_like(x)
    for ci in range(7):
        lo, hi = max(0, ci - 2), min(7, ci + 3)
        acc = (x[:, lo:hi] ** 2).sum(1)
        ref[:, ci] = x[:, ci] * (1 + 1e-4 * acc) ** -0.75
    np.testing.assert_allclose(np.asarray(r['Out'][0]), ref, rtol=1e-5)


def test_max_pool_with_index_and_unpool_roundtrip():
    x = rng.randn(2, 3, 4, 4).astype('f4')
    r = run('max_pool2d_with_index', {'X': jnp.asarray(x)},
            {'ksize': [2, 2], 'strides': [2, 2], 'paddings': [0, 0]})
    out, mask = np.asarray(r['Out'][0]), np.asarray(r['Mask'][0])
    ref = x.reshape(2, 3, 2, 2, 2, 2).transpose(0, 1, 2, 4, 3, 5) \
        .reshape(2, 3, 2, 2, 4).max(-1)
    np.testing.assert_allclose(out, ref)
    r2 = run('unpool', {'X': jnp.asarray(out),
                        'Indices': jnp.asarray(mask)},
             {'unpooled_size': [4, 4]})
    up = np.asarray(r2['Out'][0])
    assert up.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(up.sum(), out.sum(), rtol=1e-6)
    r3 = run('max_pool3d_with_index',
             {'X': jnp.asarray(rng.randn(1, 2, 4, 4, 4).astype('f4'))},
             {'ksize': [2, 2, 2], 'strides': [2, 2, 2],
              'paddings': [0, 0, 0]})
    assert np.asarray(r3['Out'][0]).shape == (1, 2, 2, 2, 2)


def test_depthwise_conv2d_transpose_matches_per_channel():
    x = rng.randn(1, 2, 3, 3).astype('f4')
    w = rng.randn(2, 1, 3, 3).astype('f4')
    got = np.asarray(run(
        'depthwise_conv2d_transpose',
        {'Input': jnp.asarray(x), 'Filter': jnp.asarray(w)},
        {'strides': [2, 2], 'paddings': [1, 1], 'groups': 2}
    )['Output'][0])
    for ch in range(2):
        ref = np.asarray(run(
            'conv2d_transpose',
            {'Input': jnp.asarray(x[:, ch:ch + 1]),
             'Filter': jnp.asarray(w[ch:ch + 1])},
            {'strides': [2, 2], 'paddings': [1, 1]})['Output'][0])
        np.testing.assert_allclose(got[:, ch:ch + 1], ref,
                                   rtol=1e-4, atol=1e-5)


def test_row_conv_and_conv_shift():
    x = np.arange(12.0).reshape(1, 4, 3).astype('f4')
    w = np.ones((2, 3), 'f4')
    r = run('row_conv', {'X': jnp.asarray(x), 'Filter': jnp.asarray(w)})
    ref = x.copy()
    ref[:, :3] += x[:, 1:]
    np.testing.assert_allclose(np.asarray(r['Out'][0]), ref)

    x = rng.randn(2, 5).astype('f4')
    y = rng.randn(2, 3).astype('f4')
    r = run('conv_shift', {'X': jnp.asarray(x), 'Y': jnp.asarray(y)})
    ref = np.zeros_like(x)
    for b in range(2):
        for i in range(5):
            for j in range(3):
                ref[b, i] += x[b, (i + j - 1) % 5] * y[b, j]
    np.testing.assert_allclose(np.asarray(r['Out'][0]), ref, rtol=1e-5)


def test_sync_batch_norm_psums_inside_shard_map():
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ('dp',))
    x = rng.randn(8, 3, 2, 2).astype('f4')

    def f(xs):
        out = registry.get('sync_batch_norm').fn(
            registry.LowerCtx(0, 1),
            {'X': [xs], 'Scale': [jnp.ones(3)], 'Bias': [jnp.zeros(3)],
             'Mean': [jnp.zeros(3)], 'Variance': [jnp.ones(3)]}, {})
        return out['Y'][0], out['SavedMean'][0]

    y, m = shard_map(f, mesh=mesh, in_specs=P('dp'),
                     out_specs=(P('dp'), P()))(x)
    # global moments == plain batch_norm over the full batch
    ref = run('batch_norm', {'X': jnp.asarray(x), 'Scale': jnp.ones(3),
                             'Bias': jnp.zeros(3), 'Mean': jnp.zeros(3),
                             'Variance': jnp.ones(3)})
    np.testing.assert_allclose(np.asarray(m),
                               np.asarray(ref['SavedMean'][0]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref['Y'][0]),
                               rtol=1e-4, atol=1e-4)


# ------------------------------ rnn ----------------------------------------


def test_gru_unit_matches_one_step_of_gru():
    b, h = 2, 4
    x = rng.randn(b, 3 * h).astype('f4')
    hp = rng.randn(b, h).astype('f4')
    w = rng.randn(h, 3 * h).astype('f4')
    o = run('gru_unit', {'Input': jnp.asarray(x),
                         'HiddenPrev': jnp.asarray(hp),
                         'Weight': jnp.asarray(w)})
    full = run('gru', {'Input': jnp.asarray(x[:, None, :]),
                       'Weight': jnp.asarray(w), 'H0': jnp.asarray(hp)})
    np.testing.assert_allclose(np.asarray(o['Hidden'][0]),
                               np.asarray(full['Hidden'][0][:, 0]),
                               rtol=2e-4, atol=1e-5)


def test_lstm_unit_math():
    b, h = 2, 4
    x4 = rng.randn(b, 4 * h).astype('f4')
    cp = rng.randn(b, h).astype('f4')
    o = run('lstm_unit', {'X': jnp.asarray(x4), 'C_prev': jnp.asarray(cp)},
            {'forget_bias': 1.0})

    def sig(v):
        return 1 / (1 + np.exp(-v))

    cref = sig(x4[:, h:2 * h] + 1) * cp + \
        sig(x4[:, :h]) * np.tanh(x4[:, 3 * h:])
    np.testing.assert_allclose(np.asarray(o['C'][0]), cref,
                               rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(o['H'][0]),
                               sig(x4[:, 2 * h:3 * h]) * np.tanh(cref),
                               rtol=2e-5, atol=1e-5)


def test_lstmp_and_cudnn_lstm_and_attention_lstm_shapes():
    h = 4
    o = run('lstmp', {'Input': jnp.asarray(rng.randn(2, 5, 4 * h)
                                           .astype('f4')),
                      'Weight': jnp.asarray(rng.randn(3, 4 * h)
                                            .astype('f4')),
                      'ProjWeight': jnp.asarray(rng.randn(h, 3)
                                                .astype('f4'))})
    assert o['Projection'][0].shape == (2, 5, 3)
    assert o['Cell'][0].shape == (2, 5, h)

    t_len, b, d, hid, layers = 5, 2, 3, 4, 2
    size, din = 0, d
    for _ in range(layers):
        size += 2 * (din * 4 * hid + hid * 4 * hid + 4 * hid)
        din = 2 * hid
    o = run('cudnn_lstm',
            {'Input': jnp.asarray(rng.randn(t_len, b, d).astype('f4')),
             'W': jnp.asarray(rng.randn(size).astype('f4'))},
            {'hidden_size': hid, 'num_layers': layers, 'is_bidirec': True})
    assert o['Out'][0].shape == (t_len, b, 2 * hid)
    assert o['LastH'][0].shape == (4, b, hid)

    o = run('attention_lstm',
            {'X': jnp.asarray(rng.randn(2, 6, 3).astype('f4')),
             'C0': jnp.asarray(rng.randn(2, 4).astype('f4')),
             'AttentionWeight': jnp.asarray(rng.randn(7, 1).astype('f4')),
             'LSTMWeight': jnp.asarray(rng.randn(7, 16).astype('f4')),
             'LSTMBias': jnp.asarray(rng.randn(1, 16).astype('f4'))})
    assert o['Hidden'][0].shape == (2, 6, 4)


# ----------------------------- fused ---------------------------------------


def test_fusion_gru_lstm_match_composition():
    x = rng.randn(2, 5, 3).astype('f4')
    wx = rng.randn(3, 12).astype('f4')
    wh = rng.randn(4, 12).astype('f4')
    o = run('fusion_gru', {'X': jnp.asarray(x), 'WeightX': jnp.asarray(wx),
                           'WeightH': jnp.asarray(wh)})
    full = run('gru', {'Input': jnp.asarray(x @ wx),
                       'Weight': jnp.asarray(wh)})
    np.testing.assert_allclose(np.asarray(o['Hidden'][0]),
                               np.asarray(full['Hidden'][0]),
                               rtol=2e-4, atol=2e-5)
    wx4 = rng.randn(3, 16).astype('f4')
    wh4 = rng.randn(4, 16).astype('f4')
    o = run('fusion_lstm', {'X': jnp.asarray(x),
                            'WeightX': jnp.asarray(wx4),
                            'WeightH': jnp.asarray(wh4)})
    full = run('lstm', {'Input': jnp.asarray(x @ wx4),
                        'Weight': jnp.asarray(wh4)})
    np.testing.assert_allclose(np.asarray(o['Hidden'][0]),
                               np.asarray(full['Hidden'][0]),
                               rtol=2e-4, atol=2e-5)


def test_fusion_misc():
    x = rng.randn(3, 4).astype('f4')
    y = rng.randn(4, 5).astype('f4')
    o = run('fusion_squared_mat_sub',
            {'X': jnp.asarray(x), 'Y': jnp.asarray(y)}, {'scalar': 0.5})
    np.testing.assert_allclose(
        np.asarray(o['Out'][0]),
        0.5 * ((x @ y) ** 2 - (x ** 2) @ (y ** 2)),
        rtol=1e-4, atol=1e-4)
    o = run('fusion_repeated_fc_relu',
            {'X': jnp.asarray(x),
             'W': [jnp.asarray(y), jnp.asarray(rng.randn(5, 2)
                                               .astype('f4'))],
             'Bias': [jnp.zeros(5), jnp.zeros(2)]})
    assert o['Out'][0].shape == (3, 2)
    o = run('fusion_seqpool_concat',
            {'X': [jnp.asarray(rng.randn(2, 4, 3).astype('f4')),
                   jnp.asarray(rng.randn(2, 4, 5).astype('f4'))]},
            {'pooltype': 'SUM'})
    assert o['Out'][0].shape == (2, 8)
    o = run('fusion_seqexpand_concat_fc',
            {'X': [jnp.asarray(rng.randn(2, 4, 3).astype('f4')),
                   jnp.asarray(rng.randn(2, 5).astype('f4'))],
             'FCWeight': jnp.asarray(rng.randn(8, 6).astype('f4'))})
    assert o['Out'][0].shape == (2, 4, 6)
    o = run('fused_embedding_fc_lstm',
            {'Ids': jnp.asarray(rng.randint(0, 10, (2, 5))),
             'Embeddings': jnp.asarray(rng.randn(10, 16).astype('f4')),
             'WeightH': jnp.asarray(rng.randn(4, 16).astype('f4'))})
    assert o['Hidden'][0].shape == (2, 5, 4)
    o = run('fusion_seqconv_eltadd_relu',
            {'X': jnp.asarray(rng.randn(2, 5, 3).astype('f4')),
             'Filter': jnp.asarray(rng.randn(9, 4).astype('f4')),
             'Bias': jnp.zeros(4)}, {'contextLength': 3})
    assert o['Out'][0].shape == (2, 5, 4)
    assert (np.asarray(o['Out'][0]) >= 0).all()


# --------------------------- vision / detection ----------------------------


def test_deformable_conv_zero_offset_is_conv():
    x = rng.randn(2, 4, 5, 5).astype('f4')
    w = rng.randn(3, 4, 3, 3).astype('f4')
    off = np.zeros((2, 18, 5, 5), 'f4')
    attrs = {'strides': [1, 1], 'paddings': [1, 1], 'dilations': [1, 1],
             'groups': 1, 'deformable_groups': 1}
    ref = run('conv2d', {'Input': jnp.asarray(x), 'Filter': jnp.asarray(w)},
              {'strides': [1, 1], 'paddings': [1, 1]})
    o = run('deformable_conv',
            {'Input': jnp.asarray(x), 'Offset': jnp.asarray(off),
             'Mask': jnp.asarray(np.ones((2, 9, 5, 5), 'f4')),
             'Filter': jnp.asarray(w)}, attrs)
    np.testing.assert_allclose(np.asarray(o['Output'][0]),
                               np.asarray(ref['Output'][0]),
                               rtol=1e-4, atol=1e-4)
    o = run('deformable_conv_v1',
            {'Input': jnp.asarray(x), 'Offset': jnp.asarray(off),
             'Filter': jnp.asarray(w)}, attrs)
    np.testing.assert_allclose(np.asarray(o['Output'][0]),
                               np.asarray(ref['Output'][0]),
                               rtol=1e-4, atol=1e-4)


def test_prroi_pool_constant():
    x = np.full((1, 2, 8, 8), 3.0, 'f4')
    rois = np.array([[0, 0, 4, 4]], 'f4')
    o = run('prroi_pool', {'X': jnp.asarray(x), 'ROIs': jnp.asarray(rois)},
            {'pooled_height': 2, 'pooled_width': 2, 'spatial_scale': 1.0})
    np.testing.assert_allclose(np.asarray(o['Out'][0]), 3.0, rtol=1e-5)


def test_sigmoid_focal_loss():
    x = np.zeros((4, 3), 'f4')
    lbl = np.array([[1], [0], [2], [3]])
    o = run('sigmoid_focal_loss',
            {'X': jnp.asarray(x), 'Label': jnp.asarray(lbl),
             'FgNum': jnp.asarray([3])})
    out = np.asarray(o['Out'][0])
    assert out.shape == (4, 3) and (out > 0).all()
    np.testing.assert_allclose(out[0, 0], 0.25 * 0.25 * np.log(2) / 3,
                               rtol=1e-4)


def test_yolov3_loss():
    n, a, cls, h, w = 2, 3, 4, 5, 5
    x = rng.randn(n, a * (5 + cls), h, w).astype('f4') * 0.1
    gtb = np.zeros((n, 6, 4), 'f4')
    gtl = np.zeros((n, 6), 'i4')
    gtb[0, 0] = [0.5, 0.5, 0.1, 0.15]
    gtl[0, 0] = 2
    attrs = {'anchors': [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119],
             'anchor_mask': [0, 1, 2], 'class_num': cls,
             'ignore_thresh': 0.7, 'downsample_ratio': 32}
    o = run('yolov3_loss', {'X': jnp.asarray(x), 'GTBox': jnp.asarray(gtb),
                            'GTLabel': jnp.asarray(gtl)}, attrs)
    loss = np.asarray(o['Loss'][0])
    assert loss.shape == (n,) and np.isfinite(loss).all()
    # sample 1 has no gt: loss is exactly the all-negative objectness BCE
    pobj = x.reshape(n, a, 5 + cls, h, w)[1, :, 4]
    ref_neg = -np.log(1 - 1 / (1 + np.exp(-pobj))).sum()
    np.testing.assert_allclose(loss[1], ref_neg, rtol=1e-4)
    # sample 0's responsible anchor is recorded in the match mask
    assert np.asarray(o['GTMatchMask'][0]).shape == (n, 6)
    assert np.asarray(o['GTMatchMask'][0])[0, 0] >= 0


# ------------------------------ quant --------------------------------------


def test_int8_quant_roundtrip():
    x = rng.randn(3, 4).astype('f4')
    q = run('quantize', {'Input': jnp.asarray(x)}, {'Scale': 30.0})
    assert q['Output'][0].dtype == jnp.int8
    dq = run('dequantize', {'Input': q['Output'][0]}, {'Scale': 30.0})
    np.testing.assert_allclose(np.asarray(dq['Output'][0]), x, atol=1 / 30.)
    rq = run('requantize', {'Input': q['Output'][0]},
             {'Scale_in': 30.0, 'Scale_out': 15.0})
    assert rq['Output'][0].dtype == jnp.int8


# ------------------------------ lang ---------------------------------------


def test_sample_logits():
    logits = rng.randn(4, 50).astype('f4')
    labels = rng.randint(0, 50, (4, 1))
    o = run('sample_logits', {'Logits': jnp.asarray(logits),
                              'Labels': jnp.asarray(labels)},
            {'num_samples': 8})
    assert o['SampledLogits'][0].shape == (4, 9)
    assert (np.asarray(o['Samples'][0])[:, 0] == labels[:, 0]).all()


def test_pyramid_hash_and_filter_by_instag_and_var_conv():
    o = run('pyramid_hash', {'X': jnp.asarray(rng.randint(0, 100, (2, 6))),
                             'W': jnp.asarray(rng.randn(64, 8)
                                              .astype('f4'))},
            {'pyramid_layer': 3})
    assert o['Out'][0].shape == (2, 6, 8)
    assert np.isfinite(np.asarray(o['Out'][0])).all()

    o = run('filter_by_instag',
            {'Ins': jnp.asarray(np.ones((4, 3), 'f4')),
             'Ins_tag': jnp.asarray([1, 2, 3, 2]),
             'Filter_tag': jnp.asarray([2])})
    assert (np.asarray(o['LossWeight'][0]).ravel() == [0, 1, 0, 1]).all()

    o = run('var_conv_2d', {'X': jnp.asarray(rng.randn(2, 1, 6, 6)
                                             .astype('f4')),
                            'W': jnp.asarray(rng.randn(4, 9).astype('f4'))},
            {'output_channel': 4, 'input_channel': 1,
             'kernel_h': 3, 'kernel_w': 3})
    assert o['Out'][0].shape == (2, 4, 6, 6)


def test_tree_conv_leaf_gets_self_term_only():
    nodes = rng.randn(1, 3, 4).astype('f4')
    edges = np.array([[[0, 1], [0, 2], [-1, -1]]])
    w = rng.randn(4, 3, 5, 2).astype('f4')
    o = run('tree_conv', {'NodesVector': jnp.asarray(nodes),
                          'EdgeSet': jnp.asarray(edges),
                          'Filter': jnp.asarray(w)})
    out = np.asarray(o['Out'][0])
    assert out.shape == (1, 3, 10)
    ref_leaf = np.tanh(np.einsum('f,fhc->hc', nodes[0, 1],
                                 w[:, 0])).reshape(-1)
    np.testing.assert_allclose(out[0, 1], ref_leaf, rtol=1e-4, atol=1e-5)


# --------------------- SelectedRows / PS host ops --------------------------


def test_selected_rows_host_ops():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import core

    class FakeOp(object):
        def __init__(self, ins, outs, attrs):
            self._i, self._o, self._a = ins, outs, attrs

        def input(self, s):
            return self._i[s]

        def output(self, s):
            return self._o[s]

        def attr(self, k, default=None):
            return self._a.get(k, default)

    scope = fluid.Scope()
    sr = core.SelectedRows(np.array([1, 3, 1]),
                           np.array([[1.], [2.], [3.]], 'f4'), 6)
    scope.set_var('x', sr)
    registry.get('merge_selected_rows').fn(
        None, scope, FakeOp({'X': ['x']}, {'Out': ['m']}, {}))
    m = scope.find_var('m')
    assert list(m.rows) == [1, 3]
    np.testing.assert_allclose(m.value[:, 0], [4., 2.])

    registry.get('split_selected_rows').fn(
        None, scope, FakeOp({'X': ['x']}, {'Out': ['a', 'b']},
                            {'height_sections': [3, 3]}))
    assert list(scope.find_var('a').rows) == [1, 1]
    assert list(scope.find_var('b').rows) == [0]

    scope.set_var('ids', np.array([0, 1, 2, 3, 4, 5]))
    registry.get('split_ids').fn(
        None, scope, FakeOp({'Ids': ['ids']}, {'Out': ['s0', 's1']}, {}))
    assert list(scope.find_var('s0')) == [0, 2, 4]
    # shard rows come back in id order
    scope.set_var('r0', np.array([[0.], [20.], [40.]], 'f4'))
    scope.set_var('r1', np.array([[10.], [30.], [50.]], 'f4'))
    registry.get('merge_ids').fn(
        None, scope, FakeOp({'Ids': ['ids'], 'X': ['r0', 'r1']},
                            {'Out': ['merged']}, {}))
    np.testing.assert_allclose(
        scope.find_var('merged')[:, 0], [0., 10., 20., 30., 40., 50.])


def test_conv2d_transpose_torch_parity_asymmetric():
    """Round-3 regression: conv2d_transpose channel mapping + padding
    were wrong whenever in_c != out_c or p != k-1-p (the old p=1, k=3
    parity case coincidentally masked both)."""
    import torch
    import torch.nn.functional as F
    for stride, pad, inc, outc, dil, groups in (
            (1, 0, 3, 2, 1, 1), (2, 1, 3, 2, 1, 1), (2, 0, 2, 4, 1, 1),
            (2, 1, 4, 6, 2, 1), (2, 1, 4, 6, 1, 2)):
        x = rng.randn(2, inc, 5, 5).astype('f4')
        w = rng.randn(inc, outc // groups, 3, 3).astype('f4')
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 stride=stride, padding=pad,
                                 dilation=dil, groups=groups).numpy()
        got = np.asarray(run(
            'conv2d_transpose',
            {'Input': jnp.asarray(x), 'Filter': jnp.asarray(w)},
            {'strides': [stride] * 2, 'paddings': [pad] * 2,
             'dilations': [dil] * 2, 'groups': groups})['Output'][0])
        np.testing.assert_allclose(
            got, ref, rtol=1e-4, atol=1e-4,
            err_msg='s=%d p=%d %d->%d d=%d g=%d'
                    % (stride, pad, inc, outc, dil, groups))


def test_conv3d_transpose_torch_parity():
    import torch
    import torch.nn.functional as F
    x = rng.randn(1, 2, 4, 4, 4).astype('f4')
    w = rng.randn(2, 3, 2, 2, 2).astype('f4')
    ref = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                             stride=2, padding=1).numpy()
    got = np.asarray(run(
        'conv3d_transpose',
        {'Input': jnp.asarray(x), 'Filter': jnp.asarray(w)},
        {'strides': [2, 2, 2], 'paddings': [1, 1, 1]})['Output'][0])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
