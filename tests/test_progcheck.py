"""fluid.progcheck — static Program verifier.

One seeded-defect test per diagnostic class, a clean bill on the
model-program corpus (LeNet/BERT/GPT), the executor/warmup/transpiler
wiring, the disabled-path cost contract, and the /statusz section
schema.  The regression pins at the bottom cover the real-program
idioms the tier-1 verify sweep surfaced (AMP master-f32 declarations,
loop-carry dtype pinning, LoD-representation sequence ops)."""

import json

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, progcheck
from paddle_tpu.fluid.flags import _DEFAULTS, set_flags


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    set_flags({'FLAGS_program_verify':
               _DEFAULTS['FLAGS_program_verify']})
    from paddle_tpu.fluid import faultinject
    faultinject.reset()


def _mlp():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 16, act='relu')
        pred = layers.fc(h, 4)
        loss = layers.reduce_mean(pred)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _verify(main, loss=None, **kw):
    kw.setdefault('feed_names', ('x',))
    if loss is not None:
        kw.setdefault('fetch_names', (loss.name,))
    kw.setdefault('level', 'full')
    kw.setdefault('raise_on_error', False)
    return progcheck.verify_program(main, **kw)


# ------------------------------------------------ one test per class

def test_clean_program_verifies_clean():
    main, startup, loss = _mlp()
    rep = _verify(main, loss, startup_program=startup)
    assert rep.ok(), rep.format()
    assert rep.ops_checked > 0 and rep.shape_checked > 0
    assert rep.counts() == {}


def test_undefined_read():
    main, _, loss = _mlp()
    main.global_block().ops[0].inputs['X'][0] = '__nope__'
    rep = _verify(main, loss)
    assert [d.cls for d in rep.errors] and \
        rep.errors[0].cls == 'undefined_read'
    assert rep.errors[0].var == '__nope__'
    assert rep.errors[0].hint


def test_undeclared_write():
    main, _, loss = _mlp()
    main.global_block().ops[0].outputs['Out'][0] = '__orphan__'
    rep = _verify(main, loss)
    assert any(d.cls == 'undeclared_write' for d in rep.errors)


def test_read_before_init_warns():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        b = main.global_block()
        b.create_var(name='ghost', shape=[4], dtype='float32')
        layers.reduce_mean(b.vars['ghost'])
    rep = progcheck.verify_program(main, level='fast',
                                   raise_on_error=False)
    assert rep.ok()   # warning, not error: the scope may hold it
    assert any(d.cls == 'read_before_init' for d in rep.warnings)


def test_persistable_uninit_needs_startup_view():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b = main.global_block()
        b.create_var(name='stat', shape=[4], dtype='float32',
                     persistable=True)
        layers.reduce_mean(b.vars['stat'])
    # without a startup program the check stays silent (unknowable)
    rep = progcheck.verify_program(main, level='fast',
                                   raise_on_error=False)
    assert not any(d.cls == 'persistable_uninit'
                   for d in rep.diagnostics)
    rep = progcheck.verify_program(main, level='fast',
                                   startup_program=startup,
                                   raise_on_error=False)
    assert any(d.cls == 'persistable_uninit' for d in rep.warnings)


def test_dead_op_and_dead_var_warn():
    main, _, loss = _mlp()
    b = main.global_block()
    b.create_var(name='unused', shape=[2], dtype='float32')
    assert progcheck.mutate(main, 7) is not None   # appends dead op
    rep = _verify(main, loss)
    assert rep.ok()
    assert any(d.cls == 'dead_op' for d in rep.warnings)
    assert any(d.cls == 'dead_var' and d.var == 'unused'
               for d in rep.warnings)


def test_torn_subblock():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data('x', shape=[4], dtype='float32')
        i = layers.fill_constant([1], 'int64', 0)
        n = layers.fill_constant([1], 'int64', 2)
        cond = layers.less_than(i, n)
        wl = layers.While(cond, max_trip_count=4)
        with wl.block():
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
        loss = layers.reduce_mean(layers.fc(x, 4))
    assert progcheck.mutate(main, 3) == ('torn_subblock',
                                         'torn_subblock')
    rep = _verify(main, loss)
    assert any(d.cls == 'torn_subblock' for d in rep.errors)


def test_shape_mismatch_names_op_and_callstack():
    main, _, loss = _mlp()
    assert progcheck.mutate(main, 5) is not None
    rep = _verify(main, loss)
    errs = [d for d in rep.errors if d.cls == 'shape_mismatch']
    assert errs, rep.format()
    # the static NaN-provenance analog: op desc + creation callstack
    assert errs[0].op_type and errs[0].callstack
    assert 'test_progcheck.py' in errs[0].callstack[0]


def test_dtype_mismatch():
    main, _, loss = _mlp()
    assert progcheck.mutate(main, 2) is not None
    rep = _verify(main, loss)
    assert any(d.cls == 'dtype_mismatch' for d in rep.errors)


def test_infer_fail_on_untraceable_op():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data('x', shape=[4, 8], dtype='float32',
                        append_batch_size=False)
        b = main.global_block()
        b.create_var(name='bad_w', shape=[7, 5], dtype='float32',
                     persistable=True)
        b.create_var(name='bad_out', shape=[4, 5], dtype='float32')
        b.append_op('mul', inputs={'X': 'x', 'Y': 'bad_w'},
                    outputs={'Out': 'bad_out'}, infer_shape=False)
    rep = progcheck.verify_program(main, feed_names=('x',),
                                   fetch_names=('bad_out',),
                                   level='full', raise_on_error=False)
    assert any(d.cls == 'infer_fail' and d.op_type == 'mul'
               for d in rep.errors), rep.format()


def test_dynamic_batch_factoring_op_skips_inference():
    """An op appended with infer_shape=False because the sentinel
    batch cannot divide (temporal_shift's N -> N/seg) must SKIP, not
    infer_fail (tier-1 sweep: test_api_surface)."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data('x', shape=[4, 8, 8], dtype='float32')
        t = layers.temporal_shift(x, seg_num=2)
        loss = layers.reduce_mean(t)
    rep = progcheck.verify_program(
        main, feed_names=('x',), fetch_names=(loss.name,),
        level='full', raise_on_error=False)
    assert rep.ok(), rep.format()


def test_host_op_scope_resolution_exempt():
    """Host ops (save/load/print) resolve names through the SCOPE at
    runtime; a save program naming undeclared scope vars is the v1.6
    idiom, not a dangling read (tier-1 sweep: test_fastpath
    save/load roundtrip)."""
    prog = fluid.Program()
    prog.global_block().append_op(
        'save', inputs={'X': ['some_scope_var']},
        attrs={'file_path': '/tmp/x'}, infer_shape=False)
    rep = progcheck.verify_program(prog, level='fast',
                                   raise_on_error=False)
    assert rep.ok(), rep.format()


def test_first_inconsistent_op_only():
    """Downstream cascades of the first break stay unreported."""
    main, _, loss = _mlp()
    assert progcheck.mutate(main, 2) is not None
    rep = _verify(main, loss)
    assert len([d for d in rep.errors
                if d.cls in ('dtype_mismatch', 'shape_mismatch',
                             'infer_fail')]) == 1


def test_unstable_attr_warns():
    main, _, loss = _mlp()
    main.global_block().ops[0].attrs['bad'] = object()
    main.global_block().ops[1].attrs['worse'] = lambda: None
    rep = _verify(main, loss)
    assert rep.ok()
    hits = [d for d in rep.warnings if d.cls == 'unstable_attr']
    assert len(hits) == 2
    # the volatile attrs the fingerprint skips stay exempt
    assert all('__op_callstack__' not in d.message for d in hits)


def test_sharding_classes():
    from jax.sharding import PartitionSpec as P
    sizes = {'dp': 4, 'mp': 2}
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        progcheck.check_sharding({'w': (8, 8)}, {'w': P('ep')}, sizes)
    assert 'shard_unknown_axis' in str(ei.value)
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        progcheck.check_sharding({'w': (6, 8)}, {'w': P('dp')}, sizes)
    assert 'shard_indivisible' in str(ei.value)
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        progcheck.check_sharding({'w': (8, 8)},
                                 {'w': P('dp', 'dp')}, sizes)
    assert 'shard_conflict' in str(ei.value)
    # aliased vars carrying different specs conflict
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        progcheck.check_sharding(
            {'w': (8, 8), 'w@ZERO': (8, 8)},
            {'w': P('dp'), 'w@ZERO': P('mp')}, sizes,
            aliases={'w@ZERO': 'w'})
    assert 'shard_conflict' in str(ei.value)
    # and a legal layout sails through
    rep = progcheck.check_sharding({'w': (8, 8)},
                                   {'w': P('dp', 'mp')}, sizes)
    assert rep.ok()


def test_use_after_donate_via_plan():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        loss = layers.reduce_mean(layers.fc(x, 4))
        fluid.optimizer.SGD(0.1).minimize(loss)
        w = main.global_block().all_parameters()[0]
        probe = main.current_block().create_var(
            name='probe', shape=list(w.shape), dtype='float32')
        layers.py_func(lambda a: a, w, probe)
    exe = fluid.Executor(fluid.XLAPlace(0))
    plan = exe._get_plan(main, ('x',), (loss.name,))
    assert progcheck.verify_plan(plan).ok()
    assert progcheck.mutate(main, 8, plan=plan) is not None
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        progcheck.verify_plan(plan)
    assert 'use_after_donate' in str(ei.value)


# ------------------------------------------------------ corpus + wiring

def test_model_corpus_clean():
    from paddle_tpu.models import bert, gpt, lenet
    progs = []
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _p, loss, _a = lenet.build()
        fluid.optimizer.SGD(0.05).minimize(loss)
    progs.append((m, s, tuple(feeds), loss))
    cfg = bert.BertConfig(vocab_size=128, hidden=32, layers=1, heads=2)
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _e, loss = bert.build_pretrain(cfg, seq_len=8)
        fluid.optimizer.Adam(1e-4).minimize(loss)
    progs.append((m, s, tuple(feeds), loss))
    gcfg = gpt.GptConfig(vocab_size=128, hidden=32, layers=1, heads=2)
    m, s = fluid.Program(), fluid.Program()
    with fluid.program_guard(m, s):
        feeds, _l, loss = gpt.build_lm(gcfg, seq_len=8)
        fluid.optimizer.Adam(1e-4).minimize(loss)
    progs.append((m, s, tuple(feeds), loss))
    for m, s, feeds, loss in progs:
        rep = progcheck.verify_program(
            m, feed_names=feeds, fetch_names=(loss.name,),
            level='full', startup_program=s, raise_on_error=False)
        assert rep.ok(), rep.format()
        assert progcheck.verify_program(
            s, level='full', raise_on_error=False).ok()


def test_executor_flag_gates_and_raises():
    set_flags({'FLAGS_program_verify': True})
    main, startup, loss = _mlp()
    main.global_block().ops[0].inputs['X'][0] = '__nope__'
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(progcheck.ProgramVerifyError) as ei:
            exe.run(main, feed={'x': np.zeros((2, 8), 'float32')},
                    fetch_list=[loss])
    assert 'undefined_read' in str(ei.value)
    assert '__nope__' in str(ei.value)


def test_executor_flag_off_no_verify_and_no_step_cost():
    set_flags({'FLAGS_program_verify': False})
    main, startup, loss = _mlp()
    exe = fluid.Executor(fluid.XLAPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={'x': np.zeros((2, 8), 'float32')},
                fetch_list=[loss])
        before = monitor.counter_value('verify/programs')
        for _ in range(4):   # steady state: plan cache hits
            exe.run(main, feed={'x': np.zeros((2, 8), 'float32')},
                    fetch_list=[loss])
        assert monitor.counter_value('verify/programs') == before


def test_warmup_forces_fast_verification():
    from paddle_tpu.fluid import compile_cache
    set_flags({'FLAGS_program_verify': False})
    main, startup, loss = _mlp()
    main.global_block().ops[0].inputs['X'][0] = '__nope__'
    exe = fluid.Executor(fluid.XLAPlace(0))
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            with pytest.raises(progcheck.ProgramVerifyError):
                exe.warmup(main,
                           feed_shapes={'x': ((2, 8), 'float32')},
                           fetch_list=[loss], wait=True)
    finally:
        # warmup marks the process-wide plane warmed before planning;
        # drop that so later tests keep the lazy-jit run path (the
        # test_compile_cache convention)
        compile_cache.reset_plane()


def test_transpiler_output_verified():
    from paddle_tpu.fluid.transpiler import GradAllReduce
    main, startup, loss = _mlp()
    before = monitor.counter_value('verify/programs')
    GradAllReduce().transpile(startup, main, 0,
                              ['127.0.0.1:0'], '127.0.0.1:0')
    assert monitor.counter_value('verify/programs') > before


def test_transpiler_catches_torn_rewrite():
    from paddle_tpu.fluid.transpiler import GradAllReduce

    class Torn(GradAllReduce):
        def _transpile_main_program(self):
            super(Torn, self)._transpile_main_program()
            block = self.main_program.global_block()
            for op in block.ops:
                if op.type.startswith('c_allreduce'):
                    op.inputs['X'][0] = '__torn_grad__'
                    break

    main, startup, loss = _mlp()
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        Torn().transpile(startup, main, 0,
                         ['127.0.0.1:0'], '127.0.0.1:0')
    assert 'undefined_read' in str(ei.value)


def test_comms_plan_bucket_legality():
    from paddle_tpu.fluid import comms_plan
    main, _, _ = _mlp()
    block = main.global_block()
    w = block.all_parameters()[0].name
    good = [{'names': [w], 'bytes': 512.0, 'dtype': 'float32'}]
    assert comms_plan.verify_buckets(block, good) is good
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        comms_plan.verify_buckets(block, [
            {'names': ['__no_such_grad__'], 'bytes': 4.0,
             'dtype': 'float32'}])
    assert 'undefined_read' in str(ei.value)
    with pytest.raises(progcheck.ProgramVerifyError) as ei:
        comms_plan.verify_buckets(block, [
            {'names': [w], 'bytes': 4.0, 'dtype': 'float32'},
            {'names': [w], 'bytes': 4.0, 'dtype': 'float32'}])
    assert 'shard_conflict' in str(ei.value)


def test_faultinject_mutate_clause_parses():
    from paddle_tpu.fluid import faultinject
    assert faultinject.configure('progcheck.mutate:mutate:3@1')
    assert 'progcheck.mutate' in faultinject.SITES
    c = faultinject.check('progcheck.mutate')
    assert c is not None and c['action'] == 'mutate' \
        and c['arg'] == 3.0
    assert faultinject.check('progcheck.mutate') is None  # @1 one-shot
    # kinds spell as names too, end to end through the executor hook
    assert faultinject.configure('progcheck.mutate:mutate:dtype_flip')
    c = faultinject.check('progcheck.mutate')
    assert c is not None and c['arg'] == 'dtype_flip'
    faultinject.reset()
    main, _, loss = _mlp()
    assert progcheck.mutate(main, 'dtype_flip') == (
        'dtype_flip', 'dtype_mismatch')
    rep = _verify(main, loss)
    assert any(d.cls == 'dtype_mismatch' for d in rep.errors)


def test_warmup_verifies_once_with_flag_on():
    from paddle_tpu.fluid import compile_cache
    set_flags({'FLAGS_program_verify': True})
    main, startup, loss = _mlp()
    exe = fluid.Executor(fluid.XLAPlace(0))
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            before = monitor.counter_value('verify/programs')
            exe.warmup(main, feed_shapes={'x': ((2, 8), 'float32')},
                       fetch_list=[loss], wait=True)
            # the plan-build hook defers to warmup's forced pass —
            # exactly ONE verification (no double stats, one trail
            # entry), and it carries the warmup origin
            assert monitor.counter_value('verify/programs') \
                == before + 1
            assert progcheck.report()['reports'][-1]['origin'] \
                == 'warmup'
    finally:
        compile_cache.reset_plane()


def test_bucket_verification_reaches_statusz():
    from paddle_tpu.fluid import comms_plan
    main, _, _ = _mlp()
    block = main.global_block()
    w = block.all_parameters()[0].name
    before = monitor.counter_value('verify/programs')
    comms_plan.verify_buckets(
        block, [{'names': [w], 'bytes': 4.0, 'dtype': 'float32'}])
    assert monitor.counter_value('verify/programs') == before + 1
    assert progcheck.report()['reports'][-1]['origin'] \
        == 'transpile:bucket'


def test_statusz_verify_section_schema():
    main, startup, loss = _mlp()
    _verify(main, loss)
    from paddle_tpu.fluid import health
    sz = health.statusz()
    v = sz['verify']
    assert v is not None
    assert set(v) == {'enabled', 'counters', 'by_class', 'reports'}
    assert v['counters']['programs'] >= 1
    rep = v['reports'][-1]
    assert {'label', 'origin', 'ok', 'counts',
            'diagnostics'} <= set(rep)
    json.dumps(sz['verify'])   # JSON-able end to end


def test_report_trail_bounded():
    progcheck.reset()
    main, startup, loss = _mlp()
    for _ in range(40):
        _verify(main, loss, level='fast')
    assert len(progcheck.report()['reports']) <= 32


# ------------------------- regression pins from the tier-1 verify sweep

def test_amp_master_f32_declarations_verify_clean():
    """AMP programs declare f32 master params/activations while the
    lowering runs bf16 — a float-WIDTH change is the design, not a
    dtype_mismatch (tier-1 sweep: test_amp_semantics)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(layers.fc(x, 16, act='relu'), 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(0.01), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rep = progcheck.verify_program(
        main, feed_names=('x', 'y'), fetch_names=(loss.name,),
        level='full', raise_on_error=False)
    assert rep.ok(), rep.format()
    # a float->int flip still reports even under AMP
    assert progcheck._dtype_conflict('float32', 'int32', amp=True)
    assert not progcheck._dtype_conflict('float32', 'bfloat16',
                                         amp=True)
    assert progcheck._dtype_conflict('float32', 'bfloat16', amp=False)


def test_loop_carry_dtype_pinning_exempt():
    """The executor pins while-carry dtypes to the loop-entry dtype;
    build-time inference may stamp the body's promoted dtype on the
    declaration (int carry + float step) — not a defect (tier-1
    sweep: test_amp_semantics while-loop case)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        i = layers.fill_constant([1], 'int64', 0)
        n = layers.fill_constant([1], 'int64', 3)
        cond = layers.less_than(i, n)
        wl = layers.While(cond, max_trip_count=4)
        with wl.block():
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
        loss = layers.reduce_mean(layers.fc(x, 4))
    rep = progcheck.verify_program(
        main, feed_names=('x',), fetch_names=(loss.name,),
        level='full', raise_on_error=False)
    assert rep.ok(), rep.format()


def test_sequence_ops_skip_static_inference():
    """Sequence lowerings consume the padded(+mask) representation,
    not the declared LoD shape — the walk must skip them rather than
    guess (tier-1 sweep: test_bucketing)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32', lod_level=1)
        h = layers.sequence_pool(x, 'sum')
        loss = layers.reduce_mean(layers.fc(h, 4))
    rep = progcheck.verify_program(
        main, feed_names=('x',), fetch_names=(loss.name,),
        level='full', raise_on_error=False)
    assert rep.ok(), rep.format()
