"""SP/EP as first-class fluid citizens (round-4 VERDICT item 1):
ring attention and MoE reachable from the Program IR via
layers.context_parallel_attention / layers.moe, compiled through
CompiledProgram.with_mesh onto 'sp'/'ep' axes the way 'dp'/'mp' work —
parity-tested against the parallel/ library path and the dense math,
plus the 3D dp x pp x mp composition from ONE fluid Program
(program_pipeline.build_train_step data_axis/param_specs)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.parallel import mesh as pmesh

B, T, H, D, E, FF = 4, 16, 4, 8, 4, 32
DIM = H * D


def _build_block(seed=5):
    """Transformer-ish block: qkv fc -> context-parallel causal
    attention -> proj -> residual -> MoE FFN -> residual -> mse+aux."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[T, DIM], dtype='float32')
        y = layers.data('y', shape=[T, DIM], dtype='float32')
        qkv = layers.fc(x, size=3 * DIM, num_flatten_dims=2,
                        bias_attr=False)
        q, k, v = layers.split(qkv, 3, dim=-1)
        q = layers.reshape(q, [-1, T, H, D])
        k = layers.reshape(k, [-1, T, H, D])
        v = layers.reshape(v, [-1, T, H, D])
        att = layers.context_parallel_attention(q, k, v, causal=True)
        att = layers.reshape(att, [-1, T, DIM])
        proj = layers.fc(att, size=DIM, num_flatten_dims=2,
                         bias_attr=False)
        h1 = layers.elementwise_add(x, proj)
        mo, aux = layers.moe(h1, num_experts=E, hidden_size=FF,
                             aux_weight=0.01)
        out = layers.elementwise_add(h1, mo)
        mse = layers.reduce_mean(
            layers.square(layers.elementwise_sub(out, y)))
        loss = layers.elementwise_add(mse, aux)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _run_losses(program, startup, loss, feed, steps, compiled=None):
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        target = compiled if compiled is not None else program
        out = []
        for _ in range(steps):
            l, = exe.run(target, feed=feed, fetch_list=[loss])
            out.append(float(np.asarray(l).ravel()[0]))
    return out


def test_ring_attention_op_matches_library_and_dense():
    """The fluid op on an 'sp' mesh == parallel.ring_attention ==
    dense reference, same inputs."""
    from paddle_tpu.parallel.ring_attention import (
        ring_attention, reference_attention)
    rng = np.random.RandomState(3)
    q = rng.randn(B, T, H, D).astype('float32')
    k = rng.randn(B, T, H, D).astype('float32')
    v = rng.randn(B, T, H, D).astype('float32')

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        qv = layers.data('q', shape=[T, H, D], dtype='float32')
        kv = layers.data('k', shape=[T, H, D], dtype='float32')
        vv = layers.data('v', shape=[T, H, D], dtype='float32')
        out = layers.context_parallel_attention(qv, kv, vv, causal=True)

    feed = {'q': q, 'k': k, 'v': v}
    # single device: dense fallback
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        single, = exe.run(main, feed=feed, fetch_list=[out])
    # sp mesh through the SAME program
    mesh = pmesh.create_mesh(dp=2, sp=4)
    comp = fluid.CompiledProgram(main).with_data_parallel().with_mesh(
        mesh)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sharded, = exe.run(comp, feed=feed, fetch_list=[out])
    # library path on the same mesh
    lib = np.asarray(ring_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), mesh, axis='sp',
                                    causal=True))
    dense = np.asarray(reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True))
    np.testing.assert_allclose(single, dense, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(sharded, lib, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(sharded, dense, rtol=2e-4, atol=2e-5)


def test_moe_op_sharded_matches_library_path():
    """The fluid moe op under an ep mesh == moe_ffn_inner shard_mapped
    with the SAME token layout (dp x (sp,ep) token sharding)."""
    from paddle_tpu.parallel.moe import moe_ffn_inner
    rng = np.random.RandomState(4)
    x = rng.randn(B, T, DIM).astype('float32')
    wg = rng.randn(DIM, E).astype('float32') * 0.1
    w1 = rng.randn(E, DIM, FF).astype('float32') * 0.1
    w2 = rng.randn(E, FF, DIM).astype('float32') * 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = layers.data('x', shape=[T, DIM], dtype='float32')
        mo, aux = layers.moe(xv, num_experts=E, hidden_size=FF,
                             aux_weight=1.0)
    wg_n, w1_n, w2_n = [p.name for p in main.all_parameters()]

    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    comp = fluid.CompiledProgram(main).with_data_parallel().with_mesh(
        mesh)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        sc.set_var(wg_n, wg)
        sc.set_var(w1_n, w1)
        sc.set_var(w2_n, w2)
        got, gaux = exe.run(comp, feed={'x': x}, fetch_list=[mo, aux])

    # library path: same token layout the op uses
    b_loc, t_loc = B // 2, T // (2 * 2)

    def inner(xl, wg_, w1_, w2_):
        o, a = moe_ffn_inner(xl.reshape(b_loc * t_loc, DIM), wg_, w1_,
                             w2_, 'ep', 2.0)
        for ax in mesh.axis_names:
            a = jax.lax.pmean(a, ax)
        return o.reshape(b_loc, t_loc, DIM), a

    from paddle_tpu.compat import shard_map
    f = shard_map(
        inner, mesh=mesh,
        in_specs=(P('dp', ('sp', 'ep'), None), P(), P('ep'), P('ep')),
        out_specs=(P('dp', ('sp', 'ep'), None), P()))
    lib, laux = f(jnp.asarray(x), jnp.asarray(wg), jnp.asarray(w1),
                  jnp.asarray(w2))
    np.testing.assert_allclose(got, np.asarray(lib), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(float(np.asarray(gaux).ravel()[0]),
                               float(laux), rtol=2e-4)


def test_block_trains_same_single_vs_spep_mesh():
    """Same program + same seeds: single-device dense fallbacks and the
    dp2 x sp2 x ep2 sharded path learn the same loss curve (tokens per
    shard match, so capacity semantics agree)."""
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    main, startup, loss = _build_block()
    single = _run_losses(main, startup, loss, feed, 4)
    assert single[-1] < single[0]

    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    main2, startup2, loss2 = _build_block()
    comp = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name).with_mesh(mesh)
    sharded = _run_losses(main2, startup2, loss2, feed, 4,
                          compiled=comp)
    np.testing.assert_allclose(sharded, single, rtol=5e-3, atol=5e-4)


def test_moe_expert_weights_actually_shard_over_ep():
    """The layer-stamped hints must land: after a mesh step, the
    expert weights live sharded over 'ep' (not replicated)."""
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    mesh = pmesh.create_mesh(dp=2, sp=2, ep=2)
    main, startup, loss = _build_block()
    w1_n = next(p.name for p in main.all_parameters()
                if tuple(p.shape) == (E, DIM, FF))
    comp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name).with_mesh(mesh)
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(comp, feed=feed, fetch_list=[loss])
        w1 = sc.find_var(w1_n)  # jax.Array after the mesh step
        spec = w1.sharding.spec
    assert spec[0] == 'ep', spec


def test_3d_dp_pp_mp_through_fluid_program():
    """dp2 x pp2 x mp2 from ONE fluid Program: two Megatron stages
    (column-parallel fc + row-parallel fc + c_allreduce_sum over 'mp')
    cut into a GPipe pipeline, batch sharded over 'dp' — with a numpy
    oracle for the first loss."""
    from paddle_tpu.parallel.program_pipeline import build_train_step
    d, ff, b = 16, 32, 8
    rng = np.random.RandomState(13)
    x_np = rng.randn(b, d).astype('float32')
    y_np = rng.randn(b, d).astype('float32')

    mesh = pmesh.create_mesh(dp=2, mp=2, pp=2)
    pmesh.set_global_mesh(mesh)  # ring 1 -> 'mp'
    mp_ring = list(mesh.axis_names).index('mp')

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[d], dtype='float32')
        cuts = []
        h = x
        for s in range(2):
            col = layers.fc(h, size=ff, act='tanh', bias_attr=False)
            row = layers.fc(col, size=d, bias_attr=False)
            blk = main.current_block()
            red = blk.create_var(
                name='stage%d_out' % s, dtype='float32',
                shape=(-1, d), stop_gradient=False)
            blk.append_op('c_allreduce_sum', inputs={'X': row},
                          outputs={'Out': red},
                          attrs={'ring_id': mp_ring})
            h = red
            if s == 0:
                cuts.append(red.name)
        out_name = h.name

    pnames = [p.name for p in main.all_parameters()]
    param_specs = {}
    for n in pnames:
        shp = tuple(main.global_block().var(n).shape)
        param_specs[n] = P(None, 'mp') if shp == (d, ff) \
            else P('mp', None)

    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        step, params = build_train_step(
            main, sc, 'x', cuts, out_name,
            lambda o, yy: jnp.mean((o - yy) ** 2), mesh,
            n_microbatches=4, learning_rate=0.2,
            data_axis='dp', param_specs=param_specs)
        ws = {n: np.asarray(fluid.core.as_array(sc.find_var(n)))
              for n in pnames}

    # numpy oracle: allreduce makes each stage tanh(x@W1)@W2 exactly
    # (all_parameters preserves creation order: w1_s0, w2_s0, w1_s1, ...)
    w1s = [n for n in pnames if ws[n].shape == (d, ff)]
    w2s = [n for n in pnames if ws[n].shape == (ff, d)]
    ref = x_np
    for s in range(2):
        ref = np.tanh(ref @ ws[w1s[s]]) @ ws[w2s[s]]
    ref_loss = float(np.mean((ref - y_np) ** 2))

    loss, params = step(params, x_np, y_np)
    loss2, _ = step(params, x_np, y_np)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-4)
    assert float(loss2) < float(loss)


def _build_attn_dropout(seed=9, rate=0.3, use_flash=False):
    """Attention-only program with IN-RING attention-prob dropout
    (round 5): mask drawn at GLOBAL positions so sharded and dense
    paths agree bit-for-bit."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[T, DIM], dtype='float32')
        y = layers.data('y', shape=[T, DIM], dtype='float32')
        qkv = layers.fc(x, size=3 * DIM, num_flatten_dims=2,
                        bias_attr=False)
        q, k, v = layers.split(qkv, 3, dim=-1)
        q = layers.reshape(q, [-1, T, H, D])
        k = layers.reshape(k, [-1, T, H, D])
        v = layers.reshape(v, [-1, T, H, D])
        att = layers.context_parallel_attention(
            q, k, v, causal=True, use_flash=use_flash,
            dropout_rate=rate)
        att = layers.reshape(att, [-1, T, DIM])
        loss = layers.reduce_mean(
            layers.square(layers.elementwise_sub(att, y)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_ring_attention_dropout_sharded_matches_dense():
    """Round 5: attention-prob dropout under context parallelism —
    the global-position counter-hash mask makes the sp-sharded ring
    and the single-device dense fallback IDENTICAL stochastic
    functions; training losses must match across the mesh boundary."""
    rng = np.random.RandomState(3)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    main, startup, loss = _build_attn_dropout()
    single = _run_losses(main, startup, loss, feed, 4)

    mesh = pmesh.create_mesh(dp=2, sp=4)
    main2, startup2, loss2 = _build_attn_dropout()
    comp = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name).with_mesh(mesh)
    sharded = _run_losses(main2, startup2, loss2, feed, 4,
                          compiled=comp)
    np.testing.assert_allclose(sharded, single, rtol=5e-3, atol=5e-4)


def test_ring_flash_attention_dropout_sharded_matches_dense():
    """Same contract with the Pallas flash per-block engine (interpret
    mode on CPU): dropout offsets ride the packed seed operand into
    the kernels."""
    rng = np.random.RandomState(4)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    main, startup, loss = _build_attn_dropout(use_flash=True)
    single = _run_losses(main, startup, loss, feed, 3)

    mesh = pmesh.create_mesh(sp=2)
    main2, startup2, loss2 = _build_attn_dropout(use_flash=True)
    comp = fluid.CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name).with_mesh(mesh)
    sharded = _run_losses(main2, startup2, loss2, feed, 3,
                          compiled=comp)
    np.testing.assert_allclose(sharded, single, rtol=5e-3, atol=5e-4)


def test_cp_attention_dropout_eval_clone_is_deterministic():
    """for_test clones drop the stochastic mask (prefer_test lowering
    skips dropout): two eval runs produce identical losses."""
    main, startup, loss = _build_attn_dropout(rate=0.5)
    test_prog = main.clone(for_test=True)
    rng = np.random.RandomState(5)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        a, = exe.run(test_prog, feed=feed, fetch_list=[loss])
        b, = exe.run(test_prog, feed=feed, fetch_list=[loss])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_moe_top2_gating_properties():
    """GShard top-2 (round 5): combine weights of an uncapped token
    sum to 1 over its two routes (renormalized pair); under capacity
    pressure second choices drop FIRST; top_k=1 path unchanged."""
    import jax.numpy as jnp
    from paddle_tpu.parallel.moe import topk_gating

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 6).astype('float32'))
    wg = jnp.asarray(rng.randn(6, 4).astype('float32'))

    # generous capacity: nothing drops; each token's combine mass == 1
    d, c, aux = topk_gating(x, wg, 4, capacity=16, top_k=2)
    np.testing.assert_allclose(np.asarray(c.sum(axis=(1, 2))),
                               np.ones(8), rtol=1e-5)
    # each token occupies exactly two dispatch slots
    np.testing.assert_allclose(np.asarray(d.sum(axis=(1, 2))),
                               2 * np.ones(8), rtol=1e-6)
    # tight capacity: total kept slots per expert <= capacity, and the
    # kept mass never exceeds the uncapped mass
    d2, c2, _ = topk_gating(x, wg, 4, capacity=1, top_k=2)
    per_expert = np.asarray(d2.sum(axis=(0, 2)))
    assert (per_expert <= 1 + 1e-6).all(), per_expert
    assert float(c2.sum()) <= float(c.sum()) + 1e-6
    # top_k=1 equals the legacy top1_gating exactly
    from paddle_tpu.parallel.moe import top1_gating
    d1a, c1a, aux1a = topk_gating(x, wg, 4, capacity=4, top_k=1)
    d1b, c1b, aux1b = top1_gating(x, wg, 4, capacity=4)
    np.testing.assert_array_equal(np.asarray(d1a), np.asarray(d1b))
    np.testing.assert_array_equal(np.asarray(c1a), np.asarray(c1b))


def test_moe_top2_sharded_matches_dense():
    """top_k=2 through the fluid op: ep-sharded all_to_all routing ==
    dense fallback at shard-divisible shapes (the top-1 parity
    contract extended to GShard routing)."""
    def build(seed=21):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[T, DIM], dtype='float32')
            y = layers.data('y', shape=[T, DIM], dtype='float32')
            mo, aux = layers.moe(x, num_experts=E, hidden_size=FF,
                                 aux_weight=0.01, top_k=2)
            out = layers.elementwise_add(x, mo)
            mse = layers.reduce_mean(
                layers.square(layers.elementwise_sub(out, y)))
            loss = layers.elementwise_add(mse, aux)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(6)
    feed = {'x': rng.randn(B, T, DIM).astype('float32'),
            'y': rng.randn(B, T, DIM).astype('float32')}
    main, startup, loss = build()
    single = _run_losses(main, startup, loss, feed, 3)

    mesh = pmesh.create_mesh(dp=4, ep=2)
    m2, s2, loss2 = build()
    comp = fluid.CompiledProgram(m2).with_data_parallel(
        loss_name=loss2.name).with_mesh(mesh)
    sharded = _run_losses(m2, s2, loss2, feed, 3, compiled=comp)
    np.testing.assert_allclose(sharded, single, rtol=5e-3, atol=5e-4)
