"""fluid.timeseries + fluid.slo — windowed history, SLO burn-rate
alerting, and the regression-gate comparer.

The acceptance contract: window math survives the ugly inputs real
jobs produce — counter resets from a restarted worker (the post-reset
value IS the delta, prometheus rate() semantics), gauge gaps from a
dead worker's missed heartbeats (reported as holes, never bridged),
empty windows (None, not a crash, and no-data neither fires nor
resolves an SLO); the alert state machine holds its hysteresis
against a flapping series and scales its slow window honestly on
short histories; the exposition linter rejects the per-bucket-count
histogram rendering; rate_limited_dump claims atomically; and the
run-to-run comparer passes honest reruns while failing seeded
slowdowns by name."""

import json
import os
import sys

import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (health, monitor, slo, supervisor,
                              timeseries, trace)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), 'tools'))
import check_regress  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    fluid.set_flags({'FLAGS_timeseries': False,
                     'FLAGS_timeseries_window': 512,
                     'FLAGS_timeseries_sample_steps': 1,
                     'FLAGS_slo': '',
                     'FLAGS_slo_fast_points': 12,
                     'FLAGS_slo_slow_points': 96,
                     'FLAGS_slo_hysteresis': 3})
    slo.reset()
    timeseries.reset()
    supervisor.reset()
    trace.reset()
    monitor.reset()


# ------------------------------------------------------- window math
class TestWindowMath:
    def test_counter_reset_is_delta_not_negative(self):
        # 10, 25, 40, restart -> 5, 20: the reset interval contributes
        # the post-reset cumulative (5), never -35
        pts = [(0.0, 0, 10.0), (1.0, 1, 25.0), (2.0, 2, 40.0),
               (3.0, 3, 5.0), (4.0, 4, 20.0)]
        deltas = [d for _t, _s, d in timeseries.counter_deltas(pts)]
        assert deltas == [15.0, 15.0, 5.0, 15.0]
        assert timeseries.counter_resets(pts) == 1
        # rate spans the whole window with the reset-aware total
        assert timeseries.rate_per_s(pts) == pytest.approx(50.0 / 4.0)

    def test_rate_needs_two_points_and_elapsed_time(self):
        assert timeseries.rate_per_s([]) is None
        assert timeseries.rate_per_s([(1.0, 0, 5.0)]) is None
        assert timeseries.rate_per_s([(1.0, 0, 5.0),
                                      (1.0, 1, 9.0)]) is None

    def test_gauge_gaps_counted_not_bridged(self):
        pts = [(0.0, 0, 4.0), (1.0, None, None), (2.0, None, None),
               (3.0, 3, 8.0)]
        st = timeseries.gauge_stats(pts)
        assert st['gaps'] == 2 and st['n'] == 2
        assert st['min'] == 4.0 and st['max'] == 8.0 and st['last'] == 8.0

    def test_gauge_stats_empty(self):
        st = timeseries.gauge_stats([(0.0, None, None)])
        assert st['last'] is None and st['n'] == 0 and st['gaps'] == 1

    def test_percentile_interpolates_and_pins_overflow(self):
        edges = (1.0, 2.0, 4.0)
        # 4 obs in (1, 2]: p50 lands mid-bucket
        assert timeseries.percentile_from_counts(
            edges, [0, 4, 0, 0], 0.5) == pytest.approx(1.5)
        # all overflow: the honest answer is the last finite edge
        assert timeseries.percentile_from_counts(
            edges, [0, 0, 0, 7], 0.99) == 4.0
        assert timeseries.percentile_from_counts(edges, [0, 0, 0, 0],
                                                 0.5) is None

    def test_hist_window_subtracts_cumulative_state(self):
        edges = (1.0, 2.0)
        # cumulative (count, sum, buckets) at window start and end:
        # the window saw 3 obs totalling 4.5, all in (1, 2]
        pts = [(0.0, 0, 10, 8.0, (10, 0, 0)),
               (5.0, 5, 13, 12.5, (10, 3, 0))]
        hw = timeseries.hist_window(edges, pts)
        assert hw['count'] == 3
        assert hw['sum'] == pytest.approx(4.5)
        assert hw['mean'] == pytest.approx(1.5)
        assert 1.0 <= hw['percentiles']['p50'] <= 2.0

    def test_hist_window_reset_falls_back_to_end_state(self):
        edges = (1.0,)
        pts = [(0.0, 0, 50, 50.0, (50, 0)),
               (5.0, 5, 4, 2.0, (4, 0))]    # restarted mid-window
        hw = timeseries.hist_window(edges, pts)
        assert hw['count'] == 4 and hw['sum'] == pytest.approx(2.0)

    def test_hist_window_empty(self):
        hw = timeseries.hist_window((1.0,), [])
        assert hw['count'] == 0 and hw['mean'] is None
        assert hw['percentiles']['p99'] is None

    def test_downsample_keeps_last_per_bucket(self):
        pts = [(t * 0.1, t, float(t)) for t in range(40)]
        ds = timeseries.downsample(pts, 1.0)
        assert len(ds) == 4
        assert [p[2] for p in ds] == [9.0, 19.0, 29.0, 39.0]
        assert timeseries.downsample(pts, 0) == pts

    def test_spark_normalizes(self):
        s = timeseries.spark([1, 2, 3, 4, 5, 6, 7, 8])
        assert s[0] == u'▁' and s[-1] == u'█' and len(s) == 8
        assert timeseries.spark([None, None]) == ''
        assert timeseries.spark([3.0, 3.0]) == u'▁▁'


# ----------------------------------------------------- live sampling
class TestSampling:
    def test_maybe_sample_off_by_default(self):
        monitor.add('demo/c', 5)
        assert timeseries.maybe_sample(step=1) is False
        assert timeseries.report()['samples'] == 0

    def test_sample_appends_one_point_per_registry_entry(self):
        fluid.set_flags({'FLAGS_timeseries': True})
        monitor.add('demo/c', 5)
        monitor.set_gauge('demo/g', 2.0)
        monitor.observe('demo/h', 0.01)
        assert timeseries.maybe_sample(step=1) is True
        monitor.add('demo/c', 3)
        assert timeseries.maybe_sample(step=2) is True
        doc = timeseries.window('demo/c')
        assert doc['kind'] == 'counter' and doc['n'] == 2
        assert doc['derived']['total_delta'] == pytest.approx(3.0)
        assert timeseries.window('demo/g')['kind'] == 'gauge'
        hdoc = timeseries.window('demo/h')
        assert hdoc['kind'] == 'hist' and hdoc['edges']
        # points carry (ts, step, value)
        assert doc['points'][0][1] == 1 and doc['points'][1][1] == 2

    def test_sample_stride(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_timeseries_sample_steps': 4})
        monitor.add('demo/c')
        assert timeseries.maybe_sample(step=3) is False
        assert timeseries.maybe_sample(step=4) is True
        # heartbeat-source samples ignore the step stride
        assert timeseries.maybe_sample(source='heartbeat') is True

    def test_window_bounded_by_flag(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_timeseries_window': 8})
        for i in range(30):
            monitor.add('demo/c')
            timeseries.sample(step=i)
        assert timeseries.window('demo/c')['n'] == 8

    def test_window_unknown_series_and_empty_window(self):
        fluid.set_flags({'FLAGS_timeseries': True})
        assert timeseries.window('no/such') is None
        monitor.add('demo/c')
        timeseries.sample(step=1, now=100.0)
        doc = timeseries.window('demo/c', seconds=5, now=1000.0)
        assert doc['n'] == 0 and doc['derived']['rate_per_s'] is None
        assert doc['derived']['total_delta'] == 0

    def test_job_history_and_gap_markers(self):
        st = {'counters': {'w/c': 5.0}, 'gauges': {'w/g': 1.0},
              'hists': {}}
        timeseries.job_sample(1, st, now=10.0)
        st2 = {'counters': {'w/c': 9.0}, 'gauges': {'w/g': 2.0},
               'hists': {}}
        timeseries.job_sample(1, st2, now=11.0)
        # dead worker: two missed heartbeats leave explicit holes in
        # its GAUGE series (counters stay cumulative)
        assert timeseries.job_gap(1, now=12.0) == 1
        assert timeseries.job_gap(1, now=13.0) == 1
        assert timeseries.job_gap(7, now=12.0) == 0   # never seen
        doc = timeseries.window('w/g', rank=1)
        assert doc['derived']['gaps'] == 2
        assert doc['derived']['last'] == 2.0
        cdoc = timeseries.window('w/c', rank=1)
        assert cdoc['n'] == 2 and cdoc['derived']['total_delta'] == 4.0
        assert timeseries.job_ranks() == ['1']

    def test_http_query_surfaces(self):
        fluid.set_flags({'FLAGS_timeseries': True})
        monitor.add('demo/c')
        timeseries.sample(step=1)
        code, doc = timeseries.http_query({})
        assert code == 200 and 'demo/c' in doc['series']
        code, doc = timeseries.http_query({'name': 'demo/c',
                                           'point': '1'})
        assert code == 200 and len(doc['point']) == 3
        code, doc = timeseries.http_query({'name': 'no/such'})
        assert code == 404 and doc['series']
        code, doc = timeseries.http_query({'name': 'demo/c',
                                           'points': 'nan-ish'})
        assert code == 400

    def test_statusz_rollup_renders_rows(self):
        fluid.set_flags({'FLAGS_timeseries': True})
        for i in range(6):
            monitor.add('executor/run_calls')
            monitor.set_gauge('demo/g', float(i))
            timeseries.sample(step=i, now=100.0 + i)
        roll = timeseries.statusz_rollup()
        names = [r['name'] for r in roll['series']]
        # preferred ordering puts executor series first
        assert names[0] == 'executor/run_calls'
        assert all(r['spark'] for r in roll['series'])


# --------------------------------------------------------------- slo
def _gauge_run(values, start=100.0):
    """Feed a synthetic gauge level per sample tick and evaluate."""
    for i, v in enumerate(values):
        monitor.set_gauge('demo/level', float(v))
        timeseries.sample(step=i, now=start + i)


class TestSLO:
    def test_parse_units_and_forms(self):
        assert slo.parse('a/b p99 < 20ms') == ('a/b', 'p99', '<',
                                               pytest.approx(0.02))
        assert slo.parse('a/b rate == 0') == ('a/b', 'rate', '==', 0.0)
        assert slo.parse('a/b < 90%') == ('a/b', 'value', '<',
                                          pytest.approx(0.9))
        assert slo.parse('a/b value <= 5us')[3] == pytest.approx(5e-6)
        for bad in ('a/b', 'a/b frobnicate < 1', 'a/b ~ 1',
                    'a/b < 1parsec', 'a/b p99 < 1 extra'):
            with pytest.raises(ValueError):
                slo.parse(bad)

    def test_bad_flag_clause_counts_not_crashes(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo': 'broken clause here extra;'
                                      'demo/level < 10'})
        monitor.set_gauge('demo/level', 1.0)
        timeseries.sample(step=0)
        assert monitor.counter_value('slo/bad_clauses') == 1
        assert len(slo.objectives()) == 1

    def test_fires_after_hysteresis_and_cites_supervisor(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_fast_points': 3,
                         'FLAGS_slo_slow_points': 6,
                         'FLAGS_slo_hysteresis': 2})
        slo.declare('demo/level < 10', name='level_cap')
        _gauge_run([1, 1, 1])                    # healthy
        assert slo.objectives()[0]['state'] == 'ok'
        _gauge_run([50], start=103.0)            # first breach
        assert slo.objectives()[0]['state'] == 'pending'
        assert monitor.counter_value('slo/alerts_fired') == 0
        _gauge_run([50, 50], start=104.0)        # hold the breach
        doc = slo.objectives()[0]
        assert doc['state'] == 'firing'
        assert doc['burn_fast'] == pytest.approx(5.0)
        assert monitor.counter_value('slo/alerts_fired') == 1
        recs = [d for d in supervisor.decisions()
                if d.get('kind') == 'slo_breach']
        assert recs and recs[-1]['info']['series'] == 'demo/level'
        assert recs[-1]['info']['window']['fast_points'] == 3
        az = slo.alertz()
        assert [a['name'] for a in az['firing']] == ['level_cap']

    def test_flapping_series_neither_fires_nor_resolves(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_fast_points': 2,
                         'FLAGS_slo_slow_points': 4,
                         'FLAGS_slo_hysteresis': 3})
        slo.declare('demo/level < 10', name='level_cap')
        # oscillate across the threshold every sample: the bad streak
        # never reaches 3 (both-window breaches), the good streak is
        # zeroed by every breach -> pending forever, zero alerts
        _gauge_run([50, 1] * 12)
        assert monitor.counter_value('slo/alerts_fired') == 0
        assert monitor.counter_value('slo/alerts_resolved') == 0
        assert slo.objectives()[0]['state'] == 'pending'

    def test_resolve_path_and_trail(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_fast_points': 2,
                         'FLAGS_slo_slow_points': 4,
                         'FLAGS_slo_hysteresis': 2})
        slo.declare('demo/level < 10', name='level_cap')
        _gauge_run([50, 50, 50, 50])
        assert slo.objectives()[0]['state'] == 'firing'
        _gauge_run([1, 1], start=110.0)     # clean run >= hysteresis
        doc = slo.objectives()[0]
        assert doc['state'] == 'resolved'
        assert monitor.counter_value('slo/alerts_resolved') == 1
        az = slo.alertz()
        assert az['resolved_trail']
        _gauge_run([1, 1, 1, 1], start=115.0)   # 2h clean -> ok
        assert slo.objectives()[0]['state'] == 'ok'

    def test_short_history_scales_slow_window(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_fast_points': 2,
                         'FLAGS_slo_slow_points': 96,
                         'FLAGS_slo_hysteresis': 1})
        slo.declare('demo/level < 10', name='level_cap')
        _gauge_run([50, 50, 50])
        doc = slo.objectives()[0]
        w = doc['window']
        assert w['scaled'] is True
        assert w['available_points'] == 3 < w['slow_points'] == 96
        # the scaled slow window still measured (and breached): a
        # short job is not blind for an hour of steps
        assert doc['measured_slow'] == 50.0 and doc['state'] == 'firing'

    def test_empty_window_neither_fires_nor_resolves(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_hysteresis': 1})
        slo.declare('demo/never_recorded < 1', name='ghost')
        for _ in range(5):
            slo.evaluate_all(now=100.0)
        doc = slo.objectives()[0]
        assert doc['state'] == 'ok' and doc.get('no_data') is True
        assert monitor.counter_value('slo/alerts_fired') == 0

    def test_zero_budget_burn_reports_raw_measure(self):
        fluid.set_flags({'FLAGS_timeseries': True,
                         'FLAGS_slo_fast_points': 2,
                         'FLAGS_slo_slow_points': 4,
                         'FLAGS_slo_hysteresis': 1})
        slo.declare('demo/level == 0', name='zero_budget')
        _gauge_run([3, 3, 3])
        doc = slo.objectives()[0]
        assert doc['state'] == 'firing'
        assert doc['burn_fast'] == pytest.approx(3.0)


# ----------------------------------------------------- exposition lint
class TestPromLint:
    def test_live_exposition_is_clean(self):
        monitor.add('demo/c')
        monitor.observe('demo/h', 0.01)
        monitor.observe('demo/h', 99.0)    # overflow bucket populated
        assert health.prom_lint(monitor.prometheus_text()) == []

    def test_per_bucket_counts_rejected(self):
        text = '\n'.join([
            '# HELP m demo', '# TYPE m histogram',
            'm_bucket{le="0.1"} 5',
            'm_bucket{le="1"} 2',          # decrease: per-bucket form
            'm_bucket{le="+Inf"} 1',
            'm_sum 1.5', 'm_count 8', ''])
        problems = health.prom_lint(text)
        assert any('not cumulative' in p for p in problems)

    def test_finite_bucket_above_inf_rejected(self):
        text = '\n'.join([
            '# HELP m demo', '# TYPE m histogram',
            'm_bucket{le="0.1"} 0',
            'm_bucket{le="1"} 7',
            'm_bucket{le="+Inf"} 7',
            'm_sum 1.5', 'm_count 9', ''])
        problems = health.prom_lint(text)
        assert any('+Inf bucket 7 != _count' in p for p in problems)
        text = text.replace('m_count 9', 'm_count 7').replace(
            'm_bucket{le="+Inf"} 7', 'm_bucket{le="+Inf"} 7\n'
            'm_bucket{le="2"} 9')
        problems = health.prom_lint(text)
        assert any('out of order' in p for p in problems)

    def test_job_merged_render_stays_cumulative(self):
        st = {'counters': {}, 'gauges': {},
              'hists': {'demo/h': {'edges': [0.1, 1.0],
                                   'counts': [2, 3, 1],
                                   'sum': 4.0, 'count': 6}}}
        text = health.render_merged([('0', st), ('1', st)])
        assert health.prom_lint(text) == []
        assert 'le="+Inf"} 12' in text


# ----------------------------------------------------- rate_limited_dump
class TestRateLimitedDump:
    def test_claims_once_per_interval(self, tmp_path):
        fluid.set_flags({'FLAGS_trace_dir': str(tmp_path)})
        trace.enable()
        assert trace.rate_limited_dump('t/key', 3600.0,
                                       tag='rld') is not None
        before = monitor.counter_value('trace/dumps_suppressed')
        assert trace.rate_limited_dump('t/key', 3600.0) is None
        assert monitor.counter_value('trace/dumps_suppressed') == \
            before + 1
        # a different key has its own claim
        assert trace.rate_limited_dump('t/other', 3600.0,
                                       tag='rld2') is not None

    def test_interval_zero_never_limits(self, tmp_path):
        fluid.set_flags({'FLAGS_trace_dir': str(tmp_path)})
        trace.enable()
        assert trace.rate_limited_dump('t/key', 0.0,
                                       tag='a') is not None
        assert trace.rate_limited_dump('t/key', 0.0,
                                       tag='b') is not None

    def test_reset_rate_limits_reopens(self, tmp_path):
        fluid.set_flags({'FLAGS_trace_dir': str(tmp_path)})
        trace.enable()
        assert trace.rate_limited_dump('m/key', 3600.0,
                                       tag='x') is not None
        assert trace.rate_limited_dump('m/key', 3600.0) is None
        trace.reset_rate_limits('m/')
        assert trace.rate_limited_dump('m/key', 3600.0,
                                       tag='y') is not None


# -------------------------------------------------------- check_regress
def _hist_lines(entry, vals, metric='step_s'):
    return [{'ts': float(i), 'entry': entry, 'run_id': None,
             'metrics': {metric: v}} for i, v in enumerate(vals)]


class TestCheckRegress:
    def test_honest_run_passes(self):
        lines = _hist_lines('bench', [0.10, 0.11, 0.09, 0.105])
        v = [x for x in check_regress.compare(lines)
             if x['metric'] == 'step_s'][0]
        assert v['status'] == 'PASS'

    def test_slowdown_regresses_by_name(self):
        lines = _hist_lines('bench', [0.10, 0.11, 0.09, 0.50])
        v = [x for x in check_regress.compare(lines)
             if x['metric'] == 'step_s'][0]
        assert v['status'] == 'REGRESS' and v['direction'] == 'lower'

    def test_throughput_drop_regresses(self):
        lines = _hist_lines('bench', [1000.0, 980.0, 1020.0, 300.0],
                            metric='examples_per_sec')
        v = [x for x in check_regress.compare(lines)
             if x['metric'] == 'examples_per_sec'][0]
        assert v['status'] == 'REGRESS' and v['direction'] == 'higher'
        # a throughput INCREASE is not a regression
        lines = _hist_lines('bench', [1000.0, 980.0, 1020.0, 2500.0],
                            metric='examples_per_sec')
        v = [x for x in check_regress.compare(lines)
             if x['metric'] == 'examples_per_sec'][0]
        assert v['status'] == 'PASS'

    def test_median_of_n_absorbs_one_outlier(self):
        lines = _hist_lines('bench', [0.10, 0.11, 0.09,
                                      0.50, 0.10, 0.105])
        v = [x for x in check_regress.compare(lines, current_n=3)
             if x['metric'] == 'step_s'][0]
        assert v['status'] == 'PASS'

    def test_thin_baseline_and_unknown_direction_are_info(self):
        lines = _hist_lines('bench', [0.10, 0.50])
        v = [x for x in check_regress.compare(lines)
             if x['metric'] == 'step_s'][0]
        assert v['status'] == 'INFO'
        lines = _hist_lines('bench', [1.0, 2.0, 3.0, 99.0],
                            metric='monitor.executor.retraces')
        assert all(x['status'] == 'INFO'
                   for x in check_regress.compare(lines))

    def test_bench_history_append_and_load(self, tmp_path):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench
        path = str(tmp_path / 'h.jsonl')
        rec = {'step_s': 0.1, 'note': 'text-skipped',
               'nested': {'p99': 0.2, 'flag': True}}
        bench.append_history('demo', rec, path=path)
        lines = check_regress.load_history(path)
        assert len(lines) == 1
        m = lines[0]['metrics']
        assert m['step_s'] == 0.1 and m['nested.p99'] == 0.2
        assert 'note' not in m and 'nested.flag' not in m
        # a torn tail line is skipped, not fatal
        with open(path, 'a') as f:
            f.write('{"entry": "demo", "metr')
        assert len(check_regress.load_history(path)) == 1
