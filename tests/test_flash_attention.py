"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.parallel.ring_attention import reference_attention


@pytest.mark.parametrize('causal', [False, True])
def test_flash_matches_dense(causal):
    rng = np.random.RandomState(0)
    q = rng.randn(2, 64, 4, 16).astype('float32')
    k = rng.randn(2, 64, 4, 16).astype('float32')
    v = rng.randn(2, 64, 4, 16).astype('float32')
    out = flash_attention(jnp.asarray(q), jnp.asarray(k),
                          jnp.asarray(v), causal=causal)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_grad():
    rng = np.random.RandomState(1)
    q = rng.randn(1, 32, 2, 8).astype('float32')
    k = rng.randn(1, 32, 2, 8).astype('float32')
    v = rng.randn(1, 32, 2, 8).astype('float32')

    def f_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def r_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(f_loss, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v))
    gr = jax.grad(r_loss, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_fused_op_registered():
    from paddle_tpu.ops import registry
    rng = np.random.RandomState(2)
    q = rng.randn(1, 16, 2, 8).astype('float32')
    out = registry.get('fused_multihead_attention').fn(
        registry.LowerCtx(0), {'Q': [q], 'K': [q], 'V': [q]},
        {'causal': False})
    assert out['Out'][0].shape == q.shape
