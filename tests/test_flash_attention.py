"""Pallas flash attention vs dense reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention
from paddle_tpu.parallel.ring_attention import reference_attention


@pytest.mark.parametrize('causal', [False, True])
def test_flash_matches_dense(causal):
    rng = np.random.RandomState(0)
    q = rng.randn(2, 64, 4, 16).astype('float32')
    k = rng.randn(2, 64, 4, 16).astype('float32')
    v = rng.randn(2, 64, 4, 16).astype('float32')
    out = flash_attention(jnp.asarray(q), jnp.asarray(k),
                          jnp.asarray(v), causal=causal)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_grad():
    rng = np.random.RandomState(1)
    q = rng.randn(1, 32, 2, 8).astype('float32')
    k = rng.randn(1, 32, 2, 8).astype('float32')
    v = rng.randn(1, 32, 2, 8).astype('float32')

    def f_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def r_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(f_loss, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v))
    gr = jax.grad(r_loss, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_fused_op_registered():
    from paddle_tpu.ops import registry
    rng = np.random.RandomState(2)
    q = rng.randn(1, 16, 2, 8).astype('float32')
    out = registry.get('fused_multihead_attention').fn(
        registry.LowerCtx(0), {'Q': [q], 'K': [q], 'V': [q]},
        {'causal': False})
    assert out['Out'][0].shape == q.shape


@pytest.mark.parametrize('causal', [False, True])
def test_flash_grad_noncausal_and_odd_t(causal):
    """Backward Pallas kernels (dq + dkv) against the dense vjp at a
    sequence length that forces block-size shrinkage (t=48)."""
    rng = np.random.RandomState(3)
    q = rng.randn(1, 48, 2, 8).astype('float32')
    k = rng.randn(1, 48, 2, 8).astype('float32')
    v = rng.randn(1, 48, 2, 8).astype('float32')
    cot = rng.randn(1, 48, 2, 8).astype('float32')

    def f(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal=causal),
                        jnp.asarray(cot))

    def r(q, k, v):
        return jnp.vdot(reference_attention(q, k, v, causal=causal),
                        jnp.asarray(cot))

    gf = jax.grad(f, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v))
    gr = jax.grad(r, (0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_grad_bf16():
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 32, 1, 8), jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 32, 1, 8), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 32, 1, 8), jnp.bfloat16)
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2),
        (0, 1, 2))(q, k, v)
    for a in g:
        assert a.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(a, np.float32)).all()


def test_bert_flash_path_parity():
    """BERT encoder with the fused flash op == naive attention chain
    (same weights/seeds), forward loss and parameter gradients."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    def run(use_flash):
        cfg = models.bert.BertConfig(
            vocab_size=500, hidden=32, layers=2, heads=2,
            intermediate=64, max_pos=64, dropout=0.0,
            attn_dropout=0.0, use_flash=use_flash)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 31
        with fluid.program_guard(main, startup):
            feeds, enc, loss = models.bert.build_pretrain(cfg, 16)
            fluid.optimizer.SGD(0.1).minimize(loss)
        rng = np.random.RandomState(0)
        batch = models.bert.synthetic_batch(cfg, 4, 16, rng)
        batch['input_mask'][:, 12:] = 0.0  # exercise the key bias
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            out = [exe.run(main, feed=batch, fetch_list=[loss])[0]
                   for _ in range(3)]
        return [float(np.asarray(l).ravel()[0]) for l in out]

    flash, naive = run(True), run(False)
    np.testing.assert_allclose(flash, naive, rtol=2e-4)


@pytest.mark.parametrize('causal', [False, True])
def test_ring_flash_attention_parity(causal):
    """Flash-in-the-ring (sequence parallelism with the Pallas kernel
    per block): output and gradients match dense attention on a 4-way
    'sp' mesh."""
    from paddle_tpu.parallel import mesh as pmesh
    from paddle_tpu.parallel.ring_attention import ring_flash_attention

    mesh = pmesh.create_mesh(sp=4, devices=jax.devices()[:4])
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32)
    cot = jnp.asarray(rng.randn(2, 64, 2, 8), jnp.float32)

    def rf(q, k, v):
        return jnp.vdot(ring_flash_attention(q, k, v, mesh,
                                             causal=causal), cot)

    def dense(q, k, v):
        return jnp.vdot(reference_attention(q, k, v, causal=causal),
                        cot)

    out = ring_flash_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)
    gf = jax.grad(rf, (0, 1, 2))(q, k, v)
    gr = jax.grad(dense, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_auto_dispatch_and_vmem_clamp():
    """Round-4 VERDICT item 7: below the measured crossover the public
    entry runs the dense XLA chain (same math), and oversized block
    configs clamp to the VMEM budget instead of failing to compile."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.RandomState(7)
    b, t, h, d = 2, 128, 2, 64
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    assert t < fa.FLASH_MIN_SEQ  # the regression pocket
    o_auto = fa.flash_attention(q, k, v, causal=True)
    o_forced = fa.flash_attention(q, k, v, causal=True, min_seq=0)
    o_dense = fa.flash_attention(q, k, v, causal=True, min_seq=10 ** 9)
    np.testing.assert_allclose(np.asarray(o_auto), np.asarray(o_dense),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(o_forced),
                               np.asarray(o_dense), rtol=2e-4,
                               atol=2e-5)
    # dense fallback honors the key bias too
    bias = jnp.asarray(rng.randn(b, t) * -2.0, jnp.float32)
    ob_auto = fa.flash_attention(q, k, v, key_bias=bias)
    ob_forced = fa.flash_attention(q, k, v, key_bias=bias, min_seq=0)
    np.testing.assert_allclose(np.asarray(ob_auto),
                               np.asarray(ob_forced), rtol=2e-4,
                               atol=2e-5)
    # grads agree across the dispatch boundary
    gf = jax.grad(lambda q_: jnp.sum(
        fa.flash_attention(q_, k, v, causal=True, min_seq=0) ** 2))(q)
    gd = jax.grad(lambda q_: jnp.sum(
        fa.flash_attention(q_, k, v, causal=True,
                           min_seq=10 ** 9) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=5e-3, atol=5e-4)
    # oversized blocks degrade inside the budget, never raise
    bq, bk = fa._block_sizes(4096, 4096, 4096, d=128, itemsize=2)
    assert fa._vmem_estimate(4096, 128, bq, bk, 2) <= \
        fa.VMEM_BUDGET_BYTES
    # d=128 runs through the kernels (interpret off-TPU)
    q2 = jnp.asarray(rng.randn(1, 64, 2, 128), jnp.float32)
    o2 = fa.flash_attention(q2, q2, q2, min_seq=0)
    assert o2.shape == (1, 64, 2, 128)


def test_conv_precision_flag():
    """FLAGS_conv_precision selects the f32 MXU algorithm (escape
    hatch for the multi-pass dW-conv compile hang,
    tools/repro_conv_wedge.py) without changing results beyond
    algorithm tolerance."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.ops.nn_ops import _f32_conv_precision
    import jax

    assert _f32_conv_precision() == jax.lax.Precision.HIGHEST
    rng = np.random.RandomState(0)
    x = rng.rand(4, 3, 16, 16).astype('float32')

    def run():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            img = layers.data('img', shape=[3, 16, 16],
                              dtype='float32')
            out = layers.conv2d(img, num_filters=8, filter_size=3)
            loss = layers.reduce_mean(out)
            fluid.optimizer.SGD(0.1).minimize(loss)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            l, = exe.run(main, feed={'img': x}, fetch_list=[loss])
        return float(np.asarray(l).ravel()[0])

    base = run()
    try:
        fluid.flags.set_flags({'FLAGS_conv_precision': 'default'})
        assert _f32_conv_precision() == jax.lax.Precision.DEFAULT
        got = run()
    finally:
        fluid.flags.set_flags({'FLAGS_conv_precision': 'highest'})
    # single-pass bf16 vs 6-pass: same value within bf16 tolerance
    assert abs(got - base) < 5e-2 * max(1.0, abs(base)), (got, base)


def test_conv_precision_flag_rekeys_executable_cache():
    """Toggling FLAGS_conv_precision after first compile must produce
    a NEW executable for the SAME program (the cache keys on it), not
    silently reuse the stale one."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.executor import _Segment
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 8, 8).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = layers.data('img', shape=[3, 8, 8], dtype='float32')
        out = layers.conv2d(img, num_filters=4, filter_size=3)
        loss = layers.reduce_mean(out)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'img': x}, fetch_list=[loss])
        try:
            fluid.flags.set_flags({'FLAGS_conv_precision': 'default'})
            exe.run(main, feed={'img': x}, fetch_list=[loss])
        finally:
            fluid.flags.set_flags({'FLAGS_conv_precision': 'highest'})
        plan = exe._get_plan(main, ('img',), (loss.name,))
        seg = next(it for it in plan if isinstance(it, _Segment))
        precs = {k[1] for k in seg.compiled if isinstance(k, tuple)
                 and len(k) >= 2 and isinstance(k[1], str)}
    assert {'highest', 'default'} <= precs, seg.compiled.keys()


# ---------------------------------------------------------------------------
# In-kernel attention dropout (round 5).  Reference default: dropout on
# the attention probabilities (python/paddle/fluid/layers/nn.py dropout
# around softmax, operators/dropout_op.cu); the flash kernels apply it
# to the probs without materializing [T, T], mask keyed on
# (seed, head, q, k) via a counter hash shared by fwd, both bwd
# kernels, and the dense dispatch arm.
# ---------------------------------------------------------------------------


def test_flash_dropout_matches_dense_same_mask():
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 64, 2, 16).astype('float32'))
    k = jnp.asarray(rng.randn(2, 64, 2, 16).astype('float32'))
    v = jnp.asarray(rng.randn(2, 64, 2, 16).astype('float32'))
    seed = jnp.uint32(1234)
    out = fa.flash_attention(q, k, v, min_seq=0, dropout_rate=0.3,
                             dropout_seed=seed)
    ref = fa._dense_path(q, k, v, False, None, 0.3, seed)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_flash_dropout_grads_match_dense_same_mask(causal):
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 32, 2, 8).astype('float32'))
    k = jnp.asarray(rng.randn(1, 32, 2, 8).astype('float32'))
    v = jnp.asarray(rng.randn(1, 32, 2, 8).astype('float32'))
    seed = jnp.uint32(77)

    def f_loss(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal, min_seq=0,
                               dropout_rate=0.25, dropout_seed=seed)
        return jnp.sum(o ** 2)

    def r_loss(q, k, v):
        o = fa._dense_path(q, k, v, causal, None, 0.25, seed)
        return jnp.sum(o ** 2)

    gf = jax.grad(f_loss, (0, 1, 2))(q, k, v)
    gr = jax.grad(r_loss, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_dropout_key_bias_grad_matches_dense():
    """dbias under dropout: the key-bias gradient rides ds_raw, which
    now carries the dropout-masked dp term."""
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 32, 2, 8).astype('float32'))
    k = jnp.asarray(rng.randn(2, 32, 2, 8).astype('float32'))
    v = jnp.asarray(rng.randn(2, 32, 2, 8).astype('float32'))
    bias = jnp.asarray(rng.randn(2, 32).astype('float32'))
    seed = jnp.uint32(99)

    def f_loss(bias):
        o = fa.flash_attention(q, k, v, key_bias=bias, min_seq=0,
                               dropout_rate=0.2, dropout_seed=seed)
        return jnp.sum(o ** 2)

    def r_loss(bias):
        d = q.shape[-1]
        s = jnp.einsum('bthd,bshd->bhts', q, k) / (d ** 0.5)
        s = s + bias[:, None, None, :]
        p = jax.nn.softmax(s, axis=-1)
        b, t, h, _ = q.shape
        # per-element head index array: matches the kernels' scalar
        # program_id per grid instance
        g = (jax.lax.broadcasted_iota(jnp.int32, (b, h, t, t), 0) * h +
             jax.lax.broadcasted_iota(jnp.int32, (b, h, t, t), 1))
        qp = jax.lax.broadcasted_iota(jnp.int32, (b, h, t, t), 2)
        kp = jax.lax.broadcasted_iota(jnp.int32, (b, h, t, t), 3)
        keep = fa._dropout_keep(seed, g, qp, kp, fa._keep_threshold(0.2))
        p = jnp.where(keep, p / 0.8, 0.0)
        o = jnp.einsum('bhts,bshd->bthd', p, v)
        return jnp.sum(o ** 2)

    gf = jax.grad(f_loss)(bias)
    gr = jax.grad(r_loss)(bias)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               atol=2e-4, rtol=2e-4)


def test_flash_dropout_deterministic_and_seed_sensitive():
    from paddle_tpu.ops.pallas import flash_attention as fa
    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(1, 64, 2, 8).astype('float32'))
    o1 = fa.flash_attention(q, q, q, min_seq=0, dropout_rate=0.5,
                            dropout_seed=jnp.uint32(42))
    o2 = fa.flash_attention(q, q, q, min_seq=0, dropout_rate=0.5,
                            dropout_seed=jnp.uint32(42))
    o3 = fa.flash_attention(q, q, q, min_seq=0, dropout_rate=0.5,
                            dropout_seed=jnp.uint32(43))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    # expectation stays the undropped attention (upscale_in_train):
    # the across-seed mean converges to the dropout-free output — a
    # statistical check, so the tolerance is generous (64 seeds,
    # per-element sampling std ~ o/sqrt(64))
    o0 = fa.flash_attention(q, q, q, min_seq=0)
    outs = [fa.flash_attention(q, q, q, min_seq=0, dropout_rate=0.5,
                               dropout_seed=jnp.uint32(s))
            for s in range(64)]
    mean = np.mean([np.asarray(o) for o in outs], axis=0)
    err = np.abs(mean - np.asarray(o0))
    assert np.mean(err) < 0.08, np.mean(err)
    assert np.max(err) < 0.6, np.max(err)


def test_bert_trains_with_attn_dropout_on_flash_path():
    """Reference-default config (attn dropout 0.1) takes the flash path
    and per-op vs whole-program backward produce IDENTICAL losses (the
    counter-hash mask regenerates bit-for-bit in any replay)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.flags import get_flag, set_flags
    from paddle_tpu import models

    def run(wpg):
        cfg = models.bert.BertConfig(
            vocab_size=500, hidden=32, layers=2, heads=2,
            intermediate=64, max_pos=64, dropout=0.1,
            attn_dropout=0.1, use_flash=True)
        cfg.flash_min_len = 16  # force flash at this tiny seq
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 77
        with fluid.program_guard(main, startup):
            feeds, enc, loss = models.bert.build_pretrain(cfg, 16)
            fluid.optimizer.SGD(0.1).minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert 'fused_multihead_attention' in types
        for op in main.global_block().ops:
            if op.type == 'fused_multihead_attention':
                assert op.attrs['dropout_rate'] == 0.1
        rng = np.random.RandomState(0)
        batch = models.bert.synthetic_batch(cfg, 4, 16, rng)
        old = get_flag('FLAGS_whole_program_grad')
        set_flags({'FLAGS_whole_program_grad': wpg})
        try:
            with fluid.scope_guard(fluid.Scope()):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                out = [exe.run(main, feed=batch, fetch_list=[loss])[0]
                       for _ in range(3)]
        finally:
            set_flags({'FLAGS_whole_program_grad': old})
        return [float(np.asarray(l).ravel()[0]) for l in out]

    wpg, per_op = run(True), run(False)
    assert all(np.isfinite(wpg))
    np.testing.assert_allclose(wpg, per_op, rtol=2e-5)
