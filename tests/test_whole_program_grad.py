"""FLAGS_whole_program_grad: eligible train segments lower as forward
ops + ONE jax.vjp over the whole forward region instead of per-op
synthesized grad replay (executor._wpg_partition).  Parity: the same
program must train to the same losses with the flag on and off —
including under AMP dynamic loss scaling (the vjp seed rides the
scaled-loss fill) and with dropout (RNG keyed on (op_seed, step) makes
replay and whole-trace masks identical)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _train(wpg, amp, dropout, steps=6):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, 16, act='relu')
        if dropout:
            h = layers.dropout(h, 0.3,
                               dropout_implementation='upscale_in_train')
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.optimizer.Adam(0.02)
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(
                opt, use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    rng = np.random.RandomState(4)
    w = rng.randn(8, 1).astype('float32')
    feeds = []
    for _ in range(steps):
        xb = rng.randn(32, 8).astype('float32')
        feeds.append({'x': xb, 'y': (xb @ w).astype('float32')})
    fluid.set_flags({'FLAGS_whole_program_grad': wpg})
    try:
        losses = []
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for fd in feeds:
                l, = exe.run(main, feed=fd, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
    finally:
        fluid.set_flags({'FLAGS_whole_program_grad':
                         fluid.flags._DEFAULTS[
                             'FLAGS_whole_program_grad']})
    return losses


@pytest.mark.parametrize('amp,dropout', [(False, False), (False, True),
                                         (True, False), (True, True)])
def test_wpg_loss_parity(amp, dropout):
    a = _train(False, amp, dropout)
    b = _train(True, amp, dropout)
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5,
                               err_msg='amp=%s dropout=%s' % (amp,
                                                              dropout))


def test_wpg_partition_shape():
    """The partition recognizes the standard train segment and routes
    every optimizer-consumed gradient to a boundary primal."""
    from paddle_tpu.fluid.executor import _Segment, _wpg_partition
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, 1), y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        plan = exe._build_plan(main, ('x', 'y'), (loss.name,))
    segs = [it for it in plan if isinstance(it, _Segment)]
    assert len(segs) == 1
    part = _wpg_partition(segs[0])
    assert part is not None
    assert [v for _, _, v in part['seeds']] == [1.0]
    assert all(p in segs[0].state_names or p in segs[0].input_names
               for p, _ in part['grad_to_primal'].values())
    # param grads are among the routed gradients
    gnames = set(part['grad_to_primal'])
    assert any('w_0' in g for g in gnames), gnames


def test_wpg_stop_gradient_parity():
    """stop_gradient on an intermediate of a value-dependent loss path:
    the vjp must treat it as a constant exactly like append_backward's
    pruning does (write-time lax.stop_gradient pin)."""
    def train(wpg, steps=4):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 13
        with fluid.program_guard(main, startup):
            x = fluid.layers.data('x', shape=[6], dtype='float32')
            y = fluid.layers.data('y', shape=[1], dtype='float32')
            h = layers.fc(x, 8, act='tanh')
            frozen = layers.scale(h, scale=2.0)
            frozen.stop_gradient = True       # detach()-style branch
            pred = layers.fc(layers.elementwise_add(h, frozen), 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(0.05).minimize(loss)
        rng = np.random.RandomState(8)
        feeds = [{'x': rng.randn(16, 6).astype('float32'),
                  'y': rng.randn(16, 1).astype('float32')}
                 for _ in range(steps)]
        fluid.set_flags({'FLAGS_whole_program_grad': wpg})
        try:
            out = []
            with fluid.scope_guard(fluid.Scope()):
                exe = fluid.Executor(fluid.XLAPlace(0))
                exe.run(startup)
                for fd in feeds:
                    l, = exe.run(main, feed=fd, fetch_list=[loss])
                    out.append(float(np.asarray(l).ravel()[0]))
        finally:
            fluid.set_flags({'FLAGS_whole_program_grad':
                             fluid.flags._DEFAULTS[
                                 'FLAGS_whole_program_grad']})
        return out

    np.testing.assert_allclose(train(False), train(True),
                               rtol=2e-4, atol=2e-5)


def test_wpg_host_op_split_falls_back():
    """A host op between forward and backward: since round 5
    read-only host ops DEFER past the device ops they don't depend on
    (executor._defer_readonly_host_ops), so the segment stays fused
    and wpg-eligible; training must work either way."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='float32')
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, 1), y))
        layers.Print(loss, message='wpg-split')
        fluid.optimizer.SGD(0.1).minimize(loss)
    rng = np.random.RandomState(2)
    fd = {'x': rng.randn(8, 4).astype('float32'),
          'y': rng.randn(8, 1).astype('float32')}
    fluid.set_flags({'FLAGS_whole_program_grad': True})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            l1, = exe.run(main, feed=fd, fetch_list=[loss])
            l2, = exe.run(main, feed=fd, fetch_list=[loss])
        assert float(np.asarray(l2).ravel()[0]) < \
            float(np.asarray(l1).ravel()[0])
    finally:
        fluid.set_flags({'FLAGS_whole_program_grad':
                         fluid.flags._DEFAULTS[
                             'FLAGS_whole_program_grad']})
