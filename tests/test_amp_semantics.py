"""Dynamic loss-scaling semantics of the AMP decorator.

Reference behavior (contrib/mixed_precision/decorator.py:27 +
operators/amp/update_loss_scaling_op.cc, check_finite_and_unscale_op.cc):
an overflowing step SKIPS the parameter update and decays the loss
scale after decr_every_n_nan_or_inf bad steps; incr_every_n_steps
consecutive good steps grow it by incr_ratio; master weights stay f32.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _build(incr_every=3, decr_every=1, init_scale=2.0 ** 10):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(0.01),
            init_loss_scaling=init_scale,
            use_dynamic_loss_scaling=True,
            incr_every_n_steps=incr_every,
            decr_every_n_nan_or_inf=decr_every,
            incr_ratio=2.0, decr_ratio=0.5)
        opt.minimize(loss)
        scale_var = opt.get_loss_scaling()
    return main, startup, loss, scale_var


def test_overflow_skips_update_and_decays_scale():
    main, startup, loss, scale_var = _build()
    rng = np.random.RandomState(0)
    xb = rng.randn(16, 4).astype('float32')
    yb = rng.randn(16, 1).astype('float32')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        pname = main.all_parameters()[0].name
        # one healthy step: params move, scale unchanged (incr_every=3)
        p0 = np.asarray(scope.find_var(pname)).copy()
        exe.run(main, feed={'x': xb, 'y': yb}, fetch_list=[])
        p1 = np.asarray(scope.find_var(pname)).copy()
        s1 = float(np.asarray(scope.find_var(scale_var.name)).ravel()[0])
        assert not np.allclose(p0, p1)
        assert s1 == 2.0 ** 10
        # overflow step: huge feed makes grads non-finite at this scale
        exe.run(main, feed={'x': xb * 1e30, 'y': yb},
                fetch_list=[])
        p2 = np.asarray(scope.find_var(pname)).copy()
        s2 = float(np.asarray(scope.find_var(scale_var.name)).ravel()[0])
        np.testing.assert_allclose(p2, p1, rtol=0,
                                   err_msg='overflow step must skip '
                                           'the parameter update')
        assert s2 == 2.0 ** 9, s2  # decayed by decr_ratio after 1 bad
        # params stay f32 master copies
        assert np.asarray(scope.find_var(pname)).dtype == np.float32


def test_scale_grows_after_n_good_steps():
    main, startup, loss, scale_var = _build(incr_every=3)
    rng = np.random.RandomState(1)
    xb = rng.randn(16, 4).astype('float32')
    yb = rng.randn(16, 1).astype('float32')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        scales = []
        for _ in range(7):
            exe.run(main, feed={'x': xb, 'y': yb}, fetch_list=[])
            scales.append(float(np.asarray(
                scope.find_var(scale_var.name)).ravel()[0]))
    # after every 3 consecutive good steps the scale doubles
    assert scales[2] == 2.0 ** 11, scales
    assert scales[5] == 2.0 ** 12, scales
    assert scales[0] == scales[1] == 2.0 ** 10, scales


def test_amp_training_converges_with_bf16_compute():
    """bf16 MXU compute + f32 masters trains to the same answer as
    full-f32 within loose tolerance."""
    def train(amp):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[8], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            pred = layers.fc(layers.fc(x, 16, act='relu'), 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = fluid.optimizer.SGD(0.05)
            if amp:
                opt = fluid.contrib.mixed_precision.decorate(
                    opt, use_dynamic_loss_scaling=True)
            opt.minimize(loss)
        rng = np.random.RandomState(3)
        w = rng.randn(8, 1).astype('float32')
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for i in range(80):
                xb = rng.randn(32, 8).astype('float32')
                l, = exe.run(main, feed={'x': xb, 'y': xb @ w},
                             fetch_list=[loss])
        return float(np.asarray(l).ravel()[0])

    ref = train(False)
    amp = train(True)
    # bf16 mantissa (8 bits) slows the tail slightly; the loss must
    # still be near-converged and track the f32 run
    assert amp < 0.25, amp
    assert abs(amp - ref) < 0.15, (amp, ref)


def test_gray_ops_follow_bf16_not_promote_f32():
    """The fp16_utils follow rule: a gray op (bias add) fed a bf16
    white-op output and an f32 master param casts the PARAM down, so
    the activation stream stays bf16 — jnp promotion casting the whole
    downstream f32 (double HBM traffic; round-4 BERT-long root cause)
    is the bug this pins.  Master params and their gradients stay f32."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, 16, act='relu')     # mul + add + relu
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(0.01), use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    add_outs = [op.output('Out')[0] for op in main.global_block().ops
                if op.type == 'elementwise_add'
                and op.attrs.get('__amp_gray__')]
    assert add_outs, 'no gray-marked bias adds found'
    rng = np.random.RandomState(0)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        fetched = exe.run(main, feed={
            'x': rng.randn(4, 8).astype('float32'),
            'y': rng.randn(4, 1).astype('float32')},
            fetch_list=[add_outs[0], loss], return_numpy=False)
        import jax.numpy as jnp
        # the bias-add OUTPUT rides bf16 (the follow rule)
        assert fetched[0].dtype == jnp.bfloat16, fetched[0].dtype
        # master weights stay f32 in the scope
        import paddle_tpu.fluid.core as core
        params = [v.name for v in main.global_block().all_parameters()]
        assert params, 'no parameters found'
        for p in params:
            v = core.as_array(scope.find_var(p))
            assert v.dtype == jnp.float32, (p, v.dtype)


def test_amp_while_loop_carry_dtype_stable():
    """A while loop whose body runs AMP-marked matmuls must keep its
    f32 carry dtype across iterations (lax.while_loop rejects carry
    aval changes): the executor pins body outputs to the entry dtype,
    the same boundary where the reference would re-insert cast ops."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4, 4], dtype='float32',
                        append_batch_size=False)
        w = layers.create_parameter([4, 4], 'float32', name='loop_w')
        i = layers.fill_constant([1], 'int64', 0)
        n = layers.fill_constant([1], 'int64', 3)
        cond = layers.less_than(i, n)
        wl = layers.While(cond)
        with wl.block():
            nx = layers.matmul(x, w)
            layers.assign(nx, x)
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
        out = layers.reduce_sum(x)
        # mark the program the way decorate() would
        from paddle_tpu.fluid.contrib.mixed_precision.decorator import \
            _mark_amp_ops
        from paddle_tpu.fluid.contrib.mixed_precision.fp16_lists import \
            AutoMixedPrecisionLists
        _mark_amp_ops(main, AutoMixedPrecisionLists())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        val, = exe.run(main, feed={'x': np.eye(4, dtype='float32')},
                       fetch_list=[out])
    assert np.isfinite(np.asarray(val)).all()


def test_amp_loss_output_is_f32():
    """Reference AMP black-list rule: f32 Loss even from bf16 logits
    (ADVICE r4) — fetched losses keep f32 precision while the
    activation-sized Softmax stays low-precision."""
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        y = fluid.layers.data('y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 16, act='relu')
        logits = fluid.layers.fc(h, 4)
        loss_v = fluid.layers.softmax_with_cross_entropy(logits, y)
        loss = fluid.layers.mean(loss_v)
        opt = fluid.contrib.mixed_precision.decorate(
            fluid.optimizer.SGD(0.1), use_dynamic_loss_scaling=False)
        opt.minimize(loss)
    swce = [op for op in main.global_block().ops
            if op.type == 'softmax_with_cross_entropy']
    assert swce and swce[0].attrs.get('__amp_black_out__')
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(4, 8).astype('float32'),
            'y': rng.randint(0, 4, (4, 1)).astype('int64')}
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        out, = exe.run(main, feed=feed, fetch_list=[loss_v],
                       return_numpy=False)
    import jax.numpy as jnp
    assert jnp.asarray(out).dtype == jnp.float32


def test_mul_mixed_dtype_promotes():
    """mul with AMP off and mixed operand dtypes promotes like jnp
    instead of erroring in dot_general (ADVICE r4)."""
    from paddle_tpu.ops import registry
    import jax.numpy as jnp
    x = jnp.ones((2, 3), jnp.bfloat16)
    w = jnp.ones((3, 4), jnp.float32)
    out = registry.get('mul').fn(
        registry.LowerCtx(0), {'X': [x], 'Y': [w]},
        {'x_num_col_dims': 1, 'y_num_col_dims': 1})['Out'][0]
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.full((2, 4), 3.0))
