"""Subprocess worker for the job-wide observability tests/gates
(tools/check_comms.py, tests/test_comms.py, bench.py --parallel):
boots a REAL executor on a GradAllReduce-transpiled program (the
collective runner path — c_allreduce_sum per grad over the 'dp' mesh
of this process's devices), enables the fluid.trace flight recorder,
and serves the status plane on the port given in argv[1] (the parent
sets PADDLE_TRAINER_ID / PADDLE_TPU_STATUS_WORKERS / aggregation env
the way distributed/launch.py would).  Prints READY after the first
step; runs until killed or the argv[2] deadline (seconds).  argv[3]
(optional) is a batch multiplier — a deliberately fatter per-step
workload that makes this worker a REAL straggler (its step wall
grows), for skew-detection runs."""

import os
import sys
import time


def main():
    port = int(sys.argv[1])
    run_for = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    batch_mult = int(sys.argv[3]) if len(sys.argv) > 3 else 1
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, monitor, trace
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    fluid.set_flags({'FLAGS_status_port': port})
    trace.enable()
    rank = os.environ.get('PADDLE_TRAINER_ID', '0')
    monitor.add('comms/test_marker_rank%s' % rank)

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 3
    with fluid.program_guard(main_p, startup):
        x = layers.data('x', shape=[32], dtype='float32')
        h = layers.fc(x, 32, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    GradAllReduce().transpile(startup, main_p, 0, ['127.0.0.1:0'],
                              '127.0.0.1:0')

    exe = fluid.Executor(fluid.XLAPlace(0))  # starts the status server
    exe.run(startup)
    feed = {'x': np.ones((8 * batch_mult, 32), 'float32')}
    exe.run(main_p, feed=feed, fetch_list=[loss])
    print('READY', flush=True)
    deadline = time.time() + run_for
    while time.time() < deadline:
        exe.run(main_p, feed=feed, fetch_list=[loss])
        time.sleep(0.02)


if __name__ == '__main__':
    main()
