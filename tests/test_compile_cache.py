"""AOT compile plane: content-addressed segment fingerprints, the
persistent on-disk executable store, background warmup, and the LRU
caps on the in-memory caches (fluid/compile_cache.py + executor.py)."""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import (compile_cache, layers, monitor,
                              unique_name)
from paddle_tpu.fluid import executor as executor_mod


@pytest.fixture
def plane_dir(tmp_path):
    """A fresh cache dir + a fresh plane, restored afterwards so the
    rest of the suite keeps the plane-off fast path."""
    d = str(tmp_path / 'ccache')
    compile_cache.reset_plane()
    fluid.set_flags({'FLAGS_compile_cache_dir': d})
    try:
        yield d
    finally:
        fluid.set_flags({'FLAGS_compile_cache_dir': ''})
        compile_cache.reset_plane()
        import jax
        try:
            jax.config.update('jax_compilation_cache_dir', None)
        except Exception:
            pass


def _prog(seed, width=4):
    """Identical programs on demand: unique_name.guard() resets the
    process-global name counters, so a rebuild names its vars exactly
    like a fresh process would — the executable interface (pytree
    keys) matches and fingerprints collide on purpose."""
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            x = layers.data('x', shape=[8], dtype='float32')
            h = layers.fc(x, width, act='relu')
            loss = layers.reduce_mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _xs(n=4):
    return np.random.RandomState(0).randn(n, 8).astype('float32')


def _run_steps(main, startup, loss, xs, steps=3):
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        return [np.asarray(exe.run(main, feed={'x': xs},
                                   fetch_list=[loss])[0])
                for _ in range(steps)]


def _seg_entries(d):
    return sorted(os.listdir(os.path.join(d, 'segments')))


def test_disk_roundtrip_second_process_zero_retraces(plane_dir):
    """Process 1 populates the store; 'process 2' (fresh plane, fresh
    name scope — the in-process stand-in two real subprocesses exercise
    in tools/check_compile_cache.py) must run entirely from disk: hits
    > 0, zero retraces, bit-identical trajectory."""
    xs = _xs()
    ref = _run_steps(*_prog(101), xs=xs)
    entries = _seg_entries(plane_dir)
    assert entries, 'first process wrote no cache entries'
    assert monitor.counter_value('executor/aot_compiles') > 0

    compile_cache.reset_plane()
    lower0 = monitor.counter_value('executor/segments_lowered')
    hit0 = monitor.counter_value('executor/compile_cache_disk_hit')
    got = _run_steps(*_prog(101), xs=xs)
    assert monitor.counter_value(
        'executor/compile_cache_disk_hit') - hit0 >= len(entries)
    assert monitor.counter_value(
        'executor/segments_lowered') - lower0 == 0
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_identical_program_shares_executable_in_memory(plane_dir):
    """Two content-identical programs in ONE process share the
    executable through the fingerprint map — no second compile."""
    xs = _xs()
    ref = _run_steps(*_prog(102), xs=xs)
    aot0 = monitor.counter_value('executor/aot_compiles')
    mem0 = monitor.counter_value('executor/compile_cache_memory_hit')
    got = _run_steps(*_prog(102), xs=xs)
    assert monitor.counter_value('executor/aot_compiles') == aot0
    assert monitor.counter_value(
        'executor/compile_cache_memory_hit') > mem0
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)


def test_fingerprint_invalidation_axes():
    """The fingerprint must move when anything that changes the
    lowering moves: flags, boundary shapes, dtypes, jax version —
    and must NOT move on volatile attrs (op callstacks)."""
    main, startup, loss = _prog(103)
    exe = fluid.Executor(fluid.XLAPlace(0))
    plan = exe._get_plan(main, ('x',), (loss.name,))
    seg = [it for it in plan
           if isinstance(it, executor_mod._Segment)][0]
    specs = ((('x', (4, 8), '<f4'),), ())
    base_flags = executor_mod._lowering_flag_items(False, True)

    def fp(specs=specs, flags=base_flags, donate=True, purpose='aot'):
        return compile_cache.fingerprint(seg.ops, specs, flags,
                                         donate=donate, purpose=purpose)

    base = fp()
    assert base == fp()  # deterministic
    # flags that change lowering: prefer_test / whole_program_grad /
    # auto layout / conv precision
    assert fp(flags=executor_mod._lowering_flag_items(True, True)) \
        != base
    assert fp(flags=executor_mod._lowering_flag_items(False, False)) \
        != base
    assert fp(flags=executor_mod._lowering_flag_items(
        False, True, auto=True)) != base
    # boundary shape / dtype
    assert fp(specs=((('x', (8, 8), '<f4'),), ())) != base
    assert fp(specs=((('x', (4, 8), '<f2'),), ())) != base
    # donation + executable family
    assert fp(donate=False) != base
    assert fp(purpose='jit') != base
    # volatile attrs must NOT move it
    saved = seg.ops[0].attrs.get('__op_callstack__')
    seg.ops[0].attrs['__op_callstack__'] = ['somewhere:1 (else)']
    try:
        assert fp() == base
    finally:
        seg.ops[0].attrs['__op_callstack__'] = saved
    # op content MUST move it
    seg.ops[0].attrs['__fp_probe__'] = 1
    try:
        assert fp() != base
    finally:
        del seg.ops[0].attrs['__fp_probe__']


def test_fingerprint_keys_on_jax_version(monkeypatch):
    main, startup, loss = _prog(104)
    exe = fluid.Executor(fluid.XLAPlace(0))
    plan = exe._get_plan(main, ('x',), (loss.name,))
    seg = [it for it in plan
           if isinstance(it, executor_mod._Segment)][0]
    flags = executor_mod._lowering_flag_items(False, True)
    base = compile_cache.fingerprint(seg.ops, (), flags)
    real = compile_cache._env_key()
    monkeypatch.setattr(compile_cache, '_env_key',
                        lambda: real[:1] + ('99.99.99',) + real[2:])
    assert compile_cache.fingerprint(seg.ops, (), flags) != base


def test_corrupted_entry_recompiles_never_crashes(plane_dir):
    xs = _xs()
    ref = _run_steps(*_prog(105), xs=xs)
    seg_dir = os.path.join(plane_dir, 'segments')
    entries = _seg_entries(plane_dir)
    assert entries
    # truncate one entry, fill another (or the same) with garbage
    with open(os.path.join(seg_dir, entries[0]), 'r+b') as f:
        f.truncate(16)
    with open(os.path.join(seg_dir, entries[-1]), 'wb') as f:
        f.write(b'not a cache entry at all')
    compile_cache.reset_plane()
    corrupt0 = monitor.counter_value('executor/compile_cache_corrupt')
    got = _run_steps(*_prog(105), xs=xs)
    assert monitor.counter_value(
        'executor/compile_cache_corrupt') > corrupt0
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)
    # a further restart is clean: the poisoned entries were either
    # rewritten (verified round-trippable) or unlinked — they are
    # never served corrupt twice
    compile_cache.reset_plane()
    c1 = monitor.counter_value('executor/compile_cache_corrupt')
    got2 = _run_steps(*_prog(105), xs=xs)
    assert monitor.counter_value(
        'executor/compile_cache_corrupt') == c1
    for r, g in zip(ref, got2):
        np.testing.assert_array_equal(r, g)


def test_flag_toggle_compiles_fresh_executable(plane_dir):
    """Toggling a lowering-changing flag after the first compile must
    land on a DIFFERENT cache entry (the silent-stale-executable
    failure mode), and both settings must keep working."""
    xs = _xs()
    _run_steps(*_prog(106), xs=xs)
    n_entries = len(_seg_entries(plane_dir))
    prev = fluid.flags.get_flag('FLAGS_whole_program_grad')
    fluid.set_flags({'FLAGS_whole_program_grad': not prev})
    try:
        got = _run_steps(*_prog(106), xs=xs)
        assert np.isfinite(np.asarray(got)).all()
        assert len(_seg_entries(plane_dir)) > n_entries
    finally:
        fluid.set_flags({'FLAGS_whole_program_grad': prev})


def test_shape_change_compiles_fresh_executable(plane_dir):
    xs4, xs6 = _xs(4), _xs(6)
    main, startup, loss = _prog(107)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': xs4}, fetch_list=[loss])
        n4 = len(_seg_entries(plane_dir))
        out, = exe.run(main, feed={'x': xs6}, fetch_list=[loss])
        assert np.isfinite(np.asarray(out)).all()
        assert len(_seg_entries(plane_dir)) > n4


def test_warmup_matches_lazy_bit_for_bit(plane_dir):
    xs = _xs()
    # lazy path, fresh dir half A: plane is ACTIVE here too (dir set),
    # so this also proves warmup-compiled executables == run-compiled
    ref = _run_steps(*_prog(108), xs=xs)
    compile_cache.reset_plane()
    fluid.set_flags({'FLAGS_compile_cache_dir':
                     plane_dir + '_warmed'})
    main, startup, loss = _prog(108)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        res = exe.warmup(main, feed_shapes={'x': ((4, 8), 'float32')},
                         fetch_list=[loss], wait=True)
        assert res.submitted >= 1
        assert res.done()
        got = [np.asarray(exe.run(main, feed={'x': xs},
                                  fetch_list=[loss])[0])
               for _ in range(3)]
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, g)
    assert monitor.histogram_value('executor/warmup_seconds')


def test_warmup_memory_only_without_dir():
    """warmup() without a cache dir still primes the process (memory
    plane): the first run's segments come from the warmup futures."""
    compile_cache.reset_plane()
    try:
        xs = _xs()
        ref = _run_steps(*_prog(109), xs=xs)  # plane off: legacy path
        compile_cache.reset_plane()
        main, startup, loss = _prog(109)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            aot0 = monitor.counter_value('executor/aot_compiles')
            res = exe.warmup(main, feed_shapes={'x': xs},
                             fetch_list=[loss], wait=True)
            assert res.submitted >= 1
            assert monitor.counter_value(
                'executor/aot_compiles') > aot0
            got = [np.asarray(exe.run(main, feed={'x': xs},
                                      fetch_list=[loss])[0])
                   for _ in range(3)]
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(r, g)
    finally:
        compile_cache.reset_plane()


def test_warmup_skips_host_cut_segments():
    """Segments downstream of a host op (whose outputs only a real
    step can shape) are skipped, not mis-compiled."""
    compile_cache.reset_plane()
    try:
        with unique_name.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = layers.data('x', shape=[4], dtype='float32')
                y = layers.scale(x, scale=2.0)
                mid = main.current_block().create_var(
                    name='wu_mid', shape=[-1, 4], dtype='float32')
                layers.py_func(lambda a: a + 1.0, y, mid)
                z = layers.scale(mid, scale=3.0)
        exe = fluid.Executor(fluid.XLAPlace(0))
        with fluid.scope_guard(fluid.Scope()):
            res = exe.warmup(main,
                             feed_shapes={'x': ((2, 4), 'float32')},
                             fetch_list=[z], wait=True)
            # segment 1 (scale before py_func) compiles; segment 2
            # reads the host op's output -> skipped
            assert res.submitted == 1
            assert res.skipped == 1
            xv = np.ones((2, 4), 'float32')
            got, = exe.run(main, feed={'x': xv}, fetch_list=[z])
            np.testing.assert_allclose(np.asarray(got),
                                       (xv * 2 + 1) * 3, rtol=1e-6)
    finally:
        compile_cache.reset_plane()


def test_segment_cache_lru_eviction(plane_dir):
    """Per-shape AOT entries are LRU-capped: cycling more shapes than
    the cap evicts (counted) and re-running an evicted shape still
    computes correctly (recompile or plane re-load)."""
    prev = fluid.flags.get_flag('FLAGS_segment_cache_capacity')
    fluid.set_flags({'FLAGS_segment_cache_capacity': 2})
    try:
        main, startup, loss = _prog(110)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            ev0 = monitor.counter_value(
                'executor/segment_cache_evictions')
            first = None
            for n in (2, 3, 4, 5):
                out, = exe.run(main, feed={'x': _xs(n)},
                               fetch_list=[loss])
                if first is None:
                    first = np.asarray(out)
            assert monitor.counter_value(
                'executor/segment_cache_evictions') > ev0
            # the evicted first shape still runs and agrees (params
            # moved since, so just require finite + same shape)
            again, = exe.run(main, feed={'x': _xs(2)},
                             fetch_list=[loss])
            assert np.isfinite(np.asarray(again)).all()
    finally:
        fluid.set_flags({'FLAGS_segment_cache_capacity': prev})


def test_plan_cache_lru_eviction():
    prev = fluid.flags.get_flag('FLAGS_plan_cache_capacity')
    fluid.set_flags({'FLAGS_plan_cache_capacity': 2})
    try:
        main, startup, loss = _prog(111)
        xs = _xs()
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            ev0 = monitor.counter_value(
                'executor/plan_cache_evictions')
            # three distinct plan keys under a cap of 2: two fetch
            # sets on this executor + one from a second executor (the
            # key includes the executor identity)
            exe_b = fluid.Executor(fluid.XLAPlace(0))
            exe.run(main, feed={'x': xs}, fetch_list=[loss])
            exe.run(main, feed={'x': xs}, fetch_list=[])
            out, = exe_b.run(main, feed={'x': xs},
                             fetch_list=[loss.name])
            assert monitor.counter_value(
                'executor/plan_cache_evictions') > ev0
            assert len(main._exec_cache) <= 2
            assert np.isfinite(np.asarray(out)).all()
    finally:
        fluid.set_flags({'FLAGS_plan_cache_capacity': prev})


def test_compiled_step_reuses_jit_across_identical_programs():
    """Executor.compile: repeated CALLS never re-trace (jit-backed),
    and a second CompiledStep of a content-identical program reuses
    the first one's jit through the plane (the run/compile shared
    fingerprint surface)."""
    compile_cache.reset_plane()
    try:
        def build():
            with unique_name.guard():
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = startup.random_seed = 3
                with fluid.program_guard(main, startup):
                    x = layers.data('x', shape=[6], dtype='float32')
                    y = layers.fc(x, 3, act='tanh')
            return main, startup, y

        main, startup, y = build()
        exe = fluid.Executor(fluid.XLAPlace(0))
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            step = exe.compile(main, feed_names=('x',),
                               fetch_names=(y.name,))
            # inference program: params are pure INPUTS (nothing is
            # updated in place), so they ride in `data`
            scope = fluid.global_scope()
            data = {n: fluid.core.as_array(scope.find_var(n))
                    for n in step.input_names if n != 'x'}
            data['x'] = np.ones((2, 6), 'float32')
            state = {n: fluid.core.as_array(scope.find_var(n))
                     for n in step.state_names}
            out1 = step(0, state, data)
            out2 = step(1, state, data)
            np.testing.assert_array_equal(np.asarray(out1[y.name]),
                                          np.asarray(out2[y.name]))
        mem0 = monitor.counter_value(
            'executor/compile_cache_memory_hit')
        main2, startup2, y2 = build()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup2)
            step2 = exe.compile(main2, feed_names=('x',),
                                fetch_names=(y2.name,))
            assert step2._jitted is step._jitted
        assert monitor.counter_value(
            'executor/compile_cache_memory_hit') > mem0
    finally:
        compile_cache.reset_plane()


def test_compiled_step_composes_under_jit():
    """Under an outer trace the CompiledStep must fall back to the raw
    traceable fn (no nested-jit recompilation surprises, grads flow)."""
    import jax
    import jax.numpy as jnp
    compile_cache.reset_plane()
    try:
        with unique_name.guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 5
            with fluid.program_guard(main, startup):
                x = layers.data('x', shape=[4], dtype='float32')
                y = layers.fc(x, 2)
        exe = fluid.Executor(fluid.XLAPlace(0))
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            step = exe.compile(main, feed_names=('x',),
                               fetch_names=(y.name,))
            scope = fluid.global_scope()
            params = {n: np.asarray(fluid.core.as_array(
                scope.find_var(n)))
                for n in step.input_names if n != 'x'}
            xv = np.ones((2, 4), 'float32')

            def call(p):
                d = dict(p)
                d['x'] = xv
                return step(0, {}, d)[y.name]

            eager = call(params)

            def f(p):
                return jnp.sum(call(p))

            g = jax.grad(f)(params)
            assert set(g) == set(params)
            jitted_out = jax.jit(call)(params)
            np.testing.assert_allclose(np.asarray(jitted_out),
                                       np.asarray(eager), rtol=1e-6)
    finally:
        compile_cache.reset_plane()


def test_lru_cache_semantics():
    ev_key = 'test/lru_evictions_%d' % os.getpid()
    c = compile_cache.LRUCache(2, ev_key)
    c['a'] = 1
    c['b'] = 2
    assert c.get('a') == 1          # refresh: 'a' becomes MRU
    c['c'] = 3                      # evicts 'b'
    assert 'b' not in c and 'a' in c and 'c' in c
    assert monitor.counter_value(ev_key) == 1
    assert sorted(c.keys()) == ['a', 'c']
    assert len(c) == 2
    c.clear()
    assert len(c) == 0
    unbounded = compile_cache.LRUCache(0)
    for i in range(100):
        unbounded[i] = i
    assert len(unbounded) == 100


def test_fetch_set_keys_executable_identity(plane_dir):
    """The check_grad two-fetch pattern with the plane ACTIVE: the
    same program planned for the analytic-grad fetch set and then for
    the loss fetch set shares its op list between the two segments,
    but each exports DIFFERENT vars.  The fingerprint folds the
    segment's output_names in, so the second plan compiles its own
    executable instead of taking a content-addressed hit on the
    first's (which returns the wrong vars — 'fetch var not
    produced')."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from op_test import OpTest
    ot = OpTest()
    ot.grad_atol = ot.grad_rtol = 2e-2
    ot.check_grad(
        'sum',
        {'X': [('x0', np.random.RandomState(7).rand(3, 4)
                .astype('float32')),
               ('x1', np.random.RandomState(8).rand(3, 4)
                .astype('float32'))]},
        attrs={}, out_slot='Out')
    # and the distinct executables both landed in the store
    assert len(_seg_entries(plane_dir)) >= 2
