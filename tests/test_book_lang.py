"""Book-model integration tests (reference tests/book/):
label_semantic_roles (CRF), machine_translation / rnn_encoder_decoder
(seq2seq + beam search), recommender_system (cos_sim).  Together with
test_book.py, test_models.py and test_rnn.py this covers all 9 reference
book models with loss-decrease assertions."""

import numpy as np

import paddle_tpu.fluid as fluid


def _run_train(main, startup, loss, batch_fn, steps=25):
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(steps):
            l, = exe.run(main, feed=batch_fn(), fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    return losses


def test_label_semantic_roles_crf():
    """Embedding -> lstm -> emission fc -> linear_chain_crf, then
    crf_decoding + chunk_eval on the eval clone (book ch. 7)."""
    vocab, emb_dim, hid, n_tags, t = 60, 16, 16, 5, 12
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        words = fluid.layers.data('words', shape=[t], dtype='int64')
        target = fluid.layers.data('target', shape=[t], dtype='int64')
        length = fluid.layers.data('length', shape=[1], dtype='int64')
        mask = fluid.layers.data('mask', shape=[t], dtype='float32')
        emb = fluid.layers.embedding(words, size=[vocab, emb_dim])
        proj = fluid.layers.fc(emb, size=4 * hid, num_flatten_dims=2)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=4 * hid,
                                              mask=mask)
        emission = fluid.layers.fc(hidden, size=n_tags,
                                   num_flatten_dims=2)
        crf_attr = fluid.ParamAttr(name='crfw')
        crf_cost = fluid.layers.linear_chain_crf(
            emission, target, param_attr=crf_attr, length=length)
        loss = fluid.layers.mean(crf_cost)
        fluid.optimizer.Adam(5e-3).minimize(loss)
        decoded = fluid.layers.crf_decoding(emission, crf_attr,
                                            length=length)

    rng = np.random.RandomState(0)

    def batch(n=16):
        w = rng.randint(0, vocab, (n, t)).astype('int64')
        lens = rng.randint(3, t + 1, n)
        m = (np.arange(t)[None] < lens[:, None]).astype('float32')
        # learnable mapping: tag = word % n_tags
        tags = (w % n_tags).astype('int64')
        return {'words': w, 'target': tags,
                'length': lens[:, None].astype('int64'), 'mask': m}

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for _ in range(40):
            l, = exe.run(main, feed=batch(), fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
        # decode path on a fresh batch and sanity-check tag range
        b = batch(4)
        d, = exe.run(main, feed=b, fetch_list=[decoded])
        d = np.asarray(d)
        assert d.shape == (4, t)
        assert (d >= 0).all() and (d < n_tags).all()


def test_machine_translation_seq2seq_beam_decode():
    """GRU encoder -> GRU decoder w/ teacher forcing (book ch. 8), then
    step-by-step beam-search decode with layers.beam_search +
    gather_tree."""
    src_vocab, tgt_vocab, emb_dim, hid, ts, tt = 40, 30, 16, 16, 8, 6
    beam, end_id = 3, 1
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 12
    with fluid.program_guard(main, startup):
        src = fluid.layers.data('src', shape=[ts], dtype='int64')
        tgt_in = fluid.layers.data('tgt_in', shape=[tt], dtype='int64')
        tgt_out = fluid.layers.data('tgt_out', shape=[tt], dtype='int64')
        semb = fluid.layers.embedding(src, size=[src_vocab, emb_dim],
                                      param_attr=fluid.ParamAttr('semb'))
        sproj = fluid.layers.fc(semb, size=3 * hid, num_flatten_dims=2)
        enc = fluid.layers.dynamic_gru(sproj, size=hid)
        enc_last = fluid.layers.sequence_pool(enc, 'last')
        temb = fluid.layers.embedding(tgt_in, size=[tgt_vocab, emb_dim],
                                      param_attr=fluid.ParamAttr('temb'))
        tproj = fluid.layers.fc(temb, size=3 * hid, num_flatten_dims=2,
                                param_attr=fluid.ParamAttr('tproj_w'),
                                bias_attr=fluid.ParamAttr('tproj_b'))
        dec = fluid.layers.dynamic_gru(tproj, size=hid, h_0=enc_last,
                                       param_attr=fluid.ParamAttr('dgru'),
                                       bias_attr=fluid.ParamAttr('dgru_b'))
        logits = fluid.layers.fc(dec, size=tgt_vocab, num_flatten_dims=2,
                                 param_attr=fluid.ParamAttr('out_w'),
                                 bias_attr=fluid.ParamAttr('out_b'))
        probs = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(
            fluid.layers.reshape(probs, [-1, tgt_vocab]),
            fluid.layers.reshape(tgt_out, [-1, 1])))
        fluid.optimizer.Adam(1e-2).minimize(loss)

    rng = np.random.RandomState(1)

    def batch(n=16):
        s = rng.randint(2, src_vocab, (n, ts)).astype('int64')
        # toy task: t[0] = s[0] % V, t[k] = (t[k-1] + 3) % V — learnable
        # from teacher-forcing input + encoder state
        t_full = np.zeros((n, tt), 'int64')
        t_full[:, 0] = s[:, 0] % tgt_vocab
        for k in range(1, tt):
            t_full[:, k] = (t_full[:, k - 1] + 3) % tgt_vocab
        t_in = np.concatenate(
            [np.zeros((n, 1), 'int64'), t_full[:, :-1]], 1)
        return {'src': s, 'tgt_in': t_in, 'tgt_out': t_full}

    losses = _run_train(main, startup, loss, batch, steps=40)
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

    # ---- step-by-step beam decode program (single decode step) ----
    step_prog, step_startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(step_prog, step_startup):
        pre_ids = fluid.layers.data('pre_ids', shape=[beam], dtype='int64')
        pre_scores = fluid.layers.data('pre_scores', shape=[beam],
                                       dtype='float32')
        h_in = fluid.layers.data('h_in', shape=[beam, hid],
                                 dtype='float32')
        temb2 = fluid.layers.embedding(
            pre_ids, size=[tgt_vocab, emb_dim],
            param_attr=fluid.ParamAttr('temb'))            # share weights
        flat = fluid.layers.reshape(temb2, [-1, emb_dim])
        tproj2 = fluid.layers.fc(flat, size=3 * hid,
                                 param_attr=fluid.ParamAttr('tproj_w'),
                                 bias_attr=fluid.ParamAttr('tproj_b'))
        seq = fluid.layers.reshape(tproj2, [-1, 1, 3 * hid])
        h_flat = fluid.layers.reshape(h_in, [-1, hid])
        dec2 = fluid.layers.dynamic_gru(
            seq, size=hid, h_0=h_flat,
            param_attr=fluid.ParamAttr('dgru'),
            bias_attr=fluid.ParamAttr('dgru_b'))
        h_new = fluid.layers.reshape(dec2, [-1, beam, hid])
        logits2 = fluid.layers.fc(
            fluid.layers.reshape(dec2, [-1, hid]), size=tgt_vocab,
            param_attr=fluid.ParamAttr('out_w'),
            bias_attr=fluid.ParamAttr('out_b'))
        logp = fluid.layers.log_softmax(logits2)
        scores3 = fluid.layers.reshape(logp, [-1, beam, tgt_vocab])
        sel_ids, sel_scores, parents = fluid.layers.beam_search(
            pre_ids, pre_scores, scores3, beam_size=beam, end_id=end_id)

    # encoder program to get h0
    enc_prog = main.clone(for_test=True)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        # params are shared by name; startup of step_prog would clobber
        # them, so only run it for vars not already initialized (none).
        b = batch(2)
        h0, = exe.run(enc_prog, feed=b, fetch_list=[enc_last])
        h0 = np.asarray(h0)
        n = h0.shape[0]
        ids = np.zeros((n, beam), 'int64')
        scores = np.full((n, beam), -1e9, 'float32')
        scores[:, 0] = 0.0                       # one live beam at start
        h = np.tile(h0[:, None, :], (1, beam, 1)).astype('float32')
        all_ids, all_parents = [], []
        for _ in range(tt):
            ids_v, sc_v, par_v, h_v = exe.run(
                step_prog,
                feed={'pre_ids': ids, 'pre_scores': scores, 'h_in': h},
                fetch_list=[sel_ids, sel_scores, parents, h_new])
            ids, scores, par = (np.asarray(ids_v), np.asarray(sc_v),
                                np.asarray(par_v))
            h = np.take_along_axis(np.asarray(h_v),
                                   par[:, :, None], axis=1)
            all_ids.append(ids)
            all_parents.append(par)
        idst = np.stack(all_ids)                  # [T, B, K]
        part = np.stack(all_parents)
        dec_prog = fluid.Program()
        with fluid.program_guard(dec_prog, fluid.Program()):
            iv = fluid.layers.data('ids', shape=[n, beam], dtype='int64')
            pv = fluid.layers.data('parents', shape=[n, beam],
                                   dtype='int64')
            tree = fluid.layers.gather_tree(iv, pv)
        tr, = exe.run(dec_prog, feed={'ids': idst, 'parents': part},
                      fetch_list=[tree])
        tr = np.asarray(tr)
        assert tr.shape == (tt, n, beam)
        assert (tr >= 0).all() and (tr < tgt_vocab).all()


def test_recommender_system_cos_sim():
    """User/item embeddings -> cos_sim -> scaled rating, square error
    (book ch. 5)."""
    n_users, n_items, dim = 30, 40, 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data('uid', shape=[1], dtype='int64')
        mid = fluid.layers.data('mid', shape=[1], dtype='int64')
        rating = fluid.layers.data('rating', shape=[1], dtype='float32')
        uemb = fluid.layers.embedding(uid, size=[n_users, dim])
        memb = fluid.layers.embedding(mid, size=[n_items, dim])
        uvec = fluid.layers.fc(fluid.layers.reshape(uemb, [-1, dim]), 32,
                               act='relu')
        mvec = fluid.layers.fc(fluid.layers.reshape(memb, [-1, dim]), 32,
                               act='relu')
        sim = fluid.layers.cos_sim(uvec, mvec)
        pred = fluid.layers.scale(sim, scale=5.0)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, rating))
        fluid.optimizer.Adam(1e-2).minimize(loss)

    rng = np.random.RandomState(2)
    true_u = rng.randn(n_users, 4)
    true_m = rng.randn(n_items, 4)

    def batch(n=32):
        u = rng.randint(0, n_users, (n, 1)).astype('int64')
        m = rng.randint(0, n_items, (n, 1)).astype('int64')
        r = np.clip((true_u[u[:, 0]] * true_m[m[:, 0]]).sum(1), -5, 5)
        return {'uid': u, 'mid': m,
                'rating': r[:, None].astype('float32')}

    losses = _run_train(main, startup, loss, batch, steps=40)
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_understand_sentiment_conv_lstm():
    """Book ch.6 understand_sentiment: embedding + conv / LSTM text
    classifiers train on the sentiment reader
    (reference tests/book/test_understand_sentiment.py)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu import dataset

    word_dict = dataset.sentiment.get_word_dict()
    vocab = len(word_dict)
    seq_len = 64

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        words = fluid.layers.data('words', shape=[seq_len],
                                  dtype='int64')
        mask = fluid.layers.data('mask', shape=[seq_len],
                                 dtype='float32')
        label = fluid.layers.data('label', shape=[1], dtype='int64')
        emb = fluid.layers.embedding(words, size=[vocab, 32])
        # conv branch (sequence_conv analog on padded rep)
        conv = fluid.layers.sequence_conv(emb, num_filters=32,
                                          filter_size=3, mask=mask)
        pooled = fluid.layers.sequence_pool(conv, 'max', mask=mask)
        # lstm branch
        proj = fluid.layers.fc(emb, 4 * 32, num_flatten_dims=2)
        h, c = fluid.layers.dynamic_lstm(proj, size=4 * 32, mask=mask)
        lpool = fluid.layers.sequence_pool(h, 'max', mask=mask)
        feat = fluid.layers.concat([pooled, lpool], axis=1)
        logits = fluid.layers.fc(feat, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(2e-3).minimize(loss)

    def batches(reader, batch):
        buf = []
        for ws, lab in reader():
            ids = np.zeros(seq_len, 'int64')
            m = np.zeros(seq_len, 'float32')
            n = min(len(ws), seq_len)
            ids[:n] = ws[:n]
            m[:n] = 1.0
            buf.append((ids, m, lab))
            if len(buf) == batch:
                yield buf
                buf = []

    it = iter(list(batches(dataset.sentiment.train(), 16))[:40])

    def batch_fn():
        ws, ms, lb = zip(*next(it))
        return {'words': np.stack(ws), 'mask': np.stack(ms),
                'label': np.array(lb, 'int64')[:, None]}

    losses = _run_train(main, startup, loss, batch_fn, steps=40)
    assert np.isfinite(losses).all()
    # synthetic sentiment is separable: training must make progress
    assert np.mean(losses[-10:]) < np.mean(losses[:10]), (
        losses[:5], losses[-5:])
