"""Per-op tests: NN ops (conv/pool/norm/dropout/losses/tensor manip).

Mirrors reference tests test_conv2d_op.py, test_pool2d_op.py,
test_batch_norm_op.py, test_softmax_with_cross_entropy_op.py, etc.
"""

import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(7)


def ref_conv2d(x, w, stride, pad):
    n, c, h, wdt = x.shape
    oc, ic, kh, kw = w.shape
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wdt + 2 * pad - kw) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((n, oc, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.tensordot(patch, w, ([1, 2, 3],
                                                      [1, 2, 3]))
    return out


class TestConv2D(OpTest):
    def test_forward(self):
        x = rng.randn(2, 3, 8, 8).astype('float32')
        w = rng.randn(4, 3, 3, 3).astype('float32')
        self.check_output('conv2d', {'Input': x, 'Filter': w},
                          attrs={'strides': [1, 1], 'paddings': [1, 1]},
                          expect={'Output': ref_conv2d(x, w, 1, 1)},
                          atol=1e-3, rtol=1e-3)

    def test_stride2(self):
        x = rng.randn(1, 2, 9, 9).astype('float32')
        w = rng.randn(3, 2, 3, 3).astype('float32')
        self.check_output('conv2d', {'Input': x, 'Filter': w},
                          attrs={'strides': [2, 2], 'paddings': [0, 0]},
                          expect={'Output': ref_conv2d(x, w, 2, 0)},
                          atol=1e-3, rtol=1e-3)

    def test_grad(self):
        x = rng.randn(1, 2, 5, 5).astype('float32')
        w = rng.randn(2, 2, 3, 3).astype('float32')
        self.check_grad('conv2d', {'Input': x, 'Filter': w},
                        attrs={'strides': [1, 1], 'paddings': [1, 1]},
                        out_slot='Output', atol=2e-2, rtol=2e-2)


class TestPool2D(OpTest):
    def test_maxpool(self):
        x = rng.randn(2, 3, 4, 4).astype('float32')
        expect = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.check_output('pool2d', {'X': x},
                          attrs={'pooling_type': 'max', 'ksize': [2, 2],
                                 'strides': [2, 2], 'paddings': [0, 0]},
                          expect={'Out': expect})

    def test_avgpool(self):
        x = rng.randn(2, 3, 4, 4).astype('float32')
        expect = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.check_output('pool2d', {'X': x},
                          attrs={'pooling_type': 'avg', 'ksize': [2, 2],
                                 'strides': [2, 2], 'paddings': [0, 0]},
                          expect={'Out': expect})

    def test_global(self):
        x = rng.randn(2, 3, 4, 4).astype('float32')
        self.check_output('pool2d', {'X': x},
                          attrs={'pooling_type': 'avg',
                                 'global_pooling': True, 'ksize': [1, 1]},
                          expect={'Out': x.mean((2, 3), keepdims=True)})

    def test_grad(self):
        x = rng.randn(1, 2, 4, 4).astype('float32')
        self.check_grad('pool2d', {'X': x},
                        attrs={'pooling_type': 'avg', 'ksize': [2, 2],
                               'strides': [2, 2], 'paddings': [0, 0]})


class TestBatchNorm(OpTest):
    def _inputs(self, c=4):
        x = rng.randn(3, c, 5, 5).astype('float32')
        return {'X': x,
                'Scale': rng.rand(c).astype('float32') + 0.5,
                'Bias': rng.randn(c).astype('float32'),
                'Mean': np.zeros(c, 'float32'),
                'Variance': np.ones(c, 'float32')}

    def test_train_forward(self):
        ins = self._inputs()
        x = ins['X']
        m = x.mean((0, 2, 3))
        v = x.var((0, 2, 3))
        y = (x - m.reshape(1, -1, 1, 1)) / np.sqrt(
            v.reshape(1, -1, 1, 1) + 1e-5)
        y = y * ins['Scale'].reshape(1, -1, 1, 1) + \
            ins['Bias'].reshape(1, -1, 1, 1)
        got = self.run_op('batch_norm', ins,
                          attrs={'is_test': False, 'epsilon': 1e-5,
                                 'momentum': 0.9},
                          out_slots=('Y', 'MeanOut', 'VarianceOut',
                                     'SavedMean', 'SavedVariance'))
        np.testing.assert_allclose(got['Y'], y, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(got['MeanOut'], 0.1 * m, atol=1e-5)

    def test_train_forward_large_mean_no_cancellation(self):
        """f32 one-pass stats about the running-mean shift: variance
        must survive |mean| >> std (the naive E[x^2]-E[x]^2 form
        collapses to 0 -> inv=1/sqrt(eps) and blows up Y)."""
        x = (1e4 + rng.randn(8, 4, 5, 5) * 0.01).astype('float32')
        # COLD START: running mean still 0 — the shift must come from
        # the batch itself, not the (useless) running stats
        ins = {'X': x,
               'Scale': np.ones(4, 'float32'),
               'Bias': np.zeros(4, 'float32'),
               'Mean': np.zeros(4, 'float32'),
               'Variance': np.ones(4, 'float32')}
        got = self.run_op('batch_norm', ins,
                          attrs={'is_test': False, 'epsilon': 1e-5,
                                 'momentum': 0.9},
                          out_slots=('Y', 'SavedMean'))
        y = np.asarray(got['Y'])
        # normalized output has ~unit std; the cancellation bug gives
        # std ~ x.std/sqrt(eps) ~ 3
        assert abs(float(y.std()) - 1.0) < 0.2, y.std()
        np.testing.assert_allclose(got['SavedMean'],
                                   x.transpose(1, 0, 2, 3).reshape(
                                       4, -1).mean(1), rtol=1e-6)

    def test_eval_forward(self):
        ins = self._inputs()
        ins['Mean'] = rng.randn(4).astype('float32') * 0.1
        ins['Variance'] = rng.rand(4).astype('float32') + 0.5
        x = ins['X']
        y = (x - ins['Mean'].reshape(1, -1, 1, 1)) / np.sqrt(
            ins['Variance'].reshape(1, -1, 1, 1) + 1e-5)
        y = y * ins['Scale'].reshape(1, -1, 1, 1) + \
            ins['Bias'].reshape(1, -1, 1, 1)
        got = self.run_op('batch_norm', ins,
                          attrs={'is_test': True, 'epsilon': 1e-5},
                          out_slots=('Y',))
        np.testing.assert_allclose(got['Y'], y, atol=1e-4, rtol=1e-4)


class TestLayerNorm(OpTest):
    def test_forward(self):
        x = rng.randn(4, 10).astype('float32')
        scale = rng.rand(10).astype('float32') + 0.5
        bias = rng.randn(10).astype('float32')
        m = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        y = (x - m) / np.sqrt(v + 1e-5) * scale + bias
        self.check_output('layer_norm',
                          {'X': x, 'Scale': scale, 'Bias': bias},
                          attrs={'epsilon': 1e-5, 'begin_norm_axis': 1},
                          expect={'Y': y}, atol=1e-4, rtol=1e-4,
                          out_slots=['Y'])

    def test_grad(self):
        x = rng.randn(3, 6).astype('float32')
        scale = rng.rand(6).astype('float32') + 0.5
        bias = rng.randn(6).astype('float32')
        self.check_grad('layer_norm',
                        {'X': x, 'Scale': scale, 'Bias': bias},
                        attrs={'epsilon': 1e-5, 'begin_norm_axis': 1},
                        out_slot='Y', atol=2e-2, rtol=2e-2)


class TestDropout(OpTest):
    def test_train_stats(self):
        x = np.ones((100, 100), 'float32')
        got = self.run_op('dropout', {'X': x},
                          attrs={'dropout_prob': 0.3, 'is_test': False,
                                 'dropout_implementation':
                                     'upscale_in_train'})
        keep_rate = (np.asarray(got['Out']) != 0).mean()
        assert abs(keep_rate - 0.7) < 0.03
        # kept values upscaled by 1/0.7
        kept = np.asarray(got['Out'])[np.asarray(got['Out']) != 0]
        np.testing.assert_allclose(kept, 1.0 / 0.7, rtol=1e-5)

    def test_eval_identity(self):
        x = rng.randn(5, 5).astype('float32')
        self.check_output('dropout', {'X': x},
                          attrs={'dropout_prob': 0.3, 'is_test': True,
                                 'dropout_implementation':
                                     'upscale_in_train'},
                          expect={'Out': x})


class TestSoftmaxWithCrossEntropy(OpTest):
    def test_forward(self):
        logits = rng.randn(4, 6).astype('float32')
        label = rng.randint(0, 6, (4, 1)).astype('int64')
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(4), label[:, 0]])[:, None]
        got = self.run_op('softmax_with_cross_entropy',
                          {'Logits': logits, 'Label': label},
                          out_slots=('Softmax', 'Loss'))
        np.testing.assert_allclose(got['Softmax'], sm, atol=1e-5,
                                   rtol=1e-4)
        np.testing.assert_allclose(got['Loss'], loss, atol=1e-5,
                                   rtol=1e-4)

    def test_grad(self):
        logits = rng.randn(3, 5).astype('float32')
        label = rng.randint(0, 5, (3, 1)).astype('int64')
        self.check_grad('softmax_with_cross_entropy',
                        {'Logits': logits, 'Label': label},
                        out_slot='Loss', grad_slots=['Logits'])


class TestCrossEntropy(OpTest):
    def test_forward(self):
        probs = rng.dirichlet(np.ones(5), 4).astype('float32')
        label = rng.randint(0, 5, (4, 1)).astype('int64')
        loss = -np.log(probs[np.arange(4), label[:, 0]])[:, None]
        self.check_output('cross_entropy',
                          {'X': probs, 'Label': label},
                          expect={'Y': loss}, out_slots=['Y'],
                          atol=1e-5)


class TestLookupTable(OpTest):
    def test_forward(self):
        w = rng.randn(10, 4).astype('float32')
        ids = rng.randint(0, 10, (3, 5)).astype('int64')
        self.check_output('lookup_table_v2', {'W': w, 'Ids': ids},
                          expect={'Out': w[ids]})

    def test_padding_idx(self):
        w = rng.randn(10, 4).astype('float32')
        ids = np.array([[0, 2, 0], [1, 0, 3]], 'int64')
        out = w[ids].copy()
        out[ids == 0] = 0
        self.check_output('lookup_table_v2', {'W': w, 'Ids': ids},
                          attrs={'padding_idx': 0}, expect={'Out': out})

    def test_grad_scatter(self):
        """Embedding grad = scatter-add of output grads into rows."""
        w = rng.randn(6, 3).astype('float32')
        ids = np.array([1, 1, 4], 'int64')
        self.check_grad('lookup_table_v2',
                        {'W': w, 'Ids': ids}, grad_slots=['W'])


class TestTensorManip(OpTest):
    def test_reshape_transpose_concat(self):
        x = rng.randn(2, 6).astype('float32')
        self.check_output('reshape2', {'X': x}, attrs={'shape': [3, 4]},
                          expect={'Out': x.reshape(3, 4)})
        self.check_output('reshape2', {'X': x}, attrs={'shape': [0, -1]},
                          expect={'Out': x})
        self.check_output('transpose2', {'X': x}, attrs={'axis': [1, 0]},
                          expect={'Out': x.T})
        ys = [('p', rng.randn(2, 3).astype('float32')),
              ('q', rng.randn(2, 2).astype('float32'))]
        self.check_output('concat', {'X': ys}, attrs={'axis': 1},
                          expect={'Out': np.concatenate(
                              [a for _, a in ys], 1)})

    def test_split_sections(self):
        x = rng.randn(2, 10).astype('float32')
        got = self.run_op('split', {'X': x},
                          attrs={'axis': 1, 'sections': [2, -1, 3]},
                          out_slots=('Out',))
        # only first returned through Out[0]; use full runner instead
        # -> validate via direct lowering
        from paddle_tpu.ops import registry
        outs = registry.get('split').fn(
            registry.LowerCtx(0), {'X': [x]},
            {'axis': 1, 'sections': [2, -1, 3]})['Out']
        np.testing.assert_allclose(outs[0], x[:, :2])
        np.testing.assert_allclose(outs[1], x[:, 2:7])
        np.testing.assert_allclose(outs[2], x[:, 7:])

    def test_slice_gather(self):
        x = rng.randn(5, 6).astype('float32')
        self.check_output('slice', {'Input': x},
                          attrs={'axes': [0, 1], 'starts': [1, 2],
                                 'ends': [4, 6]},
                          expect={'Out': x[1:4, 2:6]})
        idx = np.array([3, 0, 1], 'int64')
        self.check_output('gather', {'X': x, 'Index': idx},
                          expect={'Out': x[idx]})

    def test_onehot_cast(self):
        ids = np.array([[1], [3]], 'int64')
        oh = np.zeros((2, 5), 'float32')
        oh[0, 1] = oh[1, 3] = 1
        self.check_output('one_hot', {'X': ids}, attrs={'depth': 5},
                          expect={'Out': oh})
        x = rng.randn(3, 3).astype('float32')
        self.check_output('cast', {'X': x},
                          attrs={'out_dtype': 'int32'},
                          expect={'Out': x.astype(np.int32)})


class TestAccuracyOp(OpTest):
    def test_accuracy(self):
        idx = np.array([[0, 1], [2, 3], [4, 5]], 'int64')
        label = np.array([[1], [0], [4]], 'int64')
        got = self.run_op('accuracy',
                          {'Out': rng.rand(3, 2).astype('float32'),
                           'Indices': idx, 'Label': label},
                          out_slots=('Accuracy',))
        np.testing.assert_allclose(got['Accuracy'], 2.0 / 3.0, rtol=1e-6)
