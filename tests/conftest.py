"""Test config: run on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): single-host
"cluster-in-a-box" — here an 8-device XLA host platform so sharding /
collective paths compile and execute without TPU hardware.
"""

import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()
