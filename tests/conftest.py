"""Test config: run on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): single-host
"cluster-in-a-box" — an 8-device XLA host platform so sharding /
collective paths compile and execute without TPU hardware.

The ambient environment may pre-register a real TPU backend (axon) via
sitecustomize and pin jax_platforms programmatically, so setting the env
var is not enough — override the jax config after import.  Set
PADDLE_TPU_TEST_PLATFORM to run the suite on another platform.
"""

import os

# PADDLE_TPU_VERIFY=1 arms the static Program verifier
# (fluid.progcheck, FLAGS_program_verify) for the WHOLE suite: every
# Program any test plans gets the full invariant + shape/dtype +
# donation pass before anything traces — the sweep that keeps the
# transpiler/planner rewrite paths verifier-clean.  Must be set
# before paddle_tpu imports (flags read the env at import).
if os.environ.get('PADDLE_TPU_VERIFY'):
    os.environ.setdefault('FLAGS_program_verify', '1')

_platform = os.environ.get('PADDLE_TPU_TEST_PLATFORM', 'cpu')
os.environ['JAX_PLATFORMS'] = _platform
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', _platform)
