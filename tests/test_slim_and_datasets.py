"""contrib.slim (prune / distillation / NAS) + dataset loaders.

Mirrors the reference's slim tests
(reference: python/paddle/fluid/contrib/slim/tests/) and dataset unit
tests (python/paddle/dataset/tests/): pruning must zero the right
fraction and keep the model runnable, distill losses must be positive
scalars that shrink as student approaches teacher, the SA controller
must find a planted optimum, and every loader must yield records with
the documented shapes.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib.slim import prune, distillation, nas
from paddle_tpu.fluid.contrib.slim.searcher import SAController
import paddle_tpu.dataset as dataset


def _sparsity(a):
    return float((a == 0).mean())


def test_magnitude_pruner_ratio_and_model_still_runs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[16], dtype='float32')
        h = fluid.layers.fc(input=x, size=32, act='relu')
        out = fluid.layers.fc(input=h, size=4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        params = [p.name for p in main.all_parameters()
                  if len(p.shape) >= 2]  # weight matrices, not biases
        masks = prune.MagnitudePruner().prune(
            main, scope, params=params, ratios=[0.5] * len(params))
        for name in params:
            arr = np.asarray(fluid.core.as_array(scope.find_var(name)))
            assert 0.4 < _sparsity(arr) <= 0.6, (name, _sparsity(arr))
            assert masks[name].shape == arr.shape
        o, = exe.run(main, feed={'x': np.ones((2, 16), 'float32')},
                     fetch_list=[out])
        assert o.shape == (2, 4)


def test_structure_pruner_zeroes_whole_filters():
    a = np.arange(1, 25, dtype='float32').reshape(4, 3, 2, 1)
    mask = prune.StructurePruner(pruned_axis=0).prune_tensor(a, 0.5)
    per_filter = mask.reshape(4, -1)
    # 2 of 4 filters fully zero, rest fully kept
    zero_rows = (per_filter == 0).all(axis=1)
    one_rows = (per_filter == 1).all(axis=1)
    assert zero_rows.sum() == 2 and one_rows.sum() == 2
    # lowest-l1 filters (the first ones here) are dropped
    assert zero_rows[0] and zero_rows[1]


def test_uniform_prune_strategy_and_sensitivity():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        out = fluid.layers.fc(input=x, size=2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pname = main.all_parameters()[0].name
        base = np.asarray(
            fluid.core.as_array(scope.find_var(pname))).copy()
        sens = prune.sensitivity(main, scope, pname,
                                 eval_fn=lambda: 1.0,
                                 ratios=(0.3, 0.6))
        assert set(sens) == {0.3, 0.6}
        # param restored after the sweep
        np.testing.assert_array_equal(
            np.asarray(fluid.core.as_array(scope.find_var(pname))), base)
        prune.UniformPruneStrategy(
            target_ratio=0.25).on_compression_begin(main, scope)


def test_distillers_build_and_shrink():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = fluid.layers.data('s', shape=[6], dtype='float32')
        t = fluid.layers.data('t', shape=[6], dtype='float32')
        l2 = distillation.L2Distiller(s, t).distiller_loss()
        soft = distillation.SoftLabelDistiller(
            s, t, teacher_temperature=2.0).distiller_loss()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        tv = np.arange(12, dtype='float32').reshape(2, 6)
        far = np.zeros((2, 6), 'float32')
        near = tv + 0.1
        l2_far, soft_far = exe.run(
            main, feed={'s': far, 't': tv}, fetch_list=[l2, soft])
        l2_near, soft_near = exe.run(
            main, feed={'s': near, 't': tv}, fetch_list=[l2, soft])
        assert float(l2_near) < float(l2_far)
        assert float(soft_near) < float(soft_far)
        assert float(l2_near) >= 0 and float(soft_near) >= 0


def test_fsp_distiller():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        sa = fluid.layers.data('sa', shape=[3, 4, 4], dtype='float32')
        sb = fluid.layers.data('sb', shape=[5, 4, 4], dtype='float32')
        ta = fluid.layers.data('ta', shape=[3, 4, 4], dtype='float32')
        tb = fluid.layers.data('tb', shape=[5, 4, 4], dtype='float32')
        loss = distillation.FSPDistiller([(sa, sb)],
                                         [(ta, tb)]).distiller_loss()
    rng = np.random.RandomState(0)
    va = rng.randn(2, 3, 4, 4).astype('float32')
    vb = rng.randn(2, 5, 4, 4).astype('float32')
    wa = rng.randn(2, 3, 4, 4).astype('float32')
    wb = rng.randn(2, 5, 4, 4).astype('float32')
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        z, = exe.run(main, feed={'sa': va, 'sb': vb, 'ta': va, 'tb': vb},
                     fetch_list=[loss])
        assert abs(float(z)) < 1e-6  # identical pairs -> zero distance
        v, = exe.run(main, feed={'sa': va, 'sb': vb, 'ta': wa, 'tb': wb},
                     fetch_list=[loss])
        # value parity vs numpy FSP (reference fsp_op semantics)
        def fsp(a, b):
            return np.einsum('nchw,ndhw->ncd', a, b) / (4 * 4)
        expect = np.mean((fsp(va, vb) - fsp(wa, wb)) ** 2)
        np.testing.assert_allclose(float(v), expect, rtol=1e-5)


def test_sa_controller_finds_planted_optimum():
    target = [3, 1, 4, 1, 5]
    ctrl = SAController(seed=0)

    class Space(nas.SearchSpace):
        def init_tokens(self):
            return [0, 0, 0, 0, 0]

        def range_table(self):
            return [8, 8, 8, 8, 8]

    strategy = nas.LightNASStrategy(Space(), controller=ctrl,
                                    search_steps=400)

    def reward(tokens):
        return -sum(abs(a - b) for a, b in zip(tokens, target))

    best, best_r = strategy.search(reward)
    assert best_r > -3, (best, best_r)


def test_dataset_loaders_shapes():
    img, label = next(dataset.cifar.train10()())
    assert img.shape == (3072,) and 0 <= label < 10
    img, label = next(dataset.cifar.train100()())
    assert img.shape == (3072,) and 0 <= label < 100

    word_idx = dataset.imikolov.build_dict(min_word_freq=1)
    gram = next(dataset.imikolov.train(word_idx, 5)())
    assert len(gram) == 5
    assert all(0 <= g < len(word_idx) for g in gram)

    rec = next(dataset.movielens.train()())
    assert len(rec) == 8
    assert 1 <= rec[0] <= dataset.movielens.max_user_id()
    assert isinstance(rec[5], list) and isinstance(rec[6], list)
    assert 1.0 <= rec[7] <= 5.0

    img, label = next(dataset.flowers.train()())
    assert img.shape == (3, 224, 224) and 0 <= label < 102

    src, trg, trg_next = next(dataset.wmt16.train(100, 100)())
    assert src[0] == dataset.wmt16.start_mark()
    assert src[-1] == dataset.wmt16.end_mark()
    assert len(trg) == len(trg_next)
    assert trg[1:] == trg_next[:-1]

    rec = next(dataset.conll05.test()())
    assert len(rec) == 9
    n = len(rec[0])
    assert all(len(col) == n for col in rec[1:])
    emb = dataset.conll05.get_embedding()
    assert emb.shape == (dataset.conll05.WORD_VOCAB,
                         dataset.conll05.EMB_DIM)


def test_soft_label_distillation_transfers_knowledge_e2e():
    """End-to-end knowledge transfer (round 5): a student trained ONLY
    on the SoftLabelDistiller loss (zero hard labels) learns to agree
    with a trained teacher on held-out data — the reference
    distillation contract (distiller.py:195) exercised through real
    training, not just loss shrinkage."""
    rng = np.random.RandomState(0)

    def make_batch(n=64):
        y = rng.randint(0, 2, n)
        x = rng.randn(n, 8).astype('float32')
        x[y == 1, :4] += 1.6
        return x, y.astype('int64').reshape(-1, 1)

    # --- teacher: train a wider net on labels ---
    tmain, tstart = fluid.Program(), fluid.Program()
    tmain.random_seed = tstart.random_seed = 1
    with fluid.program_guard(tmain, tstart):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        yv = fluid.layers.data('y', shape=[1], dtype='int64')
        h = fluid.layers.fc(x, 32, act='relu')
        tlogits = fluid.layers.fc(h, 2)
        tloss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(tlogits, yv))
        fluid.optimizer.Adam(5e-3).minimize(tloss)
    tscope = fluid.Scope()
    with fluid.scope_guard(tscope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(tstart)
        for _ in range(40):
            xb, yb = make_batch()
            exe.run(tmain, feed={'x': xb, 'y': yb}, fetch_list=[])
        tparams = {p.name: np.asarray(tscope.find_var(p.name))
                   for p in tmain.all_parameters()}

    # --- student: teacher forward (frozen) + student net + soft loss
    # in ONE program, the reference graph-merging recipe ---
    smain, sstart = fluid.Program(), fluid.Program()
    smain.random_seed = sstart.random_seed = 2
    with fluid.program_guard(smain, sstart):
        x = fluid.layers.data('x', shape=[8], dtype='float32')
        th = fluid.layers.fc(x, 32, act='relu',
                             param_attr=fluid.ParamAttr(name='t_w0'),
                             bias_attr=fluid.ParamAttr(name='t_b0'))
        tlog = fluid.layers.fc(th, 2,
                               param_attr=fluid.ParamAttr(name='t_w1'),
                               bias_attr=fluid.ParamAttr(name='t_b1'))
        tlog.stop_gradient = True
        sh = fluid.layers.fc(x, 8, act='relu')   # smaller student
        slog = fluid.layers.fc(sh, 2)
        dloss = distillation.SoftLabelDistiller(
            slog, tlog, teacher_temperature=2.0,
            student_temperature=2.0).distiller_loss()
        eval_prog = smain.clone(for_test=True)
        fluid.optimizer.Adam(
            1e-2).minimize(dloss,
                           no_grad_set=['t_w0', 't_b0', 't_w1', 't_b1'])
    sscope = fluid.Scope()
    with fluid.scope_guard(sscope):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(sstart)
        # load the frozen teacher weights under their t_* names,
        # mapped BY SHAPE so a change in fc's param-creation order
        # fails loudly here instead of at the agreement assertion
        want = {'t_w0': (8, 32), 't_b0': (32,),
                't_w1': (32, 2), 't_b1': (2,)}
        for dst, shape in want.items():
            srcs = [n for n, v in tparams.items()
                    if tuple(v.shape) == shape]
            assert len(srcs) == 1, (dst, shape, srcs)
            sscope.set_var(dst, tparams[srcs[0]])
        frozen_before = np.array(np.asarray(sscope.find_var('t_w1')))
        d0 = None
        for i in range(200):
            xb, _ = make_batch()
            d, = exe.run(smain, feed={'x': xb}, fetch_list=[dloss])
            if d0 is None:
                d0 = float(np.asarray(d).ravel()[0])
        d1 = float(np.asarray(d).ravel()[0])
        assert d1 < d0, (d0, d1)
        # teacher stayed frozen
        np.testing.assert_array_equal(
            frozen_before, np.asarray(sscope.find_var('t_w1')))

        # held-out agreement: student mimics the teacher WITHOUT ever
        # seeing a label (pure eval clone — no optimizer ops run on
        # the held-out batch)
        xe, _ = make_batch(256)
        s_out, t_out = exe.run(eval_prog, feed={'x': xe},
                               fetch_list=[slog, tlog])
    agree = (np.argmax(np.asarray(s_out), 1) ==
             np.argmax(np.asarray(t_out), 1)).mean()
    assert agree > 0.9, agree


def test_light_nas_finds_better_architecture_e2e():
    """LightNASStrategy driven end-to-end (round 5): the SA controller
    searches a real space of fluid programs (hidden width x
    activation), each candidate TRAINS and is scored by held-out
    accuracy; the search must beat the deliberately-bad initial
    architecture (reference light_nas_strategy.py:34 contract)."""
    WIDTHS = [1, 24]
    ACTS = ['relu', 'tanh']
    data_rng = np.random.RandomState(0)

    def make_batch(n=64):
        y = data_rng.randint(0, 2, n)
        x = data_rng.randn(n, 8).astype('float32')
        # xor-ish structure: a width-1 net cannot separate it
        x[:, 0] += (2 * y - 1) * (2 * (x[:, 1] > 0) - 1) * 1.5
        return x, y.astype('int64').reshape(-1, 1)

    train_batches = [make_batch() for _ in range(12)]
    xe, ye = make_batch(256)

    class Space(nas.SearchSpace):
        def init_tokens(self):
            return [0, 0]      # width 1: the worst choice on purpose

        def range_table(self):
            return [len(WIDTHS), len(ACTS)]

        def create_net(self, tokens=None):
            w, act = WIDTHS[tokens[0]], ACTS[tokens[1]]
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 7
            with fluid.program_guard(main, startup):
                x = fluid.layers.data('x', shape=[8], dtype='float32')
                yv = fluid.layers.data('y', shape=[1], dtype='int64')
                h = fluid.layers.fc(x, w, act=act)
                logits = fluid.layers.fc(h, 2)
                loss = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, yv))
                test_prog = main.clone(for_test=True)
                fluid.optimizer.Adam(2e-2).minimize(loss)
            return startup, main, test_prog, [loss], [logits]

    space = Space()

    _cache = {}

    def eval_fn(tokens):
        # deterministic per-tokens result: memoize so the strategy's
        # own init evaluation reuses the test's baseline run
        key = tuple(tokens)
        if key in _cache:
            return _cache[key]
        startup, main, test_prog, _, (logits,) = \
            space.create_net(tokens)
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(4):            # epochs over the fixed set
                for xb, yb in train_batches:
                    exe.run(main, feed={'x': xb, 'y': yb},
                            fetch_list=[])
            out, = exe.run(test_prog, feed={'x': xe, 'y': ye},
                           fetch_list=[logits])
        _cache[key] = float((np.argmax(np.asarray(out), 1) ==
                             ye.ravel()).mean())
        return _cache[key]

    init_reward = eval_fn(space.init_tokens())
    strat = nas.LightNASStrategy(space, search_steps=10, seed=3)
    best_tokens, best_reward = strat.search(eval_fn)
    assert best_reward > init_reward + 0.05, (init_reward, best_reward,
                                              best_tokens)
    assert WIDTHS[best_tokens[0]] > 1, best_tokens
    assert best_reward > 0.8, best_reward
