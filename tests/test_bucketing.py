"""LoD-replacement bucketing front-end.

Reference: the LoD machinery (framework/lod_tensor.h:219,
operators/math/sequence_padding.h) let one program consume ragged
batches; on XLA, BucketedGeneratorLoader pads ragged samples into a
small set of bucket shapes and jax.jit caches ONE executable per bucket
— recompiles bounded by n_buckets.
"""

import numpy as np

import paddle_tpu.fluid as fluid

layers = fluid.layers


def _ragged_samples(n, lo=3, hi=30, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ln = rng.randint(lo, hi + 1)
        ids = rng.randint(1, 100, ln).astype('int64')
        label = np.int64(rng.randint(0, 2))
        yield ids, label


def test_bucketed_loader_shapes_and_masks():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[1], dtype='int64', lod_level=1)
        label = layers.data('label', shape=[1], dtype='int64')
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[ids, label], bucket_boundaries=[8, 16, 32],
        batch_size=4)
    loader.set_sample_generator(lambda: _ragged_samples(24))
    seen_t = set()
    n_batches = 0
    for feed in loader:
        n_batches += 1
        t = feed['ids'].shape[1]
        seen_t.add(t)
        assert t in (8, 16, 32)
        assert feed['ids@MASK'].shape == feed['ids'].shape[:2]
        lens = feed['ids@MASK'].sum(1).astype(int)
        # every sample fits its bucket and would not fit the previous
        for ln in lens:
            assert ln <= t
        # mask matches the zero-padding
        assert (feed['ids'] * (1 - feed['ids@MASK'])).sum() == 0
    assert n_batches >= 3 and len(seen_t) >= 2


def test_bucketed_loader_rejects_oversize():
    import pytest
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[1], dtype='int64', lod_level=1)
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[ids], bucket_boundaries=[8], batch_size=2)
    loader.set_sample_generator(
        lambda: iter([(np.arange(20, dtype='int64'),)]))
    with pytest.raises(ValueError, match='bucket boundary'):
        list(loader)


def test_sequence_conv_pool_trains_from_ragged():
    """understand_sentiment-style net on genuinely ragged text via the
    bucketed loader; the nets.sequence_conv_pool stub is gone."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[1], dtype='int64', lod_level=1)
        mask = layers.data('ids@MASK', shape=[1], dtype='float32')
        label = layers.data('label', shape=[1], dtype='int64')
        emb = layers.embedding(ids, size=[100, 16])
        feat = fluid.nets.sequence_conv_pool(emb, 32, 3, act='tanh',
                                             pool_type='max', mask=mask)
        logits = layers.fc(feat, 2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(1e-2).minimize(loss)

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[ids, label], bucket_boundaries=[8, 32],
        batch_size=4)
    loader.set_sample_generator(lambda: _ragged_samples(32, seed=3))

    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for epoch in range(3):
            for feed in loader:
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])


def _ragged_nmt_samples(n, seed=0, lo=5, hi=32):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        sl = rng.randint(lo, hi + 1)
        tl = rng.randint(lo, hi + 1)
        src = rng.randint(1, 200, sl).astype('int64')
        tgt = rng.randint(1, 200, tl).astype('int64')
        tgt_label = rng.randint(1, 200, tl).astype('int64')
        yield src, tgt, tgt_label


def test_transformer_trains_from_ragged_with_bounded_compiles():
    """Transformer NMT (BASELINE config 4) trains from genuinely ragged
    pairs with at most n_buckets executables — the VERDICT round-1
    'done' criterion for the LoD bucketing front-end."""
    from paddle_tpu import models

    cfg = models.transformer.TINY
    boundaries = [16, 32]
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        feeds, logits, loss = models.transformer.build(
            cfg, src_len=32, tgt_len=32)
        fluid.optimizer.Adam(1e-3).minimize(loss)

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[feeds['src_ids'], feeds['tgt_ids'],
                   feeds['tgt_label']],
        bucket_boundaries=boundaries, batch_size=4,
        ragged_fields=['src_ids', 'tgt_ids', 'tgt_label'],
        mask_map={'src_ids': 'src_mask', 'tgt_ids': 'tgt_mask'})
    loader.set_sample_generator(lambda: _ragged_nmt_samples(40, seed=5))

    losses = []
    seen_shapes = set()
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        for feed in loader:
            feed.pop('tgt_label@MASK')  # tgt_mask already covers it
            seen_shapes.add((feed['src_ids'].shape[1],
                             feed['tgt_ids'].shape[1]))
            l, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        # one executable per bucket shape: inspect the jit cache of the
        # (single) device segment
        from paddle_tpu.fluid.executor import _Segment
        plans = [p for p in main._exec_cache.values()]
        segs = [it for p in plans for it in p
                if isinstance(it, _Segment) and it.compiled is not None]
        for seg in segs:
            try:
                n_exec = seg.compiled._cache_size()
            except Exception:
                n_exec = None
            if n_exec is not None:
                assert n_exec <= len(boundaries) ** 2, n_exec
    assert len(seen_shapes) >= 2, seen_shapes
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_imdb_sentiment_end_to_end_via_bucketed_loader():
    """Round 3: the bucketed loader over a REAL dataset reader
    (paddle_tpu.dataset.imdb, the reference's understand_sentiment
    data path) — ragged reviews, learnable sentiment signal, accuracy
    must beat chance by a wide margin after one epoch."""
    from paddle_tpu import dataset

    word_dict = dataset.imdb.word_dict()
    vocab = len(word_dict)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', shape=[1], dtype='int64', lod_level=1)
        mask = layers.data('ids@MASK', shape=[1], dtype='float32')
        label = layers.data('label', shape=[1], dtype='int64')
        emb = layers.embedding(ids, size=[vocab, 32])
        feat = fluid.nets.sequence_conv_pool(emb, 48, 3, act='tanh',
                                             pool_type='max', mask=mask)
        logits = layers.fc(feat, 2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(2e-3).minimize(loss)

    def train_samples():
        for seq, lab in dataset.imdb.train()():
            yield np.asarray(seq, 'int64'), np.int64(lab)

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[ids, label], bucket_boundaries=[32, 64, 128],
        batch_size=32)
    loader.set_sample_generator(train_samples)

    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        losses = []
        for epoch in range(2):
            for feed in loader:
                l, = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert np.isfinite(losses).all()

        # eval on the held-out synthetic test split
        test_loader = fluid.io.DataLoader.from_generator(
            feed_list=[ids, label], bucket_boundaries=[32, 64, 128],
            batch_size=32)

        def test_samples():
            for seq, lab in dataset.imdb.test()():
                yield np.asarray(seq, 'int64'), np.int64(lab)

        test_loader.set_sample_generator(test_samples)
        correct = total = 0
        for feed in test_loader:
            lg, = exe.run(test_prog, feed=feed, fetch_list=[logits])
            pred = np.asarray(lg).argmax(1)
            correct += int((pred == feed['label'].ravel()).sum())
            total += len(pred)
    acc = correct / total
    assert acc > 0.8, (acc, correct, total)


def test_bucketed_loader_properties_random_lengths():
    """Property check over random ragged distributions: every sample is
    delivered exactly once, each batch's pad length is the smallest
    boundary covering its samples, masks are exactly 1 over real
    tokens / 0 over padding, and padded cells are 0."""
    rng = np.random.RandomState(123)
    for trial in range(4):
        n = int(rng.randint(20, 60))
        lengths = rng.randint(1, 33, size=n)
        boundaries = [4, 8, 16, 32]
        samples = [(np.arange(1, L + 1, dtype='int64'),
                    np.int64(i)) for i, L in enumerate(lengths)]

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data('ids', shape=[1], dtype='int64',
                              lod_level=1)
            tag = layers.data('tag', shape=[1], dtype='int64')

        loader = fluid.io.DataLoader.from_generator(
            feed_list=[ids, tag], bucket_boundaries=boundaries,
            batch_size=8)
        loader.set_sample_generator(lambda: iter(samples))

        seen = {}
        for feed in loader:
            arr = feed['ids']
            mask = feed['ids@MASK']
            tags = feed['tag'].ravel()
            T = arr.shape[1]
            assert T in boundaries, T
            batch_lens = []
            for row, mrow, t in zip(arr, mask, tags):
                L = int(mrow.sum())
                batch_lens.append(L)
                assert int(t) not in seen
                seen[int(t)] = L
                # mask is a 1/0 prefix; padded cells are zero
                np.testing.assert_array_equal(
                    mrow.ravel()[:L], np.ones(L, 'float32'))
                np.testing.assert_array_equal(
                    mrow.ravel()[L:], np.zeros(T - L, 'float32'))
                np.testing.assert_array_equal(
                    row.ravel()[:L], np.arange(1, L + 1))
                np.testing.assert_array_equal(
                    row.ravel()[L:], np.zeros(T - L, 'int64'))
            # tightest covering boundary for this batch
            lo = max(batch_lens)
            want_T = min(b for b in boundaries if b >= lo)
            assert T == want_T, (T, want_T, batch_lens)
        # exactly-once delivery, and lengths survived the roundtrip
        assert sorted(seen) == list(range(n))
        for i, L in enumerate(lengths):
            assert seen[i] == L, (i, seen[i], L)
