"""Coverage audits stay green (the CI-gate analog of reference
tools/check_op_desc.py + diff_api.py + check_api_approvals.sh)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(tool, *args):
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    p = subprocess.run([sys.executable, os.path.join(REPO, 'tools',
                                                     tool)] + list(args),
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=300)
    return p


def test_op_coverage_complete():
    p = _run('check_op_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert 'coverage: complete' in p.stdout


def test_api_coverage_complete():
    p = _run('check_api_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert '(100.0%)' in p.stdout


def test_every_op_is_test_referenced():
    p = _run('check_test_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert 'every registered op is referenced' in p.stdout


def test_timeline_export(tmp_path):
    """fluid.profiler capture -> tools/timeline.py -> chrome-trace JSON
    (the reference's tools/timeline.py flow)."""
    import gzip
    import json

    prof = tmp_path / 'profile'
    # synthesize the jax-profiler layout the tool consumes
    d = prof / 'plugins' / 'profile' / 'run1'
    d.mkdir(parents=True)
    trace = {'traceEvents': [
        {'ph': 'M', 'pid': 1, 'name': 'process_name',
         'args': {'name': '/device:TPU:0'}},
        {'ph': 'X', 'pid': 1, 'tid': 0, 'ts': 0, 'dur': 5,
         'name': 'fusion.1'}]}
    with gzip.open(str(d / 'vm.trace.json.gz'), 'wt') as f:
        json.dump(trace, f)
    out = tmp_path / 'timeline.json'
    p = _run('timeline.py', '--profile_path', str(prof),
             '--timeline_path', str(out))
    assert p.returncode == 0, p.stdout + p.stderr
    got = json.load(open(str(out)))
    assert got['traceEvents'][1]['name'] == 'fusion.1'
