"""Coverage audits stay green (the CI-gate analog of reference
tools/check_op_desc.py + diff_api.py + check_api_approvals.sh)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(tool):
    env = dict(os.environ)
    env.setdefault('JAX_PLATFORMS', 'cpu')
    p = subprocess.run([sys.executable, os.path.join(REPO, 'tools',
                                                     tool)],
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=300)
    return p


def test_op_coverage_complete():
    p = _run('check_op_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert 'coverage: complete' in p.stdout


def test_api_coverage_complete():
    p = _run('check_api_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert '(100.0%)' in p.stdout


def test_every_op_is_test_referenced():
    p = _run('check_test_coverage.py')
    assert p.returncode == 0, p.stdout + p.stderr
    assert 'every registered op is referenced' in p.stdout
