"""While loop, LR schedulers, sequence ops."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_while_loop_sum():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], 'float32', 0.0)
        i.stop_gradient = True
        limit = fluid.layers.fill_constant([1], 'float32', 10.0)
        acc = fluid.layers.fill_constant([1], 'float32', 0.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond=cond)
        with w.block():
            fluid.layers.increment(i, value=1.0, in_place=True)
            fluid.layers.sums([acc, i], out=acc)
            fluid.layers.less_than(i, limit, cond=cond)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        out, = exe.run(main, fetch_list=[acc])
    assert float(out) == 55.0, out


@pytest.mark.parametrize('name,fn,expect0,expect5', [
    ('exp', lambda: fluid.layers.exponential_decay(0.1, 10, 0.5),
     0.1, 0.1 * 0.5 ** 0.5),
    ('piecewise', lambda: fluid.layers.piecewise_decay([3, 6],
                                                       [0.1, 0.01, 0.001]),
     0.1, 0.01),
])
def test_lr_schedulers(name, fn, expect0, expect5):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        lr = fn()
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(pred)
        opt = fluid.optimizer.SGD(lr)
        opt.minimize(loss)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        lrs = []
        for _ in range(6):
            v, = exe.run(main, feed={'x': np.ones((4, 2), 'float32')},
                         fetch_list=[lr])
            lrs.append(float(np.asarray(v).ravel()[0]))
    np.testing.assert_allclose(lrs[0], expect0, rtol=1e-5)
    np.testing.assert_allclose(lrs[5], expect5, rtol=1e-5)


def test_noam_warmup_rises_then_falls():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[2], dtype='float32')
        lr = fluid.layers.noam_decay(64, warmup_steps=5)
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(pred)
        fluid.optimizer.SGD(lr).minimize(loss)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        lrs = [float(np.asarray(exe.run(
            main, feed={'x': np.ones((2, 2), 'float32')},
            fetch_list=[lr])[0]).ravel()[0]) for _ in range(10)]
    assert lrs[0] < lrs[4] and lrs[9] < lrs[4] * 1.01, lrs


def test_sequence_ops():
    from paddle_tpu.ops import registry
    x = np.arange(24, dtype=np.float32).reshape(2, 4, 3)
    mask = np.array([[1, 1, 1, 0], [1, 1, 0, 0]], np.float32)
    ctx = registry.LowerCtx(0)
    out = registry.get('sequence_pool').fn(
        ctx, {'X': [x], 'Mask': [mask]}, {'pooltype': 'AVERAGE'})
    np.testing.assert_allclose(out['Out'][0][0], x[0, :3].mean(0))
    np.testing.assert_allclose(out['Out'][0][1], x[1, :2].mean(0))
    out = registry.get('sequence_pool').fn(
        ctx, {'X': [x], 'Mask': [mask]}, {'pooltype': 'MAX'})
    np.testing.assert_allclose(out['Out'][0][1], x[1, :2].max(0))
    out = registry.get('sequence_pool').fn(
        ctx, {'X': [x], 'Mask': [mask]}, {'pooltype': 'LAST'})
    np.testing.assert_allclose(out['Out'][0][0], x[0, 2])
    sm = registry.get('sequence_softmax').fn(
        ctx, {'X': [x[:, :, 0]], 'Mask': [mask]}, {})['Out'][0]
    np.testing.assert_allclose(np.asarray(sm).sum(-1), [1.0, 1.0],
                               rtol=1e-5)
    assert sm[0, 3] == 0 and sm[1, 2] == 0
    m = registry.get('sequence_mask').fn(
        ctx, {'X': [np.array([3, 2])]}, {'maxlen': 4,
                                         'out_dtype': 'float32'})
    np.testing.assert_allclose(m['Y'][0], mask)


def test_static_rnn_unroll():
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        x = fluid.layers.data('x', shape=[4, 3], dtype='float32')
        from paddle_tpu.fluid.layers.control_flow import StaticRNN
        rnn = StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            prev = rnn.memory(batch_ref=xt, shape=[3])
            h = fluid.layers.elementwise_add(xt, prev)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()
        total = fluid.layers.reduce_sum(out)
    xs = np.arange(24, dtype='float32').reshape(2, 4, 3)
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        o, = exe.run(main, feed={'x': xs}, fetch_list=[out])
    # h_t = cumulative sum over time
    np.testing.assert_allclose(o, np.cumsum(xs, axis=1), rtol=1e-5)
