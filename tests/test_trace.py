"""fluid.trace — span tracer, flight recorder, merged export, report.

The acceptance contract: spans nest and stay thread-attributed; the
ring buffer retains exactly FLAGS_trace_buffer_steps steps; the
DISABLED tracer costs (near) nothing per call site; the merged
host+device export loads as valid chrome-trace JSON with the device
clock aligned; and step_report() phase sums account for the step's
wall time."""

import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, monitor, trace


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    trace.reset()
    yield
    trace.disable()
    trace.reset()


def _build(width=16):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[width], dtype='float32')
        h = layers.fc(x, size=width, act='relu')
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------- spans
def test_span_nesting_and_threading():
    trace.enable(buffer_steps=4)
    results = {}

    def worker():
        with trace.span('outer_w'):
            with trace.span('inner_w'):
                time.sleep(0.002)
        results['tid'] = threading.get_ident()

    with trace.step_span(1):
        with trace.span('outer', tag='a'):
            with trace.span('inner'):
                time.sleep(0.002)
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    recs = trace.steps()
    assert len(recs) == 1
    spans = {s[0]: s for s in recs[0]['spans']}
    assert set(spans) == {'outer', 'inner', 'outer_w', 'inner_w'}
    main_tid = threading.get_ident()
    # thread attribution
    assert spans['outer'][3] == main_tid
    assert spans['inner'][3] == main_tid
    assert spans['outer_w'][3] == results['tid'] != main_tid
    # depth: step=0, outer=1, inner=2; worker thread starts at 0
    assert spans['outer'][4] == 1 and spans['inner'][4] == 2
    assert spans['outer_w'][4] == 0 and spans['inner_w'][4] == 1
    # nesting by interval: inner inside outer
    assert spans['outer'][1] <= spans['inner'][1]
    assert spans['inner'][2] <= spans['outer'][2]
    # args survive
    assert spans['outer'][5] == {'tag': 'a'}
    assert monitor.counter_value('trace/steps_recorded') >= 1.0


def test_record_and_decorator():
    trace.enable(buffer_steps=4)

    @trace.traced('decorated_phase')
    def work():
        return 41 + 1

    with trace.step_span(7):
        assert work() == 42
        t0 = time.perf_counter()
        trace.record('manual', t0, t0 + 0.5, {'k': 1})
    rec = trace.steps()[-1]
    names = [s[0] for s in rec['spans']]
    assert 'decorated_phase' in names and 'manual' in names
    manual = next(s for s in rec['spans'] if s[0] == 'manual')
    assert abs((manual[2] - manual[1]) - 0.5) < 1e-9


def test_ring_buffer_evicts_at_flag_capacity():
    fluid.set_flags({'FLAGS_trace_buffer_steps': 3})
    try:
        monitor.reset()
        trace.enable()
        for i in range(5):
            with trace.step_span(i):
                with trace.span('phase'):
                    pass
        recs = trace.steps()
        assert len(recs) == 3
        assert [r['step'] for r in recs] == [2, 3, 4]
        assert monitor.counter_value('trace/steps_dropped') == 2.0
        assert monitor.counter_value('trace/steps_recorded') == 5.0
    finally:
        fluid.set_flags({'FLAGS_trace_buffer_steps': 16})


def test_disabled_mode_overhead_budget():
    """Off (the default), a span site is one function call + a global
    load: 10k call pairs must stay far under a us-scale budget (50us
    per site would already be a hot-path regression)."""
    assert not trace.is_active()
    spans_before = monitor.counter_value('trace/spans_recorded')
    n = 10000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span('x'):
            pass
        with trace.span('y', nbytes=4096, vars=2):  # kwargs site shape
            pass
        trace.record('z', 0.0, 1.0)
    dt = time.perf_counter() - t0
    per_site = dt / (3 * n)
    assert per_site < 20e-6, 'disabled span site costs %.1fus' % (
        per_site * 1e6)
    # and nothing was recorded
    assert trace.steps() == []
    assert monitor.counter_value('trace/spans_recorded') == spans_before


# ------------------------------------------------------- chrome export
def test_merged_export_is_valid_chrome_trace(tmp_path):
    trace.enable(buffer_steps=4)
    with trace.step_span(1):
        with trace.span('dispatch', ops=3):
            time.sleep(0.001)
    host = trace.chrome_events()
    sync_host_us = trace.now_us()
    # synthetic jax-style device trace on a session-relative clock
    device = [
        {'ph': 'M', 'pid': 7, 'name': 'process_name',
         'args': {'name': '/device:TPU:0'}},
        {'ph': 'X', 'pid': 7, 'tid': 0, 'ts': 1000.0, 'dur': 5.0,
         'name': 'pt_clock_sync'},
        {'ph': 'X', 'pid': 7, 'tid': 0, 'ts': 1500.0, 'dur': 80.0,
         'name': 'fusion.1'},
    ]
    merged = trace.merge_device_trace(host, device,
                                      sync_host_us=sync_host_us)
    out = str(tmp_path / 'merged.json')
    trace.write_chrome(out, merged)
    doc = json.load(open(out))
    evs = doc['traceEvents']
    assert isinstance(evs, list) and evs
    # sync marker aligned exactly onto the host clock
    sync = next(e for e in evs if e['name'] == 'pt_clock_sync')
    assert abs(sync['ts'] - sync_host_us) < 1e-6
    fusion = next(e for e in evs if e['name'] == 'fusion.1')
    assert abs(fusion['ts'] - (sync_host_us + 500.0)) < 1e-6
    # host events re-homed above the device pids, schema complete
    host_evs = [e for e in evs if e.get('cat') == 'pt_host']
    assert host_evs and all(e['pid'] == 8 for e in host_evs)
    for e in evs:
        if e.get('ph') == 'X':
            assert isinstance(e['ts'], (int, float))
            assert isinstance(e['dur'], (int, float))
            assert isinstance(e['name'], str)
    names = set(e['name'] for e in host_evs if e.get('ph') == 'X')
    assert {'dispatch', 'step'} <= names


def test_merge_without_sync_aligns_on_capture_start():
    host = [{'ph': 'X', 'pid': 0, 'tid': 0, 'ts': 5_000_000.0,
             'dur': 10.0, 'name': 'bind', 'cat': 'pt_host'}]
    device = [{'ph': 'X', 'pid': 3, 'tid': 0, 'ts': 100.0, 'dur': 5.0,
               'name': 'fusion.2'}]
    merged = trace.merge_device_trace(host, device,
                                      capture_t0_us=4_999_900.0)
    fusion = next(e for e in merged if e['name'] == 'fusion.2')
    assert fusion['ts'] == pytest.approx(4_999_900.0)
    # epoch-like device clocks pass through untouched
    device_epoch = [{'ph': 'X', 'pid': 3, 'tid': 0, 'ts': 2e15,
                     'dur': 5.0, 'name': 'fusion.3'}]
    merged = trace.merge_device_trace(host, device_epoch)
    assert next(e for e in merged
                if e['name'] == 'fusion.3')['ts'] == 2e15


# ---------------------------------------------------------------- report
def test_report_sums_approximate_step_wall():
    """Synthetic step with known phases: top-level sums must account
    for the wall time and nested spans must NOT double count."""
    rec = {'step': 9, 't0': 100.0, 't1': 100.010, 'tid': 1,
           'spans': [
               ('bind', 100.0, 100.001, 1, 1, None),
               ('dispatch', 100.001, 100.008, 1, 1, None),
               ('compile', 100.002, 100.007, 1, 2, None),  # nested
               ('fetch_d2h', 100.008, 100.0095, 1, 1, None),
           ]}
    rep = trace.report_from_records([rec])
    s = rep['steps'][0]
    assert s['wall_ms'] == pytest.approx(10.0)
    # nested compile excluded from the phase sums
    assert set(s['phases_ms']) == {'bind', 'dispatch', 'fetch_d2h'}
    assert s['phases_ms']['dispatch'] == pytest.approx(7.0)
    assert s['accounted_ms'] == pytest.approx(9.5)
    assert s['coverage'] >= 0.8
    roll = rep['rollup']
    assert roll['count'] == 1
    assert roll['wall_p50_ms'] == pytest.approx(10.0)
    assert roll['slowest']['step'] == 9
    # JSON round trip (the dump() path) produces the same report
    js = json.loads(json.dumps(rec))
    rep2 = trace.report_from_records([js])
    assert rep2['steps'][0]['phases_ms'] == s['phases_ms']
    # and it renders
    table = trace.format_step_report(rep)
    assert 'dispatch' in table and 'p50' in table


def test_live_program_records_phases_and_covers_wall():
    """End-to-end: a real (tiny) program's traced steps carry the
    bind/dispatch phases and the report explains most of the wall."""
    main, startup, loss = _build()
    x = np.random.RandomState(0).randn(8, 16).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': x}, fetch_list=[loss])  # compile cold
        trace.enable(buffer_steps=8)
        for _ in range(3):
            exe.run(main, feed={'x': x}, fetch_list=[loss])
        trace.disable()
    recs = trace.steps()
    assert len(recs) == 3
    names = set(s[0] for r in recs for s in r['spans'])
    assert {'bind', 'dispatch', 'feed_h2d', 'fetch_d2h',
            'state_release'} <= names
    rep = trace.step_report(last=2)
    assert rep['rollup']['count'] == 2
    # the per-step monitor counters moved with the spans (two planes
    # stay consistent)
    assert monitor.counter_value('trace/steps_recorded') >= 3.0
    assert monitor.counter_value('trace/spans_recorded') >= 12.0


def test_dump_and_stat_summary_steps(tmp_path, capsys):
    import os
    import sys
    trace.enable(buffer_steps=4)
    with trace.step_span(3):
        with trace.span('dispatch'):
            time.sleep(0.001)
    p = str(tmp_path / 'flight.json')
    out = trace.dump(p)
    assert out == p
    doc = json.load(open(p))
    assert doc['ptSteps'] and doc['traceEvents']
    assert monitor.counter_value('trace/dumps_written') == 1.0
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, 'tools'))
    try:
        import stat_summary
    finally:
        sys.path.pop(0)
    assert stat_summary.main(['--steps', p]) == 0
    rendered = capsys.readouterr().out
    assert 'dispatch' in rendered and 'wall(ms)' in rendered


def test_dump_on_error_from_nan_check(tmp_path):
    """FLAGS_check_nan_inf failure dumps the flight recorder (the
    error notes name the path on interpreters with PEP 678)."""
    import glob
    import os
    import tempfile
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.log(x)  # log(0) -> -inf
    fluid.set_flags({'FLAGS_check_nan_inf': True})
    trace.enable(buffer_steps=4)
    dumps_before = monitor.counter_value('trace/dumps_written')
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            with pytest.raises(FloatingPointError):
                exe.run(main, feed={'x': np.zeros((2, 4), 'float32')},
                        fetch_list=[y])
        assert monitor.counter_value('trace/dumps_written') == \
            dumps_before + 1
        paths = glob.glob(os.path.join(
            tempfile.gettempdir(),
            'pt_trace_%d_nan_*.json' % os.getpid()))
        assert paths, 'no flight-recorder dump written'
        doc = json.load(open(max(paths, key=os.path.getmtime)))
        assert doc['ptSteps']  # the failing step window is in the dump
    finally:
        fluid.set_flags({'FLAGS_check_nan_inf': False})


def test_profiler_capture_attaches_tracer(tmp_path):
    """start_trace/stop_trace auto-attach: one capture yields the
    host_trace.json sidecar and restores the tracer's prior state."""
    from paddle_tpu.fluid import profiler
    main, startup, loss = _build()
    x = np.zeros((4, 16), 'float32')
    assert not trace.is_active()
    logdir = str(tmp_path / 'cap')
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
        exe.run(main, feed={'x': x}, fetch_list=[loss])
        profiler.start_trace(logdir)
        assert trace.is_active()
        exe.run(main, feed={'x': x}, fetch_list=[loss])
        path = profiler.stop_trace()
    assert not trace.is_active()
    host = json.load(open(str(tmp_path / 'cap' / 'host_trace.json')))
    assert path == logdir
    names = set(e['name'] for e in host['ptHostEvents']
                if e.get('ph') == 'X')
    assert {'bind', 'dispatch'} <= names
    assert host['ptSync'] is not None
