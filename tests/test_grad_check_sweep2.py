"""Gradient-check sweep, part 2: the round-3 extension toward full
differentiable-op coverage (reference discipline: OpTest.check_grad
finite differences on every differentiable op, op_test.py:57).

Part 1 (test_grad_check_sweep.py) covers the activation/elementwise/
reduction core; this file adds shape/index manipulation, interpolation,
normalization variants, conv/pool variants, losses, sequence ops under
masks, structured-prediction vjps (CRF, warpctc), roi ops, and the
hand-written flash-attention custom_vjp at multiple shapes/modes.

Inputs live in each op's smooth region (away from kinks) exactly like
part 1."""

import numpy as np
import pytest

from op_test import OpTest

rng = np.random.RandomState(11)


def away(x, bad, margin=0.15):
    x = np.array(x)
    for b in bad:
        close = np.abs(x - b) < margin
        x[close] = b + margin * np.sign(x[close] - b + 1e-8) * 2
    return x


# ---------------------------------------------------------------------------
# single-input ops: op -> (inputs dict builder, attrs, out_slot, kwargs)

SINGLE = {
    'tan': (lambda: {'X': rng.uniform(-1.0, 1.0, (2, 3))}, {}, 'Out', {}),
    'log2': (lambda: {'X': rng.rand(2, 3) + 0.5}, {}, 'Out', {}),
    'log10': (lambda: {'X': rng.rand(2, 3) + 0.5}, {}, 'Out', {}),
    'silu': (lambda: {'X': rng.randn(2, 3)}, {}, 'Out', {}),
    'soft_relu': (lambda: {'X': rng.randn(2, 3)}, {'threshold': 40.0},
                  'Out', {}),
    'soft_shrink': (lambda: {'X': away(rng.randn(2, 3) * 2,
                                       [-0.5, 0.5])},
                    {'lambda': 0.5}, 'Out', {}),
    'cumsum': (lambda: {'X': rng.randn(2, 4)}, {'axis': 1}, 'Out', {}),
    'reduce_max': (lambda: {'X': np.arange(6.).reshape(2, 3) +
                            rng.rand(2, 3) * 0.1},
                   {'dim': [1]}, 'Out', {}),
    'reduce_min': (lambda: {'X': np.arange(6.).reshape(2, 3) +
                            rng.rand(2, 3) * 0.1},
                   {'dim': [1]}, 'Out', {}),
    'expand': (lambda: {'X': rng.randn(2, 3)},
               {'expand_times': [2, 1]}, 'Out', {}),
    'tile': (lambda: {'X': rng.randn(2, 3)},
             {'repeat_times': [1, 2]}, 'Out', {}),
    'reverse': (lambda: {'X': rng.randn(2, 3)}, {'axis': [1]}, 'Out', {}),
    'flip': (lambda: {'X': rng.randn(2, 3)}, {'axis': [0]}, 'Out', {}),
    'roll': (lambda: {'X': rng.randn(2, 4)},
             {'shifts': [1], 'axis': [1]}, 'Out', {}),
    'tril_triu': (lambda: {'X': rng.randn(3, 3)},
                  {'diagonal': 0, 'lower': True}, 'Out', {}),
    'pad2d': (lambda: {'X': rng.randn(1, 2, 3, 3)},
              {'paddings': [1, 1, 1, 1], 'mode': 'constant',
               'pad_value': 0.0}, 'Out', {}),
    'pixel_shuffle': (lambda: {'X': rng.randn(1, 4, 2, 2)},
                      {'upscale_factor': 2}, 'Out', {}),
    'space_to_depth': (lambda: {'X': rng.randn(1, 2, 4, 4)},
                       {'blocksize': 2}, 'Out', {}),
    'shuffle_channel': (lambda: {'X': rng.randn(1, 4, 2, 2)},
                        {'group': 2}, 'Out', {}),
    'unfold': (lambda: {'X': rng.randn(1, 2, 4, 4)},
               {'kernel_sizes': [2, 2], 'strides': [2, 2],
                'paddings': [0, 0, 0, 0], 'dilations': [1, 1]},
               'Y', {}),
    'slice': (lambda: {'Input': rng.randn(3, 4)},
              {'axes': [0, 1], 'starts': [1, 0], 'ends': [3, 3]},
              'Out', {}),
    'strided_slice': (lambda: {'Input': rng.randn(4, 6)},
                      {'axes': [1], 'starts': [0], 'ends': [6],
                       'strides': [2]}, 'Out', {}),
    'crop': (lambda: {'X': rng.randn(3, 4)},
             {'shape': [2, 2], 'offsets': [1, 1]}, 'Out', {}),
    'crop_tensor': (lambda: {'X': rng.randn(3, 4)},
                    {'shape': [2, 2], 'offsets': [0, 1]}, 'Out', {}),
    'label_smooth': (lambda: {'X': rng.rand(2, 5)},
                     {'epsilon': 0.1}, 'Out', {}),
    'temporal_shift': (lambda: {'X': rng.randn(4, 4, 2, 2)},
                       {'seg_num': 2, 'shift_ratio': 0.25}, 'Out', {}),
    'transpose2': (lambda: {'X': rng.randn(2, 3)}, {'axis': [1, 0]},
                   'Out', {}),
    'reshape2': (lambda: {'X': rng.randn(2, 3)}, {'shape': [3, 2]},
                 'Out', {}),
    'squeeze2': (lambda: {'X': rng.randn(2, 1, 3)}, {'axes': [1]},
                 'Out', {}),
    'unsqueeze2': (lambda: {'X': rng.randn(2, 3)}, {'axes': [0]},
                   'Out', {}),
    'flatten2': (lambda: {'X': rng.randn(2, 3, 2)}, {'axis': 1},
                 'Out', {}),
    'flatten_contiguous_range': (lambda: {'X': rng.randn(2, 3, 2)},
                                 {'start_axis': 1, 'stop_axis': 2},
                                 'Out', {}),
    'p_norm': (lambda: {'X': rng.rand(2, 4) + 0.5},
               {'porder': 3.0, 'axis': 1}, 'Out', {}),
    'norm': (lambda: {'X': rng.rand(2, 4) + 0.5}, {'axis': 1},
             'Out', {}),
    'lrn': (lambda: {'X': rng.randn(1, 4, 3, 3)},
            {'n': 3, 'k': 1.0, 'alpha': 1e-2, 'beta': 0.75},
            'Out', {}),
    'maxout': (lambda: {'X': rng.randn(1, 4, 3, 3) +
                        np.arange(4).reshape(1, 4, 1, 1)},
               {'groups': 2}, 'Out', {}),
    'spp': (lambda: {'X': rng.randn(1, 2, 4, 4)},
            {'pyramid_height': 2, 'pooling_type': 'avg'}, 'Out', {}),
    'add_position_encoding': (lambda: {'X': rng.randn(2, 4, 6)},
                              {'alpha': 1.0, 'beta': 1.0}, 'Out', {}),
    'bilinear_interp': (lambda: {'X': rng.randn(1, 2, 4, 4)},
                        {'out_h': 8, 'out_w': 8,
                         'align_corners': False}, 'Out', {}),
    'nearest_interp': (lambda: {'X': rng.randn(1, 2, 4, 4)},
                       {'out_h': 8, 'out_w': 8,
                        'align_corners': False}, 'Out', {}),
    'trilinear_interp': (lambda: {'X': rng.randn(1, 2, 3, 3, 3)},
                         {'out_d': 6, 'out_h': 6, 'out_w': 6,
                          'align_corners': False}, 'Out', {}),
    'mean_iou': None,   # integer semantics
    'square_error_cost': None,  # binary, below
}


@pytest.mark.parametrize('op', sorted(k for k, v in SINGLE.items() if v))
def test_single_grad(op):
    gen, attrs, out_slot, kw = SINGLE[op]
    ins = {k: np.asarray(v, 'float32') for k, v in gen().items()}
    OpTest().check_grad(op, ins, attrs, out_slot=out_slot, **kw)


# ---------------------------------------------------------------------------
# multi-input ops

MULTI = {
    'bmm': (lambda: {'X': rng.randn(2, 3, 4), 'Y': rng.randn(2, 4, 5)},
            {}, 'Out', {}),
    'matmul_v2': (lambda: {'X': rng.randn(2, 3), 'Y': rng.randn(2, 4)},
                  {'trans_x': True}, 'Out', {}),
    'minus': (lambda: {'X': rng.randn(2, 3), 'Y': rng.randn(2, 3)},
              {}, 'Out', {}),
    'elementwise_mod': (lambda: {'X': rng.rand(2, 3) * 3 + 3.2,
                                 'Y': np.full((2, 3), 2.0)},
                        {}, 'Out', {'grad_slots': ['X']}),
    'square_error_cost': (lambda: {'X': rng.randn(2, 3),
                                   'Y': rng.randn(2, 3)}, {}, 'Out', {}),
    'mse_loss': (lambda: {'X': rng.randn(2, 3), 'Y': rng.randn(2, 3)},
                 {}, 'Out', {}),
    'huber_loss': (lambda: {'X': away(rng.randn(4, 1), []),
                            'Y': away(rng.randn(4, 1) * 3, [])},
                   {'delta': 1.0}, 'Out', {}),
    'smooth_l1_loss': (lambda: {'X': rng.randn(3, 4),
                                'Y': rng.randn(3, 4) + 3.0},
                       {'sigma': 1.0}, 'Out', {}),
    'log_loss': (lambda: {'Predicted': rng.uniform(0.2, 0.8, (4, 1)),
                          'Labels': rng.randint(0, 2, (4, 1)).astype(
                              'float32')},
                 {'epsilon': 1e-4}, 'Loss', {'grad_slots': ['Predicted']}),
    'rank_loss': (lambda: {'Label': rng.randint(0, 2, (4, 1)).astype(
                               'float32'),
                           'Left': rng.randn(4, 1),
                           'Right': rng.randn(4, 1)},
                  {}, 'Out', {'grad_slots': ['Left', 'Right'],
                              'stop_gradients': ('Label',)}),
    'margin_rank_loss': (lambda: {'Label': np.ones((4, 1), 'float32'),
                                  'X1': rng.randn(4, 1),
                                  'X2': rng.randn(4, 1) - 3.0},
                         {'margin': 0.1}, 'Out',
                         {'grad_slots': ['X1', 'X2'],
                          'stop_gradients': ('Label',)}),
    'kldiv_loss': (lambda: {'X': np.log(rng.rand(3, 4) + 0.2),
                            'Target': rng.rand(3, 4) + 0.2},
                   {'reduction': 'mean'}, 'Loss',
                   {'grad_slots': ['X']}),
    'sigmoid_cross_entropy_with_logits': (
        lambda: {'X': rng.randn(3, 4),
                 'Label': rng.rand(3, 4)},
        {}, 'Out', {'grad_slots': ['X']}),
    'hinge_loss': (lambda: {'Logits': away(rng.randn(4, 1) * 2, [1, -1],
                                           0.3),
                            'Labels': np.ones((4, 1), 'float32')},
                   {}, 'Loss', {'grad_slots': ['Logits'],
                                'stop_gradients': ('Labels',)}),
    'bpr_loss': (lambda: {'X': rng.rand(3, 4) + 0.5,
                          'Label': rng.randint(0, 4, (3, 1)).astype(
                              'int64')},
                 {}, 'Y', {'grad_slots': ['X']}),
    'cross_entropy': (lambda: {'X': (lambda p: p / p.sum(
                                     1, keepdims=True))(
                                         rng.rand(3, 4) + 0.3),
                               'Label': rng.randint(0, 4, (3, 1)).astype(
                                   'int64')},
                      {'soft_label': False}, 'Y', {'grad_slots': ['X']}),
    'cross_entropy2': (lambda: {'X': (lambda p: p / p.sum(
                                      1, keepdims=True))(
                                          rng.rand(3, 4) + 0.3),
                                'Label': rng.randint(0, 4, (3, 1)).astype(
                                    'int64')},
                       {}, 'Y', {'grad_slots': ['X']}),
    'fsp': (lambda: {'X': rng.randn(1, 2, 3, 3),
                     'Y': rng.randn(1, 3, 3, 3)}, {}, 'Out', {}),
    'conv_shift': (lambda: {'X': rng.randn(2, 5),
                            'Y': rng.randn(2, 3)}, {}, 'Out', {}),
    'pad_constant_like': (lambda: {'X': rng.randn(3, 4),
                                   'Y': rng.randn(2, 3)},
                          {'pad_value': 0.0}, 'Out',
                          {'grad_slots': ['Y']}),
    'bilinear_tensor_product': (
        lambda: {'X': rng.randn(2, 3), 'Y': rng.randn(2, 4),
                 'Weight': rng.randn(5, 3, 4)},
        {}, 'Out', {}),
    'prelu': (lambda: {'X': away(rng.randn(2, 3, 2, 2), [0.0]),
                       'Alpha': rng.rand(1) + 0.1},
              {'mode': 'all'}, 'Out', {}),
    # bilinear sampling's Grid-gradient has kinks where the sample
    # point crosses an integer pixel coordinate (for a 4-wide input,
    # normalized coords -1/3 and 1/3): the numeric gradient straddling
    # a kink is garbage, and whether the shared rng lands near one
    # depends on which tests ran before (pytest -k flake) — keep the
    # draws away from the kinks
    'grid_sampler': (lambda: {'X': rng.randn(1, 2, 4, 4),
                              'Grid': away(rng.uniform(-0.7, 0.7,
                                                       (1, 3, 3, 2)),
                                           [-1.0 / 3, 1.0 / 3],
                                           margin=0.04)},
                     {}, 'Output', {}),
    'kron': None,
    'dist': None,
}


@pytest.mark.parametrize('op', sorted(k for k, v in MULTI.items() if v))
def test_multi_grad(op):
    gen, attrs, out_slot, kw = MULTI[op]
    ins = {}
    for k, v in gen().items():
        v = np.asarray(v)
        ins[k] = v if v.dtype.kind in 'iu' else v.astype('float32')
    OpTest().check_grad(op, ins, attrs, out_slot=out_slot, **kw)


# ---------------------------------------------------------------------------
# normalization variants

def test_group_norm_grad():
    OpTest().check_grad(
        'group_norm',
        {'X': rng.randn(2, 4, 3, 3).astype('float32'),
         'Scale': (rng.rand(4) + 0.5).astype('float32'),
         'Bias': rng.randn(4).astype('float32')},
        {'groups': 2, 'epsilon': 1e-5}, out_slot='Y',
        grad_slots=['X', 'Scale', 'Bias'])


def test_instance_norm_grad():
    OpTest().check_grad(
        'instance_norm',
        {'X': rng.randn(2, 3, 4, 4).astype('float32'),
         'Scale': (rng.rand(3) + 0.5).astype('float32'),
         'Bias': rng.randn(3).astype('float32')},
        {'epsilon': 1e-5}, out_slot='Y',
        grad_slots=['X', 'Scale', 'Bias'])


def test_affine_channel_grad():
    OpTest().check_grad(
        'affine_channel',
        {'X': rng.randn(2, 3, 2, 2).astype('float32'),
         'Scale': (rng.rand(3) + 0.5).astype('float32'),
         'Bias': rng.randn(3).astype('float32')},
        {'data_layout': 'NCHW'}, out_slot='Out')


def test_data_norm_grad():
    OpTest().check_grad(
        'data_norm',
        {'X': rng.randn(4, 3).astype('float32'),
         'BatchSize': np.full(3, 10.0, 'float32'),
         'BatchSum': rng.randn(3).astype('float32'),
         'BatchSquareSum': (np.full(3, 10.0) +
                            rng.rand(3)).astype('float32')},
        {'epsilon': 1e-4}, out_slot='Y', grad_slots=['X'],
        stop_gradients=('BatchSize', 'BatchSum', 'BatchSquareSum'))


# ---------------------------------------------------------------------------
# conv / pool variants

def test_conv2d_transpose_grad():
    OpTest().check_grad(
        'conv2d_transpose',
        {'Input': rng.randn(1, 3, 4, 4).astype('float32'),
         'Filter': rng.randn(3, 2, 3, 3).astype('float32')},
        {'strides': [2, 2], 'paddings': [1, 1], 'dilations': [1, 1],
         'groups': 1}, out_slot='Output')


def test_conv3d_grad():
    OpTest().check_grad(
        'conv3d',
        {'Input': rng.randn(1, 2, 4, 4, 4).astype('float32'),
         'Filter': rng.randn(3, 2, 2, 2, 2).astype('float32')},
        {'strides': [1, 1, 1], 'paddings': [0, 0, 0],
         'dilations': [1, 1, 1], 'groups': 1}, out_slot='Output')


def test_conv3d_transpose_grad():
    OpTest().check_grad(
        'conv3d_transpose',
        {'Input': rng.randn(1, 2, 3, 3, 3).astype('float32'),
         'Filter': rng.randn(2, 2, 2, 2, 2).astype('float32')},
        {'strides': [1, 1, 1], 'paddings': [0, 0, 0],
         'dilations': [1, 1, 1], 'groups': 1}, out_slot='Output')


def test_pool3d_avg_grad():
    OpTest().check_grad(
        'pool3d', {'X': rng.randn(1, 2, 4, 4, 4).astype('float32')},
        {'pooling_type': 'avg', 'ksize': [2, 2, 2],
         'strides': [2, 2, 2], 'paddings': [0, 0, 0]})


def test_max_pool2d_with_index_grad():
    x = rng.randn(1, 2, 4, 4).astype('float32')
    x += np.arange(16, dtype='float32').reshape(1, 1, 4, 4) * 0.05
    OpTest().check_grad(
        'max_pool2d_with_index', {'X': x},
        {'ksize': [2, 2], 'strides': [2, 2], 'paddings': [0, 0]},
        out_slot='Out')


def test_deformable_conv_grad():
    n, cin, h, w = 1, 2, 4, 4
    kh = kw = 3
    OpTest().check_grad(
        'deformable_conv',
        {'Input': rng.randn(n, cin, h, w).astype('float32'),
         'Offset': (rng.randn(n, 2 * kh * kw, h, w) * 0.1).astype(
             'float32'),
         'Mask': rng.uniform(0.3, 0.9, (n, kh * kw, h, w)).astype(
             'float32'),
         'Filter': rng.randn(4, cin, kh, kw).astype('float32')},
        {'strides': [1, 1], 'paddings': [1, 1], 'dilations': [1, 1],
         'groups': 1, 'deformable_groups': 1, 'im2col_step': 1},
        out_slot='Output', grad_slots=['Input', 'Filter'])


# ---------------------------------------------------------------------------
# sequence ops under masks (the LoD surface: X [B,T,D] + Mask [B,T])

def _mask(b, t):
    m = np.zeros((b, t), 'float32')
    lens = rng.randint(1, t + 1, b)
    for i, L in enumerate(lens):
        m[i, :L] = 1.0
    return m


def test_sequence_pool_grads():
    for ptype in ('SUM', 'AVERAGE', 'SQRT', 'MAX'):
        x = rng.randn(3, 5, 4).astype('float32')
        if ptype == 'MAX':
            x += np.arange(5, dtype='float32')[None, :, None] * 0.37
        OpTest().check_grad(
            'sequence_pool',
            {'X': x, 'Mask': _mask(3, 5)},
            {'pooltype': ptype}, out_slot='Out', grad_slots=['X'],
            stop_gradients=('Mask',))


def test_sequence_softmax_grad():
    OpTest().check_grad(
        'sequence_softmax',
        {'X': rng.randn(3, 5).astype('float32'),
         'Mask': _mask(3, 5)}, {}, out_slot='Out', grad_slots=['X'],
        stop_gradients=('Mask',))


def test_sequence_conv_grad():
    OpTest().check_grad(
        'sequence_conv',
        {'X': rng.randn(2, 6, 3).astype('float32'),
         'Filter': rng.randn(9, 4).astype('float32'),
         'Mask': _mask(2, 6)},
        {'contextLength': 3, 'contextStart': -1, 'contextStride': 1},
        out_slot='Out', grad_slots=['X', 'Filter'],
        stop_gradients=('Mask',))


def test_sequence_reverse_grad():
    OpTest().check_grad(
        'sequence_reverse',
        {'X': rng.randn(2, 5, 3).astype('float32'),
         'Mask': _mask(2, 5)}, {}, out_slot='Y', grad_slots=['X'],
        stop_gradients=('Mask',))


def test_row_conv_grad():
    OpTest().check_grad(
        'row_conv',
        {'X': rng.randn(2, 6, 3).astype('float32'),
         'Filter': rng.randn(3, 3).astype('float32')},
        {}, out_slot='Out')


# ---------------------------------------------------------------------------
# structured prediction (hand-written vjps)

def test_linear_chain_crf_grad():
    b, t, n = 2, 4, 3
    OpTest().check_grad(
        'linear_chain_crf',
        {'Emission': rng.randn(b, t, n).astype('float32'),
         'Transition': rng.randn(n + 2, n).astype('float32'),
         'Label': rng.randint(0, n, (b, t, 1)).astype('int64'),
         'Mask': _mask(b, t)},
        {}, out_slot='LogLikelihood',
        grad_slots=['Emission', 'Transition'],
        stop_gradients=('Label', 'Mask'))


def test_warpctc_grad():
    b, t, nc = 2, 6, 4
    logits = rng.randn(b, t, nc).astype('float32')
    label = rng.randint(1, nc, (b, 3)).astype('int64')
    OpTest().check_grad(
        'warpctc',
        {'Logits': logits, 'Label': label},
        {'blank': 0, 'norm_by_times': False},
        out_slot='Loss', grad_slots=['Logits'])


# ---------------------------------------------------------------------------
# roi ops

def _rois():
    # [K, 4] (x1, y1, x2, y2) boxes with batch index slot
    return np.array([[0.5, 0.5, 3.0, 3.0],
                     [1.0, 1.0, 3.5, 3.5]], 'float32')


def test_roi_align_grad():
    OpTest().check_grad(
        'roi_align',
        {'X': rng.randn(1, 2, 6, 6).astype('float32'),
         'ROIs': _rois()},
        {'spatial_scale': 1.0, 'pooled_height': 2, 'pooled_width': 2,
         'sampling_ratio': 2},
        out_slot='Out', grad_slots=['X'], stop_gradients=('ROIs',))


def test_roi_pool_grad():
    x = rng.randn(1, 2, 6, 6).astype('float32')
    x += np.arange(36, dtype='float32').reshape(1, 1, 6, 6) * 0.11
    OpTest().check_grad(
        'roi_pool',
        {'X': x, 'ROIs': _rois()},
        {'spatial_scale': 1.0, 'pooled_height': 2, 'pooled_width': 2},
        out_slot='Out', grad_slots=['X'], stop_gradients=('ROIs',))


def test_psroi_pool_grad():
    OpTest().check_grad(
        'psroi_pool',
        {'X': rng.randn(1, 8, 6, 6).astype('float32'),
         'ROIs': _rois()},
        {'spatial_scale': 1.0, 'pooled_height': 2, 'pooled_width': 2,
         'output_channels': 2},
        out_slot='Out', grad_slots=['X'], stop_gradients=('ROIs',))


def test_sigmoid_focal_loss_grad():
    OpTest().check_grad(
        'sigmoid_focal_loss',
        {'X': rng.randn(4, 3).astype('float32'),
         'Label': rng.randint(0, 4, (4, 1)).astype('int64'),
         'FgNum': np.array([2], 'int32')},
        {'gamma': 2.0, 'alpha': 0.25},
        out_slot='Out', grad_slots=['X'])


# ---------------------------------------------------------------------------
# flash attention custom_vjp: fwd/bwd at multiple shapes, modes, dtypes
# (the hand-written two-pass Pallas backward — VERDICT round-2 item 8)

def _dense_ref(q, k, v, causal, key_bias=None):
    import jax
    import jax.numpy as jnp
    d = q.shape[-1]
    s = jnp.einsum('bthd,bshd->bhts', q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if key_bias is not None:
        s = s + key_bias[:, None, None, :].astype(jnp.float32)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhts,bshd->bthd', p,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize('shape,causal,with_bias', [
    ((1, 128, 1, 32), False, False),
    ((2, 128, 2, 64), False, False),
    ((2, 128, 2, 64), True, False),
    ((1, 256, 2, 64), False, True),
    ((1, 256, 1, 128), True, True),
])
def test_flash_attention_grads_match_dense(shape, causal, with_bias):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    b, t, h, d = shape
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)
    bias = jnp.asarray(rng.randn(b, t) * 0.5, jnp.float32) \
        if with_bias else None

    def loss_flash(q, k, v, bias):
        o = fa.flash_attention(q, k, v, causal=causal, key_bias=bias)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_dense(q, k, v, bias):
        o = _dense_ref(q, k, v, causal, bias)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    args = (q, k, v, bias)
    argnums = (0, 1, 2, 3) if with_bias else (0, 1, 2)
    gf = jax.grad(loss_flash, argnums)(*args)
    gd = jax.grad(loss_dense, argnums)(*args)
    for a, b2, name in zip(gf, gd, 'qkvb'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg='d%s %s' % (name, shape))


def test_flash_attention_lse_grads():
    """The lse-output variant (ring-attention merge state): both o and
    lse cotangents flow; compare against the jax-native computation of
    (o, lse)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    shape = (1, 128, 2, 64)
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)

    def ref_lse(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum('bthd,bshd->bhts', q, k) / (d ** 0.5)
        lse = jax.nn.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        o = jnp.einsum('bhts,bshd->bthd', p, v)
        return o, lse

    def loss_flash(q, k, v):
        o, lse = fa.flash_attention_with_lse(q, k, v)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        o, lse = ref_lse(q, k, v)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b2, name in zip(gf, gd, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg='d' + name)


def test_flash_attention_bf16_grads_finite_and_close():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    shape = (1, 128, 2, 64)
    qf = rng.randn(*shape)
    kf = rng.randn(*shape)
    vf = rng.randn(*shape)

    def loss(att, q, k, v):
        return jnp.sum(att(q, k, v).astype(jnp.float32) ** 2)

    g_bf = jax.grad(lambda q, k, v: loss(fa.flash_attention, q, k, v),
                    (0, 1, 2))(
        jnp.asarray(qf, jnp.bfloat16), jnp.asarray(kf, jnp.bfloat16),
        jnp.asarray(vf, jnp.bfloat16))
    g_f32 = jax.grad(
        lambda q, k, v: loss(
            lambda a, b, c: _dense_ref(a, b, c, False), q, k, v),
        (0, 1, 2))(jnp.asarray(qf, jnp.float32),
                   jnp.asarray(kf, jnp.float32),
                   jnp.asarray(vf, jnp.float32))
    for a, b2, name in zip(g_bf, g_f32, 'qkv'):
        a = np.asarray(a, 'float32')
        b2 = np.asarray(b2)
        assert np.isfinite(a).all()
        # bf16 tolerance: relative error on the grad norm
        denom = np.linalg.norm(b2) + 1e-6
        assert np.linalg.norm(a - b2) / denom < 0.08, \
            (name, np.linalg.norm(a - b2) / denom)
