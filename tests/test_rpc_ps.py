"""Native RPC parameter service (runtime/ps_service.cc) — the
listen_and_serv / gRPC layer analog: dense slots with server-side SGD,
sparse row tables with per-row adagrad, barriers; exercised both
in-process and across a real subprocess boundary."""

import os
import subprocess
import sys
import time

import numpy as np

from paddle_tpu.distributed import PsServer, PsClient, \
    RpcParameterServerStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dense_roundtrip_and_server_sgd():
    srv = PsServer(lr=0.1)
    try:
        c = PsClient(srv.endpoint)
        w = np.arange(6, dtype='float32').reshape(2, 3)
        c.init_dense('w', w)
        np.testing.assert_allclose(c.pull_dense('w'), w.reshape(-1))
        g = np.ones(6, 'float32')
        c.push_dense_grad('w', g)
        # server applied p -= lr * g (the optimize sub-block analog)
        np.testing.assert_allclose(c.pull_dense('w'),
                                   w.reshape(-1) - 0.1)
        assert 'w' in c.list_vars()
        c.close()
    finally:
        srv.stop()


def test_sparse_rows_adagrad():
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_sparse('emb', rows=100, dim=4, optimizer='adagrad',
                      lr=1.0)
        ids = np.array([3, 50, 99], 'int64')
        vals = np.arange(12, dtype='float32').reshape(3, 4)
        c.set_rows('emb', ids, vals)
        np.testing.assert_allclose(c.pull_rows('emb', ids, 4), vals)
        # untouched rows stay zero
        np.testing.assert_allclose(
            c.pull_rows('emb', np.array([0], 'int64'), 4),
            np.zeros((1, 4), 'float32'))
        g = np.ones((3, 4), 'float32')
        c.push_rows('emb', ids, g)
        got = c.pull_rows('emb', ids, 4)
        # adagrad: acc = mean(g^2) = 1 -> step = 1/(sqrt(1)+1e-6)
        np.testing.assert_allclose(got, vals - 1.0 / (1.0 + 1e-6),
                                   rtol=1e-5, atol=1e-6)
        c.close()
    finally:
        srv.stop()


def test_store_interface_with_async_communicator():
    """The AsyncCommunicator (merge-before-send) drives a REMOTE
    server through RpcParameterServerStore unchanged."""
    from paddle_tpu.distributed import AsyncCommunicator
    srv = PsServer(lr=0.5)
    try:
        store = RpcParameterServerStore(srv.endpoint)
        store.init_var('p', np.zeros((4,), 'float32'))
        # merge_num=1: every grad applies individually (deterministic;
        # the default merges-and-AVERAGES pending grads, reference
        # MergeVars semantics)
        comm = AsyncCommunicator(store, merge_num=1)
        comm.start()
        for _ in range(10):
            comm.send('p', np.ones((4,), 'float32'))
        comm.flush()
        comm.stop()
        np.testing.assert_allclose(store.get('p'),
                                   np.full((4,), -5.0), rtol=1e-6)
    finally:
        srv.stop()


def test_cross_process_trainers_with_barrier():
    """Reference test_dist_base.py shape: a real pserver SUBPROCESS +
    two trainer subprocesses; trainers push sparse grads and meet at
    the barrier; parent verifies the table saw both."""
    server_code = '''
import sys, time
sys.path.insert(0, %r)
from paddle_tpu.distributed import PsServer
srv = PsServer(port=int(sys.argv[1]))
print('READY', srv.port, flush=True)
time.sleep(30)
'''
    trainer_code = '''
import sys
import numpy as np
sys.path.insert(0, %r)
from paddle_tpu.distributed import PsClient
rank = int(sys.argv[2])
c = PsClient('127.0.0.1:' + sys.argv[1])
c.init_sparse('emb', rows=10, dim=2, optimizer='sgd', lr=1.0)
ids = np.array([rank, 5], 'int64')
c.push_rows('emb', ids, np.ones((2, 2), 'float32'))
c.barrier(2)
print('trainer', rank, 'done', flush=True)
'''
    import socket
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, PYTHONPATH=REPO)
    srv_proc = subprocess.Popen(
        [sys.executable, '-c', server_code % REPO, str(port)],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        assert srv_proc.stdout.readline().startswith('READY')
        trainers = [subprocess.Popen(
            [sys.executable, '-c', trainer_code % REPO, str(port),
             str(r)], env=env) for r in range(2)]
        for t in trainers:
            assert t.wait(timeout=60) == 0
        c = PsClient('127.0.0.1:%d' % port)
        rows = c.pull_rows('emb', np.array([0, 1, 5], 'int64'), 2)
        np.testing.assert_allclose(rows[0], [-1, -1])  # rank 0
        np.testing.assert_allclose(rows[1], [-1, -1])  # rank 1
        np.testing.assert_allclose(rows[2], [-2, -2])  # both pushed
    finally:
        srv_proc.kill()


def test_out_of_range_ids_are_safe():
    """Bad embedding ids (CTR data reality) must not corrupt the
    server: pulls read zeros, pushes drop, the process survives."""
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_sparse('t', rows=10, dim=2, optimizer='sgd', lr=1.0)
        c.set_rows('t', np.array([1], 'int64'),
                   np.ones((1, 2), 'float32'))
        bad = np.array([-3, 99, 1], 'int64')
        got = c.pull_rows('t', bad, 2)
        np.testing.assert_allclose(got[0], [0, 0])
        np.testing.assert_allclose(got[1], [0, 0])
        np.testing.assert_allclose(got[2], [1, 1])
        c.push_rows('t', bad, np.full((3, 2), 2.0, 'float32'))
        got = c.pull_rows('t', np.array([1], 'int64'), 2)
        np.testing.assert_allclose(got[0], [-1, -1])  # only row 1 moved
        c.close()
    finally:
        srv.stop()


def test_geo_sgd_delta_over_rpc():
    srv = PsServer(lr=0.1)
    try:
        store = RpcParameterServerStore(srv.endpoint)
        store.init_var('p', np.zeros((2, 2), 'float32'))
        store.apply_delta('p', np.full((2, 2), 0.25, 'float32'))
        np.testing.assert_allclose(store.get('p'),
                                   np.full((2, 2), 0.25))
    finally:
        srv.stop()


def test_rpc_sharded_embedding_trains():
    """End-to-end: the embedding table lives on TWO native pserver
    shards; a fluid model trains against them through the same
    lookup/apply_gradients program surface."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel.sparse_embedding import RpcShardedEmbedding

    srv1, srv2 = PsServer(), PsServer()
    try:
        emb = RpcShardedEmbedding(
            'rpc_emb_t', 300, 8, [srv1.endpoint, srv2.endpoint],
            optimizer='adagrad', learning_rate=0.1, seed=3)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data('ids', shape=[5], dtype='int64')
            label = fluid.layers.data('label', shape=[1],
                                      dtype='float32')
            rows = emb.lookup(ids)
            feat = fluid.layers.reshape(rows, [0, 5 * 8])
            pred = fluid.layers.fc(feat, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, label))
            fluid.optimizer.SGD(0.1).minimize(loss)
            emb.apply_gradients(main)
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 300, (16, 5)).astype('int64')
        y_np = rng.rand(16, 1).astype('float32')
        losses = []
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.XLAPlace(0))
            exe.run(startup)
            for _ in range(30):
                l, = exe.run(main, feed={'ids': ids_np,
                                         'label': y_np},
                             fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # both shards hold touched rows
        c1, c2 = PsClient(srv1.endpoint), PsClient(srv2.endpoint)
        assert 'rpc_emb_t' in c1.list_vars()
        assert 'rpc_emb_t' in c2.list_vars()
        # a RE-ATTACHING trainer must not wipe the trained rows
        before = c1.pull_rows('rpc_emb_t',
                              np.arange(5, dtype='int64'), 8)
        emb2 = RpcShardedEmbedding(
            'rpc_emb_t', 300, 8, [srv1.endpoint, srv2.endpoint],
            optimizer='adagrad', learning_rate=0.1, seed=99)
        after = c1.pull_rows('rpc_emb_t',
                             np.arange(5, dtype='int64'), 8)
        np.testing.assert_allclose(after, before)
        del emb2
    finally:
        from paddle_tpu.parallel.sparse_embedding import \
            HostShardedEmbedding
        HostShardedEmbedding._REGISTRY.pop('rpc_emb_t', None)
        srv1.stop()
        srv2.stop()


def test_set_shard_validates_adam_state_before_packing():
    """ADVICE r3: a partial adam state dict (m/v without t) must raise
    a clear ValueError BEFORE any payload is sent, not a KeyError."""
    import pytest
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        c.init_sparse('vt', rows=10, dim=4, optimizer='adam', lr=0.01)
        rows = np.ones((10, 4), 'float32')
        m = np.zeros((10, 4), 'float32')
        v = np.zeros((10, 4), 'float32')
        t = np.zeros(10, 'float32')
        with pytest.raises(ValueError, match='missing t'):
            c.set_shard('vt', 0, rows, {'m': m, 'v': v})
        with pytest.raises(ValueError, match='shape mismatch'):
            c.set_shard('vt', 0, rows, {'m': m[:5], 'v': v, 't': t})
        with pytest.raises(ValueError, match='acc has'):
            c.set_shard('vt', 0, rows, {'acc': t[:5]})
        # the valid triple still lands
        c.set_shard('vt', 0, rows, {'m': m, 'v': v, 't': t})
        got, st = c.pull_shard('vt', 0, 10, dim=4)
        np.testing.assert_allclose(got, rows)
        assert set(st) == {'m', 'v', 't'}
        c.close()
    finally:
        srv.stop()


def test_state_dict_geometry_mismatch_raises_not_spins():
    """ADVICE r3: pull-all must fail fast when the server shard holds
    fewer rows than the client-side geometry predicts (snapshot from a
    different vocab loaded server-side), not loop forever on k=0."""
    import pytest
    from paddle_tpu.parallel.sparse_embedding import RpcShardedEmbedding
    srv = PsServer()
    try:
        emb = RpcShardedEmbedding('geom_t', 64, 8, [srv.endpoint],
                                  optimizer='sgd', learning_rate=0.1,
                                  seed=7)
        d = emb.state_dict()
        assert d['geom_t.table'].shape == (64, 8)
        # shrink the server table out from under the client by loading
        # a snapshot with different geometry (init_sparse alone is an
        # idempotent no-op on an existing table, by design)
        import tempfile
        srv2 = PsServer()
        c = PsClient(srv.endpoint)
        try:
            c2 = PsClient(srv2.endpoint)
            c2.init_sparse('geom_t', rows=16, dim=8, optimizer='sgd',
                           lr=0.1)
            with tempfile.TemporaryDirectory() as td:
                path = os.path.join(td, 'small.ptps')
                c2.save(path)
                c.load(path)
            c2.close()
        finally:
            srv2.stop()
        with pytest.raises(RuntimeError, match='geometry mismatch'):
            emb.state_dict()
        c.close()
    finally:
        from paddle_tpu.parallel.sparse_embedding import \
            HostShardedEmbedding
        HostShardedEmbedding._REGISTRY.pop('geom_t', None)
        srv.stop()


def test_save_snapshot_does_not_block_other_tables():
    """ADVICE r3: SAVE must not hold the global table map lock across
    disk I/O — a pull on an unrelated table during a snapshot must
    complete well inside the deadline."""
    import tempfile
    import threading
    srv = PsServer()
    try:
        c = PsClient(srv.endpoint)
        # a table big enough that serialization takes measurable time
        c.init_sparse('big', rows=200000, dim=64, optimizer='adam',
                      lr=0.01)
        c.init_dense('small', np.ones(8, 'float32'))
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, 'snap.ptps')
            t0 = time.monotonic()
            saver = threading.Thread(
                target=lambda: PsClient(srv.endpoint).save(path))
            saver.start()
            # pulls racing the save must keep flowing
            c2 = PsClient(srv.endpoint)
            worst = 0.0
            while saver.is_alive():
                p0 = time.monotonic()
                c2.pull_dense('small')
                worst = max(worst, time.monotonic() - p0)
            saver.join()
            assert os.path.exists(path)
            # generous bound: without the fix the pull waits for the
            # whole ~50 MB adam-state serialization
            assert worst < 1.0, 'pull stalled %.2fs behind SAVE' % worst
            c2.close()
        c.close()
    finally:
        srv.stop()
