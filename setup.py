"""Packaging for paddle_tpu: the python tree + the prebuilt native
artifacts (run `make` first; package_data ships the .so files the way
the reference wheel ships libpaddle_framework)."""
from setuptools import setup, find_packages

setup(
    name='paddle_tpu',
    version='0.4.0',
    description='fluid-v1.6-compatible TPU-native deep learning '
                'framework (JAX/XLA/Pallas compute, C++ runtime)',
    packages=find_packages(include=['paddle_tpu', 'paddle_tpu.*']),
    package_data={
        'paddle_tpu.runtime': ['libptruntime.so', 'Makefile', '*.cc'],
        'paddle_tpu.inference.capi': ['libpaddle_tpu_capi.so',
                                      'Makefile', '*.cc', '*.h'],
        'paddle_tpu.train.demo': ['*.cc'],
    },
    install_requires=['numpy', 'jax'],
    python_requires='>=3.9',
)
