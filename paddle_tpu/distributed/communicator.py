"""Async parameter-server communicator: bounded-staleness gradient
shipping and GeoSGD delta shipping.

Reference: operators/distributed/communicator.h — AsyncCommunicator
(:175) batches per-variable send queues in background threads and merges
up to `merge_num` pending grads before one RPC; GeoSgdCommunicator
(:343) trains locally and ships parameter *deltas* every
`geo_need_push_nums` steps.  listen_and_serv's async loop applies grads
with no barrier (operators/distributed_ops/listen_and_serv_op.cc:226).

TPU-native re-design: dense synchronous training rides XLA collectives
(ICI/DCN) and never goes through here; this path exists for the
CTR/sparse workload where huge embedding tables live host-side
(parallel/sparse_embedding.py) and workers tolerate bounded staleness.
The "server" is a thread-safe host store (one per process; multi-host
deployments shard tables across hosts the same way the reference shards
param blocks across pservers).
"""

import threading
import time
import queue as _queue

import numpy as np

# dependency-free stats module (no fluid package init required)
from ..fluid import monitor


class ParameterServerStore(object):
    """In-process stand-in for the pserver side: name -> np.ndarray with
    an optimizer applied under a lock (the reference runs per-param
    optimize sub-blocks inside listen_and_serv — sgd, momentum, and
    adam rules alike, listen_and_serv_op.cc:110 /
    distribute_transpiler.py:1110).  Per-var rules are set with
    conf_var(); unconfigured vars fall back to global-lr sgd.  The
    update rules match the native RPC server (runtime/ps_service.cc
    dense_apply) and the in-program optimizer ops
    (ops/optimizer_ops.py), so async-PS training is step-for-step
    comparable with a locally-optimized program."""

    def __init__(self, lr=1.0):
        self._params = {}
        self._locks = {}
        self._rules = {}   # name -> dict(kind, lr, b1, b2, eps)
        self._state = {}   # name -> dict(m, v, t)
        self._global_lock = threading.Lock()
        self.lr = lr

    def init_var(self, name, value):
        with self._global_lock:
            self._params[name] = np.array(value, copy=True)
            self._locks[name] = threading.Lock()

    def conf_var(self, name, optimizer='sgd', lr=0.01, momentum=0.9,
                 beta1=0.9, beta2=0.999, epsilon=1e-8):
        """Per-var server-side update rule (the pserver optimize
        sub-block analog)."""
        b1 = momentum if optimizer == 'momentum' else beta1
        with self._global_lock:
            self._rules[name] = dict(kind=optimizer, lr=lr, b1=b1,
                                     b2=beta2, eps=epsilon)
            self._state[name] = {}

    def apply_grad(self, name, grad):
        with self._locks[name]:
            rule = self._rules.get(name)
            if rule is None:  # default: global-lr sgd
                self._params[name] -= self.lr * grad
                return
            g = np.asarray(grad, dtype=self._params[name].dtype)
            st = self._state[name]
            if rule['kind'] == 'sgd':
                self._params[name] -= rule['lr'] * g
            elif rule['kind'] == 'momentum':
                # velocity = mu*velocity + g; p -= lr*velocity
                v = st.setdefault('m', np.zeros_like(self._params[name]))
                v *= rule['b1']
                v += g
                self._params[name] -= rule['lr'] * v
            else:  # adam, matching ops/optimizer_ops.py adam()
                m = st.setdefault('m', np.zeros_like(self._params[name]))
                v = st.setdefault('v', np.zeros_like(self._params[name]))
                st['t'] = st.get('t', 0) + 1
                b1, b2 = rule['b1'], rule['b2']
                m *= b1
                m += (1 - b1) * g
                v *= b2
                v += (1 - b2) * g * g
                lr_t = rule['lr'] * np.sqrt(1 - b2 ** st['t']) / \
                    (1 - b1 ** st['t'])
                self._params[name] -= lr_t * m / (np.sqrt(v) +
                                                  rule['eps'])

    def apply_delta(self, name, delta):
        with self._locks[name]:
            self._params[name] += delta

    def get(self, name):
        with self._locks[name]:
            return self._params[name].copy()

    def names(self):
        with self._global_lock:
            return list(self._params)


class AsyncCommunicator(object):
    """Background-thread gradient shipper with merge-before-send.

    send(name, grad) enqueues; a send thread drains each var's queue,
    averages up to `merge_num` pending grads (the reference's
    MergeVars), and applies them to the server store.  recv(name) pulls
    the current server value (the reference's RecvThread batch-pulls on
    a cadence)."""

    def __init__(self, server, send_queue_size=20, merge_num=20,
                 send_wait_times=5):
        self.server = server
        self.merge_num = max(1, int(merge_num))
        self.send_wait_times = send_wait_times
        self._queues = {}
        self._qsize = int(send_queue_size)
        self._threads = []
        self._running = False
        self._lock = threading.Lock()

    # -- lifecycle (reference: Communicator::Start/Stop) ---------------
    def start(self):
        self._running = True

    def stop(self):
        self._running = False
        for t in self._threads:
            t.join()
        self._threads = []

    def is_running(self):
        return self._running

    def _queue_of(self, name):
        with self._lock:
            q = self._queues.get(name)
            if q is None:
                q = _queue.Queue(maxsize=self._qsize)
                self._queues[name] = q
                t = threading.Thread(target=self._send_loop,
                                     args=(name, q), daemon=True)
                t.start()
                self._threads.append(t)
            return q

    def send(self, name, grad):
        if not self._running:
            raise RuntimeError('communicator not started')
        grad = np.asarray(grad)
        monitor.add('communicator/sends')
        monitor.add('communicator/send_bytes', float(grad.nbytes))
        self._queue_of(name).put(grad)
        # total backlog ACROSS the per-variable queues: a single slow
        # variable's pile-up must show even when others drain fine
        monitor.set_gauge('communicator/send_queue_depth',
                          sum(q.qsize()
                              for q in list(self._queues.values())))

    def _send_loop(self, name, q):
        while self._running or not q.empty():
            try:
                g = q.get(timeout=0.01)
            except _queue.Empty:
                continue
            merged, n = np.array(g, dtype=np.float64), 1
            while n < self.merge_num:
                try:
                    merged += q.get_nowait()
                    n += 1
                except _queue.Empty:
                    break
            # MergeVars accounting: grads folded into one server apply
            monitor.add('communicator/grads_merged', float(n))
            monitor.add('communicator/server_applies')
            self.server.apply_grad(name, (merged / n).astype(g.dtype))

    def recv(self, name):
        return self.server.get(name)

    def flush(self):
        """Block until every queue is drained (test/shutdown helper)."""
        for q in list(self._queues.values()):
            while not q.empty():
                time.sleep(0.005)


class GeoSgdCommunicator(object):
    """GeoSGD: train locally, ship deltas.

    Every `geo_need_push_nums` local steps, push
    (local - last_synced) / trainers to the server and pull the merged
    global value (reference: GeoSgdCommunicator::SendThread +
    RecvUpdateVars)."""

    def __init__(self, server, trainers, geo_need_push_nums=100):
        self.server = server
        self.trainers = max(1, int(trainers))
        self.push_nums = max(1, int(geo_need_push_nums))
        self._old = {}
        self._steps = {}
        self._running = False

    def start(self):
        self._running = True

    def stop(self):
        self._running = False

    def init_from_server(self, name):
        val = self.server.get(name)
        self._old[name] = val.copy()
        self._steps[name] = 0
        return val

    def step(self, name, local_value):
        """Record one local training step; returns the (possibly
        refreshed) local value."""
        if not self._running:
            raise RuntimeError('communicator not started')
        self._steps[name] += 1
        if self._steps[name] < self.push_nums:
            return local_value
        self._steps[name] = 0
        delta = (np.asarray(local_value) - self._old[name]) / self.trainers
        self.server.apply_delta(name, delta)
        fresh = self.server.get(name)
        self._old[name] = fresh.copy()
        return fresh
