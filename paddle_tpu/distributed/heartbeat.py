"""Worker-liveness heartbeat monitor (failure detection).

Reference: operators/distributed/heart_beat_monitor.h:38-104 — the chief
pserver tracks every trainer's state {UNINITED, RUNNING, COMPLETED} with
a timestamp updated on each received grad; a monitor thread logs workers
whose heartbeat is older than a threshold.  Recovery remains
"checkpoint + restart" (SURVEY.md §5), same as the reference.

TPU-native placement: in a jax.distributed job the chief host runs this
next to the coordinator; workers call update() from their train loop (or
the communicator calls it on every send)."""

import logging
import threading
import time

UNINITED = 0
RUNNING = 1
COMPLETED = 2

_STATUS_NAMES = {UNINITED: 'UNINITED', RUNNING: 'RUNNING',
                 COMPLETED: 'COMPLETED'}

logger = logging.getLogger('paddle_tpu.heartbeat')


class HeartBeatMonitor(object):
    def __init__(self, workers, is_chief=True, monitored_var='',
                 timeout=60.0, check_interval=1.0, on_lost=None,
                 misses=None):
        if workers <= 0:
            raise ValueError('trainers must be one or more')
        self.workers = workers
        self.is_chief = is_chief
        self.monitored_var = monitored_var
        self.timeout = timeout
        self.check_interval = check_interval
        self.on_lost = on_lost          # callback(worker_id, age_seconds)
        # FLAGS_heartbeat_misses: consecutive expired checks before a
        # worker flips LOST — one late packet is not a death.  A
        # recovery short of the threshold counts a flap.
        if misses is None:
            try:
                from ..fluid.flags import get_flag
                misses = int(get_flag('FLAGS_heartbeat_misses', 3)
                             or 3)
            except Exception:
                misses = 3
        self.misses = max(1, int(misses))
        self._status = {i: UNINITED for i in range(workers)}
        self._stamp = {i: 0.0 for i in range(workers)}
        self._miss = {i: 0 for i in range(workers)}
        self._lost = set()
        self._lock = threading.Lock()
        self._running = False
        self._thread = None

    # -- lifecycle ----------------------------------------------------
    def start(self):
        self._running = True
        if self.is_chief:
            self._thread = threading.Thread(target=self._monitor_loop,
                                            daemon=True)
            self._thread.start()

    def stop(self):
        self._running = False
        if self._thread:
            self._thread.join()
            self._thread = None

    # -- worker side --------------------------------------------------
    def update(self, worker_id, status=RUNNING):
        """Heartbeat from `worker_id` (reference: Update called from the
        request handler on every received var).  A worker returning
        from LOST is RE-ADMITTED (the elastic trainer-set-change leg:
        a restarted trainer takes its dead predecessor's slot); a
        recovery that had accumulated misses short of the threshold
        counts a flap."""
        from ..fluid import monitor as _monitor
        with self._lock:
            self._status[worker_id] = status
            self._stamp[worker_id] = time.monotonic()
            if worker_id in self._lost:
                self._lost.discard(worker_id)
                _monitor.add('elastic/readmissions')
                logger.warning('worker %d re-admitted after loss',
                               worker_id)
            elif self._miss.get(worker_id, 0) > 0:
                _monitor.add('elastic/heartbeat_flaps')
            self._miss[worker_id] = 0

    # -- chief side ---------------------------------------------------
    def _monitor_loop(self):
        while self._running:
            now = time.monotonic()
            callbacks = []
            with self._lock:
                for wid, st in self._status.items():
                    if st != RUNNING or wid in self._lost:
                        continue
                    age = now - self._stamp[wid]
                    if age <= self.timeout:
                        self._miss[wid] = 0
                        continue
                    self._miss[wid] = self._miss.get(wid, 0) + 1
                    if self._miss[wid] < self.misses:
                        continue
                    self._lost.add(wid)
                    logger.warning(
                        'worker %d lost: no heartbeat for %.1fs '
                        '(%d consecutive expired checks)',
                        wid, age, self._miss[wid])
                    if self.on_lost is not None:
                        callbacks.append((wid, age))
            for wid, age in callbacks:
                # outside the lock: an on_lost that re-admits (or
                # queries) the monitor must not deadlock
                self.on_lost(wid, age)
            time.sleep(self.check_interval)

    def lost_workers(self):
        with self._lock:
            return sorted(self._lost)

    def worker_status(self, worker_id):
        with self._lock:
            return _STATUS_NAMES[self._status[worker_id]]

    def all_completed(self):
        with self._lock:
            return all(s == COMPLETED for s in self._status.values())
