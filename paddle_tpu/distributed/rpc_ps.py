"""RPC parameter-server transport: native TCP service + Python client.

Reference: the gRPC/bRPC parameter plane —
operators/distributed_ops/listen_and_serv_op.cc:110 (server loop),
operators/distributed/grpc/grpc_client.h (async client),
send_recv.proto.in:19 (SendVariable/GetVariable), and
framework/fleet/fleet_wrapper.h:77-145 (PullSparse/PushSparse).

TPU-native split: dense TRAINING sync rides XLA collectives, so what
keeps an RPC plane on TPU is the CTR parameter-server shape — a
long-lived service process holding dense slots (server-side SGD, the
reference's optimize sub-blocks) and big sparse row tables (per-row
adagrad/sgd).  The service itself is native C++
(runtime/ps_service.cc, threaded TCP, binary frames); this module is
the ctypes server handle + the client.

RpcParameterServerStore is interface-compatible with
distributed.ParameterServerStore, so the AsyncCommunicator
(merge-before-send, bounded staleness) works unchanged against a
REMOTE server process.
"""

import socket
import struct
import threading

import numpy as np

OP_INIT_DENSE = 1
OP_PUSH_DENSE = 2
OP_PULL_DENSE = 3
OP_INIT_SPARSE = 4
OP_PULL_ROWS = 5
OP_PUSH_ROWS = 6
OP_SET_ROWS = 7
OP_BARRIER = 8
OP_LIST = 9
OP_ADD_DENSE = 10


class PsServer(object):
    """In-process handle on the native service (the listen_and_serv
    analog).  Run one of these in the pserver process; trainers connect
    with PsClient."""

    def __init__(self, port=0, lr=0.01):
        from ..runtime import _load
        lib = _load()
        import ctypes
        lib.ps_serve_start.restype = ctypes.c_void_p
        lib.ps_serve_start.argtypes = [ctypes.c_int, ctypes.c_float]
        lib.ps_serve_port.argtypes = [ctypes.c_void_p]
        lib.ps_serve_stop.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._handle = lib.ps_serve_start(port, lr)
        if not self._handle:
            raise RuntimeError('ps_serve_start failed (port %d)' % port)
        self.port = lib.ps_serve_port(self._handle)
        self.endpoint = '127.0.0.1:%d' % self.port

    def stop(self):
        if self._handle:
            self._lib.ps_serve_stop(self._handle)
            self._handle = None

    def __del__(self):  # best effort
        try:
            self.stop()
        except Exception:
            pass


class PsClient(object):
    """Blocking client (reference RPCClient / grpc_client.h: the async
    completion-queue machinery collapses to one in-flight request per
    connection; open several clients for parallelism)."""

    def __init__(self, endpoint):
        host, port = endpoint.rsplit(':', 1)
        self._sock = socket.create_connection((host, int(port)))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # one in-flight request per connection: the lock makes a shared
        # client safe under AsyncCommunicator's per-variable send
        # threads (request/response stay paired)
        self._lock = threading.Lock()

    def close(self):
        self._sock.close()

    # -- framing ----------------------------------------------------------
    def _call(self, op, name, payload=b''):
        nb = name.encode()
        frame = struct.pack('<BI', op, len(nb)) + nb + payload
        with self._lock:
            self._sock.sendall(struct.pack('<I', len(frame)) + frame)
            (rlen,) = struct.unpack('<I', self._recv(4))
            return self._recv(rlen) if rlen else b''

    def _recv(self, n):
        out = b''
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError('ps server closed the connection')
            out += chunk
        return out

    # -- dense slots ------------------------------------------------------
    def init_dense(self, name, value):
        v = np.ascontiguousarray(value, np.float32).reshape(-1)
        self._call(OP_INIT_DENSE, name,
                   struct.pack('<Q', v.size) + v.tobytes())

    def push_dense_grad(self, name, grad):
        g = np.ascontiguousarray(grad, np.float32).reshape(-1)
        self._call(OP_PUSH_DENSE, name,
                   struct.pack('<Q', g.size) + g.tobytes())

    def add_dense(self, name, delta):
        """p += delta: the GeoSGD delta-shipping leg
        (operators/distributed/communicator.h:343)."""
        d = np.ascontiguousarray(delta, np.float32).reshape(-1)
        self._call(OP_ADD_DENSE, name,
                   struct.pack('<Q', d.size) + d.tobytes())

    def pull_dense(self, name):
        out = self._call(OP_PULL_DENSE, name)
        (n,) = struct.unpack('<Q', out[:8])
        return np.frombuffer(out[8:], np.float32, n).copy()

    # -- sparse tables ----------------------------------------------------
    def init_sparse(self, name, rows, dim, optimizer='sgd', lr=0.01):
        opt = 1 if optimizer == 'adagrad' else 0
        self._call(OP_INIT_SPARSE, name,
                   struct.pack('<QQBf', rows, dim, opt, lr))

    def set_rows(self, name, ids, values):
        self._rows_op(OP_SET_ROWS, name, ids, values)

    def push_rows(self, name, ids, grads):
        self._rows_op(OP_PUSH_ROWS, name, ids, grads)

    def _rows_op(self, op, name, ids, values):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        v = np.ascontiguousarray(values, np.float32).reshape(ids.size, -1)
        self._call(op, name, struct.pack('<Q', ids.size) + ids.tobytes() +
                   v.tobytes())

    def pull_rows(self, name, ids, dim):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = self._call(OP_PULL_ROWS, name,
                         struct.pack('<Q', ids.size) + ids.tobytes())
        return np.frombuffer(out, np.float32).reshape(ids.size,
                                                      dim).copy()

    # -- control ----------------------------------------------------------
    def barrier(self, n_trainers):
        """send_barrier/fetch_barrier analog: blocks until n_trainers
        processes reach the barrier."""
        self._call(OP_BARRIER, '', struct.pack('<Q', n_trainers))

    def list_vars(self):
        out = self._call(OP_LIST, '')
        (count,) = struct.unpack('<I', out[:4])
        names, off = [], 4
        for _ in range(count):
            (ln,) = struct.unpack('<I', out[off:off + 4])
            off += 4
            names.append(out[off:off + ln].decode())
            off += ln
        return names


class RpcParameterServerStore(object):
    """distributed.ParameterServerStore over the RPC transport: the
    AsyncCommunicator (merge-before-send) talks to a REMOTE native
    server process through this without changes."""

    def __init__(self, endpoint):
        self._client = PsClient(endpoint)

    def init_var(self, name, value):
        self._client.init_dense(name, value)
        self._shapes = getattr(self, '_shapes', {})
        self._shapes[name] = np.asarray(value).shape

    def apply_grad(self, name, grad):
        self._client.push_dense_grad(name, grad)

    def apply_delta(self, name, delta):
        self._client.add_dense(name, delta)

    def get(self, name):
        flat = self._client.pull_dense(name)
        shape = getattr(self, '_shapes', {}).get(name)
        return flat.reshape(shape) if shape else flat

    def names(self):
        return [n for n in self._client.list_vars()]
